"""``pw.io.fs`` — filesystem connector (csv/json/plaintext/binary).

Re-design of ``python/pathway/io/fs`` + the Rust filesystem scanner/parsers
(``src/connectors/posix_like.rs``, ``data_format.rs`` DsvParser :500,
JsonLinesParser :1443). Static mode reads files at build time; streaming
mode (directory watching) arrives with the realtime executor loop.
"""

from __future__ import annotations

import csv as _csv
import glob
import json
import os
from typing import Any

from ..engine.executor import RealtimeSource
from ..internals import dtype as dt
from ..internals.parse_graph import G
from ..internals.schema import SchemaMetaclass, schema_from_types
from ..internals.table import Table
from ..internals.table_io import rows_to_table


def _paths_of(path: str | os.PathLike) -> list[str]:
    path = os.fspath(path)
    if os.path.isdir(path):
        return sorted(
            os.path.join(root, f)
            for root, _, files in os.walk(path)
            for f in files
        )
    matched = sorted(glob.glob(path))
    return matched if matched else [path]


def _convert(value: str, col: Any) -> Any:
    dtype = col.dtype if hasattr(col, "dtype") else col
    u = dt.unoptionalize(dtype)
    if value == "":
        # an empty cell takes the schema default when one is declared
        # (reference test_io.py:458 test_csv_default_values), else None
        # for optional columns
        if getattr(col, "has_default", False):
            return col.default_value
        if dtype.is_optional:
            return None
    if u == dt.INT:
        return int(value)
    if u == dt.FLOAT:
        return float(value)
    if u == dt.BOOL:
        return value.strip().lower() in ("true", "1", "yes", "on")
    return value


class FsStreamSource(RealtimeSource):
    """Directory/glob watcher: polls for new files and appended lines,
    emitting one committed batch per poll round.

    Re-design of the Rust posix scanner + parser thread
    (``src/connectors/posix_like.rs``, ``scanner/filesystem``): offsets are
    (path → bytes consumed), which is this source's ``OffsetAntichain``
    (``src/connectors/offset.rs``) for persistence seek/resume. Each poll
    reads only the appended tail (stat + seek), never the whole file; a
    shrunk file (truncate/rotate) resets its offset and is re-read.
    """

    def __init__(
        self,
        path: str,
        format: str,
        schema: SchemaMetaclass | None,
        names: list[str],
        delimiter: str = ",",
        autocommit_ms: int | None = 1500,
    ):
        super().__init__(list(names))
        self.path = path
        self.format = format
        self.fschema = schema
        self.names = list(names)
        self.delimiter = delimiter
        self.autocommit_ms = autocommit_ms
        #: bytes actually delivered to the engine (the persisted offset);
        #: bytes parsed into _pending but not yet emitted stay in _staged so
        #: a checkpoint never covers input the snapshot doesn't contain
        self._consumed: dict[str, int] = {}
        self._staged: dict[str, int] = {}
        self._headers: dict[str, list[str]] = {}
        self._pending: list[tuple] = []
        #: columnar-parsed chunks awaiting emission: (path, columns, n).
        #: Keys are derived at EMISSION time (poll), like the dict path —
        #: a truncation dropping staged chunks must not have registered
        #: key pairs for rows that never ship
        self._pending_cols: list[tuple[str, dict, int]] = []
        self._plan: list | None = None  # lazy columnar csv parse plan
        self._last_emit: float | None = None  # None = emit first batch now

    # -- persistence protocol --

    def offset_state(self):
        return {"files": dict(self._consumed)}

    def seek(self, state) -> None:
        self._consumed = {str(k): int(v) for k, v in state.get("files", {}).items()}
        self._staged = {}
        self._pending = []
        self._pending_cols = []
        # headers live before the persisted offsets — recover them
        for fpath in list(self._consumed):
            self._load_header(fpath)

    # -- polling --

    def _load_header(self, fpath: str) -> bool:
        if self.format not in ("csv", "dsv") or fpath in self._headers:
            return True
        try:
            with open(fpath, "rb") as f:
                first = f.readline()
        except OSError:
            return False
        if not first.endswith(b"\n"):
            return False  # header not fully written yet
        self._headers[fpath] = next(
            _csv.reader([first.decode("utf-8").rstrip("\r\n")],
                        delimiter=self.delimiter)
        )
        # a fresh file starts past its header line
        if self._consumed.get(fpath, 0) < len(first):
            self._consumed[fpath] = len(first)
        return True

    def _parse_line(self, fpath: str, line: str):
        if self.format in ("csv", "dsv"):
            header = self._headers[fpath]
            rec = dict(zip(header, next(_csv.reader([line], delimiter=self.delimiter))))
            if self.fschema is not None:
                return tuple(
                    _convert(rec.get(n, ""), self.fschema.columns()[n])
                    for n in self.names
                )
            return tuple(_auto(rec.get(n, "")) for n in self.names)
        if self.format in ("json", "jsonlines"):
            obj = json.loads(line)
            return tuple(obj.get(n) for n in self.names)
        return (line,)  # plaintext

    def _parse_chunk(self, fpath: str, lines: list[str]):
        """Columnar parse of one chunk of raw lines → (columns, n), or
        :class:`columnar.ParseRefusal` when bit-parity with
        ``_parse_line`` cannot be guaranteed for this chunk."""
        from . import columnar as _col

        if self.format in ("csv", "dsv"):
            if self.fschema is None:
                raise _col.ParseRefusal("schemaless csv (_auto per cell)")
            if self._plan is None:
                self._plan = _col.csv_plan(self.fschema, self.names)
            return _col.parse_csv_chunk(
                lines, self._headers[fpath], self._plan, self.delimiter
            )
        if self.format in ("json", "jsonlines"):
            return _col.parse_json_chunk(lines, self.names)
        if self.format == "plaintext" and len(self.names) == 1:
            return _col.parse_plaintext_chunk(lines, self.names[0])
        raise _col.ParseRefusal(f"no columnar reader for {self.format!r}")

    def _ingest_lines(self, fpath: str, lines: list[str]) -> None:
        """Route freshly scanned lines into the parse staging area:
        columnar chunks when the columnar plane is on, the per-line dict
        path otherwise — and per CHUNK on any parse refusal (same
        values, same keys, same exceptions as the dict path)."""
        import time as _time

        from . import columnar as _col
        from .python import _accrue, _stage_sinks

        stage = _stage_sinks(f"fs-{self.format}")
        if not _col.enabled():
            t0 = _time.perf_counter_ns()
            for line in lines:
                self._pending.append((fpath, self._parse_line(fpath, line)))
            if stage is not None:
                _accrue(stage, "parse_ns", _time.perf_counter_ns() - t0)
            return
        step = _col.chunk_rows()
        for i in range(0, len(lines), step):
            sub = lines[i:i + step]
            t0 = _time.perf_counter_ns()
            try:
                data, n = self._parse_chunk(fpath, sub)
            except _col.ParseRefusal:
                # per-batch fallback: re-parse exactly this chunk row by
                # row — malformed cells raise here, where they always did
                for line in sub:
                    self._pending.append(
                        (fpath, self._parse_line(fpath, line))
                    )
                if stage is not None:
                    _accrue(stage, "parse_ns", _time.perf_counter_ns() - t0)
                continue
            if stage is not None:
                _accrue(stage, "parse_ns", _time.perf_counter_ns() - t0)
            self._pending_cols.append((fpath, data, n))

    def _scan(self) -> None:
        """Read appended tails of all watched files into _pending."""
        for fpath in _paths_of(self.path):
            if not os.path.isfile(fpath):
                continue
            try:
                size = os.stat(fpath).st_size
            except OSError:
                continue
            start = self._staged.get(fpath, self._consumed.get(fpath, 0))
            if size < start:
                # truncated/rotated — re-read from scratch; drop unemitted
                # rows parsed from the pre-truncation content
                self._consumed.pop(fpath, None)
                self._staged.pop(fpath, None)
                self._headers.pop(fpath, None)
                self._pending = [(p, r) for p, r in self._pending if p != fpath]
                self._pending_cols = [
                    (p, d, n) for p, d, n in self._pending_cols if p != fpath
                ]
                start = 0
            if not self._load_header(fpath):
                continue
            start = max(start, self._consumed.get(fpath, 0))
            if size <= start:
                continue
            try:
                with open(fpath, "rb") as f:
                    f.seek(start)
                    chunk = f.read()
            except OSError:
                continue
            # only consume complete (newline-terminated) lines; a partial
            # tail stays for the next poll
            end = chunk.rfind(b"\n")
            if end < 0:
                continue
            lines = [
                stripped
                for line in chunk[:end].decode("utf-8").split("\n")
                if (stripped := line.rstrip("\r")).strip()
            ]
            if lines:
                self._ingest_lines(fpath, lines)
            self._staged[fpath] = start + end + 1

    def poll(self):
        import time as _time

        from ..engine import keys as K
        from ..engine.delta import Delta, concat_deltas, rows_to_columns
        from ..parallel import frames as _frames
        from .python import _accrue, _stage_sinks

        self._scan()
        if not self._pending and not self._pending_cols:
            return []
        now = _time.monotonic()
        window_open = (
            self._last_emit is None
            or self.autocommit_ms is None
            or (now - self._last_emit) * 1000.0 >= self.autocommit_ms
        )
        if not window_open:
            return []
        stage = _stage_sinks(f"fs-{self.format}")
        pk = (
            self.fschema.primary_key_columns()
            if self.fschema is not None
            else None
        )
        key_names = list(pk) if pk else list(self.names)
        deltas: list[Delta] = []
        total = 0
        if self._pending:
            rows = [r for _, r in self._pending]
            self._pending = []
            h0 = _time.perf_counter_ns()
            if pk:
                idx = [self.names.index(p) for p in pk]
                keys = K.hash_values([tuple(r[i] for i in idx) for r in rows])
            else:
                keys = K.hash_values(rows)
            h1 = _time.perf_counter_ns()
            deltas.append(Delta(keys=keys, data=rows_to_columns(rows, self.names)))
            if stage is not None:
                _accrue(stage, "hash_ns", h1 - h0)
                _accrue(stage, "delta_ns", _time.perf_counter_ns() - h1)
            total += len(rows)
        chunks, self._pending_cols = self._pending_cols, []
        for _fpath, data, n in chunks:
            # one fused native BLAKE2b pass over the parsed column
            # buffers — bit-identical to hash_values over the row tuples
            h0 = _time.perf_counter_ns()
            keys = K.mix_columns_fused([data[c] for c in key_names], n)
            h1 = _time.perf_counter_ns()
            d = Delta(keys=keys, data=data)
            d.keys_content_cols = tuple(key_names)
            # the chunk IS a wire frame: in process it travels by
            # reference (zero-copy — LocalComm.exchange's contract),
            # across processes the identical shape encodes binary
            frame = _frames.connector_frame(d)
            opened = _frames.open_connector_frame(frame)
            assert opened is d, (
                "connector frame must pass by reference in-process"
            )
            deltas.append(opened)
            if stage is not None:
                _accrue(stage, "hash_ns", h1 - h0)
                _accrue(stage, "delta_ns", _time.perf_counter_ns() - h1)
            total += n
        self._consumed.update(self._staged)  # rows now delivered → offset moves
        self._staged.clear()
        self._last_emit = now
        t0 = _time.perf_counter_ns()
        out = (
            deltas[0]
            if len(deltas) == 1
            else concat_deltas(deltas, self.names)
        )
        if stage is not None:
            if len(deltas) > 1:
                _accrue(stage, "delta_ns", _time.perf_counter_ns() - t0)
            _accrue(stage, "rows", total)
            _accrue(stage, "flushes", 1)
        return [out]

    def is_finished(self) -> bool:
        return False  # watches forever (stop via pw.request_stop)


class _LocalFsClient:
    """ObjectStoreClient over the local filesystem (reference
    ``posix_like.rs``): each file is an object versioned by
    (mtime_ns, size), so the shared scanner's modified/deleted-object
    retraction semantics apply to plain directories."""

    def __init__(self, path: str):
        self._path = path

    def list_objects(self):
        from ._object_scanner import ObjectMeta

        out = []
        for p in _paths_of(self._path):
            if not os.path.isfile(p):
                continue  # glob patterns can match directories
            try:
                st = os.stat(p)
            except OSError:
                continue
            out.append(ObjectMeta(
                key=p,
                version=f"{st.st_mtime_ns}:{st.st_size}",
                size=st.st_size,
                modified_at=st.st_mtime,
            ))
        return out

    def read_object(self, key: str) -> bytes:
        with open(key, "rb") as f:
            return f.read()


def read(
    path: str | os.PathLike,
    *,
    format: str = "csv",
    schema: SchemaMetaclass | None = None,
    mode: str = "streaming",
    csv_settings: Any = None,
    json_field_paths: dict[str, str] | None = None,
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    **kwargs: Any,
) -> Table:
    if format == "raw":
        format = "binary"  # reference alias (io/fs raw == whole-file bytes)
    if (
        mode == "streaming"
        and with_metadata
        and format in ("csv", "dsv", "json", "jsonlines", "plaintext")
    ):
        # object semantics (the reference's posix_like scanner): each file
        # is one object — a modified file retracts its old rows and inserts
        # the new version's, a deleted file retracts everything, and every
        # row carries a _metadata column. The default (tail) path below is
        # the append-log fast lane.
        from .s3 import object_source_table

        spath = os.fspath(path)
        delimiter = getattr(csv_settings, "delimiter", ",") if csv_settings else ","
        if format == "plaintext":
            schema = schema or schema_from_types(data=str)
        if schema is None:
            probe = read(spath, format=format, schema=None, mode="static",
                         csv_settings=csv_settings)
            schema = probe.schema
            if not schema.column_names():
                raise ValueError(
                    f"pw.io.fs.read({spath!r}, mode='streaming'): no files "
                    "to infer columns from yet — pass schema= explicitly"
                )
        return object_source_table(
            _LocalFsClient(spath), format, schema,
            mode="streaming", with_metadata=True,
            refresh_interval_ms=1000,
            autocommit_duration_ms=autocommit_duration_ms,
            name=name, delimiter=delimiter,
        )
    if mode == "streaming" and format in ("csv", "dsv", "json", "jsonlines", "plaintext"):
        from ..internals.parse_graph import Universe

        spath = os.fspath(path)
        delimiter = getattr(csv_settings, "delimiter", ",") if csv_settings else ","
        if format in ("plaintext",):
            schema = schema or schema_from_types(data=str)
        if schema is not None:
            names = schema.column_names()
        else:
            # sniff columns from whatever exists now
            probe = read(spath, format=format, schema=None, mode="static",
                         csv_settings=csv_settings)
            names = probe.column_names()
            schema = probe.schema
            if not names:
                raise ValueError(
                    f"pw.io.fs.read({spath!r}, mode='streaming'): no files to "
                    "infer columns from yet — pass schema= explicitly"
                )
        use_schema = schema

        def build():
            src = FsStreamSource(
                spath, format, use_schema, names, delimiter,
                autocommit_ms=autocommit_duration_ms,
            )
            src.persistent_id = name
            return src

        return Table("source", [], {"build": build}, use_schema, Universe())
    rows: list[tuple] = []
    names: list[str]
    if format in ("csv", "dsv"):
        delimiter = getattr(csv_settings, "delimiter", ",") if csv_settings else ","
        names = schema.column_names() if schema is not None else []
        for p in _paths_of(path):
            with open(p, newline="") as f:
                reader = _csv.DictReader(f, delimiter=delimiter)
                if not names:
                    names = list(reader.fieldnames or [])
                for rec in reader:
                    if schema is not None:
                        rows.append(tuple(
                            _convert(rec[n], schema.columns()[n]) for n in names
                        ))
                    else:
                        rows.append(tuple(_auto(rec[n]) for n in names))
    elif format in ("json", "jsonlines"):
        names = schema.column_names() if schema is not None else []
        for p in _paths_of(path):
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    obj = json.loads(line)
                    if not names:
                        names = list(obj.keys())
                    rows.append(tuple(obj.get(n) for n in names))
    elif format in ("plaintext", "plaintext_by_file"):
        names = ["data"]
        for p in _paths_of(path):
            if format == "plaintext_by_file":
                with open(p) as f:
                    rows.append((f.read(),))
            else:
                with open(p) as f:
                    for line in f:
                        rows.append((line.rstrip("\n"),))
        if schema is None:
            schema = schema_from_types(data=str)
    elif format == "binary":
        names = ["data"]
        for p in _paths_of(path):
            with open(p, "rb") as f:
                rows.append((f.read(),))
        if schema is None:
            schema = schema_from_types(data=bytes)
    else:
        raise ValueError(f"unknown format {format!r}")

    id_from = schema.primary_key_columns() if schema is not None else None
    return rows_to_table(names, rows, schema=schema, id_from=id_from)


class _FsSinkAdapter:
    """Transactional file writer (the reference FileWriter +
    DsvFormatter/JsonLinesFormatter, made exactly-once): the resume token
    is the byte position of the last ACKED batch — ``open`` truncates a
    recovered file back to it (a kill mid-write leaves a torn tail past
    the token; it is cut before new bytes land) and ``rollback`` does the
    same within a run, so retries after a torn write never double rows."""

    def __init__(self, filename: str, format: str, names: list[str]):
        self.filename = filename
        self.format = format
        self.names = names
        self._raw: Any = None
        self._f: Any = None
        self._writer: Any = None
        #: byte position writes resume from after a rollback: the last
        #: ACKED batch's end (or the post-header position) — NOT the last
        #: write's end, which a torn attempt may have advanced
        self._acked_pos = 0
        from .delivery import _env_f

        self._fsync = _env_f("PATHWAY_SINK_FSYNC", 1.0) > 0

    def open(self, resume_token: Any) -> None:
        import io as _io

        resume = (
            int(resume_token)
            if resume_token is not None and os.path.exists(self.filename)
            else None
        )
        self._raw = open(self.filename, "r+b" if resume is not None else "w+b")
        # text layer for csv/json rendering; byte positions come from the
        # binary layer (text-mode tell() cookies are not truncate() args)
        self._f = _io.TextIOWrapper(self._raw, encoding="utf-8", newline="")
        if self.format == "csv":
            self._writer = _csv.writer(self._f)
        if resume is not None:
            self._raw.truncate(resume)
            self._raw.seek(resume)
            self._acked_pos = resume
            return
        if self.format == "csv":
            self._writer.writerow(self.names + ["time", "diff"])
        self._f.flush()
        self._acked_pos = self._raw.tell()

    def write_batch(self, batch: Any) -> int:
        cols = [batch.delta.data[n] for n in self.names]
        if self.format == "csv":
            self._writer.writerows(
                list(vals) + [batch.time, int(diff)]
                for vals, diff in zip(zip(*cols), batch.delta.diffs)
            )
        else:
            for vals, diff in zip(zip(*cols), batch.delta.diffs):
                obj = {n: _jsonable(v) for n, v in zip(self.names, vals)}
                obj["time"] = batch.time
                obj["diff"] = int(diff)
                self._f.write(json.dumps(obj) + "\n")
        self._f.flush()
        if self._fsync:
            os.fsync(self._raw.fileno())
        return self._raw.tell()

    def rollback(self, resume_token: Any = None) -> None:
        if self._raw is None:
            return
        pos = (
            int(resume_token) if resume_token is not None else self._acked_pos
        )
        self._f.flush()
        self._raw.truncate(pos)
        self._raw.seek(pos)

    def on_timeout(self) -> None:
        """A watchdog-abandoned write thread may still be inside
        ``write_batch`` on this handle: close it so the zombie's next
        write fails on a closed fd instead of interleaving bytes with
        the retry's reopened file (delivery reopens via ``open`` with
        the last acked token, which truncates whatever the zombie
        managed to push)."""
        try:
            if self._f is not None:
                self._f.close()
        except Exception:
            pass
        self._raw = self._f = self._writer = None

    def close(self) -> None:
        if self._f is not None:
            self._f.close()


def write(table: Table, filename: str | os.PathLike, *, format: str = "csv",
          name: str | None = None, retry_policy: Any = None,
          **kwargs: Any) -> None:
    """Write the table's update stream to a file (time/diff columns
    appended). Rides the transactional delivery layer (``io/delivery``):
    with persistence on, batches are acked against the committed frontier
    and the file recovers exactly-once across crashes."""
    from .delivery import deliver

    filename = os.fspath(filename)
    names = table.column_names()

    def adapter():
        return _FsSinkAdapter(filename, format, names)

    deliver(
        table, adapter,
        name=name,
        default_name=f"fs-{os.path.basename(filename)}",
        retry_policy=retry_policy,
        meta={"path": filename},
    )


def _jsonable(v: Any) -> Any:
    import numpy as np

    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return v


def _auto(v: str) -> Any:
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        pass
    return v
