"""``pw.io.fs`` — filesystem connector (csv/json/plaintext/binary).

Re-design of ``python/pathway/io/fs`` + the Rust filesystem scanner/parsers
(``src/connectors/posix_like.rs``, ``data_format.rs`` DsvParser :500,
JsonLinesParser :1443). Static mode reads files at build time; streaming
mode (directory watching) arrives with the realtime executor loop.
"""

from __future__ import annotations

import csv as _csv
import glob
import json
import os
from typing import Any

from ..internals import dtype as dt
from ..internals.parse_graph import G
from ..internals.schema import SchemaMetaclass, schema_from_types
from ..internals.table import Table
from ..internals.table_io import rows_to_table


def _paths_of(path: str | os.PathLike) -> list[str]:
    path = os.fspath(path)
    if os.path.isdir(path):
        return sorted(
            os.path.join(root, f)
            for root, _, files in os.walk(path)
            for f in files
        )
    matched = sorted(glob.glob(path))
    return matched if matched else [path]


def _convert(value: str, dtype: dt.DType) -> Any:
    u = dt.unoptionalize(dtype)
    if value == "" and dtype.is_optional:
        return None
    if u == dt.INT:
        return int(value)
    if u == dt.FLOAT:
        return float(value)
    if u == dt.BOOL:
        return value.strip().lower() in ("true", "1", "yes", "on")
    return value


def read(
    path: str | os.PathLike,
    *,
    format: str = "csv",
    schema: SchemaMetaclass | None = None,
    mode: str = "streaming",
    csv_settings: Any = None,
    json_field_paths: dict[str, str] | None = None,
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    **kwargs: Any,
) -> Table:
    rows: list[tuple] = []
    names: list[str]
    if format in ("csv", "dsv"):
        delimiter = getattr(csv_settings, "delimiter", ",") if csv_settings else ","
        names = schema.column_names() if schema is not None else []
        for p in _paths_of(path):
            with open(p, newline="") as f:
                reader = _csv.DictReader(f, delimiter=delimiter)
                if not names:
                    names = list(reader.fieldnames or [])
                for rec in reader:
                    if schema is not None:
                        rows.append(tuple(
                            _convert(rec[n], schema.columns()[n].dtype) for n in names
                        ))
                    else:
                        rows.append(tuple(_auto(rec[n]) for n in names))
    elif format in ("json", "jsonlines"):
        names = schema.column_names() if schema is not None else []
        for p in _paths_of(path):
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    obj = json.loads(line)
                    if not names:
                        names = list(obj.keys())
                    rows.append(tuple(obj.get(n) for n in names))
    elif format in ("plaintext", "plaintext_by_file"):
        names = ["data"]
        for p in _paths_of(path):
            if format == "plaintext_by_file":
                with open(p) as f:
                    rows.append((f.read(),))
            else:
                with open(p) as f:
                    for line in f:
                        rows.append((line.rstrip("\n"),))
        if schema is None:
            schema = schema_from_types(data=str)
    elif format == "binary":
        names = ["data"]
        for p in _paths_of(path):
            with open(p, "rb") as f:
                rows.append((f.read(),))
        if schema is None:
            schema = schema_from_types(data=bytes)
    else:
        raise ValueError(f"unknown format {format!r}")

    id_from = schema.primary_key_columns() if schema is not None else None
    return rows_to_table(names, rows, schema=schema, id_from=id_from)


def write(table: Table, filename: str | os.PathLike, *, format: str = "csv", name: str | None = None, **kwargs: Any) -> None:
    """Write the table's update stream to a file (time/diff columns appended,
    like the reference's FileWriter + DsvFormatter/JsonLinesFormatter)."""
    from . import subscribe

    filename = os.fspath(filename)
    names = table.column_names()
    state: dict[str, Any] = {"f": None, "writer": None}

    def ensure_open():
        if state["f"] is None:
            state["f"] = open(filename, "w", newline="")
            if format == "csv":
                w = _csv.writer(state["f"])
                w.writerow(names + ["time", "diff"])
                state["writer"] = w
        return state["f"]

    def on_change(key, row, time, is_addition):
        f = ensure_open()
        diff = 1 if is_addition else -1
        if format == "csv":
            state["writer"].writerow([row[n] for n in names] + [time, diff])
        else:
            obj = {n: _jsonable(row[n]) for n in names}
            obj["time"] = time
            obj["diff"] = diff
            f.write(json.dumps(obj) + "\n")

    def on_end():
        ensure_open()
        state["f"].close()

    subscribe(table, on_change=on_change, on_end=on_end)


def _jsonable(v: Any) -> Any:
    import numpy as np

    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return v


def _auto(v: str) -> Any:
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        pass
    return v
