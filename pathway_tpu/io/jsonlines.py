"""``pw.io.jsonlines`` — wrapper over ``pw.io.fs`` with format=json
(reference ``python/pathway/io/jsonlines``)."""

from __future__ import annotations

from typing import Any

from . import fs


def read(path, *, schema=None, mode: str = "streaming", json_field_paths=None, **kwargs: Any):
    return fs.read(path, format="json", schema=schema, mode=mode,
                   json_field_paths=json_field_paths, **kwargs)


def write(table, filename, **kwargs: Any) -> None:
    fs.write(table, filename, format="json", **kwargs)
