"""``pw.io`` — connector framework.

Re-design of ``python/pathway/io/`` (8,122 LoC, 30+ modules) over the engine's
SourceNode/Subscribe machinery. Implemented connectors live in submodules
(``fs``, ``csv``, ``jsonlines``, ``plaintext``, ``python``, ``http``, ...);
``subscribe`` is the universal callback sink (reference ``io.subscribe``).
"""

from __future__ import annotations

from typing import Any, Callable

from ..internals.parse_graph import G
from ..internals.table import Table

from . import (  # noqa: E402,F401
    airbyte,
    bigquery,
    csv,
    debezium,
    delivery,
    deltalake,
    elasticsearch,
    fs,
    gdrive,
    http,
    jsonlines,
    kafka,
    logstash,
    minio,
    mongodb,
    nats,
    null,
    plaintext,
    postgres,
    pubsub,
    pyfilesystem,
    python,
    redpanda,
    s3,
    slack,
    sqlite,
)

__all__ = [
    "airbyte",
    "bigquery",
    "csv",
    "debezium",
    "delivery",
    "deltalake",
    "elasticsearch",
    "fs",
    "gdrive",
    "http",
    "jsonlines",
    "kafka",
    "logstash",
    "minio",
    "mongodb",
    "nats",
    "null",
    "plaintext",
    "postgres",
    "pubsub",
    "pyfilesystem",
    "python",
    "redpanda",
    "s3",
    "slack",
    "sqlite",
    "subscribe",
    "OnChangeCallback",
    "OnFinishCallback",
]

OnChangeCallback = Callable[..., None]
OnFinishCallback = Callable[[], None]


def subscribe(
    table: Table,
    on_change: Callable[..., None] | None = None,
    on_end: Callable[[], None] | None = None,
    on_time_end: Callable[[int], None] | None = None,
    *,
    on_batch: Callable[..., None] | None = None,
    skip_persisted_batch: bool = True,
    name: str | None = None,
    sort_by: Any = None,
) -> None:
    """Call ``on_change(key, row, time, is_addition)`` for every row update
    (reference ``io/subscribe``).

    ``on_batch(time, batch)`` is the columnar fast lane: called once per
    consolidated tick delta with the raw batch (``batch.keys`` uint64[n],
    ``batch.data`` {col: array}, ``batch.diffs`` ±k int64[n]) — no per-row
    dict building, for high-throughput sinks."""
    G.add_sink({
        "kind": "subscribe",
        "table": table,
        "on_change": on_change,
        "on_time_end": on_time_end,
        "on_end": on_end,
        "on_batch": on_batch,
        "skip_persisted_batch": skip_persisted_batch,
    })
