"""``pw.io.debezium`` — Debezium CDC message parsing.

Re-design of the reference ``DebeziumMessageParser``
(``src/connectors/data_format.rs:1056``) + ``python/pathway/io/debezium``.
The reference consumes Debezium envelopes from Kafka; here the transport is
pluggable (a Kafka client when available, a ``ConnectorSubject`` of raw
messages, or a jsonlines file for replay/testing) and the envelope decoding
(op c/r = insert, u = retract old + insert new, d = delete) is shared.
"""

from __future__ import annotations

import json
from typing import Any

from ..internals.schema import SchemaMetaclass
from ..internals.table import Table
from .python import ConnectorSubject, read as python_read

__all__ = ["read", "parse_debezium_message"]


def parse_debezium_message(message: str | bytes | dict) -> list[tuple[int, dict]]:
    """One Debezium envelope -> [(diff, row_dict)] events
    (data_format.rs:1056 semantics)."""
    if isinstance(message, (str, bytes)):
        message = json.loads(message)
    payload = message.get("payload", message)
    op = payload.get("op", "r")
    before = payload.get("before")
    after = payload.get("after")
    if op in ("c", "r"):
        return [(1, after)] if after is not None else []
    if op == "u":
        events: list[tuple[int, dict]] = []
        if before is not None:
            events.append((-1, before))
        if after is not None:
            events.append((1, after))
        return events
    if op == "d":
        return [(-1, before)] if before is not None else []
    return []


class _DebeziumSubject(ConnectorSubject):
    """Wraps a transport of raw envelopes into parsed row events."""

    def __init__(self, raw_messages):
        super().__init__(datasource_name="debezium")
        self._raw = raw_messages

    def _emit_envelopes(self, envelopes) -> None:
        """Decoded-envelope emission: rows keep per-row ``next``/``_remove``
        (mixed diffs ride the ingest coalescer) and the per-envelope commit
        cadence — a CDC retract+insert pair squeezed into one tick would
        cancel before any subscriber saw it, so only the *decode* is
        batched, never the tick boundaries."""
        for msg in envelopes:
            for diff, row in parse_debezium_message(msg):
                if diff > 0:
                    self.next(**row)
                else:
                    self._remove(**row)
            self.commit()

    def run(self) -> None:
        from itertools import islice

        from . import columnar as _columnar

        if not _columnar.enabled():
            for msg in self._raw:
                for diff, row in parse_debezium_message(msg):
                    if diff > 0:
                        self.next(**row)
                    else:
                        self._remove(**row)
                self.commit()
            return
        step = _columnar.chunk_rows()
        it = iter(self._raw)
        while not self.stopped:
            chunk = list(islice(it, step))
            if not chunk:
                break
            if len(chunk) > 1 and all(
                isinstance(m, (str, bytes)) for m in chunk
            ):
                # batch decode: ONE json.loads over the joined chunk; any
                # disagreement falls back to per-envelope decoding, which
                # raises at the exact envelope the row-wise path would have
                try:
                    joined = ",".join(
                        m.decode("utf-8") if isinstance(m, bytes) else m
                        for m in chunk
                    )
                    decoded = json.loads("[" + joined + "]")
                    if len(decoded) != len(chunk):
                        raise ValueError("envelope count mismatch")
                except (ValueError, UnicodeDecodeError):
                    decoded = chunk
            else:
                decoded = chunk
            self._emit_envelopes(decoded)


def read(
    source: Any = None,
    *,
    schema: SchemaMetaclass,
    rdkafka_settings: dict | None = None,
    topic_name: str | None = None,
    input_file: str | None = None,
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    **kwargs: Any,
) -> Table:
    """Read a Debezium CDC stream into a live table.

    - ``rdkafka_settings`` + ``topic_name``: consume from Kafka (requires a
      Kafka client library — gated, like ``pw.io.kafka``).
    - ``input_file``: replay a jsonlines capture of envelopes.
    - ``source``: any iterable of raw envelopes (str/bytes/dict).
    """
    if rdkafka_settings is not None:
        from . import kafka as _kafka

        _kafka._require_client()  # raises with install guidance
        raise NotImplementedError("kafka transport requires a kafka client")
    if input_file is not None:
        def _lines():
            with open(input_file) as f:
                for line in f:
                    if line.strip():
                        yield line
        source = _lines()
    if source is None:
        raise ValueError("pass rdkafka_settings+topic_name, input_file, or source")
    return python_read(
        _DebeziumSubject(source), schema=schema,
        autocommit_duration_ms=autocommit_duration_ms, name=name,
    )
