"""``pw.io.bigquery`` — BigQuery sink.

Re-design of ``python/pathway/io/bigquery``: streams the table's changes
into a BigQuery table via ``insert_rows_json``, with the reference's
``time``/``diff`` fields appended to every row. The connector logic is
complete and unit-tested with a fake client; only the real
``google-cloud-bigquery`` client construction is gated.
"""

from __future__ import annotations

from typing import Any

from ..internals.table import Table
from ._gated import unavailable

__all__ = ["write"]


def _bq_client(service_user_credentials_file: str | None):
    try:
        from google.cloud import bigquery  # type: ignore[attr-defined]
        from google.oauth2.service_account import (  # type: ignore[import-not-found]
            Credentials,
        )
    except ImportError:
        unavailable("pw.io.bigquery.write", "google-cloud-bigquery")
    creds = (
        Credentials.from_service_account_file(service_user_credentials_file)
        if service_user_credentials_file is not None else None
    )
    return bigquery.Client(credentials=creds)


def write(table: Table, dataset_name: str, table_name: str, *,
          service_user_credentials_file: str | None = None,
          name: str | None = None, _client: Any = None,
          **kwargs: Any) -> None:
    """Write ``table``'s change stream into ``dataset.table``; target schema
    must include integral ``time`` and ``diff`` fields (reference
    io/bigquery/__init__.py:55). ``_client`` injects anything exposing
    ``insert_rows_json(table_ref, rows) -> errors`` (tests use a fake)."""
    from .delivery import CallableAdapter, SinkRejectedError, deliver
    from .fs import _jsonable

    client = _client if _client is not None else _bq_client(
        service_user_credentials_file
    )
    table_ref = f"{dataset_name}.{table_name}"
    names = table.column_names()

    def write_batch(batch):
        cols = [batch.delta.data[n] for n in names]
        rows = []
        for vals, diff in zip(zip(*cols), batch.delta.diffs):
            row = {n: _jsonable(v) for n, v in zip(names, vals)}
            row["time"] = int(batch.time)
            row["diff"] = int(diff)
            rows.append(row)
        errors = client.insert_rows_json(table_ref, rows)
        if errors:
            # per-row insert errors are schema rejects, not transient
            # failures: dead-letter them instead of retrying forever.
            # BigQuery reports VALID rows of a failed insertAll with
            # reason "stopped" — those must redeliver, never dead-letter
            def _poison(entry) -> bool:
                errs = entry.get("errors") if isinstance(entry, dict) else None
                if not errs:
                    return True  # shapeless entry: treat as poison
                return any(
                    (e or {}).get("reason") != "stopped" for e in errs
                )

            indexed = [
                e for e in errors
                if isinstance(e, dict) and e.get("index") is not None
            ]
            bad = [int(e["index"]) for e in indexed if _poison(e)]
            stopped = {int(e["index"]) for e in indexed if not _poison(e)}
            unattributed_poison = any(
                not (isinstance(e, dict) and e.get("index") is not None)
                and _poison(e)
                for e in errors
            )
            if not bad and not unattributed_poison:
                # every entry is a "stopped" echo of some upstream failure
                # — nothing identifiably poison, so retry the whole batch
                raise RuntimeError(f"bigquery insert failed: {errors}")
            if unattributed_poison:
                # poison exists but can't be pinned to a row: dead-letter
                # everything EXCEPT the rows BigQuery explicitly marked
                # "stopped" (those are valid and must redeliver)
                bad = sorted(
                    set(range(len(rows))) - stopped | set(bad)
                )
            raise SinkRejectedError(
                f"bigquery insert failed: {errors}",
                row_indices=bad or None,
            )
        return None

    deliver(
        table,
        lambda: CallableAdapter(write_batch, "bigquery"),
        name=name,
        default_name=f"bigquery-{dataset_name}.{table_name}",
        retry_policy=kwargs.get("retry_policy"),
    )
