"""``pw.io.bigquery`` — BigQuery sink.

Re-design of ``python/pathway/io/bigquery``: streams the table's changes
into a BigQuery table via ``insert_rows_json``, with the reference's
``time``/``diff`` fields appended to every row. The connector logic is
complete and unit-tested with a fake client; only the real
``google-cloud-bigquery`` client construction is gated.
"""

from __future__ import annotations

from typing import Any

from ..internals.table import Table
from ._gated import unavailable

__all__ = ["write"]


def _bq_client(service_user_credentials_file: str | None):
    try:
        from google.cloud import bigquery  # type: ignore[attr-defined]
        from google.oauth2.service_account import (  # type: ignore[import-not-found]
            Credentials,
        )
    except ImportError:
        unavailable("pw.io.bigquery.write", "google-cloud-bigquery")
    creds = (
        Credentials.from_service_account_file(service_user_credentials_file)
        if service_user_credentials_file is not None else None
    )
    return bigquery.Client(credentials=creds)


def write(table: Table, dataset_name: str, table_name: str, *,
          service_user_credentials_file: str | None = None,
          name: str | None = None, _client: Any = None,
          **kwargs: Any) -> None:
    """Write ``table``'s change stream into ``dataset.table``; target schema
    must include integral ``time`` and ``diff`` fields (reference
    io/bigquery/__init__.py:55). ``_client`` injects anything exposing
    ``insert_rows_json(table_ref, rows) -> errors`` (tests use a fake)."""
    from . import subscribe
    from .fs import _jsonable

    client = _client if _client is not None else _bq_client(
        service_user_credentials_file
    )
    table_ref = f"{dataset_name}.{table_name}"
    names = table.column_names()

    def on_batch(time, batch):
        cols = [batch.data[n] for n in names]
        rows = []
        for vals, diff in zip(zip(*cols), batch.diffs):
            row = {n: _jsonable(v) for n, v in zip(names, vals)}
            row["time"] = int(time)
            row["diff"] = int(diff)
            rows.append(row)
        errors = client.insert_rows_json(table_ref, rows)
        if errors:
            raise RuntimeError(f"bigquery insert failed: {errors}")

    subscribe(table, on_batch=on_batch)
