"""``pw.io.bigquery`` — BigQuery sink (reference
``python/pathway/io/bigquery``). Gated on ``google-cloud-bigquery``."""

from __future__ import annotations

from typing import Any

from ..internals.table import Table
from ._gated import unavailable

__all__ = ["write"]


def write(table: Table, dataset_name: str, table_name: str, *,
          service_user_credentials_file: str | None = None,
          name: str | None = None, **kwargs: Any) -> None:
    try:
        from google.cloud import bigquery  # type: ignore[attr-defined]  # noqa: F401
    except ImportError:
        unavailable("pw.io.bigquery.write", "google-cloud-bigquery")
    raise NotImplementedError
