"""``pw.io.minio`` — MinIO source (reference
``python/pathway/io/minio``): S3 connector with path-style addressing."""

from __future__ import annotations

from typing import Any

from .s3 import AwsS3Settings, read as _s3_read

__all__ = ["read", "MinIOSettings"]


class MinIOSettings:
    def __init__(self, endpoint: str, bucket_name: str, access_key: str,
                 secret_access_key: str, *, with_path_style: bool = True,
                 **kwargs: Any):
        self.endpoint = endpoint
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.with_path_style = with_path_style

    def create_aws_settings(self) -> AwsS3Settings:
        return AwsS3Settings(
            bucket_name=self.bucket_name, access_key=self.access_key,
            secret_access_key=self.secret_access_key,
            with_path_style=self.with_path_style, endpoint=self.endpoint,
        )


def read(path: str, minio_settings: MinIOSettings, **kwargs: Any):
    return _s3_read(path, aws_s3_settings=minio_settings.create_aws_settings(), **kwargs)
