"""``pw.io.gdrive`` — Google Drive source.

Re-design of ``python/pathway/io/gdrive`` (a polling scanner over the
Drive API). Reuses the shared object-store scanner: the Drive folder is
listed recursively, file versions come from the Drive revision/modified
fields, and new/changed/deleted files become row insertions/retractions.
The scanner logic is unit-tested with a fake Drive client; only the real
``google-api-python-client`` service construction is gated.
"""

from __future__ import annotations

from typing import Any

from ..internals.schema import SchemaMetaclass
from ..internals.table import Table
from ._gated import unavailable
from ._object_scanner import ObjectMeta

__all__ = ["read"]


class GDriveClient:
    """ObjectStoreClient over the Drive v3 API (the gated dependency)."""

    _FOLDER = "application/vnd.google-apps.folder"

    def __init__(self, object_id: str, credentials_file: str | None,
                 object_size_limit: int | None):
        try:
            from google.oauth2.service_account import (  # type: ignore[import-not-found]
                Credentials,
            )
            from googleapiclient.discovery import (  # type: ignore[import-not-found]
                build,
            )
        except ImportError:
            unavailable("pw.io.gdrive.read", "google-api-python-client")
        creds = Credentials.from_service_account_file(
            credentials_file,
            scopes=["https://www.googleapis.com/auth/drive.readonly"],
        )
        self._service = build("drive", "v3", credentials=creds)
        self.root = object_id
        self.size_limit = object_size_limit

    def _list_dir(self, folder_id: str):
        page_token = None
        while True:
            resp = self._service.files().list(
                q=f"'{folder_id}' in parents and trashed = false",
                fields="nextPageToken, files(id, name, mimeType, version, size, modifiedTime)",
                pageToken=page_token,
            ).execute()
            yield from resp.get("files", [])
            page_token = resp.get("nextPageToken")
            if page_token is None:
                break

    def list_objects(self):
        stack = [self.root]
        while stack:
            folder = stack.pop()
            for f in self._list_dir(folder):
                if f.get("mimeType") == self._FOLDER:
                    stack.append(f["id"])
                    continue
                size = int(f.get("size", 0) or 0)
                if self.size_limit is not None and size > self.size_limit:
                    continue
                yield ObjectMeta(
                    key=f["id"],
                    version=str(f.get("version") or f.get("modifiedTime", "")),
                    size=size,
                )

    def read_object(self, key: str) -> bytes:
        return self._service.files().get_media(fileId=key).execute()


def read(object_id: str, *, mode: str = "streaming", format: str = "binary",
         object_size_limit: int | None = None, refresh_interval: int = 30,
         service_user_credentials_file: str | None = None,
         with_metadata: bool = False, name: str | None = None,
         schema: SchemaMetaclass | None = None, _client: Any = None,
         **kwargs: Any) -> Table:
    """Read files under a Drive folder/file id. ``_client`` injects any
    ObjectStoreClient (tests use a fake Drive)."""
    from .s3 import _default_schema, object_source_table

    schema = _default_schema(format, schema, "pw.io.gdrive.read")
    client = _client if _client is not None else GDriveClient(
        object_id, service_user_credentials_file, object_size_limit
    )
    return object_source_table(
        client, format, schema,
        mode=mode, with_metadata=with_metadata,
        refresh_interval_ms=refresh_interval * 1000,
        autocommit_duration_ms=1500, name=name,
    )
