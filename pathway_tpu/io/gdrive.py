"""``pw.io.gdrive`` — Google Drive source (reference
``python/pathway/io/gdrive``: polling scanner over the Drive API). Gated on
``google-api-python-client``."""

from __future__ import annotations

from typing import Any

from ..internals.schema import SchemaMetaclass
from ..internals.table import Table
from ._gated import unavailable

__all__ = ["read"]


def read(object_id: str, *, mode: str = "streaming", format: str = "binary",
         object_size_limit: int | None = None, refresh_interval: int = 30,
         service_user_credentials_file: str | None = None,
         with_metadata: bool = False, name: str | None = None,
         schema: SchemaMetaclass | None = None, **kwargs: Any) -> Table:
    try:
        import googleapiclient  # type: ignore[import-not-found]  # noqa: F401
    except ImportError:
        unavailable("pw.io.gdrive.read", "google-api-python-client")
    raise NotImplementedError
