"""``pw.io.slack`` — Slack alert sink (reference ``python/pathway/io/slack``:
posts one chat.postMessage per row of a single-text-column table)."""

from __future__ import annotations

from typing import Any

from ..internals.table import Table

__all__ = ["send_alerts"]

_SLACK_URL = "https://slack.com/api/chat.postMessage"


def send_alerts(
    messages: Table,
    slack_channel_id: str,
    slack_token: str,
    **kwargs: Any,
) -> None:
    """Each addition in the (single text column) table becomes one Slack
    message to the channel (delivered through the retrying output plane)."""
    from .delivery import CallableAdapter, deliver

    (col,) = messages.column_names()

    def write_batch(batch):
        import json
        import urllib.request

        for row, diff in batch.rows():
            if diff <= 0:
                continue
            req = urllib.request.Request(
                _SLACK_URL,
                data=json.dumps(
                    {"channel": slack_channel_id, "text": str(row[col])}
                ).encode(),
                headers={
                    "Content-Type": "application/json",
                    "Authorization": f"Bearer {slack_token}",
                },
                method="POST",
            )
            urllib.request.urlopen(req, timeout=30)
        return None

    deliver(
        messages,
        lambda: CallableAdapter(write_batch, "slack"),
        name=kwargs.get("name"),
        default_name=f"slack-{slack_channel_id}",
        retry_policy=kwargs.get("retry_policy"),
    )
