"""``pathway`` CLI (reference ``python/pathway/cli.py:53-280``):
``spawn`` launches a program over N processes × T threads with the worker
environment set; ``replay`` re-runs a program against recorded input
(``--record`` under spawn captures it).

Run as ``python -m pathway_tpu.cli`` or the ``pathway-tpu`` entry point.
"""

from __future__ import annotations

import os
import subprocess
import sys

import click

from .internals.config import MAX_WORKERS

__all__ = ["main", "spawn", "replay"]


@click.group()
def main() -> None:
    """pathway_tpu command line."""


def _spawn_processes(
    threads: int, processes: int, first_port: int, env_extra: dict, args: tuple[str, ...]
) -> int:
    if threads * processes > MAX_WORKERS:
        raise click.ClickException(
            f"{threads}×{processes} workers exceed the {MAX_WORKERS}-worker limit"
        )
    program = list(args)
    if not program:
        raise click.ClickException("pass the program to run, e.g. python app.py")
    base_env = {
        **os.environ,
        "PATHWAY_THREADS": str(threads),
        "PATHWAY_PROCESSES": str(processes),
        "PATHWAY_FIRST_PORT": str(first_port),
        **env_extra,
    }
    if processes <= 1:
        env = {**base_env, "PATHWAY_PROCESS_ID": "0"}
        return subprocess.call(program, env=env)
    procs = []
    for pid in range(processes):
        env = {**base_env, "PATHWAY_PROCESS_ID": str(pid)}
        procs.append(subprocess.Popen(program, env=env))
    code = 0
    for p in procs:
        code = p.wait() or code
    return code


@main.command(context_settings={"ignore_unknown_options": True})
@click.option("-t", "--threads", type=int, default=1, help="worker threads per process")
@click.option("-n", "--processes", type=int, default=1, help="number of processes")
@click.option("--first-port", type=int, default=10000, help="cluster port base")
@click.option("--record", is_flag=True, default=False,
              help="record input streams for later replay")
@click.option("--record-path", type=str, default="record",
              help="where recorded input lands")
@click.argument("program", nargs=-1, type=click.UNPROCESSED)
def spawn(threads, processes, first_port, record, record_path, program):
    """Launch PROGRAM with the worker environment set (reference cli.py:53)."""
    env_extra: dict[str, str] = {}
    if record:
        env_extra["PATHWAY_REPLAY_STORAGE"] = record_path
        env_extra["PATHWAY_SNAPSHOT_ACCESS"] = "record"
    sys.exit(_spawn_processes(threads, processes, first_port, env_extra, program))


@main.command(context_settings={"ignore_unknown_options": True})
@click.option("-t", "--threads", type=int, default=1)
@click.option("-n", "--processes", type=int, default=1)
@click.option("--record-path", type=str, default="record")
@click.option("--mode", type=click.Choice(["batch", "speedrun"]), default="batch",
              help="replay all at once (batch) or with original pacing")
@click.option("--continue", "continue_after_replay", is_flag=True, default=False,
              help="keep consuming live data after the replay finishes")
@click.argument("program", nargs=-1, type=click.UNPROCESSED)
def replay(threads, processes, record_path, mode, continue_after_replay, program):
    """Re-run PROGRAM against recorded input (reference cli.py:194)."""
    env_extra = {
        "PATHWAY_REPLAY_STORAGE": record_path,
        "PATHWAY_SNAPSHOT_ACCESS": "replay",
        "PATHWAY_PERSISTENCE_MODE": mode,
    }
    if continue_after_replay:
        env_extra["PATHWAY_CONTINUE_AFTER_REPLAY"] = "1"
    sys.exit(_spawn_processes(threads, processes, 10000, env_extra, program))


if __name__ == "__main__":
    main()
