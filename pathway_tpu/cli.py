"""``pathway`` CLI (reference ``python/pathway/cli.py:53-280``):
``spawn`` launches a program over N processes × T threads with the worker
environment set; ``replay`` re-runs a program against recorded input
(``--record`` under spawn captures it); ``rescale`` repartitions a
persisted cluster's state to a new worker count (``spawn --elastic``
does the same in-process at boot); ``trace merge`` assembles the
per-process ``PATHWAY_TRACE_FILE`` parts of a cluster run into one
clock-aligned Perfetto timeline.

Run as ``python -m pathway_tpu.cli`` or the ``pathway-tpu`` entry point.
"""

from __future__ import annotations

import os
import secrets
import subprocess
import sys

import click

from .internals.config import MAX_WORKERS

__all__ = [
    "main", "spawn", "replay", "rescale", "upgrade", "top", "critpath",
    "profile", "trace", "dlq", "lint",
]


@click.group()
def main() -> None:
    """pathway_tpu command line."""


def _spawn_processes(
    threads: int,
    processes: int,
    first_port: int,
    env_extra: dict,
    args: tuple[str, ...],
    addresses: str | None = None,
    local_ids: tuple[int, ...] = (),
    supervise: bool = False,
) -> int:
    if threads * processes > MAX_WORKERS:
        raise click.ClickException(
            f"{threads}×{processes} workers exceed the {MAX_WORKERS}-worker limit"
        )
    program = list(args)
    if not program:
        raise click.ClickException("pass the program to run, e.g. python app.py")
    base_env = {
        **os.environ,
        "PATHWAY_THREADS": str(threads),
        "PATHWAY_PROCESSES": str(processes),
        "PATHWAY_FIRST_PORT": str(first_port),
        **env_extra,
    }
    # one run identity for the whole ensemble: tracers mint cross-process
    # flow ids under it and `trace merge` refuses to mix different runs.
    # A multi-host ensemble runs spawn once per machine, so the generated
    # default cannot agree across machines — tell the operator to pin one.
    if (
        addresses
        and "PATHWAY_RUN_ID" not in os.environ
        and os.environ.get("PATHWAY_TRACE_FILE")
    ):
        click.echo(
            "warning: multi-host traced run without PATHWAY_RUN_ID — each "
            "machine's spawn will mint its own run id and `trace merge` "
            "will refuse to join the parts; export the same "
            "PATHWAY_RUN_ID on every machine",
            err=True,
        )
    base_env.setdefault("PATHWAY_RUN_ID", secrets.token_hex(8))
    if addresses:
        entries = [a.strip() for a in addresses.split(",") if a.strip()]
        if len(entries) != processes:
            raise click.ClickException(
                "--addresses must list one host[:port] per process"
            )
        # fail malformed entries at launch, not in every child's traceback
        from .parallel.cluster import _address_book

        try:
            _address_book(entries, processes, "127.0.0.1", first_port)
        except ValueError as e:
            raise click.ClickException(str(e))
        base_env["PATHWAY_ADDRESSES"] = ",".join(entries)
    # multi-host ensembles run spawn once per machine, each launching only
    # its own process ids (reference: timely hostfile + per-machine -p)
    pids = list(local_ids) if local_ids else list(range(processes))
    bad = [p for p in pids if not 0 <= p < processes]
    if bad:
        raise click.ClickException(
            f"--process ids {bad} out of range for {processes} processes"
        )
    if len(set(pids)) != len(pids):
        raise click.ClickException("--process ids must be distinct")
    if supervise:
        # the supervisor's contract is "restart the WHOLE ensemble from the
        # last common snapshot"; a partial ensemble (multi-host book, or a
        # -p subset of the ids) would restart only its local slice, restart
        # generations would diverge across machines, and run-gated fault
        # plans / PATHWAY_RESTART_COUNT metrics would lie
        if addresses:
            raise click.ClickException(
                "--supervise cannot coordinate a multi-host ensemble "
                "(--addresses): each machine would restart only its own "
                "processes and restart generations would diverge — "
                "supervise externally (e.g. your orchestrator) instead"
            )
        if local_ids and set(pids) != set(range(processes)):
            raise click.ClickException(
                "--supervise needs the full ensemble on this machine; "
                f"-p selects only {sorted(pids)} of {processes} processes"
            )
        return _run_supervised(base_env, program, pids)
    if processes <= 1:
        env = {**base_env, "PATHWAY_PROCESS_ID": "0"}
        return subprocess.call(program, env=env)
    procs = []
    for pid in pids:
        env = {**base_env, "PATHWAY_PROCESS_ID": str(pid)}
        procs.append(subprocess.Popen(program, env=env))
    code = 0
    for p in procs:
        code = p.wait() or code
    return code


def _run_supervised(
    base_env: dict, program: list[str], pids: list[int]
) -> int:
    """Run the ensemble under a Supervisor: on any child death, tear the
    survivors down cooperatively and relaunch the WHOLE generation (the
    engine recovers from the last snapshot common to every worker). See
    parallel/supervisor.py for the backoff/circuit-breaker contract."""
    from .parallel.supervisor import Supervisor

    # always-on black box under supervision: each child keeps an mmap ring
    # of its last ticks (observability/flightrecorder.py) which the
    # supervisor harvests into crash-<gen>-<proc>.json bundles on failure
    base_env.setdefault(
        "PATHWAY_FLIGHT_DIR", os.path.join(os.getcwd(), "pathway-flight")
    )

    def launch(generation: int, reason: str | None):
        # late-binds `sup` below; Supervisor.run() only calls launch()
        # after construction completes
        env = {**base_env, **sup.child_env(generation, reason)}
        return [
            subprocess.Popen(
                program, env={**env, "PATHWAY_PROCESS_ID": str(pid)}
            )
            for pid in pids
        ]

    health_ports: list[int] = []
    if base_env.get("PATHWAY_MONITORING_HTTP_SERVER", "").strip().lower() in (
        "1", "true", "yes", "on"
    ):
        try:
            base = int(
                base_env.get("PATHWAY_MONITORING_HTTP_PORT", "20000") or 0
            )
        except ValueError:
            # same tolerance as config._env_int/http_server: a malformed
            # port degrades to exit-code-only supervision, never a crash
            base = 0
        if base:
            health_ports = [base + pid for pid in pids]
    sup = Supervisor(
        launch,
        health_ports=health_ports,
        labels=[f"process {pid}" for pid in pids],
        flight_dir=base_env.get("PATHWAY_FLIGHT_DIR"),
        process_ids=pids,
        run_id=base_env.get("PATHWAY_RUN_ID"),
    )
    return sup.run()


@main.command(context_settings={"ignore_unknown_options": True})
@click.option("-t", "--threads", type=int, default=1, help="worker threads per process")
@click.option("-n", "--processes", type=int, default=1, help="number of processes")
@click.option("--first-port", type=int, default=10000, help="cluster port base")
@click.option("--record", is_flag=True, default=False,
              help="record input streams for later replay")
@click.option("--record-path", type=str, default="record",
              help="where recorded input lands")
@click.option("-a", "--addresses", type=str, default=None,
              help="multi-host address book: comma-separated host[:port], "
                   "one per process (timely hostfile analog)")
@click.option("-p", "--process", "local_ids", type=int, multiple=True,
              help="launch only these process ids on this machine "
                   "(repeatable; default: all — use with --addresses when "
                   "the ensemble spans machines)")
@click.option("--supervise", is_flag=True, default=False,
              help="self-healing mode: on any worker death, tear down the "
                   "survivors cooperatively and restart the ensemble from "
                   "the last common snapshot (jittered exponential backoff, "
                   "crash-loop circuit breaker — see "
                   "PATHWAY_SUPERVISE_MAX_RESTARTS and friends)")
@click.option("--elastic", is_flag=True, default=False,
              help="elastic boot: if the persisted state was written by a "
                   "different worker count, worker 0 runs the state "
                   "resharder (pathway-tpu rescale) in-process before the "
                   "engine mounts it (sets PATHWAY_ELASTIC=1)")
@click.option("--autoscale", "autoscale_range", type=str, default=None,
              metavar="MIN..MAX",
              help="closed-loop autoscaling: supervise the ensemble AND "
                   "watch the signals plane (/query on process 0), live-"
                   "rescaling the cluster between MIN and MAX workers — "
                   "drain to a delivery boundary, reshard the persisted "
                   "state, resume. Requires --store; implies --supervise "
                   "and --elastic; -n is derived from the persisted "
                   "layout (clamped into the range)")
@click.option("--store", "autoscale_store", type=str, default=None,
              help="persistence root the program writes (the path given "
                   "to pw.persistence.Backend.filesystem) — the state "
                   "--autoscale reshards between worker counts and "
                   "--upgrade-to migrates between graph versions")
@click.option("--upgrade-to", "upgrade_to", type=str, default=None,
              metavar="NEW_SCRIPT",
              help="zero-downtime code upgrade: before launching, migrate "
                   "the persisted state at --store to the graph version "
                   "NEW_SCRIPT builds (pathway-tpu upgrade --apply), then "
                   "launch PROGRAM — an empty store skips the migration "
                   "and boots fresh")
@click.option("--allow-drop", is_flag=True, default=False,
              help="with --upgrade-to: accept dropping stateful operators "
                   "that have no match in the new script")
@click.argument("program", nargs=-1, type=click.UNPROCESSED)
def spawn(threads, processes, first_port, record, record_path, addresses,
          local_ids, supervise, elastic, autoscale_range, autoscale_store,
          upgrade_to, allow_drop, program):
    """Launch PROGRAM with the worker environment set (reference cli.py:53).

    Multi-host: run once per machine with the same ``--addresses`` book and
    that machine's ``-p`` ids, e.g.
    ``spawn -n 2 -t 2 -a hostA:10000,hostB:10000 -p 0 python app.py``."""
    env_extra: dict[str, str] = {}
    if record:
        env_extra["PATHWAY_REPLAY_STORAGE"] = record_path
        env_extra["PATHWAY_SNAPSHOT_ACCESS"] = "record"
    if elastic:
        env_extra["PATHWAY_ELASTIC"] = "1"
    if upgrade_to is not None:
        if not autoscale_store:
            raise click.ClickException(
                "--upgrade-to needs --store <persistence root>: the "
                "migration rewrites the program's persisted state to the "
                "new graph version before the ensemble boots"
            )
        from .persistence import Backend
        from .upgrade import NoStoreMarker, UpgradeError, apply_upgrade

        try:
            report = apply_upgrade(
                Backend.filesystem(autoscale_store), upgrade_to,
                allow_drop=allow_drop,
                log=lambda m: click.echo(m, err=True),
            )
            if report.get("noop"):
                click.echo(
                    "[upgrade] store already matches the new graph "
                    "version — launching", err=True,
                )
        except NoStoreMarker:
            click.echo(
                "[upgrade] store is empty — nothing to migrate, the new "
                "version boots fresh", err=True,
            )
        except UpgradeError as e:
            raise click.ClickException(str(e))
    if autoscale_range is not None:
        sys.exit(_run_autoscaled(threads, autoscale_range, autoscale_store,
                                 first_port, env_extra, program,
                                 addresses=addresses, local_ids=local_ids,
                                 supervise=supervise, processes=processes))
    sys.exit(_spawn_processes(threads, processes, first_port, env_extra,
                              program, addresses=addresses,
                              local_ids=local_ids, supervise=supervise))


def _run_autoscaled(threads, autoscale_range, store, first_port, env_extra,
                    program, *, addresses, local_ids, supervise, processes):
    """Wire ``spawn --autoscale MIN..MAX`` into an AutoscaleController
    (autoscale/controller.py): supervision plus the scale loop."""
    from .autoscale import AutoscaleError, parse_range

    try:
        mn, mx = parse_range(autoscale_range)
    except AutoscaleError as e:
        raise click.ClickException(str(e))
    if not store:
        raise click.ClickException(
            "--autoscale needs --store <persistence root>: live rescaling "
            "repartitions the program's persisted state between worker "
            "counts — without persistence there is no state to carry over"
        )
    if addresses or local_ids:
        raise click.ClickException(
            "--autoscale coordinates drain/reshard/resume for the whole "
            "ensemble on this machine — it cannot drive a multi-host "
            "address book or a -p process subset"
        )
    if supervise:
        raise click.ClickException(
            "--autoscale already supervises the ensemble; drop --supervise"
        )
    if processes > 1:
        raise click.ClickException(
            "-n conflicts with --autoscale: the worker count is derived "
            "from the persisted layout (clamped into MIN..MAX)"
        )
    if threads * mx > MAX_WORKERS:
        raise click.ClickException(
            f"{threads}×{mx} workers at the top of the autoscale range "
            f"exceed the {MAX_WORKERS}-worker limit"
        )
    if not program:
        raise click.ClickException("pass the program to run, e.g. python app.py")
    base_env = {
        **os.environ,
        "PATHWAY_THREADS": str(threads),
        "PATHWAY_FIRST_PORT": str(first_port),
        **env_extra,
    }
    base_env.setdefault("PATHWAY_RUN_ID", secrets.token_hex(8))
    base_env.setdefault(
        "PATHWAY_FLIGHT_DIR", os.path.join(os.getcwd(), "pathway-flight")
    )
    # the controller's sensor is the merged /query document — the
    # monitoring server is not optional under --autoscale
    base_env.setdefault("PATHWAY_MONITORING_HTTP_SERVER", "1")
    if base_env["PATHWAY_MONITORING_HTTP_SERVER"].strip().lower() not in (
        "1", "true", "yes", "on"
    ):
        raise click.ClickException(
            "--autoscale needs the monitoring server: the controller's "
            "sensor is the merged /query document on process 0 — unset "
            "PATHWAY_MONITORING_HTTP_SERVER or set it to 1"
        )
    try:
        monitor_base = int(
            base_env.get("PATHWAY_MONITORING_HTTP_PORT", "20000") or 20000
        )
    except ValueError:
        monitor_base = 20000
    if monitor_base <= 0:
        raise click.ClickException(
            f"--autoscale cannot watch /query on port {monitor_base}: set "
            "PATHWAY_MONITORING_HTTP_PORT to a real port"
        )
    base_env["PATHWAY_MONITORING_HTTP_PORT"] = str(monitor_base)
    from .autoscale import AutoscaleController

    try:
        controller = AutoscaleController(
            program=list(program),
            min_workers=mn,
            max_workers=mx,
            store=store,
            base_env=base_env,
            monitor_base=monitor_base,
        )
    except AutoscaleError as e:
        raise click.ClickException(str(e))
    return controller.run()


@main.command()
@click.option("--to", "to_workers", type=int, required=True,
              help="target worker count")
@click.option("--backend", "backend_kind",
              type=click.Choice(["filesystem", "s3"]), default="filesystem",
              help="persistence backend kind holding the state")
@click.option("--dry-run", is_flag=True, default=False,
              help="plan only: print the split/merge each stateful "
                   "operator would undergo and the input tail to re-route, "
                   "without staging or promoting anything")
@click.argument("store")
def rescale(to_workers, backend_kind, dry_run, store):
    """Repartition persisted cluster state to --to workers.

    STORE is the persistence root (the path given to
    ``pw.persistence.Backend.filesystem``, or an ``s3://bucket/prefix``
    URI). The resharder splits every stateful operator's snapshot and
    every live input chunk by row key, writes a complete layout for the
    new worker count, and promotes it with one atomic cluster-marker
    rewrite — a crash mid-rescale leaves the old layout bootable."""
    import json as _json

    from .persistence import Backend
    from .rescale import RescaleError, rescale as _rescale

    if to_workers <= 0:
        # refuse before touching the store: a nonsensical target must not
        # depend on what (if anything) is persisted at STORE
        raise click.ClickException(
            f"refusing --to {to_workers}: the target worker count must be "
            ">= 1 (state is hash-sharded across workers; zero shards hold "
            "nothing)"
        )
    spec = (
        Backend.filesystem(store)
        if backend_kind == "filesystem"
        else Backend.s3(store)
    )
    try:
        report = _rescale(
            spec, to_workers,
            log=lambda m: click.echo(m, err=True), dry_run=dry_run,
        )
    except RescaleError as e:
        raise click.ClickException(str(e))
    if report.get("noop"):
        click.echo(
            f"store is already laid out for {to_workers} worker(s) — "
            "nothing to do"
            + (" (dry run)" if dry_run else "")
        )
    elif dry_run:
        from .upgrade.render import render_dry_run

        for line in render_dry_run(report):
            click.echo(line)
        click.echo(_json.dumps(report))
    else:
        click.echo(_json.dumps(report))


@main.command()
@click.option("--plan", "plan_only", is_flag=True, default=False,
              help="diff only (the default): classify every stateful "
                   "operator as carried / remapped / new / dropped and "
                   "exit with a lint-style severity code — nothing is "
                   "written")
@click.option("--apply", "do_apply", is_flag=True, default=False,
              help="execute the migration: stage the new graph version's "
                   "layout under upgrade-tmp/, carry offsets and delivery "
                   "ack cursors, promote with one atomic marker put")
@click.option("--json", "as_json", is_flag=True, default=False,
              help="emit the plan/report as JSON instead of prose")
@click.option("--allow-drop", is_flag=True, default=False,
              help="accept DROPPING stateful operators that have no "
                   "match in the new script (their persisted state is "
                   "discarded); without it a stateful drop is an error")
@click.option("--backend", "backend_kind",
              type=click.Choice(["filesystem", "s3"]), default="filesystem",
              help="persistence backend kind holding the state")
@click.argument("store")
@click.argument("new_script")
@click.argument("script_args", nargs=-1, type=click.UNPROCESSED)
def upgrade(plan_only, do_apply, as_json, allow_drop, backend_kind, store,
            new_script, script_args):
    """Migrate persisted state at STORE to the graph NEW_SCRIPT builds.

    NEW_SCRIPT runs build-only (``pw.run`` stubbed, like ``lint``) with
    any trailing SCRIPT_ARGS as its argv; its operators are matched
    against the fingerprint manifest the running pipeline persisted. ``--plan`` previews; ``--apply`` stages a
    complete next-epoch layout and promotes it with ONE atomic cluster-
    marker put — a crash at any earlier instant leaves the OLD code
    version bootable. Plan exit codes mirror ``pathway-tpu lint``:
    0 clean, 1 warnings, 2 errors (e.g. a stateful operator would be
    dropped without --allow-drop), 3 NEW_SCRIPT crashed while building."""
    import json as _json

    from .persistence import Backend
    from .upgrade import (
        UpgradeError,
        apply_upgrade,
        plan_exit_code,
        plan_upgrade,
        render_plan,
    )

    if plan_only and do_apply:
        raise click.ClickException("--plan and --apply are exclusive")
    spec = (
        Backend.filesystem(store)
        if backend_kind == "filesystem"
        else Backend.s3(store)
    )
    if do_apply:
        try:
            report = apply_upgrade(
                spec, new_script, script_args=tuple(script_args),
                allow_drop=allow_drop,
                log=lambda m: click.echo(m, err=True),
            )
        except UpgradeError as e:
            raise click.ClickException(str(e))
        if as_json:
            click.echo(_json.dumps(report))
        elif report.get("noop"):
            click.echo("nothing to migrate")
        else:
            click.echo(
                f"upgraded: {report['carried']} carried, "
                f"{report['remapped']} remapped, {report['new']} new, "
                f"{report['dropped']} dropped (epoch {report['epoch']})"
            )
        return
    try:
        plan, crash = plan_upgrade(
            spec, new_script, script_args=tuple(script_args),
            allow_drop=allow_drop,
            log=lambda m: click.echo(m, err=True),
        )
    except UpgradeError as e:
        raise click.ClickException(str(e))
    if as_json:
        doc = dict(plan)
        if crash is not None:
            doc["crash"] = f"{type(crash).__name__}: {crash}"
        click.echo(_json.dumps(doc))
    else:
        for line in render_plan(plan):
            click.echo(line)
        if crash is not None:
            click.echo(f"  crash: {type(crash).__name__}: {crash}")
    sys.exit(3 if crash is not None else plan_exit_code(plan))


@main.command(context_settings={"ignore_unknown_options": True})
@click.option("-t", "--threads", type=int, default=1)
@click.option("-n", "--processes", type=int, default=1)
@click.option("--record-path", type=str, default="record")
@click.option("--mode", type=click.Choice(["batch", "speedrun"]), default="batch",
              help="replay all at once (batch) or with original pacing")
@click.option("--continue", "continue_after_replay", is_flag=True, default=False,
              help="keep consuming live data after the replay finishes")
@click.argument("program", nargs=-1, type=click.UNPROCESSED)
def replay(threads, processes, record_path, mode, continue_after_replay, program):
    """Re-run PROGRAM against recorded input (reference cli.py:194)."""
    env_extra = {
        "PATHWAY_REPLAY_STORAGE": record_path,
        "PATHWAY_SNAPSHOT_ACCESS": "replay",
        "PATHWAY_PERSISTENCE_MODE": mode,
    }
    if continue_after_replay:
        env_extra["PATHWAY_CONTINUE_AFTER_REPLAY"] = "1"
    sys.exit(_spawn_processes(threads, processes, 10000, env_extra, program))


@main.command()
@click.option("--url", type=str, default=None,
              help="full /query URL (overrides --host/--port)")
@click.option("--host", type=str, default="127.0.0.1",
              help="monitoring host of process 0")
@click.option("--port", type=int, default=None,
              help="monitoring port of process 0 (default "
                   "PATHWAY_MONITORING_HTTP_PORT or 20000)")
@click.option("-i", "--interval", type=float, default=1.0,
              help="refresh interval in seconds")
@click.option("--frames", type=int, default=0,
              help="render N frames then exit (0 = run until ^C; "
                   "used by tests/smokes)")
@click.option("--no-clear", is_flag=True, default=False,
              help="append frames instead of repainting (for logs/pipes)")
def top(url, host, port, interval, frames, no_clear):
    """Live cluster dashboard over the /query signals endpoint.

    Shows per-worker tick rate, frontier lag, latency percentiles, comm
    queue depth + send MB/s, the current bottleneck operator, and firing
    SLO alerts. Point it at process 0 of a running pipeline (the merged
    view): ``pathway-tpu top --port 20000``."""
    from .observability.top import run_top

    if url is None:
        if port is None:
            try:
                port = int(
                    os.environ.get("PATHWAY_MONITORING_HTTP_PORT", "20000")
                )
            except ValueError:
                port = 20000
        url = f"http://{host}:{port}/query"
    sys.exit(run_top(url, interval_s=interval, frames=frames,
                     clear=not no_clear))


@main.command()
@click.option("--url", type=str, default=None,
              help="full /query URL (overrides --host/--port)")
@click.option("--host", type=str, default="127.0.0.1",
              help="monitoring host of process 0")
@click.option("--port", type=int, default=None,
              help="monitoring port of process 0 (default "
                   "PATHWAY_MONITORING_HTTP_PORT or 20000)")
@click.option("-k", "--top-k", "top_k", type=int, default=10,
              help="slowest waves to report")
@click.option("--json", "as_json", is_flag=True, default=False,
              help="dump the raw merged waves document instead")
def critpath(url, host, port, top_k, as_json):
    """Commit-wave critical-path report over the /query endpoint.

    Fetches the merged latency-lineage document (process 0 of a running
    pipeline) and prints the top-K slowest commit waves with the holding
    worker — the last frontier to arrive — and the per-stage split of
    each wave's wall time: ``pathway-tpu critpath --port 20000``."""
    import json as _json

    from .observability.critpath import render_report
    from .observability.top import fetch_query

    if url is None:
        if port is None:
            try:
                port = int(
                    os.environ.get("PATHWAY_MONITORING_HTTP_PORT", "20000")
                )
            except ValueError:
                port = 20000
        url = f"http://{host}:{port}/query"
    elif not url.rstrip("/").endswith("/query"):
        url = url.rstrip("/") + "/query"
    try:
        doc = fetch_query(url)
    except Exception as e:
        raise click.ClickException(f"{url} unreachable ({e})")
    waves = doc.get("waves")
    if as_json:
        click.echo(_json.dumps(waves, indent=2, sort_keys=True))
        return
    click.echo(render_report(waves, top_k=top_k))


@main.command()
@click.option("--url", type=str, default=None,
              help="full /profile URL (overrides --host/--port)")
@click.option("--host", type=str, default="127.0.0.1",
              help="monitoring host of process 0")
@click.option("--port", type=int, default=None,
              help="monitoring port of process 0 (default "
                   "PATHWAY_MONITORING_HTTP_PORT or 20000)")
@click.option("--speedscope", "as_speedscope", is_flag=True, default=False,
              help="emit speedscope JSON (paste into speedscope.app)")
@click.option("--collapsed", "as_collapsed", is_flag=True, default=False,
              help="emit collapsed-stack text (flamegraph.pl / inferno)")
@click.option("--top", "top_n", type=int, default=15,
              help="frames in the default self-time table")
@click.option("--mode", type=click.Choice(["wall", "cpu"]), default="wall",
              help="wall samples or CPU-time-weighted samples")
@click.option("--local", "local_only", is_flag=True, default=False,
              help="this process only (skip the cluster merge)")
@click.option("--heap", "as_heap", is_flag=True, default=False,
              help="on-demand tracemalloc heap snapshot instead")
@click.option("-o", "--output", type=str, default=None,
              help="write to a file instead of stdout")
def profile(url, host, port, as_speedscope, as_collapsed, top_n, mode,
            local_only, as_heap, output):
    """Cluster-merged flamegraph from the continuous profiler.

    Fetches the always-on sampling profiler's merged collapsed-stack
    table from ``/profile`` on process 0 of a running pipeline (every
    sample tagged with the executing operator, joining against
    ``/attribution``) and renders a self-time table, collapsed-stack
    text, or speedscope JSON: ``pathway-tpu profile --port 20000``."""
    import json as _json
    import urllib.request

    from .observability.profile_merge import render_top

    if as_speedscope and as_collapsed:
        raise click.ClickException("--speedscope and --collapsed are exclusive")
    if url is None:
        if port is None:
            try:
                port = int(
                    os.environ.get("PATHWAY_MONITORING_HTTP_PORT", "20000")
                )
            except ValueError:
                port = 20000
        url = f"http://{host}:{port}/profile"
    elif not url.rstrip("/").endswith("/profile"):
        url = url.rstrip("/") + "/profile"
    params = [f"mode={mode}"]
    if as_heap:
        params = ["heap=1"]
    elif as_speedscope:
        params.append("format=speedscope")
    elif as_collapsed:
        params.append("format=collapsed")
    if local_only and not as_heap:
        params.append("local=1")
    full = url + "?" + "&".join(params)
    try:
        with urllib.request.urlopen(full, timeout=10.0) as r:
            body = r.read().decode()
    except Exception as e:
        raise click.ClickException(f"{full} unreachable ({e})")
    if as_collapsed:
        text = body
    elif as_speedscope or as_heap:
        text = _json.dumps(_json.loads(body), indent=2, sort_keys=True)
    else:
        text = render_top(_json.loads(body), n=top_n, mode=mode)
    if output:
        with open(output, "w", encoding="utf-8") as f:
            f.write(text if text.endswith("\n") else text + "\n")
        click.echo(f"wrote {output}")
    else:
        click.echo(text)


@main.command()
@click.argument("dlq_dir", required=False, type=str, default=None)
@click.option("--sink", "sink_name", type=str, default=None,
              help="only this sink's entries")
@click.option("--tail", "tail_n", type=int, default=5,
              help="newest entries to print per sink (0 = summary only)")
def dlq(dlq_dir, sink_name, tail_n) -> None:
    """Inspect the sink dead-letter queue (poison rows the delivery
    layer refused to drop silently). Default directory:
    PATHWAY_SINK_DLQ_DIR or ./pathway-dlq."""
    import json as _json

    root = dlq_dir or os.environ.get("PATHWAY_SINK_DLQ_DIR", "./pathway-dlq")
    if not os.path.isdir(root):
        raise click.ClickException(f"no dead-letter directory at {root}")
    files = sorted(
        f for f in os.listdir(root)
        if f.endswith(".jsonl")
        and (sink_name is None or f == f"{sink_name}.jsonl")
    )
    if not files:
        raise click.ClickException(
            f"no dead-letter files in {root}"
            + (f" for sink {sink_name!r}" if sink_name else "")
        )
    total = 0
    for fn in files:
        path = os.path.join(root, fn)
        entries = []
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        entries.append(_json.loads(line))
                    except ValueError:
                        entries.append({"error": "<unparseable entry>"})
        total += len(entries)
        click.echo(f"{fn[:-6]}: {len(entries)} dead-lettered row(s) ({path})")
        for e in entries[-tail_n:] if tail_n else []:
            click.echo(
                f"  t={e.get('time')} stamp={e.get('stamp')} "
                f"error={e.get('error')!r} row={_json.dumps(e.get('row'))}"
            )
    click.echo(f"total: {total} row(s) across {len(files)} sink(s)")


@main.command()
@click.option("--json", "as_json", is_flag=True, default=False,
              help="machine-readable JSON report (one document per script)")
@click.option("--workers", "n_workers", type=int, default=None,
              help="worker count the deployment targets (shard-skew "
                   "modeling; default PATHWAY_LINT_WORKERS or the "
                   "current config)")
@click.option("--fail-on",
              type=click.Choice(["error", "warning", "never"]),
              default="warning", show_default=True,
              help="severity threshold for a nonzero exit code")
@click.option("--no-fingerprints", is_flag=True, default=False,
              help="omit the per-operator fingerprint table")
@click.argument("targets", nargs=-1, required=True,
                type=click.Path(exists=True))
def lint(as_json, n_workers, fail_on, no_fingerprints, targets):
    """Statically analyze pipeline scripts without running them.

    Each TARGET (a script, or a directory expanded to every .py beneath
    it) executes in build-only mode — ``pw.run()`` is stubbed, nothing
    flows — and the compiled dataflow graph is checked for unbounded
    state growth, replay-nondeterministic UDFs, per-row dispatch tax,
    fusion opportunities, shard skew and sink misconfiguration, with a
    stable structural fingerprint per operator. Suppress a finding
    inline with ``# pathway: ignore[<id>]``.

    Exit codes: 0 clean (or info only), 1 warnings, 2 errors, 3 a
    script crashed while building (thresholded by --fail-on)."""
    import json as _json

    from .analysis.lint import lint_targets

    results, code = lint_targets(
        list(targets), n_workers=n_workers, fail_on=fail_on
    )
    if as_json:
        click.echo(_json.dumps([r["doc"] for r in results], indent=2))
    else:
        for r in results:
            if r["crash"] is not None:
                click.echo(
                    f"== pathway-tpu lint: {r['report'].script} ==\n"
                    f"script crashed while building its graph: "
                    f"{r['doc']['crash']}",
                    err=True,
                )
            else:
                click.echo(
                    r["report"].render(fingerprints=not no_fingerprints)
                )
    sys.exit(code)


@main.group()
def trace() -> None:
    """Distributed-trace tooling (PATHWAY_TRACE_FILE)."""


@trace.command()
@click.argument("base")
@click.option("-o", "--output", type=str, default=None,
              help="merged timeline path (default: <base>.merged.json)")
@click.option("--allow-mixed-runs", is_flag=True, default=False,
              help="merge parts with different run ids anyway")
def merge(base, output, allow_mixed_runs):
    """Merge per-process trace parts into one cluster timeline.

    BASE is the PATHWAY_TRACE_FILE value of the run; the per-process
    ``BASE.p<N>`` parts (or BASE itself for a single-process run) are
    assembled into one clock-aligned Chrome/Perfetto JSON, using the
    per-peer clock offsets estimated during mesh establishment and
    cross-linking workers via the comm flow events."""
    from .observability.trace_merge import merge_trace

    try:
        out_path, report = merge_trace(
            base, output=output, allow_mixed_runs=allow_mixed_runs
        )
    except (OSError, ValueError) as e:
        raise click.ClickException(str(e))
    click.echo(
        f"merged {report['n_parts']} part(s), {report['n_events']} events "
        f"({report['n_flows']} flow events) -> {out_path}"
    )


if __name__ == "__main__":
    main()
