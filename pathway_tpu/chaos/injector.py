"""Runtime side of fault injection: arming plans and firing sites.

The injector is armed once per process — explicitly (``arm(plan)``) or
from ``PATHWAY_FAULT_PLAN`` at engine-construction time (``current()``).
Every injection site in the engine is guarded so an unarmed process pays
exactly one attribute/None check per site visit:

- the executor holds ``self._tick_fault`` (None unless a tick fault
  targets its worker);
- each comm backend holds ``self._chaos`` (None unless frame faults
  target it);
- ``wrap_backend`` returns the backend object *itself* (identity
  preserved) unless a persistence fault targets that worker.

Those site handles are resolved at construction, not per event, so the
steady-state cost of a disarmed build is indistinguishable from a build
with no chaos code at all.

Determinism: every fire/skip decision is appended to
``ActiveFaults.decision_log``; ``prob`` faults draw from per-fault RNGs
seeded by ``(plan.seed, fault index)``. Same plan + same event sequence
→ byte-identical logs.
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import Any

from .plan import Fault, FaultPlan, load_plan_from_env

__all__ = [
    "ChaosInjected",
    "ActiveFaults",
    "arm",
    "disarm",
    "current",
]


class ChaosInjected(RuntimeError):
    """Raised by crash/fail injections — unmistakably chaos, never a bug."""


#: the armed injector; None = chaos disabled (module-level so sites cost
#: one global read + None check)
ARMED: "ActiveFaults | None" = None


def arm(plan: FaultPlan, run: int | None = None) -> "ActiveFaults":
    """Arm ``plan`` for this process. ``run`` is the supervised restart
    generation (default: ``PATHWAY_RESTART_COUNT``); only faults gated to
    that generation activate."""
    global ARMED
    if run is None:
        run = int(os.environ.get("PATHWAY_RESTART_COUNT", "0") or 0)
    ARMED = ActiveFaults(plan.for_run(run), run)
    return ARMED


def disarm() -> None:
    global ARMED
    ARMED = None


def current() -> "ActiveFaults | None":
    """The armed injector, arming from ``PATHWAY_FAULT_PLAN`` if present.

    Called from engine-construction paths only (Executor / comm backend /
    PersistenceManager init) — never per tick/frame/put. An injector armed
    from the environment tracks it: if ``PATHWAY_FAULT_PLAN`` changes or
    disappears (test isolation, repeated pw.run calls), the stale arming
    is replaced rather than leaking into the next run."""
    global ARMED
    if ARMED is not None and ARMED.env_spec is None:
        return ARMED  # explicitly armed via arm() — env is ignored
    spec = os.environ.get("PATHWAY_FAULT_PLAN")
    spec = spec.strip() if spec else None
    if ARMED is not None and ARMED.env_spec == spec:
        return ARMED
    if not spec:
        ARMED = None
        return None
    armed = arm(load_plan_from_env())
    armed.env_spec = spec
    return armed


class ActiveFaults:
    def __init__(self, plan: FaultPlan, run: int = 0):
        self.plan = plan
        self.run = run
        #: the raw PATHWAY_FAULT_PLAN this arming came from; None when
        #: armed programmatically (see current())
        self.env_spec: str | None = None
        #: (fault index, scope, event counter, fired) — the full schedule
        self.decision_log: list[tuple[int, str, int, bool]] = []
        self.injections_total = 0
        self._rngs = [
            random.Random((plan.seed << 20) ^ i)
            for i in range(len(plan.faults))
        ]
        self._counts: dict[tuple[int, str], int] = {}
        # sites fire from concurrent worker threads (LocalComm rendezvous,
        # per-thread ClusterComm sends); an unlocked read-modify-write on
        # the event counters could double-fire or skip an nth fault
        self._lock = threading.Lock()

    # -- decision core ---------------------------------------------------

    def _decide(self, idx: int, fault: Fault, scope: str) -> bool:
        """One matching event for fault ``idx`` in ``scope``: count it,
        decide deterministically, log the decision."""
        key = (idx, scope)
        with self._lock:
            n = self._counts.get(key, 0) + 1
            self._counts[key] = n
            if fault.nth is not None:
                fired = n == fault.nth
            elif fault.prob is not None:
                fired = self._rngs[idx].random() < fault.prob
            else:
                fired = True
            self.decision_log.append((idx, scope, n, fired))
            if fired:
                self.injections_total += 1
        if fired:
            # black-box note BEFORE the fault executes: a chaos SIGKILL's
            # flight-recorder tail then documents its own cause
            from ..observability.flightrecorder import get_recorder

            recorder = get_recorder()
            if recorder is not None:
                recorder.record(
                    "chaos.fired",
                    site=fault.site,
                    action=fault.action,
                    scope=scope,
                    event=n,
                )
        return fired

    # -- site resolution (construction-time) -----------------------------

    def tick_fault(self, worker_id: int) -> "TickFault | None":
        matches = [
            (i, f) for i, f in enumerate(self.plan.faults)
            if f.site == "tick" and f.worker in (None, worker_id)
        ]
        return TickFault(self, worker_id, matches) if matches else None

    def send_faults(self, process_id: int) -> "SendFaults | None":
        matches = [
            (i, f) for i, f in enumerate(self.plan.faults)
            if f.site == "comm.send" and f.process in (None, process_id)
        ]
        return SendFaults(self, process_id, matches) if matches else None

    def local_faults(self) -> "LocalFaults | None":
        matches = [
            (i, f) for i, f in enumerate(self.plan.faults)
            if f.site == "comm.local"
        ]
        return LocalFaults(self, matches) if matches else None

    def rescale_faults(self) -> "RescaleFaults | None":
        matches = [
            (i, f) for i, f in enumerate(self.plan.faults)
            if f.site == "rescale"
        ]
        return RescaleFaults(self, matches) if matches else None

    def autoscale_faults(self) -> "AutoscaleFaults | None":
        matches = [
            (i, f) for i, f in enumerate(self.plan.faults)
            if f.site == "autoscale"
        ]
        return AutoscaleFaults(self, matches) if matches else None

    def upgrade_faults(self) -> "UpgradeFaults | None":
        matches = [
            (i, f) for i, f in enumerate(self.plan.faults)
            if f.site == "upgrade"
        ]
        return UpgradeFaults(self, matches) if matches else None

    def sink_faults(self, worker_id: int) -> "SinkFaults | None":
        matches = [
            (i, f) for i, f in enumerate(self.plan.faults)
            if f.site == "sink.write" and f.worker in (None, worker_id)
        ]
        return SinkFaults(self, worker_id, matches) if matches else None

    def serve_faults(self) -> "ServeFaults | None":
        matches = [
            (i, f) for i, f in enumerate(self.plan.faults)
            if f.site == "serve.query"
        ]
        return ServeFaults(self, matches) if matches else None

    def spill_faults(self, worker_id: int) -> "SpillFaults | None":
        matches = [
            (i, f) for i, f in enumerate(self.plan.faults)
            if f.site == "state.spill" and f.worker in (None, worker_id)
        ]
        return SpillFaults(self, worker_id, matches) if matches else None

    def wrap_backend(self, backend: Any, worker_id: int) -> Any:
        matches = [
            (i, f) for i, f in enumerate(self.plan.faults)
            if f.site == "persistence.put" and f.worker in (None, worker_id)
        ]
        if not matches:
            return backend
        return ChaosBackend(backend, self, worker_id, matches)


def wrap_backend(backend: Any, worker_id: int) -> Any:
    """Module-level convenience: wrap iff armed AND a fault targets this
    worker; otherwise the argument is returned unchanged (identity)."""
    armed = current()
    if armed is None:
        return backend
    return armed.wrap_backend(backend, worker_id)


class TickFault:
    """Bound tick-site handle for one worker's executor."""

    def __init__(self, owner: ActiveFaults, worker_id: int,
                 matches: list[tuple[int, Fault]]):
        self._owner = owner
        self._scope = f"tick/w{worker_id}"
        self._matches = matches

    def fire(self, tick_seq: int) -> None:
        for idx, f in self._matches:
            if f.tick != tick_seq:
                continue
            if not self._owner._decide(idx, f, self._scope):
                continue
            if f.action == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            elif f.action == "exit":
                os._exit(17)
            elif f.action == "hang":
                time.sleep(f.delay_s if f.delay_s is not None else 3600.0)
            else:  # crash
                raise ChaosInjected(
                    f"chaos: injected crash at tick {tick_seq} "
                    f"({self._scope})"
                )


class RescaleFaults:
    """Bound rescale-site handle for the offline resharder: fires at the
    resharder's phase boundaries (plan/stage/copy/promote/cleanup) — a
    ``kill`` here is the crash-mid-rescale the atomicity protocol must
    survive."""

    def __init__(self, owner: ActiveFaults, matches: list[tuple[int, Fault]]):
        self._owner = owner
        self._matches = matches

    def fire(self, phase: str) -> None:
        for idx, f in self._matches:
            if f.phase not in (None, phase):
                continue
            if not self._owner._decide(idx, f, f"rescale/{phase}"):
                continue
            if f.action == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            elif f.action == "exit":
                os._exit(19)
            else:  # crash
                raise ChaosInjected(
                    f"chaos: injected crash at rescale phase {phase!r}"
                )


class AutoscaleFaults:
    """Bound autoscale-site handle for the closed-loop controller: fires
    at the controller's phase boundaries (decide/drain/reshard/resume) —
    a ``kill`` here takes down the controller process itself mid-scale,
    the failure mode the persisted layout must survive at every point."""

    def __init__(self, owner: ActiveFaults, matches: list[tuple[int, Fault]]):
        self._owner = owner
        self._matches = matches

    def fire(self, phase: str) -> None:
        for idx, f in self._matches:
            if f.phase not in (None, phase):
                continue
            if not self._owner._decide(idx, f, f"autoscale/{phase}"):
                continue
            if f.action == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            elif f.action == "exit":
                os._exit(23)
            else:  # crash
                raise ChaosInjected(
                    f"chaos: injected crash at autoscale phase {phase!r}"
                )


class UpgradeFaults:
    """Bound upgrade-site handle for the offline graph-version migrator:
    fires at its phase boundaries (plan/stage/backfill/carry/promote/
    cleanup). ``kill`` mid-upgrade is the crash the atomic-marker cutover
    must survive with the OLD code version still bootable; ``torn``
    lands a truncated blob under the upgrade staging prefix (via the
    migrator-provided callback) before raising — half-written staging
    must never contaminate a bootable layout."""

    def __init__(self, owner: ActiveFaults, matches: list[tuple[int, Fault]]):
        self._owner = owner
        self._matches = matches

    def fire(self, phase: str, torn: Any = None) -> None:
        for idx, f in self._matches:
            if f.phase not in (None, phase):
                continue
            if not self._owner._decide(idx, f, f"upgrade/{phase}"):
                continue
            if f.action == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            elif f.action == "exit":
                os._exit(29)
            elif f.action == "torn":
                if torn is not None:
                    torn()
                raise ChaosInjected(
                    f"chaos: injected torn staging write at upgrade "
                    f"phase {phase!r}"
                )
            else:  # crash
                raise ChaosInjected(
                    f"chaos: injected crash at upgrade phase {phase!r}"
                )


class SendFaults:
    """Bound comm.send-site handle for one process's ClusterComm.

    Fires at frame-enqueue time on the pipelined data plane (the frame
    never reaches the peer writer queue for ``drop``/``sever``;
    ``corrupt`` mangles the encoded body so the peer's reader exercises
    its torn-frame refusal path). ``op_for`` is called from worker
    threads concurrently — the owner's decision lock keeps nth counters
    exact."""

    def __init__(self, owner: ActiveFaults, process_id: int,
                 matches: list[tuple[int, Fault]]):
        self._owner = owner
        self._process_id = process_id
        self._matches = matches

    def op_for(self, peer: int) -> tuple[str, float] | None:
        """The (action, delay_s) to apply to the next frame headed to
        ``peer``, or None. First firing fault wins."""
        for idx, f in self._matches:
            if f.peer not in (None, peer):
                continue
            scope = f"send/p{self._process_id}->p{peer}"
            if self._owner._decide(idx, f, scope):
                return f.action, (f.delay_s if f.delay_s is not None else 0.05)
        return None


class LocalFaults:
    """Bound comm.local-site handle for a LocalComm."""

    def __init__(self, owner: ActiveFaults, matches: list[tuple[int, Fault]]):
        self._owner = owner
        self._matches = matches

    def apply(self, worker_id: int, key: Any, payload: Any) -> Any:
        is_exchange = isinstance(key, tuple) and key and key[0] == "x"
        for idx, f in self._matches:
            if f.worker not in (None, worker_id):
                continue
            # 'drop' means "this worker's rows for the tick vanish" — it
            # only matches DATA-plane exchanges; a dropped control-plane
            # allgather (cycle coordination, recovery) would not simulate a
            # lost frame, it would crash every worker on a None tuple
            if f.action == "drop" and not is_exchange:
                continue
            if not self._owner._decide(idx, f, f"local/w{worker_id}"):
                continue
            if f.action == "drop":
                return None
            time.sleep(f.delay_s if f.delay_s is not None else 0.05)
        return payload


class SinkFaults:
    """Bound sink.write-site handle for one worker's delivery sinks.

    ``op_for(sink_name)`` returns the (action, delay_s) to apply to the
    NEXT write attempt of a matching sink ("fail" | "torn" | "delay" |
    "hang" | "reject") or None. The delivery layer implements the
    actions itself — it owns the retry/rollback/DLQ machinery each one
    must exercise (io/delivery.py ``_chaos_gate``)."""

    def __init__(self, owner: ActiveFaults, worker_id: int,
                 matches: list[tuple[int, Fault]]):
        self._owner = owner
        self._scope = f"sink/w{worker_id}"
        self._matches = matches

    def op_for(self, sink_name: str) -> tuple[str, float] | None:
        for idx, f in self._matches:
            if (
                f.key_prefix is not None
                and not sink_name.startswith(f.key_prefix)
            ):
                continue
            if self._owner._decide(idx, f, self._scope):
                return f.action, (
                    f.delay_s if f.delay_s is not None else 0.05
                )
        return None


class ServeFaults:
    """Bound serve.query-site handle for the serve router's hops.

    ``op_for(phase, shard_worker)`` returns the (action, delay_s) to
    apply to the NEXT matching hop event — ``drop`` / ``delay`` /
    ``fail`` (the router implements those, it owns the degraded-gather
    machinery each must exercise) — or None. ``kill`` executes HERE
    (SIGKILL self): the hop that matched runs in the process hosting
    the shard, which is exactly the shard-loss the smoke wants dead."""

    def __init__(self, owner: ActiveFaults, matches: list[tuple[int, Fault]]):
        self._owner = owner
        self._matches = matches

    def op_for(
        self, phase: str, shard_worker: int
    ) -> tuple[str, float] | None:
        for idx, f in self._matches:
            if f.phase not in (None, phase):
                continue
            if f.worker not in (None, shard_worker):
                continue
            scope = f"serve/{phase}/w{shard_worker}"
            if self._owner._decide(idx, f, scope):
                if f.action == "kill":
                    os.kill(os.getpid(), signal.SIGKILL)
                return f.action, (
                    f.delay_s if f.delay_s is not None else 0.05
                )
        return None


class SpillFaults:
    """Bound state.spill-site handle for one worker's spill stores.

    ``op_for(key)`` returns the action to apply to the NEXT spill blob
    write of a matching key ("fail" | "torn" | "kill") or None. The
    spill store implements the action itself — it owns the versioned-key
    write protocol the torn action must exercise."""

    def __init__(self, owner: ActiveFaults, worker_id: int,
                 matches: list[tuple[int, Fault]]):
        self._owner = owner
        self._scope = f"spill/w{worker_id}"
        self._matches = matches

    def op_for(self, key: str) -> str | None:
        for idx, f in self._matches:
            if f.key_prefix is not None and not key.startswith(f.key_prefix):
                continue
            if self._owner._decide(idx, f, self._scope):
                if f.action == "kill":
                    os.kill(os.getpid(), signal.SIGKILL)
                return f.action
        return None


class ChaosBackend:
    """Persistence-backend wrapper failing selected ``put_value`` calls.

    ``fail`` raises before anything lands; ``torn`` writes a truncated
    blob as the key's final content and then raises — simulating a torn
    write that slipped past the backend's atomic-rename discipline (the
    recovery path must survive both: metadata versions are tried newest
    first, unparseable ones skipped)."""

    def __init__(self, inner: Any, owner: ActiveFaults, worker_id: int,
                 matches: list[tuple[int, Fault]]):
        self._inner = inner
        self._owner = owner
        self._scope = f"put/w{worker_id}"
        self._matches = matches

    def put_value(self, key: str, value: bytes) -> None:
        for idx, f in self._matches:
            if f.key_prefix is not None and not key.startswith(f.key_prefix):
                continue
            if not self._owner._decide(idx, f, self._scope):
                continue
            if f.action == "torn":
                self._inner.put_value(key, value[: max(1, len(value) // 2)])
            raise ChaosInjected(
                f"chaos: injected put_value {f.action} on {key!r}"
            )
        self._inner.put_value(key, value)

    # pure delegation for the rest of the backend surface
    def get_value(self, key: str) -> bytes:
        return self._inner.get_value(key)

    def size_of(self, key: str) -> int:
        return self._inner.size_of(key)

    def list_keys(self) -> list[str]:
        return self._inner.list_keys()

    def remove_key(self, key: str) -> None:
        self._inner.remove_key(key)

    def close(self) -> None:
        self._inner.close()

    def describe(self) -> str:
        desc = getattr(self._inner, "describe", None)
        return f"chaos({desc()})" if desc else "chaos(?)"
