"""``pathway_tpu.chaos`` — deterministic fault injection.

The robustness analog of ``observability/``: declarative, seeded fault
plans (``plan.py``) armed into engine injection sites (``injector.py``)
across the executor tick loop, the comm backends and the persistence
backends. Paired with ``pathway-tpu spawn --supervise``
(``parallel/supervisor.py``) it turns "SIGKILL worker 1 at tick 6 and
recover exactly" into a one-line JSON plan — the reference's wordcount
``run_pw_program_suddenly_terminate`` harness, made reproducible.
"""

from .injector import (
    ActiveFaults,
    ChaosInjected,
    arm,
    current,
    disarm,
    wrap_backend,
)
from .plan import Fault, FaultPlan, load_plan_from_env

__all__ = [
    "ActiveFaults",
    "ChaosInjected",
    "Fault",
    "FaultPlan",
    "arm",
    "current",
    "disarm",
    "load_plan_from_env",
    "wrap_backend",
]
