"""Declarative fault plans — deterministic, seeded chaos schedules.

A fault plan is a small JSON document (env ``PATHWAY_FAULT_PLAN`` holds
either the JSON text itself or a path to a file containing it) naming
*where* and *when* to inject faults into a run:

.. code-block:: json

    {"seed": 7, "faults": [
        {"site": "tick",        "worker": 1, "tick": 6, "action": "kill"},
        {"site": "comm.send",   "process": 0, "peer": 1, "nth": 3,
         "action": "drop"},
        {"site": "comm.local",  "worker": 0, "nth": 2, "action": "delay",
         "delay_s": 0.05},
        {"site": "persistence.put", "worker": 0, "nth": 4,
         "key_prefix": "meta/", "action": "fail"}
    ]}

Sites and actions:

- ``tick`` — the executor's per-worker tick loop. ``action`` is ``crash``
  (raise), ``exit`` (``os._exit``), ``kill`` (SIGKILL self — the hard
  mid-tick death the wordcount recovery harness exercises) or ``hang``
  (sleep ``delay_s``, default forever-ish). Selected by ``worker`` and
  ``tick`` (the worker's 0-based tick sequence number).
- ``comm.send`` — ClusterComm outbound frames. ``action`` is ``drop``,
  ``delay``, ``duplicate``, ``sever`` (shut the peer socket down, as a
  network partition would) or ``corrupt`` (flip bytes in the frame body
  on the wire — the peer's reader must refuse the torn frame and flip
  ``_broken`` with a named origin, never deserialize garbage). Selected
  by ``process``/``peer`` and either ``nth`` (1-based matching-frame
  counter) or ``prob``. ``duplicate`` is wire-level: it exercises the
  framing/reader path with a repeated frame, which the inbox then
  absorbs idempotently (per-(collective, src) slots) — it does NOT
  duplicate rows in the dataflow. All comm.send actions fire on the
  pipelined send path, before the frame enters its peer writer queue.
- ``comm.local`` — LocalComm collective contributions (thread workers).
  ``action`` is ``drop`` (contribute None) or ``delay``.
- ``persistence.put`` — backend ``put_value``. ``action`` is ``fail``
  (raise before writing) or ``torn`` (write a truncated blob, then raise —
  a torn write landing despite the backends' atomic-rename discipline).
  Selected by ``worker``, ``nth`` and optional ``key_prefix``.
- ``rescale`` — the offline state resharder's phase boundaries
  (``rescale/resharder.py``: plan, stage, copy, promote, cleanup).
  ``action`` is ``crash``, ``exit`` or ``kill``; selected by ``phase``
  and ``nth``. A kill before ``promote`` must leave the OLD layout
  bootable; at/after ``cleanup`` the NEW one — the atomicity proof.
- ``autoscale`` — the closed-loop autoscale controller's phase
  boundaries (``autoscale/controller.py``: decide, drain, reshard,
  resume). ``action`` is ``crash``, ``exit`` or ``kill``; selected by
  ``phase`` and ``nth``. A kill at ANY phase must leave a bootable
  persisted layout: the controller only mutates state through the
  resharder's atomic-marker protocol, so a supervised elastic boot
  afterwards converges back to a healthy cluster.
- ``sink.write`` — the output-plane delivery layer's per-attempt write
  gate (``io/delivery.py``: every external sink write rides it).
  ``action`` is ``fail`` (raise before the adapter write — retryable),
  ``torn`` (write a half-batch through the adapter, then raise — the
  retry must not double the half; transactional adapters roll back),
  ``delay`` (sleep ``delay_s`` before writing), ``hang`` (sleep
  effectively-forever — the per-sink timeout watchdog must fire) or
  ``reject`` (raise a non-retryable reject naming the first row — the
  delivery layer must dead-letter it, never drop it silently or crash).
  Selected by ``worker`` (the sink worker), ``nth``/``prob`` and
  optional ``key_prefix`` matching the SINK NAME (the delivery layer's
  stable sink id).
- ``upgrade`` — the offline graph-version migrator's phase boundaries
  (``upgrade/migrator.py``: plan, stage, backfill, carry, promote,
  cleanup). ``action`` is ``crash``, ``exit``, ``kill`` or ``torn``
  (write a truncated blob under the upgrade staging prefix, then raise —
  proving half-written staging never contaminates a bootable layout);
  selected by ``phase`` and ``nth``. A kill before ``promote`` must
  leave the OLD graph version bootable; at/after ``cleanup`` the NEW
  one — exactly-once output must hold across the code-version flip.
- ``serve.query`` — the serve plane's query fan-out hops
  (``serve/router.py``): ``phase`` selects the hop — ``scatter`` (the
  origin posting a query to a shard), ``search`` (a shard responder
  about to search its local index), ``result`` (a responder posting
  its answer back). ``action`` is ``drop`` (lose the event at that
  hop — the gather must degrade, never hang), ``delay`` (sleep
  ``delay_s``), ``fail`` (the responder answers with an error) or
  ``kill`` (SIGKILL the responder's process mid-load — the shard-loss
  smoke). Selected by ``worker`` (the SHARD worker the hop concerns),
  ``nth``/``prob`` and ``phase``.
- ``state.spill`` — the memory-budget spill tier's blob writes
  (``engine/spill.py``: join-run payloads, groupby cold buckets, key-
  registry cold buckets). ``action`` is ``fail`` (raise before writing),
  ``torn`` (write a truncated blob to the NEW versioned key, then raise
  — the versioned-key protocol must keep the previous generation
  readable) or ``kill`` (SIGKILL mid-spill — recovery must restore from
  operator snapshots, never from the scratch spill dir). Selected by
  ``worker``, ``nth``/``prob`` and optional ``key_prefix``. Fail/torn
  must never corrupt resident state: the spiller keeps entries resident
  until the write succeeds.

Determinism contract: a plan plus its ``seed`` fully determines the
injection schedule. ``nth``/``tick`` faults are trivially deterministic;
``prob`` faults draw from a per-fault ``random.Random`` seeded from
``(seed, fault index)``, so the decision for the K-th matching event is a
pure function of (seed, plan, K). Every decision is appended to the
armed injector's ``decision_log`` — two runs of the same plan over the
same event sequence produce byte-identical logs (unit-tested).

Restart gating: ``run`` (default 0) scopes a fault to one supervised
restart generation (``PATHWAY_RESTART_COUNT``); ``run = -1`` fires on
every generation. This is what makes "crash at tick 6, then recover
cleanly" a single declarative plan under ``spawn --supervise``.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any

__all__ = ["Fault", "FaultPlan", "load_plan_from_env"]

_SITES = (
    "tick", "comm.send", "comm.local", "persistence.put", "rescale",
    "autoscale", "state.spill", "sink.write", "upgrade", "serve.query",
)
_ACTIONS = {
    "tick": ("crash", "exit", "kill", "hang"),
    "comm.send": ("drop", "delay", "duplicate", "sever", "corrupt"),
    "comm.local": ("drop", "delay"),
    "persistence.put": ("fail", "torn"),
    "rescale": ("crash", "exit", "kill"),
    "autoscale": ("crash", "exit", "kill"),
    "state.spill": ("fail", "torn", "kill"),
    "sink.write": ("fail", "torn", "delay", "hang", "reject"),
    "upgrade": ("crash", "exit", "kill", "torn"),
    "serve.query": ("drop", "delay", "fail", "kill"),
}
#: rescale-site phase boundaries, in execution order (resharder.py)
RESCALE_PHASES = ("plan", "stage", "copy", "promote", "cleanup")
#: autoscale-site phase boundaries, in execution order (controller.py)
AUTOSCALE_PHASES = ("decide", "drain", "reshard", "resume")
#: upgrade-site phase boundaries, in execution order (upgrade/migrator.py)
UPGRADE_PHASES = ("plan", "stage", "backfill", "carry", "promote", "cleanup")
#: serve.query-site hops, in query-lifecycle order (serve/router.py)
SERVE_PHASES = ("scatter", "search", "result")
#: which phase vocabulary each phased site validates against
_PHASES_BY_SITE = {
    "rescale": RESCALE_PHASES,
    "autoscale": AUTOSCALE_PHASES,
    "upgrade": UPGRADE_PHASES,
    "serve.query": SERVE_PHASES,
}


@dataclass(frozen=True)
class Fault:
    site: str
    action: str
    #: tick / comm.local / persistence.put: worker id; None = any
    worker: int | None = None
    #: comm.send: originating process id; None = any
    process: int | None = None
    #: comm.send: destination process id; None = any
    peer: int | None = None
    #: tick site: fire at this 0-based tick sequence number
    tick: int | None = None
    #: 1-based matching-event counter (comm/persistence sites)
    nth: int | None = None
    #: seeded per-event probability (alternative to nth)
    prob: float | None = None
    #: persistence.put / state.spill: only count puts whose key starts
    #: with this; sink.write: only count writes of sinks whose NAME
    #: starts with this
    key_prefix: str | None = None
    #: phased sites (rescale/autoscale/upgrade): fire at this phase
    #: boundary (the site's *_PHASES vocabulary); None = any phase
    phase: str | None = None
    #: delay/hang duration; None = the action's default (delay 0.05s,
    #: hang effectively-forever)
    delay_s: float | None = None
    #: supervised restart generation this fault belongs to (-1 = all)
    run: int = 0

    def validate(self) -> None:
        if self.site not in _SITES:
            raise ValueError(
                f"fault plan: unknown site {self.site!r} (one of {_SITES})"
            )
        if self.action not in _ACTIONS[self.site]:
            raise ValueError(
                f"fault plan: site {self.site!r} has no action "
                f"{self.action!r} (one of {_ACTIONS[self.site]})"
            )
        if self.site == "tick" and self.tick is None:
            raise ValueError("fault plan: tick faults need a 'tick' number")
        if self.phase is not None:
            allowed = _PHASES_BY_SITE.get(self.site)
            if allowed is None:
                raise ValueError(
                    f"fault plan: site {self.site!r} takes no 'phase' "
                    f"(phased sites: {sorted(_PHASES_BY_SITE)})"
                )
            if self.phase not in allowed:
                raise ValueError(
                    f"fault plan: unknown {self.site} phase {self.phase!r} "
                    f"(one of {allowed})"
                )
        if self.prob is not None and not 0.0 <= self.prob <= 1.0:
            raise ValueError(f"fault plan: prob {self.prob} not in [0, 1]")


@dataclass
class FaultPlan:
    seed: int = 0
    faults: list[Fault] = field(default_factory=list)

    @classmethod
    def from_dict(cls, doc: dict[str, Any]) -> "FaultPlan":
        known = {f.name for f in Fault.__dataclass_fields__.values()}
        faults = []
        for i, fd in enumerate(doc.get("faults", [])):
            extra = set(fd) - known
            if extra:
                raise ValueError(
                    f"fault plan: fault #{i} has unknown fields {sorted(extra)}"
                )
            f = Fault(**fd)
            f.validate()
            faults.append(f)
        return cls(seed=int(doc.get("seed", 0)), faults=faults)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls.from_dict(json.loads(text))

    def for_run(self, run: int) -> "FaultPlan":
        """The sub-plan applicable to supervised restart generation
        ``run`` (faults with run = -1 apply to every generation)."""
        return FaultPlan(
            seed=self.seed,
            faults=[f for f in self.faults if f.run in (-1, run)],
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "seed": self.seed,
            "faults": [
                {
                    k: v
                    for k, v in vars(f).items()
                    if v is not None and not (k == "run" and v == 0)
                }
                for f in self.faults
            ],
        }


def load_plan_from_env() -> FaultPlan | None:
    """Parse ``PATHWAY_FAULT_PLAN`` (inline JSON or a file path). Returns
    None when unset/empty — the common case, costing one env read."""
    spec = os.environ.get("PATHWAY_FAULT_PLAN")
    if not spec or not spec.strip():
        return None
    spec = spec.strip()
    if not spec.startswith("{"):
        with open(spec) as f:
            spec = f.read()
    return FaultPlan.from_json(spec)
