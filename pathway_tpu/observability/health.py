"""Liveness/readiness probe logic (kubernetes-style semantics).

- ``/healthz`` — the process is alive AND no executor thread is wedged: a
  worker whose event loop has neither finished nor heartbeat within the
  wedge timeout (a stuck collective, a deadlocked UDF, a hung connector)
  fails the probe so the orchestrator can restart the process. The
  executor heartbeats every tick AND every idle park cycle
  (``engine/executor.py``), so an idle-but-live stream stays healthy.
- ``/readyz`` — the dataflow is serving: every worker's sources are
  connected and its first frontier has advanced (at least one tick swept,
  or the run already finished — an empty batch run is trivially ready).
  Load balancers use this to gate traffic during startup/recovery replay.
"""

from __future__ import annotations

from typing import Any

__all__ = ["health_status", "ready_status"]


def health_status(
    stats_list: list[Any], wedge_timeout_s: float
) -> tuple[bool, dict]:
    import time

    now = time.time()
    wedged = []
    for s in stats_list:
        if s.finished:
            continue
        age = now - s.last_heartbeat
        if age > wedge_timeout_s:
            wedged.append({"heartbeat_age_s": round(age, 3)})
    if not stats_list:
        # server up before any executor registered: alive, not wedged
        return True, {"status": "ok", "workers": 0}
    if wedged:
        return False, {"status": "wedged", "wedged_workers": wedged}
    return True, {"status": "ok", "workers": len(stats_list)}


def ready_status(stats_list: list[Any]) -> tuple[bool, dict]:
    if not stats_list:
        return False, {"status": "starting", "reason": "no executor yet"}
    not_ready = []
    for s in stats_list:
        if not s.sources_connected:
            not_ready.append("sources not connected")
        elif s.ticks == 0 and not s.finished:
            not_ready.append("first frontier not advanced")
    if not_ready:
        return False, {"status": "starting", "reasons": not_ready}
    return True, {"status": "ready", "workers": len(stats_list)}
