"""Assemble per-process trace files into one cluster timeline.

A multi-process run writes ``<PATHWAY_TRACE_FILE>.p<N>`` per process
(``internals/tracing.py``), each with timestamps relative to that
process's own ``perf_counter`` origin — N disconnected files with
unaligned clocks. This module (behind ``pathway-tpu trace merge``) joins
them into one Chrome/Perfetto JSON:

- every part's relative timestamps are anchored to the unix clock via the
  ``trace.clock_sync`` metadata its tracer wrote (origin_unix_ns);
- cross-host clock skew is corrected with the per-peer offset estimates
  the cluster handshake ping measured (``ClusterComm
  ._measure_clock_offsets``): process p's own estimate of its offset to
  the reference process wins, the reference's estimate of p is the
  fallback, raw unix anchoring the last resort;
- ``pid`` fields are rewritten to the engine process id (with
  ``process_name`` metadata), so Perfetto shows one labeled track group
  per worker process;
- comm flow events (``ph: s``/``f``) keep their cluster-unique ids and
  now bind across the merged tracks — the arrows that attribute a
  collective stall on worker 3 from worker 0's timeline.

Merging parts from different runs is refused (unless forced): their flow
ids and clocks share nothing.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any

__all__ = ["discover_parts", "merge_trace", "wave_spans"]


def discover_parts(base: str) -> list[str]:
    """The trace files belonging to ``base`` (a PATHWAY_TRACE_FILE value):
    the ``base.p<N>`` per-process parts when present, else ``base``
    itself. Sorted by process suffix."""
    parts = glob.glob(glob.escape(base) + ".p*")

    def _suffix(p: str) -> int:
        try:
            return int(p.rsplit(".p", 1)[1])
        except (IndexError, ValueError):
            return 1 << 30

    parts = [p for p in parts if _suffix(p) < 1 << 30]
    if parts:
        return sorted(parts, key=_suffix)
    if os.path.exists(base):
        return [base]
    raise OSError(
        f"no trace parts found: neither {base!r} nor {base!r}.p<N> exist"
    )


def _load_part(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError(f"{path!r} is not a Chrome trace file")
    sync: dict[str, Any] = {}
    for ev in events:
        if ev.get("name") == "trace.clock_sync":
            sync = ev.get("args") or {}
            break
    return {"path": path, "events": events, "sync": sync}


def _offset_to_ref(part: dict, ref: dict) -> float:
    """Unix-clock correction (ns) to add to ``part``'s times to land on
    the reference process's clock."""
    my_id = str(part["sync"].get("process_id", ""))
    ref_id = str(ref["sync"].get("process_id", ""))
    if my_id == ref_id:
        return 0.0
    # own measurement: offsets[ref] = ref_clock - my_clock
    own = (part["sync"].get("clock_offsets") or {}).get(ref_id)
    if own:
        return float(own[0])
    # reference's measurement of us: offsets[me] = my_clock - ref_clock
    theirs = (ref["sync"].get("clock_offsets") or {}).get(my_id)
    if theirs:
        return -float(theirs[0])
    return 0.0  # same-host clocks (or no estimate): raw unix anchoring


def merge_trace(
    base: str,
    output: str | None = None,
    allow_mixed_runs: bool = False,
) -> tuple[str, dict]:
    """Merge ``base``'s parts; returns ``(output_path, report)``."""
    parts = [_load_part(p) for p in discover_parts(base)]
    run_ids = {
        p["sync"].get("run_id") for p in parts if p["sync"].get("run_id")
    }
    if len(run_ids) > 1 and not allow_mixed_runs:
        raise ValueError(
            f"trace parts carry different run ids {sorted(run_ids)} — "
            "either these are genuinely different runs, or a multi-host "
            "ensemble was spawned without exporting the same "
            "PATHWAY_RUN_ID on every machine (--allow-mixed-runs to "
            "merge anyway)"
        )
    ref = parts[0]
    merged: list[dict] = []
    n_flows = 0
    abs_times: list[float] = []
    prepared: list[tuple[dict, float, int]] = []
    for i, part in enumerate(parts):
        origin_ns = float(part["sync"].get("origin_unix_ns") or 0.0)
        corr_ns = _offset_to_ref(part, ref)
        origin_us = (origin_ns + corr_ns) / 1e3
        proc = part["sync"].get("process_id")
        proc = int(proc) if proc is not None else i
        prepared.append((part, origin_us, proc))
        for ev in part["events"]:
            if "ts" in ev and ev.get("ph") != "M":
                abs_times.append(origin_us + float(ev["ts"]))
    t0_us = min(abs_times) if abs_times else 0.0
    for part, origin_us, proc in prepared:
        merged.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": proc,
                "args": {"name": f"pathway_tpu process {proc}"},
            }
        )
        for ev in part["events"]:
            if ev.get("name") == "process_name" and ev.get("ph") == "M":
                continue  # replaced above with the process-id-keyed one
            out = dict(ev)
            out["pid"] = proc
            if "ts" in out and out.get("ph") != "M":
                out["ts"] = origin_us + float(out["ts"]) - t0_us
            if out.get("ph") in ("s", "t", "f"):
                n_flows += 1
            merged.append(out)
    merged.sort(key=lambda e: (e.get("ts", -1.0), e.get("pid", 0)))
    doc = {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "otherData": {
            "run_id": next(iter(run_ids)) if run_ids else None,
            "merged_from": [p["path"] for p in parts],
        },
    }
    out_path = output or f"{base}.merged.json"
    with open(out_path, "w") as f:
        json.dump(doc, f)
    return out_path, {
        "n_parts": len(parts),
        "n_events": len(merged),
        "n_flows": n_flows,
        "run_id": next(iter(run_ids)) if run_ids else None,
    }


def wave_spans(doc: dict, top_k: int = 10) -> list[dict]:
    """Offline critical-path view over a merged trace document: the
    ``wave.commit`` spans (engine/executor.py ``_async_commit_wave``),
    slowest first, each carrying its pid, epoch, holding worker, and
    critical stage from the span args. Complements the live
    ``pathway-tpu critpath`` report when all that's left of a run is
    its trace."""
    spans: list[dict] = []
    for ev in doc.get("traceEvents", []):
        if ev.get("name") != "wave.commit" or ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        spans.append(
            {
                "pid": ev.get("pid"),
                "ts_us": float(ev.get("ts", 0.0)),
                "dur_ms": float(ev.get("dur", 0.0)) / 1e3,
                "epoch": args.get("epoch"),
                "T": args.get("T"),
                "holder": args.get("holder"),
                "critical": args.get("critical"),
            }
        )
    spans.sort(key=lambda s: s["dur_ms"], reverse=True)
    return spans[: max(0, top_k)]
