"""ObservabilityHub — per-process registry + cluster-wide metrics roll-up.

Re-design of the reference's ProberStats aggregation (``src/engine/
graph.rs:521-563`` feeding per-process metrics ports,
``src/engine/http_server.rs:21-60``): each process registers the
``EngineStats`` of every worker it hosts plus its comm backend, and
serves them at ``/metrics``. Under multi-process sharding
(``parallel/cluster.py``), process 0 additionally scrapes every peer
process's ``/snapshot`` endpoint (JSON, same host book as the TCP mesh,
HTTP port ``base + process_id``) and serves the merged cluster view with
per-worker labels — operators point one Prometheus target at process 0
and see the whole fleet, including exchange-queue depth and frontier-lag
backpressure gauges.

The scrape direction (0 pulls peers) rather than push-over-collectives
keeps telemetry off the data plane: a peer stuck in a collective still
gets scraped, which is exactly when its frontier-lag gauge matters.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any

from .health import health_status, ready_status

__all__ = ["ObservabilityHub", "stats_snapshot"]

_SCRAPE_TIMEOUT_S = 2.0


def stats_snapshot(stats: Any, worker_id: int = 0) -> dict:
    """JSON-serializable snapshot of one worker's EngineStats — the unit
    shipped across processes and merged by process 0. Ages are computed
    at snapshot time so remote clocks never mix."""
    now = time.time()
    snap = {
        "worker": worker_id,
        "ticks": stats.ticks,
        "rows_total": stats.rows_total,
        "input_rows": stats.input_rows,
        "output_rows": stats.output_rows,
        "latency_ms": stats.latency_ms,
        "last_time": stats.last_time,
        "uptime_s": now - stats.started_at,
        "finished": stats.finished,
        "heartbeat_age_s": now - stats.last_heartbeat,
        "sources_connected": stats.sources_connected,
        "rows_by_node": dict(stats.rows_by_node),
        "exchange_rows_out": stats.exchange_rows_out,
        "exchange_rows_in": stats.exchange_rows_in,
        "exchange_batches": stats.exchange_batches,
        "tick_duration": stats.tick_duration.snapshot(),
        "latency_hist": stats.latency_hist.snapshot(),
        "node_time_hist": {
            label: h.snapshot()
            for label, h in list(stats.node_time_hist.items())
        },
    }
    if stats.latency_updated_at is not None:
        snap["latency_age_s"] = max(0.0, now - stats.latency_updated_at)
    return snap


class ObservabilityHub:
    def __init__(
        self,
        process_id: int = 0,
        n_processes: int = 1,
        peer_http: list[tuple[str, int]] | None = None,
        wedge_timeout_s: float = 30.0,
    ):
        self.process_id = process_id
        self.n_processes = n_processes
        #: (host, port) of every OTHER process's metrics server — scraped
        #: by process 0 for the merged view
        self.peer_http = peer_http or []
        self.wedge_timeout_s = wedge_timeout_s
        self._workers: dict[int, Any] = {}
        self._comms: list[Any] = []
        self._lock = threading.Lock()
        self.scrape_errors = 0

    @classmethod
    def from_config(cls, cfg: Any) -> "ObservabilityHub":
        peers: list[tuple[str, int]] = []
        base = cfg.monitoring_http_port
        # base 0 = ephemeral ports — peers' actual ports are unknowable,
        # so the roll-up degrades to local-only rather than scraping
        # garbage targets
        if cfg.processes > 1 and cfg.process_id == 0 and base:
            hosts = (
                [a.split(":")[0] if not a.startswith("[") else
                 a[1:].partition("]")[0] for a in cfg.addresses]
                if cfg.addresses
                else ["127.0.0.1"] * cfg.processes
            )
            peers = [
                (hosts[p], base + p)
                for p in range(cfg.processes)
                if p != cfg.process_id
            ]
            if (
                any(h not in ("127.0.0.1", "localhost") for h, _ in peers)
                and cfg.monitoring_http_host == "127.0.0.1"
            ):
                import warnings

                warnings.warn(
                    "cluster metrics roll-up: peers are on other hosts but "
                    "their monitoring servers bind loopback by default — "
                    "set PATHWAY_MONITORING_HTTP_HOST=0.0.0.0 on every "
                    "process or process 0's merged /metrics will miss them",
                    RuntimeWarning,
                )
        return cls(
            process_id=cfg.process_id,
            n_processes=cfg.processes,
            peer_http=peers,
            wedge_timeout_s=cfg.health_wedge_timeout_s,
        )

    # -- registration --------------------------------------------------

    def register_worker(self, worker_id: int, stats: Any) -> None:
        with self._lock:
            self._workers[worker_id] = stats

    def register_comm(self, comm: Any) -> None:
        with self._lock:
            self._comms.append(comm)

    @property
    def worker_stats(self) -> list[Any]:
        with self._lock:
            return [self._workers[w] for w in sorted(self._workers)]

    # -- snapshots -----------------------------------------------------

    def local_snapshots(self) -> list[dict]:
        with self._lock:
            items = sorted(self._workers.items())
        return [stats_snapshot(s, w) for w, s in items]

    def comm_snapshot(self) -> dict[str, float]:
        out: dict[str, float] = {}
        with self._lock:
            comms = list(self._comms)
        for comm in comms:
            fn = getattr(comm, "comm_stats", None)
            if fn is None:
                continue
            try:
                for k, v in fn().items():
                    out[k] = out.get(k, 0) + v
            except Exception:
                # telemetry must not fail the run it observes
                pass
        return out

    @staticmethod
    def _local_trace_dropped() -> int | None:
        """This process's tracer ring-buffer drop count (None = tracing
        off) — shipped in /snapshot so the cluster roll-up can report a
        PEER's truncated timeline, not just its own."""
        from ..internals.tracing import get_tracer

        tracer = get_tracer()
        return tracer._dropped if tracer is not None else None

    def snapshot_document(self) -> dict:
        """The /snapshot payload peers serve to process 0."""
        return {
            "process_id": self.process_id,
            "workers": self.local_snapshots(),
            "comm": self.comm_snapshot(),
            "trace_dropped": self._local_trace_dropped(),
        }

    def cluster_snapshots(
        self,
    ) -> tuple[list[dict], dict[str, dict], dict[str, int]]:
        """Local snapshots plus every reachable peer's; comm stats keyed
        by process id; tracer drops per reporting process (a transiently
        unreachable peer is MISSING from the dict, so its metrics series
        disappears for a scrape instead of decreasing a summed counter).
        Peers are scraped concurrently so N hung peers cost
        one timeout, not N (a partial outage is exactly when the merged
        view must still answer inside Prometheus's scrape deadline);
        unreachable peers count in ``scrape_errors`` and the view stays
        partial rather than failing."""
        snapshots = self.local_snapshots()
        comm_stats = {str(self.process_id): self.comm_snapshot()}
        trace_dropped: dict[str, int] = {}
        local_dropped = self._local_trace_dropped()
        if local_dropped is not None:
            trace_dropped[str(self.process_id)] = local_dropped
        results: list[dict | None] = [None] * len(self.peer_http)

        def fetch(i: int, host: str, port: int) -> None:
            results[i] = self._scrape_peer(host, port)

        threads = [
            threading.Thread(target=fetch, args=(i, h, p), daemon=True)
            for i, (h, p) in enumerate(self.peer_http)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + _SCRAPE_TIMEOUT_S + 0.5
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        for doc in results:
            if doc is None:
                self.scrape_errors += 1
                continue
            snapshots.extend(doc.get("workers", []))
            comm_stats[str(doc.get("process_id", "?"))] = doc.get("comm", {})
            peer_dropped = doc.get("trace_dropped")
            if peer_dropped is not None:
                trace_dropped[str(doc.get("process_id", "?"))] = int(
                    peer_dropped
                )
        snapshots.sort(key=lambda s: s.get("worker", 0))
        return snapshots, comm_stats, trace_dropped

    @staticmethod
    def _scrape_peer(host: str, port: int) -> dict | None:
        import urllib.request

        try:
            with urllib.request.urlopen(
                f"http://{host}:{port}/snapshot", timeout=_SCRAPE_TIMEOUT_S
            ) as r:
                return json.loads(r.read().decode())
        except Exception:
            return None

    # -- rendering + probes --------------------------------------------

    def render_metrics(self) -> str:
        from .prometheus import render_snapshots

        trace_dropped: int | dict[str, int] | None
        if self.peer_http:
            snapshots, comm_stats, dropped_by_proc = self.cluster_snapshots()
            # per-process labels, like the comm gauges: series identity
            # stays stable when a peer scrape transiently fails
            trace_dropped = dropped_by_proc or None
        else:
            snapshots = self.local_snapshots()
            comm = self.comm_snapshot()
            comm_stats = {str(self.process_id): comm} if comm else {}
            trace_dropped = self._local_trace_dropped()
        # label by TOPOLOGY, not by how many snapshots this scrape got:
        # in cluster mode a transient peer outage must not flip series
        # between labeled and unlabeled (that forks Prometheus series and
        # breaks rate() continuity)
        cluster = (
            self.n_processes > 1
            or bool(self.peer_http)
            or len(self._workers) > 1
        )
        # tracer drop visibility: a truncated trace window — local OR on a
        # scraped peer — must be distinguishable from a quiet one (0
        # renders too, as the explicit "nothing dropped" signal); None
        # only when no process traces
        return render_snapshots(
            snapshots,
            comm_stats,
            scrape_errors=self.scrape_errors,
            worker_labels=True if cluster else None,
            supervisor=self._supervisor_snapshot(),
            trace_dropped=trace_dropped,
        )

    @staticmethod
    def _supervisor_snapshot() -> dict | None:
        """Self-healing metrics: restart generation + reason, stamped into
        the child environment by ``spawn --supervise``, plus the armed
        fault plan's injection count. None when neither applies (keeps the
        single-process exposition identical to the seed's)."""
        import os

        restarts = os.environ.get("PATHWAY_RESTART_COUNT")
        supervised = os.environ.get("PATHWAY_SUPERVISED")
        flight_dumps = os.environ.get("PATHWAY_FLIGHT_DUMPS")
        from ..chaos import injector as _chaos

        armed = _chaos.ARMED
        try:  # elastic boots reshard in-process before the engine mounts
            from ..rescale import stats as _rescale_stats

            rescales = _rescale_stats()
        except Exception:  # pragma: no cover — import cycle safety net
            rescales = {"total": 0}
        if (
            not supervised
            and restarts is None
            and armed is None
            and flight_dumps is None
        ):
            if not rescales["total"]:
                return None
            # an elastic rescale happened but nothing is supervised —
            # surface ONLY the rescale counters (no pathway_restarts_total
            # outside supervision)
            return {
                "rescales": int(rescales["total"]),
                "rescale_duration_s": float(rescales["duration_s"]),
            }
        doc: dict = {
            "restarts": int(restarts or 0),
            "reason": os.environ.get("PATHWAY_LAST_RESTART_REASON"),
        }
        if armed is not None:
            doc["chaos_injections"] = armed.injections_total
        if flight_dumps is not None:
            try:
                doc["flight_dumps"] = int(flight_dumps)
            except ValueError:
                pass
        if rescales["total"]:
            doc["rescales"] = int(rescales["total"])
            doc["rescale_duration_s"] = float(rescales["duration_s"])
        return doc

    def health(self) -> tuple[bool, dict]:
        return health_status(self.worker_stats, self.wedge_timeout_s)

    def ready(self) -> tuple[bool, dict]:
        return ready_status(self.worker_stats)
