"""ObservabilityHub — per-process registry + cluster-wide metrics roll-up.

Re-design of the reference's ProberStats aggregation (``src/engine/
graph.rs:521-563`` feeding per-process metrics ports,
``src/engine/http_server.rs:21-60``): each process registers the
``EngineStats`` of every worker it hosts plus its comm backend, and
serves them at ``/metrics``. Under multi-process sharding
(``parallel/cluster.py``), process 0 additionally scrapes every peer
process's ``/snapshot`` endpoint (JSON, same host book as the TCP mesh,
HTTP port ``base + process_id``) and serves the merged cluster view with
per-worker labels — operators point one Prometheus target at process 0
and see the whole fleet, including exchange-queue depth and frontier-lag
backpressure gauges.

The scrape direction (0 pulls peers) rather than push-over-collectives
keeps telemetry off the data plane: a peer stuck in a collective still
gets scraped, which is exactly when its frontier-lag gauge matters.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any

from .health import health_status, ready_status

__all__ = ["ObservabilityHub", "stats_snapshot"]

_SCRAPE_TIMEOUT_S = 2.0


def _drop_empty(stats: dict[str, dict]) -> dict[str, dict] | None:
    """Per-process stat maps with no live entries render as NO metric
    families (byte-identical exposition when a concern is disabled)."""
    out = {k: v for k, v in stats.items() if v}
    return out or None


def stats_snapshot(stats: Any, worker_id: int = 0) -> dict:
    """JSON-serializable snapshot of one worker's EngineStats — the unit
    shipped across processes and merged by process 0. Ages are computed
    at snapshot time so remote clocks never mix."""
    now = time.time()
    snap = {
        "worker": worker_id,
        "ticks": stats.ticks,
        "rows_total": stats.rows_total,
        "input_rows": stats.input_rows,
        "output_rows": stats.output_rows,
        "latency_ms": stats.latency_ms,
        "last_time": stats.last_time,
        "uptime_s": now - stats.started_at,
        "finished": stats.finished,
        "heartbeat_age_s": now - stats.last_heartbeat,
        "sources_connected": stats.sources_connected,
        "rows_by_node": dict(stats.rows_by_node),
        "exchange_rows_out": stats.exchange_rows_out,
        "exchange_rows_in": stats.exchange_rows_in,
        "exchange_batches": stats.exchange_batches,
        "tick_duration": stats.tick_duration.snapshot(),
        "latency_hist": stats.latency_hist.snapshot(),
        "e2e_latency_hist": stats.e2e_latency_hist.snapshot()
        if getattr(stats, "e2e_latency_hist", None) is not None
        else None,
        "e2e_ms": getattr(stats, "e2e_ms", None),
        "node_time_hist": {
            label: h.snapshot()
            for label, h in list(stats.node_time_hist.items())
        },
        # staged ingest→emit decomposition (executor.E2E_STAGES)
        "stage_hists": {
            name: h.snapshot()
            for name, h in (getattr(stats, "stage_hists", None) or {}).items()
        },
        # commit-wave critical path (observability/critpath.py)
        "waves_total": getattr(stats, "waves_total", 0),
        "wave_duration": stats.wave_duration.snapshot()
        if getattr(stats, "wave_duration", None) is not None
        else None,
        "wave_stage_ns": dict(getattr(stats, "wave_stage_ns", None) or {}),
        "wave_held_total": dict(
            getattr(stats, "wave_held_total", None) or {}
        ),
        "waves": stats._waves.snapshot()
        if getattr(stats, "_waves", None) is not None
        else None,
        # key-group load sketch (observability/keyload.py)
        "keyload": stats.keyload.snapshot()
        if getattr(stats, "keyload", None) is not None
        else None,
    }
    if stats.latency_updated_at is not None:
        snap["latency_age_s"] = max(0.0, now - stats.latency_updated_at)
    return snap


class ObservabilityHub:
    def __init__(
        self,
        process_id: int = 0,
        n_processes: int = 1,
        peer_http: list[tuple[str, int]] | None = None,
        wedge_timeout_s: float = 30.0,
    ):
        self.process_id = process_id
        self.n_processes = n_processes
        #: (host, port) of every OTHER process's metrics server — scraped
        #: by process 0 for the merged view
        self.peer_http = peer_http or []
        self.wedge_timeout_s = wedge_timeout_s
        self._workers: dict[int, Any] = {}
        self._comms: list[Any] = []
        self._lock = threading.Lock()
        self.scrape_errors = 0
        #: windowed signal plane (observability/timeseries.py) — started
        #: by start_signals() alongside the metrics endpoint; None until
        #: then (tests building bare hubs pay nothing)
        self.signals_plane: Any = None
        #: last successful peer scrape per peer index: (unix time, doc).
        #: A peer that stops answering is reported as STALE (last-seen
        #: age per worker) instead of silently vanishing from the merged
        #: view — the difference between "fleet shrank" and "fleet lost
        #: a member" on one scrape.
        self._peer_cache: dict[int, tuple[float, dict]] = {}
        #: same discipline for the windowed /query roll-up: a peer whose
        #: /query scrape fails is served from this cache WITH its workers
        #: named in the merged document's ``stale_workers`` — consumers
        #: that act on the numbers (the autoscaler's decider) refuse
        #: stale-marked documents rather than deciding from frozen values
        self._query_cache: dict[int, tuple[float, dict]] = {}
        #: and for the /profile roll-up: a dead peer's flamegraph serves
        #: from its last good scrape with ``stale`` ages on the merged doc
        self._profile_cache: dict[int, tuple[float, dict]] = {}
        #: per-process sampling profiler (observability/profiler.py) —
        #: started with the signals plane, stopped in close(); None when
        #: PATHWAY_PROFILE=0 or before start_signals()
        self.profiler: Any = None

    @classmethod
    def from_config(cls, cfg: Any) -> "ObservabilityHub":
        peers: list[tuple[str, int]] = []
        base = cfg.monitoring_http_port
        # base 0 = ephemeral ports — peers' actual ports are unknowable,
        # so the roll-up degrades to local-only rather than scraping
        # garbage targets
        if cfg.processes > 1 and cfg.process_id == 0 and base:
            hosts = (
                [a.split(":")[0] if not a.startswith("[") else
                 a[1:].partition("]")[0] for a in cfg.addresses]
                if cfg.addresses
                else ["127.0.0.1"] * cfg.processes
            )
            peers = [
                (hosts[p], base + p)
                for p in range(cfg.processes)
                if p != cfg.process_id
            ]
            if (
                any(h not in ("127.0.0.1", "localhost") for h, _ in peers)
                and cfg.monitoring_http_host == "127.0.0.1"
            ):
                import warnings

                warnings.warn(
                    "cluster metrics roll-up: peers are on other hosts but "
                    "their monitoring servers bind loopback by default — "
                    "set PATHWAY_MONITORING_HTTP_HOST=0.0.0.0 on every "
                    "process or process 0's merged /metrics will miss them",
                    RuntimeWarning,
                )
        return cls(
            process_id=cfg.process_id,
            n_processes=cfg.processes,
            peer_http=peers,
            wedge_timeout_s=cfg.health_wedge_timeout_s,
        )

    # -- registration --------------------------------------------------

    def register_worker(self, worker_id: int, stats: Any) -> None:
        with self._lock:
            self._workers[worker_id] = stats

    def register_comm(self, comm: Any) -> None:
        with self._lock:
            self._comms.append(comm)

    # -- signals plane (windowed time-series + SLO rules) --------------

    def start_signals(
        self,
        sample_s: float | None = None,
        window_s: float | None = None,
        slo_rules: str | None = None,
    ) -> Any:
        """Start the sampler thread + SLO engine over this hub's workers
        (``PATHWAY_SIGNALS_SAMPLE_S`` / ``PATHWAY_SIGNALS_WINDOW_S`` /
        ``PATHWAY_SLO_RULES`` fill unset arguments). Idempotent."""
        if self.signals_plane is not None:
            return self.signals_plane
        import os

        from .slo import SloEngine, load_rules
        from .timeseries import (
            DEFAULT_SAMPLE_S,
            DEFAULT_WINDOW_S,
            SignalsPlane,
        )

        if sample_s is None:
            try:
                sample_s = float(
                    os.environ.get("PATHWAY_SIGNALS_SAMPLE_S", "")
                    or DEFAULT_SAMPLE_S
                )
            except ValueError:
                sample_s = DEFAULT_SAMPLE_S
        if window_s is None:
            try:
                window_s = float(
                    os.environ.get("PATHWAY_SIGNALS_WINDOW_S", "")
                    or DEFAULT_WINDOW_S
                )
            except ValueError:
                window_s = DEFAULT_WINDOW_S
        if slo_rules is None:
            slo_rules = os.environ.get("PATHWAY_SLO_RULES")
        try:
            rules = load_rules(slo_rules)
        except ValueError as e:
            import warnings

            # a typo'd rules file must be loud — but telemetry still must
            # not abort the run it observes
            warnings.warn(str(e), RuntimeWarning)
            rules = []
        engine = SloEngine(
            rules, default_window_s=window_s, process_id=self.process_id
        )
        self.signals_plane = SignalsPlane(
            self, sample_s=sample_s, window_s=window_s, slo_engine=engine
        ).start()
        self.start_profiler()
        return self.signals_plane

    def start_profiler(self) -> Any:
        """Start the per-process sampling profiler (idempotent; no-op
        with ``PATHWAY_PROFILE=0`` — zero threads, zero series)."""
        if self.profiler is not None:
            return self.profiler
        from . import profiler as _profiler

        if not _profiler.enabled():
            return None
        try:
            self.profiler = _profiler.Profiler(
                process_id=self.process_id
            ).start()
        except Exception:
            # telemetry must not fail the run it observes
            self.profiler = None
        return self.profiler

    def close(self) -> None:
        if self.signals_plane is not None:
            self.signals_plane.stop()
        if self.profiler is not None:
            self.profiler.stop()
            self.profiler = None

    @property
    def worker_stats(self) -> list[Any]:
        with self._lock:
            return [self._workers[w] for w in sorted(self._workers)]

    # -- snapshots -----------------------------------------------------

    def local_snapshots(self) -> list[dict]:
        with self._lock:
            items = sorted(self._workers.items())
        return [stats_snapshot(s, w) for w, s in items]

    def comm_snapshot(self) -> dict[str, float]:
        out: dict[str, float] = {}
        with self._lock:
            comms = list(self._comms)
        for comm in comms:
            fn = getattr(comm, "comm_stats", None)
            if fn is None:
                continue
            try:
                for k, v in fn().items():
                    out[k] = out.get(k, 0) + v
            except Exception:
                # telemetry must not fail the run it observes
                pass
        return out

    @staticmethod
    def _local_trace_dropped() -> int | None:
        """This process's tracer ring-buffer drop count (None = tracing
        off) — shipped in /snapshot so the cluster roll-up can report a
        PEER's truncated timeline, not just its own."""
        from ..internals.tracing import get_tracer

        tracer = get_tracer()
        return tracer._dropped if tracer is not None else None

    @staticmethod
    def memory_stats_snapshot() -> dict[str, float]:
        """This process's memory/spill/key-registry gauges (RSS, state
        budget occupancy, spill counters, registry tiers) — shipped in
        /snapshot like the comm gauges so the roll-up renders them per
        process."""
        try:
            from ..engine.spill import memory_snapshot

            return memory_snapshot()
        except Exception:
            # telemetry must not fail the run it observes
            return {}

    @staticmethod
    def sink_stats_snapshot() -> dict[str, dict[str, float]]:
        """This process's output-plane sink counters (delivered / retries
        / DLQ / breaker / queue depth / delivery lag per sink —
        io/delivery.py) — shipped in /snapshot like the memory gauges."""
        try:
            from ..io.delivery import sink_stats_snapshot

            return sink_stats_snapshot()
        except Exception:
            # telemetry must not fail the run it observes
            return {}

    @staticmethod
    def udf_stats_snapshot() -> dict[str, float]:
        """This process's UDF execution-path counters (lifted / traced /
        per-row rows — internals/expression_compiler.py) — which lane
        user ``pw.apply`` callables landed on, so a slow pipeline reads
        as "N rows ran per-row Python" instead of a guess."""
        try:
            from ..internals.expression_compiler import udf_stats_snapshot

            return udf_stats_snapshot()
        except Exception:
            # telemetry must not fail the run it observes
            return {}

    @staticmethod
    def fusion_stats_snapshot() -> dict[str, float]:
        """This process's kernel-fusion counters (chains compiled, member
        operators fused, per-batch fallbacks — engine/fusion.py), so a
        pipeline that silently fell back to per-node dispatch reads as
        "N batches fell back" instead of a mystery slowdown."""
        try:
            from ..engine.fusion import fusion_stats_snapshot

            return fusion_stats_snapshot()
        except Exception:
            # telemetry must not fail the run it observes
            return {}

    @staticmethod
    def serve_stats_snapshot() -> dict[str, float]:
        """This process's serve-plane counters + live gauges (admitted /
        rejected / degraded queries, scatter posts, in-flight, queue
        depth — serve/stats.py), so an overloaded or shard-degraded
        serving cluster reads as numbers, not client anecdotes. Empty
        until the serve plane ran, keeping non-serving expositions
        byte-identical."""
        try:
            from ..serve.stats import serve_stats_snapshot

            return serve_stats_snapshot()
        except Exception:
            # telemetry must not fail the run it observes
            return {}

    @staticmethod
    def ingest_stats_snapshot() -> dict[str, Any]:
        """This process's staged ingest cost split (parse | hash | delta
        seconds + rows/flushes — io/python.INGEST_STAGE_STATS), the
        measured form of ROADMAP item 2's "hashing + delta build ~60% of
        wall". Empty until a connector flushed (or with
        ``PATHWAY_PROFILE=0``), so expositions stay byte-identical."""
        try:
            from ..io.python import INGEST_STAGE_STATS as s
            from .profiler import enabled as _prof_enabled

            # re-check the kill switch at read time: the module-global
            # counters survive a same-process PATHWAY_PROFILE flip
            if not _prof_enabled():
                return {}
            if not s["flushes"] and not s["rows"]:
                return {}
            out: dict[str, Any] = {
                "parse_s": round(s["parse_ns"] / 1e9, 6),
                "hash_s": round(s["hash_ns"] / 1e9, 6),
                "delta_s": round(s["delta_ns"] / 1e9, 6),
                "rows_total": float(s["rows"]),
                "flushes_total": float(s["flushes"]),
            }
            # per-connector stage split (io/python.INGEST_CONNECTOR_STATS)
            # so the bottleneck connector is nameable cluster-wide, not
            # just "ingest is slow somewhere"
            from ..io.python import INGEST_CONNECTOR_STATS as per_conn

            conns = {
                name: {
                    "parse_s": round(c["parse_ns"] / 1e9, 6),
                    "hash_s": round(c["hash_ns"] / 1e9, 6),
                    "delta_s": round(c["delta_ns"] / 1e9, 6),
                    "rows_total": float(c["rows"]),
                    "flushes_total": float(c["flushes"]),
                }
                for name, c in sorted(per_conn.items())
                if c["rows"] or c["flushes"]
            }
            if conns:
                out["connectors"] = conns
            return out
        except Exception:
            # telemetry must not fail the run it observes
            return {}

    def profile_stats_snapshot(self) -> dict[str, float]:
        """This process's profiler scalars (samples, distinct frames,
        top-frame/op-tagged shares — the ``pathway_profile_*`` families
        and ``profile.*`` series). Empty when the profiler is off."""
        try:
            if self.profiler is None:
                return {}
            return self.profiler.metrics_snapshot()
        except Exception:
            # telemetry must not fail the run it observes
            return {}

    def snapshot_document(self) -> dict:
        """The /snapshot payload peers serve to process 0."""
        return {
            "process_id": self.process_id,
            "workers": self.local_snapshots(),
            "comm": self.comm_snapshot(),
            "memory": self.memory_stats_snapshot(),
            "sinks": self.sink_stats_snapshot(),
            "udf": self.udf_stats_snapshot(),
            "fusion": self.fusion_stats_snapshot(),
            "serve": self.serve_stats_snapshot(),
            "ingest": self.ingest_stats_snapshot(),
            "profile": self.profile_stats_snapshot(),
            "trace_dropped": self._local_trace_dropped(),
        }

    def cluster_snapshots(
        self,
    ) -> tuple[
        list[dict],
        dict[str, dict],
        dict[str, int],
        dict[str, float],
        dict[str, dict],
        dict[str, dict],
        dict[str, dict],
        dict[str, dict],
        dict[str, dict],
        dict[str, dict],
        dict[str, dict],
    ]:
        """Local snapshots plus every reachable peer's; comm stats keyed
        by process id; tracer drops per reporting process (a transiently
        unreachable peer is MISSING from the dict, so its metrics series
        disappears for a scrape instead of decreasing a summed counter).
        Peers are scraped concurrently so N hung peers cost
        one timeout, not N (a partial outage is exactly when the merged
        view must still answer inside Prometheus's scrape deadline);
        unreachable peers count in ``scrape_errors`` and the view stays
        partial rather than failing. The fourth element maps worker id →
        last-seen age (s) for workers whose peer stopped answering but
        answered before — rendered as ``pathway_worker_last_seen_seconds``
        so a dead peer reads as STALE, not as a smaller fleet."""
        snapshots = self.local_snapshots()
        comm_stats = {str(self.process_id): self.comm_snapshot()}
        memory_stats = {str(self.process_id): self.memory_stats_snapshot()}
        sink_stats = {str(self.process_id): self.sink_stats_snapshot()}
        udf_stats = {str(self.process_id): self.udf_stats_snapshot()}
        fusion_stats = {str(self.process_id): self.fusion_stats_snapshot()}
        serve_stats = {str(self.process_id): self.serve_stats_snapshot()}
        ingest_stats = {str(self.process_id): self.ingest_stats_snapshot()}
        profile_stats = {str(self.process_id): self.profile_stats_snapshot()}
        trace_dropped: dict[str, int] = {}
        stale: dict[str, float] = {}
        local_dropped = self._local_trace_dropped()
        if local_dropped is not None:
            trace_dropped[str(self.process_id)] = local_dropped
        results: list[dict | None] = [None] * len(self.peer_http)

        def fetch(i: int, host: str, port: int) -> None:
            results[i] = self._scrape_peer(host, port)

        threads = [
            threading.Thread(target=fetch, args=(i, h, p), daemon=True)
            for i, (h, p) in enumerate(self.peer_http)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + _SCRAPE_TIMEOUT_S + 0.5
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        now = time.time()
        for i, doc in enumerate(results):
            if doc is None:
                self.scrape_errors += 1
                cached = self._peer_cache.get(i)
                if cached is not None:
                    seen_at, seen_doc = cached
                    for w in seen_doc.get("workers", []):
                        stale[str(w.get("worker", "?"))] = now - seen_at
                continue
            self._peer_cache[i] = (now, doc)
            snapshots.extend(doc.get("workers", []))
            comm_stats[str(doc.get("process_id", "?"))] = doc.get("comm", {})
            peer_mem = doc.get("memory")
            if peer_mem:
                memory_stats[str(doc.get("process_id", "?"))] = peer_mem
            peer_sinks = doc.get("sinks")
            if peer_sinks:
                sink_stats[str(doc.get("process_id", "?"))] = peer_sinks
            peer_udf = doc.get("udf")
            if peer_udf:
                udf_stats[str(doc.get("process_id", "?"))] = peer_udf
            peer_fusion = doc.get("fusion")
            if peer_fusion:
                fusion_stats[str(doc.get("process_id", "?"))] = peer_fusion
            peer_serve = doc.get("serve")
            if peer_serve:
                serve_stats[str(doc.get("process_id", "?"))] = peer_serve
            peer_ingest = doc.get("ingest")
            if peer_ingest:
                ingest_stats[str(doc.get("process_id", "?"))] = peer_ingest
            peer_profile = doc.get("profile")
            if peer_profile:
                profile_stats[str(doc.get("process_id", "?"))] = peer_profile
            peer_dropped = doc.get("trace_dropped")
            if peer_dropped is not None:
                trace_dropped[str(doc.get("process_id", "?"))] = int(
                    peer_dropped
                )
        snapshots.sort(key=lambda s: s.get("worker", 0))
        return (
            snapshots, comm_stats, trace_dropped, stale, memory_stats,
            sink_stats, udf_stats, fusion_stats, ingest_stats, profile_stats,
            serve_stats,
        )

    @staticmethod
    def _scrape_peer(host: str, port: int) -> dict | None:
        import urllib.request

        try:
            with urllib.request.urlopen(
                f"http://{host}:{port}/snapshot", timeout=_SCRAPE_TIMEOUT_S
            ) as r:
                return json.loads(r.read().decode())
        except Exception:
            return None

    @staticmethod
    def _scrape_peer_path(host: str, port: int, path: str) -> dict | None:
        import urllib.request

        try:
            with urllib.request.urlopen(
                f"http://{host}:{port}{path}", timeout=_SCRAPE_TIMEOUT_S
            ) as r:
                return json.loads(r.read().decode())
        except Exception:
            return None

    def _scrape_peers_raw(self, path: str) -> list[dict | None]:
        """Concurrently fetch ``path`` from every peer (same discipline
        as cluster_snapshots: N hung peers cost one timeout). The result
        is indexed like ``peer_http`` — None marks a failed scrape."""
        results: list[dict | None] = [None] * len(self.peer_http)

        def fetch(i: int, host: str, port: int) -> None:
            results[i] = self._scrape_peer_path(host, port, path)

        threads = [
            threading.Thread(target=fetch, args=(i, h, p), daemon=True)
            for i, (h, p) in enumerate(self.peer_http)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + _SCRAPE_TIMEOUT_S + 0.5
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        return results

    def _scrape_peers_path(self, path: str) -> list[dict]:
        return [d for d in self._scrape_peers_raw(path) if d is not None]

    # -- windowed signal queries (/query, /attribution, /alerts) -------

    def local_query_document(self) -> dict:
        """This process's windowed-signals view: per-worker rates +
        latency percentiles over the window, comm derivations, operator
        attribution, and the alert log — the ``/query`` payload a peer
        serves, and the exact document the autoscaler will consume."""
        plane = self.signals_plane
        doc: dict = {
            "process_id": self.process_id,
            "t": time.time(),
            "signals": plane is not None,
        }
        if plane is None:
            return doc
        sig, w = plane.signals, plane.window_s
        doc["sample_s"] = plane.sample_s
        doc["window_s"] = w
        doc["samples"] = plane.samples_taken
        workers: dict[str, dict] = {}
        for wid in sig.store.workers():
            entry: dict = {
                "tick_rate": sig.rate("engine_ticks", w, wid),
                "row_rate": sig.rate("rows_total", w, wid),
                "input_rate": sig.rate("input_rows", w, wid),
                "output_rate": sig.rate("output_rows", w, wid),
                "last_time": sig.last("last_time", wid),
                "latency_ms": sig.last("latency_ms", wid),
                "frontier_lag_ms": sig.last("frontier_lag_ms", wid),
            }
            for q in ("p50", "p95", "p99"):
                entry[f"tick_{q}_ms"] = sig.eval(
                    f"{q}(tick_duration)", w, wid
                )
                entry[f"e2e_{q}_ms"] = sig.eval(f"{q}(e2e_latency)", w, wid)
            # the headline windowed series, raw points included so `top`
            # and the autoscaler see trends, not just scalars
            entry["series"] = {
                "frontier_lag_ms": sig.store.points(
                    "frontier_lag_ms", wid, w
                ),
            }
            workers[str(wid)] = entry
        doc["workers"] = workers
        comm: dict[str, float | None] = {}
        for metric in sig.store.metrics(None):
            if not metric.startswith("comm."):
                continue
            key = metric[len("comm."):]
            comm[key] = sig.last(metric, None)
            if key.endswith(("_total", "_sent", "_received")):
                comm[key + "_rate"] = sig.rate(metric, w, None)
        sent_rate = sig.rate("comm.cluster_bytes_sent", w, None)
        if sent_rate is not None:
            comm["send_mb_per_sec"] = round(sent_rate / 1e6, 3)
        if "send_queue_depth" in comm:
            comm["send_queue_depth_series"] = sig.store.points(
                "comm.send_queue_depth", None, w
            )
        doc["comm"] = comm
        doc["memory"] = self.memory_stats_snapshot()
        doc["sinks"] = self.sink_stats_snapshot()
        doc["udf"] = self.udf_stats_snapshot()
        doc["fusion"] = self.fusion_stats_snapshot()
        doc["serve"] = self.serve_stats_snapshot()
        doc["ingest"] = self.ingest_stats_snapshot()
        doc["profile"] = self.profile_stats_snapshot()
        doc["waves"] = self._waves_document()
        doc["keyload"] = self._keyload_document()
        from .attribution import attribution_document

        doc["attribution"] = attribution_document(sig, w)
        doc["alerts"] = (
            plane.slo.alerts.document()
            if plane.slo is not None
            else {"active": [], "history": [], "fired_total": {}}
        )
        sup = self._supervisor_snapshot()
        if sup is not None:
            doc["supervisor"] = sup
        auto = self._autoscale_snapshot()
        if auto is not None:
            doc["autoscale"] = auto
        return doc

    def _waves_document(self) -> dict | None:
        """Process-level commit-wave merge: every local worker's
        WaveRecorder ring folded into one ``waves`` document (the
        per-epoch merge elects the holder by majority — see
        observability/critpath.py)."""
        from .critpath import merge_worker_waves

        with self._lock:
            items = sorted(self._workers.items())
        snaps = {
            str(w): s._waves.snapshot()
            for w, s in items
            if getattr(s, "_waves", None) is not None
        }
        if not snaps:
            return None
        return merge_worker_waves(snaps)

    def _keyload_document(self) -> dict | None:
        """Process-level key-group load merge over local workers'
        sketches (observability/keyload.py)."""
        from .keyload import merge_snapshots

        with self._lock:
            items = sorted(self._workers.items())
        return merge_snapshots(
            [
                s.keyload.snapshot()
                for _, s in items
                if getattr(s, "keyload", None) is not None
            ]
        )

    def query_document(self) -> dict:
        """The merged ``/query`` view: process 0 scrapes every peer's
        ``/query`` and merges — same pull direction as the /snapshot
        roll-up, so a peer stuck in a collective still gets queried.
        Adds cross-worker frontier lag (worker's logical time vs the most
        advanced worker's) which no single process can compute alone."""
        local = self.local_query_document()
        if not self.peer_http:
            merged = dict(local)
            merged["processes"] = [self.process_id]
            merged["stale_workers"] = {}
            self._add_cluster_lag(merged)
            return merged
        results = self._scrape_peers_raw("/query")
        now = time.time()
        stale_workers: dict[str, float | None] = {}
        peer_ids = [
            p for p in range(self.n_processes) if p != self.process_id
        ]
        peer_docs: list[dict] = []
        for i, doc in enumerate(results):
            if doc is None:
                self.scrape_errors += 1
                cached = self._query_cache.get(i)
                if cached is None:
                    # never scraped successfully: we cannot serve its
                    # workers, but the peer must still be VISIBLE as
                    # missing — an empty stale_workers here would let the
                    # decider act on a partial view of the cluster
                    pid = peer_ids[i] if i < len(peer_ids) else i
                    stale_workers[f"process-{pid}"] = None
                    continue
                # serve the last good scrape, but MARK every worker it
                # carries: a consumer acting on the merged numbers (the
                # autoscale decider) must see "this value is frozen",
                # not a plausible-looking live reading
                seen_at, cached_doc = cached
                age = now - seen_at
                doc = dict(cached_doc)
                doc["workers"] = {
                    wid: {**w, "stale_s": round(age, 3)}
                    for wid, w in (cached_doc.get("workers") or {}).items()
                }
                for wid in doc["workers"]:
                    stale_workers[str(wid)] = round(age, 3)
            else:
                self._query_cache[i] = (now, doc)
            peer_docs.append(doc)
        merged = dict(local)
        merged["stale_workers"] = stale_workers
        merged["workers"] = dict(local.get("workers", {}))
        merged["comm"] = {str(self.process_id): local.get("comm", {})}
        merged["memory"] = {str(self.process_id): local.get("memory", {})}
        merged["sinks"] = {str(self.process_id): local.get("sinks", {})}
        merged["udf"] = {str(self.process_id): local.get("udf", {})}
        merged["fusion"] = {str(self.process_id): local.get("fusion", {})}
        merged["serve"] = {str(self.process_id): local.get("serve", {})}
        merged["ingest"] = {str(self.process_id): local.get("ingest", {})}
        merged["profile"] = {str(self.process_id): local.get("profile", {})}
        merged["alerts"] = {
            "active": list(local.get("alerts", {}).get("active", [])),
            "history": list(local.get("alerts", {}).get("history", [])),
            "fired_total": dict(
                local.get("alerts", {}).get("fired_total", {})
            ),
        }
        processes = [self.process_id]
        attributions = [local.get("attribution")]
        for doc in peer_docs:
            pid = doc.get("process_id", "?")
            processes.append(pid)
            merged["workers"].update(doc.get("workers", {}))
            merged["comm"][str(pid)] = doc.get("comm", {})
            merged["memory"][str(pid)] = doc.get("memory", {})
            merged["sinks"][str(pid)] = doc.get("sinks", {})
            merged["udf"][str(pid)] = doc.get("udf", {})
            merged["fusion"][str(pid)] = doc.get("fusion", {})
            merged["serve"][str(pid)] = doc.get("serve", {})
            merged["ingest"][str(pid)] = doc.get("ingest", {})
            merged["profile"][str(pid)] = doc.get("profile", {})
            alerts = doc.get("alerts", {})
            merged["alerts"]["active"].extend(alerts.get("active", []))
            merged["alerts"]["history"].extend(alerts.get("history", []))
            for sev, n in alerts.get("fired_total", {}).items():
                merged["alerts"]["fired_total"][sev] = (
                    merged["alerts"]["fired_total"].get(sev, 0) + int(n)
                )
            attributions.append(doc.get("attribution"))
        merged["alerts"]["active"].sort(key=lambda e: e.get("t", 0))
        merged["alerts"]["history"].sort(key=lambda e: e.get("t", 0))
        from .attribution import merge_attribution_documents

        merged["processes"] = processes
        merged["attribution"] = merge_attribution_documents(attributions)
        # cluster-wide wave + key-load roll-ups: peer documents carry
        # the same shapes, so the merges re-merge; a stale (cached) peer
        # doc still contributes its last-good wave phases — a dead peer's
        # view is marked stale above, never silently dropped
        from .critpath import merge_process_waves
        from .keyload import merge_snapshots as _merge_keyload

        merged["waves"] = merge_process_waves(
            [local.get("waves")] + [d.get("waves") for d in peer_docs]
        )
        merged["keyload"] = _merge_keyload(
            [local.get("keyload")] + [d.get("keyload") for d in peer_docs]
        )
        self._add_cluster_lag(merged)
        return merged

    @staticmethod
    def _add_cluster_lag(doc: dict) -> None:
        """Per-worker frontier lag vs the most advanced worker in the
        (merged) view — the PR-1 backpressure gauge, windowed."""
        workers = doc.get("workers", {})
        times = [
            w.get("last_time")
            for w in workers.values()
            if w.get("last_time")
        ]
        if not times:
            return
        frontier = max(times)
        for w in workers.values():
            lt = w.get("last_time")
            w["frontier_lag_vs_max_ms"] = (
                max(0.0, frontier - lt) if lt else None
            )

    def query_eval(self, params: dict) -> dict:
        """Targeted query: ``/query?expr=rate(engine_ticks)&window=10``
        or ``?metric=tick_duration&op=p95[&worker=0]``. Returns the
        scalar plus the raw windowed points behind it."""
        plane = self.signals_plane
        if plane is None:
            raise ValueError("signals plane is not running")
        sig = plane.signals
        expr = params.get("expr")
        if not expr:
            metric = params.get("metric")
            if not metric:
                raise ValueError("pass expr=op(metric) or metric=...&op=...")
            expr = f"{params.get('op', 'last')}({metric})"
        try:
            window = float(params.get("window", plane.window_s))
        except ValueError:
            raise ValueError(f"bad window {params.get('window')!r}")
        worker_s = params.get("worker")
        metric_name = expr
        if expr.endswith(")") and "(" in expr:
            metric_name = expr.partition("(")[2][:-1].strip()
        if worker_s is None:
            value, worker = sig.eval_worst(expr, window)
        else:
            worker = int(worker_s)
            value = sig.eval(expr, window, worker)
        points = sig.store.points(metric_name, worker, window)
        if not points and worker is not None:
            points = sig.store.points(metric_name, None, window)
        return {
            "expr": expr,
            "window_s": window,
            "worker": worker,
            "value": value,
            "points": points,
        }

    def attribution_view(self) -> dict:
        """The ``/attribution`` payload (cluster-merged on process 0)."""
        doc = self.query_document()
        att = doc.get("attribution") or {"ranked": [], "bottleneck": None}
        att["processes"] = doc.get("processes", [self.process_id])
        return att

    def alerts_view(self) -> dict:
        """The ``/alerts`` payload (cluster-merged on process 0)."""
        plane = self.signals_plane
        local = (
            plane.slo.alerts.document()
            if plane is not None and plane.slo is not None
            else {"active": [], "history": [], "fired_total": {}}
        )
        if not self.peer_http:
            return local
        for doc in self._scrape_peers_path("/alerts"):
            local["active"] = local["active"] + doc.get("active", [])
            local["history"] = local["history"] + doc.get("history", [])
            for sev, n in doc.get("fired_total", {}).items():
                local["fired_total"][sev] = (
                    local["fired_total"].get(sev, 0) + int(n)
                )
        local["active"].sort(key=lambda e: e.get("t", 0))
        local["history"].sort(key=lambda e: e.get("t", 0))
        return local

    # -- continuous profiling (/profile) -------------------------------

    def profile_document(self) -> dict:
        """This process's full profile (collapsed-stack sketches + scalar
        counters) — what a peer serves at ``/profile?local=1``."""
        if self.profiler is None:
            from .profile_merge import merge_snapshots

            doc = merge_snapshots([])
            doc["process_id"] = self.process_id
            return doc
        return self.profiler.snapshot()

    def profile_view(self) -> dict:
        """The cluster-merged ``/profile`` payload: process 0 scrapes
        every peer's local profile and merges the sketches (same pull
        direction as /snapshot). A peer that stops answering serves from
        its last good scrape, marked in the merged document's ``stale``
        map (process id -> age s) — ``stale: {pid: null}`` names a peer
        that never answered at all."""
        from .profile_merge import merge_snapshots

        local = self.profile_document()
        if not self.peer_http:
            merged = merge_snapshots([local])
            merged["stale"] = {}
            return merged
        results = self._scrape_peers_raw("/profile?local=1")
        now = time.time()
        stale: dict[str, float | None] = {}
        peer_ids = [
            p for p in range(self.n_processes) if p != self.process_id
        ]
        docs: list[dict] = [local]
        for i, doc in enumerate(results):
            pid = peer_ids[i] if i < len(peer_ids) else i
            if doc is None:
                self.scrape_errors += 1
                cached = self._profile_cache.get(i)
                if cached is None:
                    stale[str(pid)] = None
                    continue
                seen_at, doc = cached
                stale[str(pid)] = round(now - seen_at, 3)
            else:
                self._profile_cache[i] = (now, doc)
            docs.append(doc)
        merged = merge_snapshots(docs)
        merged["stale"] = stale
        return merged

    # -- rendering + probes --------------------------------------------

    def render_metrics(self) -> str:
        from .prometheus import render_snapshots

        trace_dropped: int | dict[str, int] | None
        stale: dict[str, float] | None = None
        if self.peer_http:
            (
                snapshots, comm_stats, dropped_by_proc, stale,
                memory_stats, sink_stats, udf_stats, fusion_stats,
                ingest_stats, profile_stats, serve_stats,
            ) = self.cluster_snapshots()
            # per-process labels, like the comm gauges: series identity
            # stays stable when a peer scrape transiently fails
            trace_dropped = dropped_by_proc or None
        else:
            snapshots = self.local_snapshots()
            comm = self.comm_snapshot()
            comm_stats = {str(self.process_id): comm} if comm else {}
            mem = self.memory_stats_snapshot()
            memory_stats = {str(self.process_id): mem} if mem else {}
            sinks = self.sink_stats_snapshot()
            sink_stats = {str(self.process_id): sinks} if sinks else {}
            udf = self.udf_stats_snapshot()
            udf_stats = {str(self.process_id): udf} if udf else {}
            fusion = self.fusion_stats_snapshot()
            fusion_stats = {str(self.process_id): fusion} if fusion else {}
            ingest = self.ingest_stats_snapshot()
            ingest_stats = {str(self.process_id): ingest} if ingest else {}
            profile = self.profile_stats_snapshot()
            profile_stats = (
                {str(self.process_id): profile} if profile else {}
            )
            serve = self.serve_stats_snapshot()
            serve_stats = {str(self.process_id): serve} if serve else {}
            trace_dropped = self._local_trace_dropped()
        # label by TOPOLOGY, not by how many snapshots this scrape got:
        # in cluster mode a transient peer outage must not flip series
        # between labeled and unlabeled (that forks Prometheus series and
        # breaks rate() continuity)
        cluster = (
            self.n_processes > 1
            or bool(self.peer_http)
            or len(self._workers) > 1
        )
        # tracer drop visibility: a truncated trace window — local OR on a
        # scraped peer — must be distinguishable from a quiet one (0
        # renders too, as the explicit "nothing dropped" signal); None
        # only when no process traces
        bottleneck = None
        alerts_fired = None
        alerts_active = None
        plane = self.signals_plane
        if plane is not None:
            from .attribution import bottleneck_operator

            try:
                bottleneck = bottleneck_operator(
                    plane.signals, plane.window_s
                )
            except Exception:
                bottleneck = None
            if plane.slo is not None and plane.slo.rules:
                alert_doc = plane.slo.alerts.document()
                alerts_fired = alert_doc["fired_total"] or None
                alerts_active = len(alert_doc["active"])
        return render_snapshots(
            snapshots,
            comm_stats,
            scrape_errors=self.scrape_errors,
            worker_labels=True if cluster else None,
            supervisor=self._supervisor_snapshot(),
            trace_dropped=trace_dropped,
            stale_workers=stale or None,
            bottleneck=bottleneck,
            alerts_fired=alerts_fired,
            alerts_active=alerts_active,
            autoscale=self._autoscale_snapshot(),
            memory_stats=memory_stats or None,
            sink_stats=sink_stats or None,
            udf_stats=udf_stats or None,
            fusion_stats=fusion_stats or None,
            ingest_stats=_drop_empty(ingest_stats),
            profile_stats=_drop_empty(profile_stats),
            serve_stats=_drop_empty(serve_stats),
        )

    @staticmethod
    def _supervisor_snapshot() -> dict | None:
        """Self-healing metrics: restart generation + reason, stamped into
        the child environment by ``spawn --supervise``, plus the armed
        fault plan's injection count. None when neither applies (keeps the
        single-process exposition identical to the seed's)."""
        import os

        restarts = os.environ.get("PATHWAY_RESTART_COUNT")
        supervised = os.environ.get("PATHWAY_SUPERVISED")
        flight_dumps = os.environ.get("PATHWAY_FLIGHT_DUMPS")
        from ..chaos import injector as _chaos

        armed = _chaos.ARMED
        try:  # elastic boots reshard in-process before the engine mounts
            from ..rescale import stats as _rescale_stats

            rescales = _rescale_stats()
        except Exception:  # pragma: no cover — import cycle safety net
            rescales = {"total": 0}
        try:  # spawn --upgrade-to migrates in-process before launching
            from ..upgrade import stats as _upgrade_stats

            upgrades = _upgrade_stats()
        except Exception:  # pragma: no cover — import cycle safety net
            upgrades = {"total": 0}
        if (
            not supervised
            and restarts is None
            and armed is None
            and flight_dumps is None
        ):
            if not rescales["total"] and not upgrades["total"]:
                return None
            # a rescale/upgrade happened but nothing is supervised —
            # surface ONLY those counters (no pathway_restarts_total
            # outside supervision)
            doc = {}
            if rescales["total"]:
                doc["rescales"] = int(rescales["total"])
                doc["rescale_duration_s"] = float(rescales["duration_s"])
            if upgrades["total"]:
                doc["upgrades"] = int(upgrades["total"])
                doc["upgrade_duration_s"] = float(upgrades["duration_s"])
                doc["upgrade_operators"] = {
                    v: int(upgrades.get(v, 0))
                    for v in ("carried", "remapped", "new", "dropped")
                }
            return doc
        doc: dict = {
            "restarts": int(restarts or 0),
            "reason": os.environ.get("PATHWAY_LAST_RESTART_REASON"),
        }
        window_failures = os.environ.get("PATHWAY_SUPERVISE_WINDOW_FAILURES")
        if window_failures is not None:
            # circuit-breaker window position at this generation's launch:
            # a restart storm is visible BEFORE the breaker trips. The
            # budget comes from the same knob the supervisor reads, so
            # /metrics shows failures/budget and open = exhausted.
            from ..internals.config import _env_int

            try:
                failures = int(window_failures)
            except ValueError:
                failures = 0
            budget = _env_int("PATHWAY_SUPERVISE_MAX_RESTARTS", 5)
            doc["window_failures"] = failures
            doc["window_budget"] = budget
            # the supervisor trips at failures > budget and then exits
            # WITHOUT launching, so no child can ever see a stamp above
            # the budget — failures == budget is the last-chance
            # generation (the next failure is terminal) and must read as
            # open, or the gauge could never fire from a real run
            doc["circuit_open"] = failures >= budget
        if armed is not None:
            doc["chaos_injections"] = armed.injections_total
        if flight_dumps is not None:
            try:
                doc["flight_dumps"] = int(flight_dumps)
            except ValueError:
                pass
        if rescales["total"]:
            doc["rescales"] = int(rescales["total"])
            doc["rescale_duration_s"] = float(rescales["duration_s"])
        if upgrades["total"]:
            doc["upgrades"] = int(upgrades["total"])
            doc["upgrade_duration_s"] = float(upgrades["duration_s"])
            doc["upgrade_operators"] = {
                v: int(upgrades.get(v, 0))
                for v in ("carried", "remapped", "new", "dropped")
            }
        return doc

    @staticmethod
    def _autoscale_snapshot() -> dict | None:
        """Closed-loop autoscaler surface: the controller stamps its
        range, event count, and last scale decision/pause into every
        child's environment (autoscale/controller.py), so /metrics and
        /query on any worker show the loop working. None outside
        ``spawn --autoscale`` (exposition unchanged elsewhere)."""
        import os

        rng = os.environ.get("PATHWAY_AUTOSCALE")
        if not rng:
            return None
        doc: dict = {"range": rng}
        try:
            doc["events"] = int(os.environ.get("PATHWAY_AUTOSCALE_EVENTS", "0"))
        except ValueError:
            doc["events"] = 0
        pause = os.environ.get("PATHWAY_AUTOSCALE_LAST_PAUSE_MS")
        if pause is not None:
            try:
                doc["last_pause_ms"] = float(pause)
            except ValueError:
                pass
        decision = os.environ.get("PATHWAY_AUTOSCALE_LAST_DECISION")
        if decision:
            doc["last_decision"] = decision
        return doc

    def health(self) -> tuple[bool, dict]:
        return health_status(self.worker_stats, self.wedge_timeout_s)

    def ready(self) -> tuple[bool, dict]:
        return ready_status(self.worker_stats)
