"""Declarative SLO rules evaluated against the in-cluster signal store.

``PATHWAY_SLO_RULES`` holds inline JSON or a path to a JSON file (the
same convention as ``PATHWAY_FAULT_PLAN``):

.. code-block:: json

    {"rules": [
        {"name": "tick-p95", "expr": "p95(tick_duration_ms)",
         "op": ">", "threshold": 50, "for_s": 5,
         "severity": "critical"},
        {"name": "starved", "expr": "rate(output_rows)",
         "op": "<", "threshold": 1, "for_s": 30}
    ]}

``expr`` is a :class:`~pathway_tpu.observability.timeseries.Signals`
expression — ``op(metric)`` with op in rate/delta/avg/min/max/last/
p50/p95/p99, or a bare metric name (= last). Histogram percentiles read
in milliseconds; the special spellings ``p*(tick_duration_ms)`` /
``p*(e2e_latency_ms)`` alias the underlying ns histogram series. Each
evaluation pass (one per sampler tick) computes the worst value across
workers; a rule whose predicate holds CONTINUOUSLY for ``for_s`` fires
exactly once — it stays ``firing`` (no re-fire storms) until the
predicate clears, which emits a ``resolved`` event.

Every fired alert lands in three places, so it survives every failure
mode the observability arc covers:

- the in-memory :class:`AlertLog` served at ``/alerts`` (live ops);
- the trace stream as an ``slo.alert`` instant event (post-hoc
  timelines: the alert shows *on* the merged Perfetto track);
- the flight-recorder ring (``slo.alert`` record), so a crash bundle
  carries the alerts that preceded death.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

__all__ = ["AlertLog", "Rule", "SloEngine", "load_rules"]

#: /alerts history bound — an alert storm must not grow memory
_HISTORY_MAX = 256

_SEVERITIES = ("info", "warning", "critical")

#: percentile exprs read in ms; these alias the ns histogram series
_METRIC_ALIASES = {
    "tick_duration_ms": "tick_duration",
    "e2e_latency_ms": "e2e_latency",
    "ingest_to_emit_ms": "e2e_latency",
}


@dataclass
class Rule:
    name: str
    expr: str
    threshold: float
    op: str = ">"
    for_s: float = 5.0
    severity: str = "warning"
    window_s: float | None = None  # None = the plane's default window
    # -- evaluation state ---------------------------------------------
    breach_since: float | None = field(default=None, repr=False)
    active: bool = field(default=False, repr=False)

    def __post_init__(self) -> None:
        if self.op not in (">", "<", ">=", "<="):
            raise ValueError(
                f"SLO rule {self.name!r}: op must be one of > < >= <=, "
                f"got {self.op!r}"
            )
        if self.severity not in _SEVERITIES:
            raise ValueError(
                f"SLO rule {self.name!r}: severity must be one of "
                f"{_SEVERITIES}, got {self.severity!r}"
            )
        self.threshold = float(self.threshold)
        self.for_s = float(self.for_s)
        # alias ms-spelled histogram metrics to their ns series
        for alias, real in _METRIC_ALIASES.items():
            self.expr = self.expr.replace(f"({alias})", f"({real})")

    @property
    def higher_is_worse(self) -> bool:
        return self.op in (">", ">=")

    def breaches(self, value: float) -> bool:
        if self.op == ">":
            return value > self.threshold
        if self.op == ">=":
            return value >= self.threshold
        if self.op == "<":
            return value < self.threshold
        return value <= self.threshold


def load_rules(spec: str | None) -> list[Rule]:
    """Parse ``PATHWAY_SLO_RULES`` (inline JSON, or a path to a JSON
    file). Accepts ``{"rules": [...]}`` or a bare list. Raises
    ``ValueError`` on a malformed spec — a typo'd rules file must fail
    loudly at boot, not silently monitor nothing."""
    if not spec or not spec.strip():
        return []
    text = spec
    if not spec.lstrip().startswith(("{", "[")):
        try:
            with open(spec, encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            raise ValueError(
                f"PATHWAY_SLO_RULES names file {spec!r} which cannot be "
                f"read: {e}"
            ) from e
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as e:
        raise ValueError(f"PATHWAY_SLO_RULES is not valid JSON: {e}") from e
    entries = doc.get("rules", []) if isinstance(doc, dict) else doc
    if not isinstance(entries, list):
        raise ValueError("PATHWAY_SLO_RULES: expected a list of rules")
    rules: list[Rule] = []
    seen: set[str] = set()
    for i, entry in enumerate(entries):
        if not isinstance(entry, dict):
            raise ValueError(f"PATHWAY_SLO_RULES rule #{i} is not an object")
        unknown = set(entry) - {
            "name", "expr", "threshold", "op", "for_s", "severity",
            "window_s",
        }
        if unknown:
            raise ValueError(
                f"PATHWAY_SLO_RULES rule #{i}: unknown keys {sorted(unknown)}"
            )
        try:
            rule = Rule(**entry)
        except TypeError as e:
            raise ValueError(f"PATHWAY_SLO_RULES rule #{i}: {e}") from e
        if rule.name in seen:
            raise ValueError(
                f"PATHWAY_SLO_RULES: duplicate rule name {rule.name!r}"
            )
        seen.add(rule.name)
        rules.append(rule)
    return rules


def load_rules_from_env() -> list[Rule]:
    return load_rules(os.environ.get("PATHWAY_SLO_RULES"))


class AlertLog:
    """Bounded in-memory alert record — the ``/alerts`` payload."""

    def __init__(self, history_max: int = _HISTORY_MAX):
        self._history: deque = deque(maxlen=history_max)
        self._active: dict[str, dict] = {}
        self._lock = threading.Lock()
        self.fired_total: dict[str, int] = {}

    def fire(self, event: dict) -> None:
        with self._lock:
            self._history.append(event)
            self._active[event["rule"]] = event
            sev = event.get("severity", "warning")
            self.fired_total[sev] = self.fired_total.get(sev, 0) + 1

    def resolve(self, event: dict) -> None:
        with self._lock:
            self._history.append(event)
            self._active.pop(event["rule"], None)

    def document(self) -> dict:
        with self._lock:
            return {
                "active": sorted(
                    self._active.values(), key=lambda e: e["t"]
                ),
                "history": list(self._history),
                "fired_total": dict(self.fired_total),
            }


class SloEngine:
    """Evaluates the rule set against a Signals view once per sampler
    tick; owns the alert log and fans fired alerts out to the trace
    stream and the flight recorder. Never raises into the sampler."""

    def __init__(
        self,
        rules: list[Rule],
        default_window_s: float,
        process_id: int = 0,
    ):
        self.rules = rules
        self.default_window_s = default_window_s
        self.process_id = process_id
        self.alerts = AlertLog()

    def evaluate(self, signals: Any, now: float | None = None) -> None:
        if not self.rules:
            return
        if now is None:
            now = time.time()
        for rule in self.rules:
            try:
                self._evaluate_rule(rule, signals, now)
            except Exception:
                # a rule over a not-yet-sampled metric must not take the
                # evaluator down with it
                continue

    def _evaluate_rule(self, rule: Rule, signals: Any, now: float) -> None:
        window = rule.window_s or self.default_window_s
        # staleness guard: a worker whose series froze (dead worker,
        # cached peer scrape) must not win the worst-worker comparison
        # and hold a rule breaching (or block its resolve) forever —
        # generous bound so scheduler jitter never drops a live worker
        sample_s = getattr(signals, "sample_s", None)
        value, worker = signals.eval_worst(
            rule.expr, window, higher_is_worse=rule.higher_is_worse,
            max_age_s=sample_s * 8 if sample_s else None, now=now,
        )
        if value is None or not rule.breaches(value):
            rule.breach_since = None
            if rule.active:
                rule.active = False
                self._emit(rule, value, worker, now, state="resolved")
            return
        if rule.breach_since is None:
            rule.breach_since = now
        if rule.active:
            return  # fires exactly once while the breach persists
        if now - rule.breach_since + 1e-9 >= rule.for_s:
            rule.active = True
            self._emit(rule, value, worker, now, state="firing")

    def _emit(
        self, rule: Rule, value: float | None, worker: int | None,
        now: float, state: str,
    ) -> None:
        event = {
            "t": round(now, 3),
            "rule": rule.name,
            "state": state,
            "severity": rule.severity,
            "expr": rule.expr,
            "op": rule.op,
            "threshold": rule.threshold,
            "for_s": rule.for_s,
            "value": None if value is None else round(float(value), 4),
            "worker": worker,
            "process": self.process_id,
        }
        if state == "firing":
            self.alerts.fire(event)
        else:
            self.alerts.resolve(event)
        # trace stream: the alert shows ON the merged cluster timeline
        from ..internals.tracing import get_tracer

        tracer = get_tracer()
        if tracer is not None:
            tracer.instant("slo.alert", **event)
        # flight recorder: crash bundles carry the alerts that preceded
        # death (the ring survives SIGKILL)
        from .flightrecorder import get_recorder

        flight = get_recorder()
        if flight is not None:
            flight.record("slo.alert", **event)
