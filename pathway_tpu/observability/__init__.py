"""End-to-end engine observability.

The reference engine's telemetry pair — per-operator OTLP metrics
(``src/engine/telemetry.rs:47-156``) and per-process metrics ports
(``src/engine/http_server.rs:21-60``) — rebuilt as a subsystem:

- :mod:`histogram` — lock-cheap log2-bucketed latency histograms;
- :mod:`prometheus` — OpenMetrics exposition rendering (escaped labels,
  histogram families) from JSON snapshots;
- :mod:`hub` — per-process worker/comm registry + the cluster roll-up
  process 0 serves as a merged per-worker-labeled ``/metrics``;
- :mod:`health` — ``/healthz`` (executor not wedged) and ``/readyz``
  (sources connected, first frontier advanced) probe semantics;
- :mod:`exporter` — periodic OTLP/trace-file flusher so crashed runs
  still leave telemetry;
- :mod:`flightrecorder` — always-on mmap ring per process (the black
  box): the last K ticks survive SIGKILL, harvested by the supervisor
  into ``crash-<gen>-<proc>.json`` forensic bundles;
- :mod:`trace_merge` — assembles per-process ``PATHWAY_TRACE_FILE``
  parts into one clock-aligned cluster timeline
  (``pathway-tpu trace merge``);
- :mod:`timeseries` — the signals plane: windowed in-process
  time-series store over every EngineStats gauge/counter/histogram +
  comm counters, with rate/delta/percentile/sustained queries
  (``/query``, merged on process 0);
- :mod:`attribution` — per-operator bottleneck attribution over the
  signals window (``/attribution``, ``pathway_bottleneck_operator``);
- :mod:`slo` — declarative SLO rules (``PATHWAY_SLO_RULES``) evaluated
  against the store; alerts fan out to ``/alerts``, the trace stream
  and the flight recorder;
- :mod:`top` — the ``pathway-tpu top`` live terminal dashboard;
- :mod:`profiler` — the always-on sampling profiler: per-process
  collapsed-stack tables (wall + CPU) with operator tags joining
  against /attribution, flight-ring top-K deposits, tracemalloc heap
  view (``/profile``, ``PATHWAY_PROFILE*`` knobs);
- :mod:`profile_merge` — associative cluster merge of profiler
  snapshots + collapsed/speedscope/top renderers
  (``pathway-tpu profile``).

The HTTP surface itself lives in ``engine/http_server.py``; instrumented
state in ``engine/executor.EngineStats``.
"""

from .attribution import attribution_document, bottleneck_operator
from .exporter import PeriodicFlusher, start_periodic_flusher
from .flightrecorder import FlightRecorder, get_recorder, harvest
from .health import health_status, ready_status
from .histogram import LogHistogram, merge_snapshots, quantile_from_snapshot
from .hub import ObservabilityHub, stats_snapshot
from .profile_merge import (
    collapsed_text,
    render_top,
    speedscope_document,
    top_frames,
)
from .profiler import Profiler, current_op_slot, heap_document
from .prometheus import (
    escape_label_value,
    parse_exposition,
    render_snapshots,
)
from .slo import AlertLog, Rule, SloEngine, load_rules
from .timeseries import Signals, SignalsPlane, TimeSeriesStore

__all__ = [
    "AlertLog",
    "FlightRecorder",
    "LogHistogram",
    "ObservabilityHub",
    "PeriodicFlusher",
    "Profiler",
    "Rule",
    "Signals",
    "SignalsPlane",
    "SloEngine",
    "TimeSeriesStore",
    "attribution_document",
    "bottleneck_operator",
    "collapsed_text",
    "current_op_slot",
    "get_recorder",
    "harvest",
    "heap_document",
    "escape_label_value",
    "health_status",
    "load_rules",
    "merge_snapshots",
    "render_top",
    "speedscope_document",
    "top_frames",
    "parse_exposition",
    "quantile_from_snapshot",
    "ready_status",
    "render_snapshots",
    "start_periodic_flusher",
    "stats_snapshot",
]
