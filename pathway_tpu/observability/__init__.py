"""End-to-end engine observability.

The reference engine's telemetry pair — per-operator OTLP metrics
(``src/engine/telemetry.rs:47-156``) and per-process metrics ports
(``src/engine/http_server.rs:21-60``) — rebuilt as a subsystem:

- :mod:`histogram` — lock-cheap log2-bucketed latency histograms;
- :mod:`prometheus` — OpenMetrics exposition rendering (escaped labels,
  histogram families) from JSON snapshots;
- :mod:`hub` — per-process worker/comm registry + the cluster roll-up
  process 0 serves as a merged per-worker-labeled ``/metrics``;
- :mod:`health` — ``/healthz`` (executor not wedged) and ``/readyz``
  (sources connected, first frontier advanced) probe semantics;
- :mod:`exporter` — periodic OTLP/trace-file flusher so crashed runs
  still leave telemetry.

The HTTP surface itself lives in ``engine/http_server.py``; instrumented
state in ``engine/executor.EngineStats``.
"""

from .exporter import PeriodicFlusher, start_periodic_flusher
from .health import health_status, ready_status
from .histogram import LogHistogram, merge_snapshots, quantile_from_snapshot
from .hub import ObservabilityHub, stats_snapshot
from .prometheus import (
    escape_label_value,
    parse_exposition,
    render_snapshots,
)

__all__ = [
    "LogHistogram",
    "ObservabilityHub",
    "PeriodicFlusher",
    "escape_label_value",
    "health_status",
    "merge_snapshots",
    "parse_exposition",
    "quantile_from_snapshot",
    "ready_status",
    "render_snapshots",
    "start_periodic_flusher",
    "stats_snapshot",
]
