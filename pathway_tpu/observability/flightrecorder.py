"""Always-on crash-safe flight recorder — the engine's black box.

The tracer (``internals/tracing.py``) buffers spans in memory and writes
them at flush points, so a SIGKILL'd or wedged worker leaves nothing
behind — exactly the runs worth explaining. This module keeps a small
**mmap-backed ring buffer per process** (``flight-p<N>.ring`` under
``PATHWAY_FLIGHT_DIR``) recording the last K ticks of span/event/log
records *as they happen*: every write lands in the page cache through the
mapping, so the tail survives SIGKILL, ``os._exit``, and a supervisor's
SIGKILL-after-wedge without any flush discipline. The reference's analog
is timely's always-streaming event log (``DIFFERENTIAL_LOG_ADDR``,
``dataflow.rs:5540-5548``) — a record stream that exists whether or not
anyone is watching.

On worker death the supervisor (``parallel/supervisor.py``) harvests the
dead process's ring into a ``crash-<generation>-<process>.json`` forensic
bundle and stamps the bundle path into the restart reason.

Record producers (each one ``is None`` check when disarmed):

- ``engine/executor.py`` — per-tick records (time, duration, row totals)
  plus run start/end/error;
- ``parallel/cluster.py`` — mesh-broken reasons (peer death attribution);
- ``chaos/injector.py`` — every fired injection, written *before* the
  fault executes, so a chaos SIGKILL is self-documenting.

Ring format: a 64-byte header (magic, version, capacity, head, wrapped,
process id, os pid, run id) followed by ``capacity`` bytes of ring data
holding newline-delimited JSON records. Harvest linearizes the ring from
the head and drops unparseable boundary lines (a torn record at the wrap
point, a record cut mid-write by SIGKILL) — forensics never raise.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import threading
import time
from typing import Any

__all__ = [
    "FlightRecorder",
    "get_recorder",
    "harvest",
    "ring_path",
]

_MAGIC = b"PWFLIGHT"
#: magic, version, capacity, head, wrapped, process_id, os_pid, run_id
_HDR = struct.Struct("<8s6I16s")
_HDR_SIZE = 64
_VERSION = 1
_DEFAULT_RING_KB = 256


def ring_path(flight_dir: str, process_id: int) -> str:
    return os.path.join(flight_dir, f"flight-p{process_id}.ring")


class FlightRecorder:
    """Fixed-size mmap ring of JSON-line records; thread-safe, never
    raises out of :meth:`record` — the black box must not fail (or slow
    down by raising into) the run it observes."""

    def __init__(
        self,
        path: str,
        capacity_bytes: int = _DEFAULT_RING_KB * 1024,
        process_id: int = 0,
        run_id: str = "",
    ):
        self.path = path
        self._cap = max(4096, int(capacity_bytes))
        self.process_id = process_id
        self.run_id = run_id
        self._lock = threading.Lock()
        self._head = 0
        self._wrapped = 0
        self.records_written = 0
        self._closed = False
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            os.ftruncate(fd, _HDR_SIZE + self._cap)
            self._mm = mmap.mmap(fd, _HDR_SIZE + self._cap)
        finally:
            os.close(fd)
        self._write_header()

    def _write_header(self) -> None:
        _HDR.pack_into(
            self._mm,
            0,
            _MAGIC,
            _VERSION,
            self._cap,
            self._head,
            self._wrapped,
            self.process_id,
            os.getpid() & 0xFFFFFFFF,
            self.run_id.encode()[:16].ljust(16, b"\0"),
        )

    def record(self, kind: str, **fields: Any) -> None:
        """Append one record; timestamps are unix seconds so bundles read
        directly. Oversized or unserializable records are dropped, I/O
        errors are swallowed — see class docstring."""
        if self._closed:
            return
        try:
            rec = {"t": round(time.time(), 4), "kind": kind, **fields}
            line = (json.dumps(rec, default=str) + "\n").encode()
        except (TypeError, ValueError):
            return
        if len(line) >= self._cap:
            return
        try:
            with self._lock:
                head, cap = self._head, self._cap
                end = head + len(line)
                if end <= cap:
                    self._mm[_HDR_SIZE + head : _HDR_SIZE + end] = line
                    if end == cap:
                        # head resets to 0 below — without the wrap flag a
                        # harvest would read data[:0] and lose the full ring
                        self._wrapped = 1
                else:
                    first = cap - head
                    self._mm[_HDR_SIZE + head : _HDR_SIZE + cap] = line[:first]
                    self._mm[_HDR_SIZE : _HDR_SIZE + end - cap] = line[first:]
                    self._wrapped = 1
                self._head = end % cap
                self.records_written += 1
                # header updated after the payload: a harvest that races a
                # write sees the previous consistent head at worst
                self._write_header()
        except (ValueError, OSError):
            pass

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._mm.flush()
                self._mm.close()
            except (ValueError, OSError):
                pass


def harvest(path: str) -> dict:
    """Read a ring file (live, crashed, or torn) into
    ``{process_id, pid, run_id, wrapped, records}``; unparseable boundary
    lines (wrap-point garbage, a record cut mid-write) are skipped.
    Raises ``OSError``/``ValueError`` only for a missing or non-ring file."""
    with open(path, "rb") as f:
        blob = f.read()
    if len(blob) < _HDR_SIZE or not blob.startswith(_MAGIC):
        raise ValueError(f"{path!r} is not a flight-recorder ring")
    (_, version, cap, head, wrapped, process_id, pid, run_id) = _HDR.unpack_from(
        blob, 0
    )
    data = blob[_HDR_SIZE : _HDR_SIZE + cap]
    head = min(head, len(data))
    buf = data[head:] + data[:head] if wrapped else data[:head]
    records: list[dict] = []
    for line in buf.split(b"\n"):
        if not line or b"\0" in line:
            continue
        try:
            rec = json.loads(line)
        except (json.JSONDecodeError, UnicodeDecodeError, ValueError):
            continue  # torn boundary record
        if isinstance(rec, dict):
            records.append(rec)
    return {
        "path": path,
        "version": version,
        "process_id": process_id,
        "pid": pid,
        "run_id": run_id.rstrip(b"\0").decode(errors="replace"),
        "wrapped": bool(wrapped),
        "records": records,
    }


_active: FlightRecorder | None = None
_env_sig: tuple | None = None
#: arm/re-arm must be serialized: callers include concurrent ClusterComm
#: reader threads (_break) and chaos sites — an unlocked first call could
#: mmap the same ring twice with independent write heads
_arm_lock = threading.Lock()


def get_recorder() -> FlightRecorder | None:
    """The process's flight recorder, armed from ``PATHWAY_FLIGHT_DIR``
    (``pathway-tpu spawn --supervise`` sets a default; any run may opt in).
    Re-reads the environment like the chaos injector's ``current()``, so a
    test that flips the env gets a fresh ring instead of a stale one."""
    global _active, _env_sig
    sig = (
        os.environ.get("PATHWAY_FLIGHT_DIR"),
        os.environ.get("PATHWAY_PROCESS_ID", "0"),
        os.environ.get("PATHWAY_RESTART_COUNT", "0"),
    )
    if sig == _env_sig:
        return _active
    with _arm_lock:
        if sig == _env_sig:  # another thread armed while we waited
            return _active
        if _active is not None:
            _active.close()
            _active = None
        flight_dir = sig[0]
        if not flight_dir:
            _env_sig = sig
            return None
        try:
            process_id = int(sig[1] or 0)
        except ValueError:
            process_id = 0
        try:
            size_kb = int(
                os.environ.get(
                    "PATHWAY_FLIGHT_RING_KB", str(_DEFAULT_RING_KB)
                )
            )
        except ValueError:
            size_kb = _DEFAULT_RING_KB
        try:
            os.makedirs(flight_dir, exist_ok=True)
            _active = FlightRecorder(
                ring_path(flight_dir, process_id),
                capacity_bytes=size_kb * 1024,
                process_id=process_id,
                run_id=os.environ.get("PATHWAY_RUN_ID", ""),
            )
            _active.record(
                "recorder.start",
                process=process_id,
                generation=int(sig[2] or 0),
            )
        except (OSError, ValueError) as e:
            import warnings

            warnings.warn(
                f"flight recorder disabled ({e})", RuntimeWarning
            )
            _active = None
        # publish the signature only after the recorder is fully built, so
        # a racing lock-free fast-path read never sees a half-armed state
        _env_sig = sig
        return _active
