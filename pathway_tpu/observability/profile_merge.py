"""Cluster merge + rendering for profiler snapshots.

Per-process profile documents (``Profiler.snapshot()``) carry their
collapsed-stack tables as :class:`~.keyload.SpaceSaving` wire forms, so
merging peers is the sketch merge — associative in any grouping, exact
while the union of tracked stacks fits capacity, epsilon-bounded beyond
it. A merged document has the *same shape* as a per-process one (plus
``processes``/``merged`` provenance), so it can be merged again: the hub
on process 0 merges scraped peers, a fleet aggregator could merge hubs.

Renderers:

- :func:`collapsed_text` — classic folded-stack lines
  (``frame;frame;... count``), pipe straight into any flamegraph tool;
- :func:`speedscope_document` — https://www.speedscope.app sampled
  profile (paste the JSON, get the interactive flamegraph);
- :func:`top_frames` — self-time ranking by leaf frame, each with its
  dominant operator tag;
- :func:`operator_shares` / :func:`top_operator` — per-operator weight,
  the join surface against ``/attribution``'s ranking;
- :func:`render_top` — the ``pathway-tpu profile`` terminal table.
"""

from __future__ import annotations

from typing import Any

from .keyload import SpaceSaving

__all__ = [
    "merge_snapshots",
    "collapsed_text",
    "speedscope_document",
    "top_frames",
    "operator_shares",
    "top_operator",
    "render_top",
    "split_stack_key",
]

_SUM_KEYS = ("samples_total", "engine_samples", "op_tagged", "errors_total")


def _empty_doc() -> dict:
    return {
        "enabled": False,
        "samples_total": 0,
        "engine_samples": 0,
        "op_tagged": 0,
        "errors_total": 0,
        "duration_s": 0.0,
        "cpu_supported": False,
        "wall": SpaceSaving(1).snapshot(),
        "cpu": SpaceSaving(1).snapshot(),
        "processes": [],
    }


def merge_snapshots(snaps: list[dict | None]) -> dict:
    """Merge per-process (or already-merged) profile documents into one
    cluster document; ``None``/empty peers are skipped."""
    live = [s for s in snaps if s and s.get("wall")]
    if not live:
        return _empty_doc()
    wall = SpaceSaving.from_snapshot(live[0]["wall"])
    cpu = SpaceSaving.from_snapshot(live[0].get("cpu") or {"capacity": 1})
    for s in live[1:]:
        wall = wall.merge(SpaceSaving.from_snapshot(s["wall"]))
        if s.get("cpu"):
            cpu = cpu.merge(SpaceSaving.from_snapshot(s["cpu"]))
    out: dict[str, Any] = {
        "enabled": any(s.get("enabled") for s in live),
        "merged": True,
        "hz": live[0].get("hz"),
        "capacity": min(int(s.get("capacity") or wall.capacity) for s in live),
        "duration_s": max(float(s.get("duration_s") or 0.0) for s in live),
        "cpu_supported": any(s.get("cpu_supported") for s in live),
        "wall": wall.snapshot(),
        "cpu": cpu.snapshot(),
    }
    for k in _SUM_KEYS:
        out[k] = sum(int(s.get(k) or 0) for s in live)
    procs: list[int] = []
    for s in live:
        sub = s.get("processes")
        if sub:
            procs.extend(int(p) for p in sub)
        elif s.get("process_id") is not None:
            procs.append(int(s["process_id"]))
    out["processes"] = sorted(set(procs))
    eng = out["engine_samples"]
    out["op_tagged_share"] = round(out["op_tagged"] / eng, 4) if eng else 0.0
    return out


# -- stack-key helpers --------------------------------------------------


def split_stack_key(key: str) -> tuple[str | None, str | None, list[str]]:
    """Collapsed key -> ``(thread, op, frames)``; the thread/op head
    segments are optional and order-fixed (thread first)."""
    parts = key.split(";")
    thread: str | None = None
    op: str | None = None
    i = 0
    if i < len(parts) and parts[i].startswith("thread:"):
        thread = parts[i][7:]
        i += 1
    if i < len(parts) and parts[i].startswith("op:"):
        op = parts[i][3:]
        i += 1
    return thread, op, parts[i:]


def _sketch(doc: dict, mode: str) -> SpaceSaving:
    snap = doc.get(mode) or {"capacity": 1}
    return SpaceSaving.from_snapshot(snap)


def collapsed_text(doc: dict, mode: str = "wall") -> str:
    """Folded-stack lines, heaviest first — flamegraph.pl input. Wall
    counts are samples; cpu counts are seconds (rendered in ms so the
    integer-weight convention of folded files survives)."""
    scale = 1000.0 if mode == "cpu" else 1.0
    lines = []
    for key, count, _err in _sketch(doc, mode).items():
        w = int(round(count * scale))
        if w > 0:
            lines.append(f"{key} {w}")
    return "\n".join(lines) + ("\n" if lines else "")


def speedscope_document(
    doc: dict, mode: str = "wall", name: str = "pathway-tpu"
) -> dict:
    """A speedscope ``sampled`` profile: shared frame table + one entry
    per distinct collapsed stack, weighted by its fold count."""
    frame_index: dict[str, int] = {}
    frames: list[dict] = []
    samples: list[list[int]] = []
    weights: list[float] = []
    for key, count, _err in _sketch(doc, mode).items():
        thread, op, stack = split_stack_key(key)
        labels = []
        if thread:
            labels.append(f"[thread {thread}]")
        if op:
            labels.append(f"[op {op}]")
        labels.extend(stack)
        idxs = []
        for label in labels:
            at = frame_index.get(label)
            if at is None:
                at = frame_index[label] = len(frames)
                frames.append({"name": label})
            idxs.append(at)
        samples.append(idxs)
        weights.append(round(count, 4))
    total = round(sum(weights), 4)
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": [
            {
                "type": "sampled",
                "name": f"{name} ({mode})",
                "unit": "seconds" if mode == "cpu" else "none",
                "startValue": 0,
                "endValue": total,
                "samples": samples,
                "weights": weights,
            }
        ],
        "exporter": "pathway-tpu-profiler",
    }


def top_frames(doc: dict, n: int = 15, mode: str = "wall") -> list[dict]:
    """Self-time ranking: weight folded onto each stack's LEAF frame,
    with the frame's dominant operator tag riding along (the
    flamegraph-to-attribution join, row by row)."""
    self_w: dict[str, float] = {}
    by_op: dict[str, dict[str, float]] = {}
    total = 0.0
    for key, count, _err in _sketch(doc, mode).items():
        _thread, op, stack = split_stack_key(key)
        if not stack:
            continue
        leaf = stack[-1]
        self_w[leaf] = self_w.get(leaf, 0.0) + count
        total += count
        ops = by_op.setdefault(leaf, {})
        ops[op or "-"] = ops.get(op or "-", 0.0) + count
    ranked = sorted(self_w.items(), key=lambda t: (-t[1], t[0]))[: max(1, n)]
    out = []
    for frame, w in ranked:
        ops = by_op.get(frame) or {}
        dominant = max(ops, key=lambda o: (ops[o], o)) if ops else "-"
        out.append(
            {
                "frame": frame,
                "self": round(w, 4),
                "share": round(w / total, 4) if total else 0.0,
                "op": dominant,
            }
        )
    return out


def operator_shares(doc: dict, mode: str = "wall") -> dict[str, float]:
    """op label -> share of op-tagged weight (untagged stacks excluded —
    this ranks *operators*, matching what /attribution ranks)."""
    w: dict[str, float] = {}
    for key, count, _err in _sketch(doc, mode).items():
        _thread, op, _stack = split_stack_key(key)
        if op is not None:
            w[op] = w.get(op, 0.0) + count
    total = sum(w.values())
    if not total:
        return {}
    return {
        op: round(v / total, 4)
        for op, v in sorted(w.items(), key=lambda t: (-t[1], t[0]))
    }


def top_operator(doc: dict, mode: str = "wall") -> str | None:
    shares = operator_shares(doc, mode)
    return next(iter(shares), None)


def render_top(doc: dict, n: int = 15, mode: str = "wall") -> str:
    """Terminal table for ``pathway-tpu profile`` — header summary plus
    the self-time leaderboard with operator tags."""
    unit = "s" if mode == "cpu" else "samples"
    lines = [
        (
            f"profile [{mode}]  samples={int(doc.get('samples_total') or 0)}"
            f"  duration={float(doc.get('duration_s') or 0.0):.1f}s"
            f"  op-tagged={100.0 * _tagged_share(doc):.1f}%"
            f"  processes={doc.get('processes') or [doc.get('process_id', 0)]}"
        ),
        f"{'SELF%':>6}  {'SELF(' + unit + ')':>12}  {'OPERATOR':<18} FRAME",
    ]
    for row in top_frames(doc, n=n, mode=mode):
        lines.append(
            f"{100.0 * row['share']:>5.1f}%  {row['self']:>12.2f}  "
            f"{row['op']:<18} {row['frame']}"
        )
    stale = doc.get("stale")
    if stale:
        lines.append(f"stale peers: {stale}")
    return "\n".join(lines) + "\n"


def _tagged_share(doc: dict) -> float:
    eng = int(doc.get("engine_samples") or 0)
    if not eng:
        return float(doc.get("op_tagged_share") or 0.0)
    return int(doc.get("op_tagged") or 0) / eng
