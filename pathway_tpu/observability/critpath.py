"""Commit-wave critical-path attribution for the async execution plane.

PR 15's frontier-driven loop dissolved "the tick" into a pipeline of
stages that no existing surface could separate: a worker sweeps, posts
exchange buckets into peer inboxes, waits for every frontier to agree on
a commit time T, drives quiesce vote rounds, snapshots, and releases the
delivery boundary. When p99 moves, the question is *which stage* — and
*which worker held the wave* (Naiad-style frontier introspection,
SURVEY §2.9).

The executor stamps wall-clock phase marks through every commit wave
(``Executor._async_commit_wave``) and hands them here. This module is
the pure half: holding-worker election, stage-split math, the per-wave
document, the bounded per-worker history ring, cluster merge, and the
``pathway-tpu critpath`` report renderer — unit-testable without
threads or comm (``tests/test_critpath.py``).

Wave phases (``PHASES`` order is also the tie-break order):

- ``sweep`` — busy tick time accumulated since the previous wave ended
  (includes the settle sweeps this wave ran);
- ``inbox_dwell`` — summed enqueue->drain->take latency of exchange
  arrivals since the previous wave (frame meta carries the sender's
  enqueue stamp; a sum over rows, so it can exceed wall time — it is a
  load measure, like CPU-seconds);
- ``frontier_wait`` — wall time collecting every worker's ready clock
  (the wave's coordination stall);
- ``settle`` — quiesce wall time minus the busy sweep time inside it
  (pure waiting for vote rounds to go clean);
- ``snapshot`` — operator-state flush + snapshot + meta commit;
- ``release`` — delivery barrier + post-commit release
  (``io/delivery.py`` boundary acks).

Holding-worker election. Every ready broadcast carries the sender's
wave-entry wall time and its pre-wave busy time, so all workers elect
from IDENTICAL data and the verdicts agree by construction. When the
entry spread exceeds the arrival floor (``PATHWAY_WAVE_ARRIVAL_FLOOR_MS``)
the wave had a genuine straggler and the LAST frontier to arrive is the
holder. Below the floor (timer-driven waves: everyone joins within
scheduling jitter) arrival order is noise, so attribution falls to the
worker with the largest pre-wave pipeline occupancy — the frontier the
cluster would wait on under any load increase.
"""

from __future__ import annotations

import collections
from typing import Any

__all__ = [
    "PHASES",
    "WaveRecorder",
    "elect_holder",
    "attribute_holder",
    "stage_split",
    "merge_worker_waves",
    "merge_process_waves",
    "render_report",
]

PHASES = (
    "sweep",
    "inbox_dwell",
    "frontier_wait",
    "settle",
    "snapshot",
    "release",
)

DEFAULT_HISTORY = 128
DEFAULT_ARRIVAL_FLOOR_MS = 25.0


def elect_holder(
    order: list[tuple[int, int, float]],
) -> int | None:
    """Name the holding worker of a wave from the ready-arrival order.

    ``order`` holds ``(worker, ready_clock, arrival)`` triples —
    ``arrival`` is the sender's wave-entry wall time carried in its
    ready broadcast (any monotone-comparable number works). The holder
    is the LAST frontier to arrive (largest ``arrival``). Ties break by
    the larger ``ready_clock`` (the worker that forced T higher held
    the wave longer), then by the smaller worker id, so every worker
    elects the same holder from the same votes."""
    best: tuple[int, int, float] | None = None
    for w, rc, seq in order:
        key = (seq, rc, -int(w))
        if best is None or key > (best[2], best[1], -best[0]):
            best = (int(w), int(rc), float(seq))
    return best[0] if best is not None else None


def attribute_holder(
    order: list[tuple[int, int, float]],
    busy_ms: dict[int, float] | None = None,
    floor_ms: float = DEFAULT_ARRIVAL_FLOOR_MS,
) -> tuple[int | None, str]:
    """(holder, elected_by) for one wave.

    Primary signal: ready-arrival order. When the entry-time spread in
    ``order`` reaches ``floor_ms`` the wave had a real straggler —
    someone the whole cluster measurably waited for — and the last
    arrival is the holder (``elected_by == "arrival"``). Below the
    floor every worker joined within scheduler jitter (the common case
    for snapshot-timer-driven waves), so arrival order carries no
    lineage; the wave is attributed to the worker with the largest
    pre-wave busy time in ``busy_ms`` (``elected_by == "busy"``) —
    ties break toward the later arrival, then the smaller id. Without
    busy data the arrival election stands."""
    if not order:
        return None, "arrival"
    entries = [float(seq) for _w, _rc, seq in order]
    spread_ms = (max(entries) - min(entries)) * 1000.0
    if spread_ms >= float(floor_ms) or not busy_ms:
        return elect_holder(order), "arrival"
    entry_of = {int(w): float(seq) for w, _rc, seq in order}
    holder = max(
        busy_ms,
        key=lambda w: (
            float(busy_ms[w]),
            entry_of.get(int(w), 0.0),
            -int(w),
        ),
    )
    return int(holder), "busy"


def stage_split(
    phases_ms: dict[str, float],
) -> tuple[str | None, dict[str, float]]:
    """(critical stage, per-stage share of the phase total). The
    critical stage is the largest phase; ties break in ``PHASES`` order
    so the verdict is deterministic. Shares are fractions of the summed
    phase time (0.0 when nothing was measured)."""
    total = sum(max(0.0, phases_ms.get(p, 0.0)) for p in PHASES)
    shares = {
        p: (max(0.0, phases_ms.get(p, 0.0)) / total if total else 0.0)
        for p in PHASES
    }
    critical: str | None = None
    best = -1.0
    for p in PHASES:
        v = max(0.0, phases_ms.get(p, 0.0))
        if v > best:
            best, critical = v, p
    if best <= 0.0:
        critical = None
    return critical, shares


class WaveRecorder:
    """Bounded per-worker ring of wave documents + holder tally.

    One per worker, owned by the executor while the async loop is live
    (``EngineStats._waves``). ``record_wave`` builds the per-wave doc
    (election + stage split), appends it, and returns it so the caller
    can fold the numbers into its counters."""

    def __init__(
        self,
        worker_id: int,
        history: int | None = None,
        arrival_floor_ms: float | None = None,
    ):
        from ..internals.config import _env_float, _env_int

        if history is None:
            history = _env_int("PATHWAY_WAVE_HISTORY", DEFAULT_HISTORY)
        if arrival_floor_ms is None:
            arrival_floor_ms = _env_float(
                "PATHWAY_WAVE_ARRIVAL_FLOOR_MS", DEFAULT_ARRIVAL_FLOOR_MS
            )
        self.worker_id = worker_id
        self.arrival_floor_ms = float(arrival_floor_ms)
        self.recent: collections.deque = collections.deque(
            maxlen=max(1, int(history))
        )
        self.held_total: dict[str, int] = {}

    def record_wave(
        self,
        *,
        epoch: int,
        T: int,
        t: float,
        duration_ms: float,
        interval_ms: float,
        phases_ms: dict[str, float],
        settle_rounds: int,
        ready_order: list[tuple[int, int, float]],
        busy_ms: dict[int, float] | None = None,
        fin: bool = False,
    ) -> dict:
        holder, elected_by = attribute_holder(
            ready_order, busy_ms, self.arrival_floor_ms
        )
        critical, shares = stage_split(phases_ms)
        doc = {
            "epoch": int(epoch),
            "worker": self.worker_id,
            "T": int(T),
            "t": float(t),
            "duration_ms": round(float(duration_ms), 3),
            "interval_ms": round(float(interval_ms), 3),
            "phases_ms": {
                p: round(float(phases_ms.get(p, 0.0)), 3) for p in PHASES
            },
            "settle_rounds": int(settle_rounds),
            "holder": holder,
            "elected_by": elected_by,
            "critical_stage": critical,
            "shares": {p: round(s, 4) for p, s in shares.items()},
            "ready_order": [
                (int(w), int(rc), round(float(seq), 6))
                for w, rc, seq in ready_order
            ],
        }
        if fin:
            doc["fin"] = True
        self.recent.append(doc)
        if holder is not None:
            k = str(holder)
            self.held_total[k] = self.held_total.get(k, 0) + 1
        return doc

    def snapshot(self) -> dict:
        """JSON form shipped per worker in the hub snapshot/query docs."""
        return {
            "worker": self.worker_id,
            "last": self.recent[-1] if self.recent else None,
            "recent": list(self.recent),
            "held_total": dict(self.held_total),
        }


def _merge_epoch(docs: list[dict]) -> dict:
    """One cluster-wide wave doc from every worker's view of the same
    epoch. The holder is elected by majority over the per-worker
    verdicts (every ready broadcast carries the same entry/busy data,
    so disagreement normally means a stale or partial view — ties
    break toward the smaller worker id); ``agreed`` records unanimity,
    the condition under which crash bundles may name the holder."""
    votes: dict[int, int] = {}
    for d in docs:
        h = d.get("holder")
        if h is not None:
            votes[int(h)] = votes.get(int(h), 0) + 1
    holder = None
    if votes:
        holder = min(
            votes, key=lambda w: (-votes[w], w)
        )
    phases = {
        p: max(float(d.get("phases_ms", {}).get(p, 0.0)) for d in docs)
        for p in PHASES
    }
    critical, shares = stage_split(phases)
    head = max(docs, key=lambda d: d.get("duration_ms", 0.0))
    return {
        "epoch": head.get("epoch"),
        "T": head.get("T"),
        "t": min(d.get("t", 0.0) for d in docs),
        "duration_ms": head.get("duration_ms", 0.0),
        "holder": holder,
        "agreed": len(votes) == 1 and holder is not None,
        "critical_stage": critical,
        "shares": {p: round(s, 4) for p, s in shares.items()},
        "settle_rounds": max(
            int(d.get("settle_rounds", 0)) for d in docs
        ),
        "workers": {
            str(d.get("worker", "?")): {
                "duration_ms": d.get("duration_ms", 0.0),
                "phases_ms": d.get("phases_ms", {}),
                "critical_stage": d.get("critical_stage"),
                "holder": d.get("holder"),
            }
            for d in docs
        },
    }


def merge_worker_waves(worker_snaps: dict[str, dict | None]) -> dict:
    """Merge per-worker :meth:`WaveRecorder.snapshot` docs (one process)
    into the process-level ``waves`` document served on ``/query``."""
    by_epoch: dict[int, list[dict]] = {}
    held: dict[str, int] = {}
    for snap in worker_snaps.values():
        if not snap:
            continue
        for d in snap.get("recent") or []:
            by_epoch.setdefault(int(d.get("epoch", -1)), []).append(d)
        for w, n in (snap.get("held_total") or {}).items():
            held[w] = held.get(w, 0) + int(n)
    recent = [
        _merge_epoch(docs) for _, docs in sorted(by_epoch.items())
    ]
    return _finish_waves_doc(recent, held)


def merge_process_waves(docs: list[dict | None]) -> dict:
    """Cluster merge of per-process ``waves`` documents (process 0's
    /query roll-up — the same shape back, so it re-merges)."""
    by_epoch: dict[int, dict] = {}
    held: dict[str, int] = {}
    for doc in docs:
        if not doc:
            continue
        for w, n in (doc.get("held_total") or {}).items():
            held[w] = held.get(w, 0) + int(n)
        for wave in doc.get("recent") or []:
            ep = int(wave.get("epoch", -1))
            cur = by_epoch.get(ep)
            if cur is None:
                by_epoch[ep] = dict(wave)
                by_epoch[ep]["workers"] = dict(wave.get("workers", {}))
                continue
            cur["workers"].update(wave.get("workers", {}))
            if wave.get("duration_ms", 0.0) > cur.get("duration_ms", 0.0):
                for k in ("duration_ms", "critical_stage", "shares"):
                    cur[k] = wave.get(k)
            # holder re-election over the union of worker verdicts
            votes: dict[int, int] = {}
            for w in cur["workers"].values():
                h = w.get("holder")
                if h is not None:
                    votes[int(h)] = votes.get(int(h), 0) + 1
            if votes:
                cur["holder"] = min(votes, key=lambda x: (-votes[x], x))
                cur["agreed"] = len(votes) == 1
    recent = [by_epoch[ep] for ep in sorted(by_epoch)]
    return _finish_waves_doc(recent, held)


def _finish_waves_doc(recent: list[dict], held: dict[str, int]) -> dict:
    total_held = sum(held.values()) or 0
    return {
        "waves": len(recent),
        "recent": recent,
        "held_total": held,
        "holder_share": {
            w: round(n / total_held, 4) for w, n in sorted(held.items())
        }
        if total_held
        else {},
        "last": recent[-1] if recent else None,
    }


def render_report(waves_doc: dict | None, top_k: int = 10) -> str:
    """The ``pathway-tpu critpath`` report: top-K slowest waves with
    their holding worker and stage split, plus the holder tally."""
    if not waves_doc or not waves_doc.get("recent"):
        return "critpath: no commit waves recorded (async plane idle?)"
    lines = []
    held = waves_doc.get("holder_share") or {}
    if held:
        tally = "  ".join(
            f"w{w}:{share * 100:.0f}%"
            for w, share in sorted(
                held.items(), key=lambda kv: (-kv[1], kv[0])
            )
        )
        lines.append(
            f"waves held ({sum((waves_doc.get('held_total') or {}).values())}"
            f" waves): {tally}"
        )
    ranked = sorted(
        waves_doc["recent"],
        key=lambda d: -float(d.get("duration_ms", 0.0)),
    )[: max(1, int(top_k))]
    lines.append(
        f"top {len(ranked)} slowest waves "
        f"(of {len(waves_doc['recent'])} recorded):"
    )
    for d in ranked:
        split = " ".join(
            f"{p}={d.get('shares', {}).get(p, 0.0) * 100:.0f}%"
            for p in PHASES
            if d.get("shares", {}).get(p, 0.0) >= 0.005
        )
        holder = d.get("holder")
        agreed = "" if d.get("agreed", True) else " (disputed)"
        lines.append(
            f"  wave {d.get('epoch')} T={d.get('T')} "
            f"{d.get('duration_ms', 0.0):.1f}ms "
            f"holder=w{holder if holder is not None else '?'}{agreed} "
            f"critical={d.get('critical_stage')} "
            f"rounds={d.get('settle_rounds', 0)} [{split}]"
        )
    return "\n".join(lines)
