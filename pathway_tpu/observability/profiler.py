"""Always-on sampling profiler — frame-level continuous profiling.

The observability stack can *name* a bottleneck (``/attribution`` ranks
operators, the wave critical path ranks phases) but not show *which code
inside it* burns the time. This module closes that gap with the classic
continuous-profiling design (low-frequency stack sampling, collapsed
folds, cluster merge — the Google-Wide Profiling / parca lineage): a
background sampler thread walks ``sys._current_frames()`` at
``PATHWAY_PROFILE_HZ`` (default 19 Hz — a prime, so the sampler never
phase-locks with periodic engine work) and folds every thread's stack
into a bounded collapsed-stack table.

Two tables per process, both :class:`~.keyload.SpaceSaving` sketches
(``PATHWAY_PROFILE_STACKS`` counters), so eviction provably keeps the
heaviest stacks and cluster merge is associative with the usual epsilon
bound:

- **wall**: weight 1 per sample — where threads *are* (includes blocking:
  sleeps, queue waits, socket reads);
- **cpu**: weight = the thread's CPU-time delta since the previous sample
  (per-thread utime+stime via ``/proc/self/task/<tid>/stat``; Linux only,
  degrades to wall-only elsewhere) — where cycles *go*.

Every sample is tagged with the executing operator / fused-chain member
label by reading a per-thread op slot the executor updates as it sweeps
nodes — the same labels ``EngineStats.note_op_time`` feeds
``/attribution``, so profiles join against the attribution ranking
instead of floating beside it. Stack keys are collapsed-stack lines::

    thread:<name>;op:<Label#id>;root_fn (file.py:12);...;leaf_fn (f.py:9)

The profiler is ON by default and OFF with ``PATHWAY_PROFILE=0`` — the
kill switch silences everything at once: no sampler thread, no op slots
(``current_op_slot()`` returns ``None`` — one branch per node on the
executor hot path), no ingest stage counters, no ``pathway_profile_*``
metric families, no ``profile.*`` signals series. The bench A/B
(``bench.py ingest_stage_split`` lane) holds the on/off throughput delta
under 3%.

The sampler also deposits its top-K collapsed stacks into the mmap
flight ring (``flightrecorder.py``) every ``PATHWAY_PROFILE_FLIGHT_S``
seconds, so a supervisor crash bundle carries what the worker was
executing when it died. ``heap_document()`` adds the on-demand
``tracemalloc`` view (``/profile?heap=1``) for the memory plane.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any

from .keyload import SpaceSaving

__all__ = [
    "Profiler",
    "current_op_slot",
    "release_op_slot",
    "enabled",
    "heap_document",
    "THREAD_NAME",
]

DEFAULT_HZ = 19.0
DEFAULT_STACKS = 512
DEFAULT_HEAP_FRAMES = 16
DEFAULT_FLIGHT_S = 5.0
#: sampler thread name — smoke tests assert zero of these when disabled
THREAD_NAME = "pathway-profiler"
#: stacks deeper than this fold to their leaf-most suffix (bounded keys)
_MAX_DEPTH = 48
#: collapsed stacks deposited into the flight ring per flush
_FLIGHT_TOP_K = 8


def enabled() -> bool:
    """The plane-wide kill switch (``PATHWAY_PROFILE``, default on).
    Re-read per call like ``keyload.enabled()`` so tests that flip the
    env in-process see the change."""
    from ..internals.config import _env_bool

    return _env_bool("PATHWAY_PROFILE", True)


# -- per-thread operator context ---------------------------------------
#
# The executor cannot hand labels to the sampler through a thread-local
# (thread-locals are invisible cross-thread); instead each engine thread
# registers a slot object here and mutates its ``label`` attribute as it
# sweeps nodes. Attribute stores on a fixed slot are single bytecodes
# (GIL-atomic), so the hot path pays one attribute write per node and
# the sampler reads whatever label was live at sample time.


class _OpSlot:
    __slots__ = ("label",)

    def __init__(self) -> None:
        #: the /attribution label of the operator executing NOW
        #: (``Type#node_id`` — fused chains publish MEMBER labels), or
        #: None between sweeps
        self.label: str | None = None


_OP_SLOTS: dict[int, _OpSlot] = {}
_SLOTS_LOCK = threading.Lock()


def current_op_slot() -> _OpSlot | None:
    """The calling thread's operator-context slot (registered on first
    use), or ``None`` when profiling is off — callers keep the returned
    slot and null-check it once per node."""
    if not enabled():
        return None
    ident = threading.get_ident()
    slot = _OP_SLOTS.get(ident)
    if slot is None:
        slot = _OpSlot()
        with _SLOTS_LOCK:
            _OP_SLOTS[ident] = slot
    return slot


def release_op_slot() -> None:
    """Drop the calling thread's slot (executor run teardown): a parked
    pool thread no longer counts as an engine thread in the op-tagged
    share, and reused thread idents never inherit stale slots."""
    with _SLOTS_LOCK:
        _OP_SLOTS.pop(threading.get_ident(), None)


# -- the sampler --------------------------------------------------------


class Profiler:
    """Per-process sampling profiler; one instance per worker process,
    owned by the observability hub (started with the signals plane,
    stopped in ``hub.close()``)."""

    def __init__(
        self,
        hz: float | None = None,
        capacity: int | None = None,
        flight_interval_s: float | None = None,
        process_id: int = 0,
    ):
        from ..internals.config import _env_float, _env_int

        self.hz = (
            hz
            if hz is not None
            else max(0.1, _env_float("PATHWAY_PROFILE_HZ", DEFAULT_HZ))
        )
        self.capacity = (
            capacity
            if capacity is not None
            else max(8, _env_int("PATHWAY_PROFILE_STACKS", DEFAULT_STACKS))
        )
        self.flight_interval_s = (
            flight_interval_s
            if flight_interval_s is not None
            else _env_float("PATHWAY_PROFILE_FLIGHT_S", DEFAULT_FLIGHT_S)
        )
        self.process_id = int(process_id)
        self.wall = SpaceSaving(self.capacity)
        self.cpu = SpaceSaving(self.capacity)
        self.samples_total = 0
        #: AWAKE samples from threads holding an op slot (engine
        #: threads); parked waits (label-less, blocked in a scheduler
        #: wait) fold into the wall table but stay out of this
        #: denominator — an idle engine is not untagged work
        self.engine_samples = 0
        #: engine-thread samples that carried a live operator label
        self.op_tagged = 0
        self.errors_total = 0
        self.threads_last = 0
        self.cpu_supported = os.path.isdir("/proc/self/task")
        self._cpu_prev: dict[int, float] = {}
        try:
            self._clk_tck = float(os.sysconf("SC_CLK_TCK")) or 100.0
        except (AttributeError, ValueError, OSError):
            self._clk_tck = 100.0
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None
        self._started_at = time.monotonic()

    # -- lifecycle --

    def start(self) -> "Profiler":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop_evt.clear()
        t = threading.Thread(target=self._run, name=THREAD_NAME, daemon=True)
        self._thread = t
        t.start()
        return self

    def stop(self) -> None:
        """Stop and join the sampler; bounded join so a wedged sample
        read can never wedge engine shutdown (the thread is a daemon)."""
        self._stop_evt.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)
        self._thread = None

    def _run(self) -> None:
        period = 1.0 / self.hz
        next_flight = time.monotonic() + max(0.05, self.flight_interval_s)
        while not self._stop_evt.wait(period):
            try:
                self.sample_once()
            except Exception:
                self.errors_total += 1
            if self.flight_interval_s > 0:
                now = time.monotonic()
                if now >= next_flight:
                    next_flight = now + self.flight_interval_s
                    try:
                        self._deposit_flight()
                    except Exception:
                        self.errors_total += 1
        # final deposit so a clean stop leaves the last profile in the ring
        try:
            self._deposit_flight()
        except Exception:
            pass

    # -- sampling --

    def sample_once(self) -> int:
        """Walk every live thread's stack once; returns threads sampled.
        Public so tests drive the fold deterministically without timing."""
        me = threading.get_ident()
        names: dict[int, tuple[str, int | None]] = {}
        for t in threading.enumerate():
            if t.ident is not None:
                names[t.ident] = (t.name, getattr(t, "native_id", None))
        frames = sys._current_frames()
        cpu_now = self._cpu_times(names) if self.cpu_supported else {}
        n = 0
        with self._lock:
            for ident, frame in frames.items():
                if ident == me:
                    continue
                name, _tid = names.get(ident, (f"thread-{ident}", None))
                slot = _OP_SLOTS.get(ident)
                op = slot.label if slot is not None else None
                key = _fold_stack(frame, name, op)
                self.wall.observe(key, 1.0)
                self.samples_total += 1
                n += 1
                if slot is not None:
                    if op is not None:
                        self.engine_samples += 1
                        self.op_tagged += 1
                    elif not _is_parked(frame):
                        self.engine_samples += 1
                delta = cpu_now.get(ident)
                if delta:
                    self.cpu.observe(key, delta)
            self.threads_last = n
        return n

    def _cpu_times(
        self, names: dict[int, tuple[str, int | None]]
    ) -> dict[int, float]:
        """ident -> CPU seconds burned since the previous sample. The
        first sighting of a thread establishes its baseline (no delta)."""
        out: dict[int, float] = {}
        for ident, (_name, tid) in names.items():
            if tid is None:
                continue
            try:
                with open(f"/proc/self/task/{tid}/stat", "rb") as f:
                    stat = f.read()
                # fields after the parenthesized comm; utime+stime are
                # fields 14/15 of the full line = 12/13 post-comm (1-based)
                rest = stat.rsplit(b")", 1)[1].split()
                cpu = (int(rest[11]) + int(rest[12])) / self._clk_tck
            except (OSError, ValueError, IndexError):
                continue
            prev = self._cpu_prev.get(ident)
            self._cpu_prev[ident] = cpu
            if prev is not None and cpu > prev:
                out[ident] = cpu - prev
        return out

    # -- wire forms --

    def snapshot(self) -> dict:
        """JSON-serializable profile document — the per-process half of
        ``/profile`` (``profile_merge.merge_snapshots`` combines peers)."""
        with self._lock:
            return {
                "enabled": True,
                "process_id": self.process_id,
                "hz": self.hz,
                "capacity": self.capacity,
                "duration_s": round(time.monotonic() - self._started_at, 3),
                "samples_total": self.samples_total,
                "engine_samples": self.engine_samples,
                "op_tagged": self.op_tagged,
                "errors_total": self.errors_total,
                "threads": self.threads_last,
                "cpu_supported": self.cpu_supported,
                "wall": self.wall.snapshot(),
                "cpu": self.cpu.snapshot(),
            }

    def metrics_snapshot(self) -> dict[str, float]:
        """Small scalar surface for /metrics + the signals plane
        (``pathway_profile_*`` families, ``profile.*`` series)."""
        with self._lock:
            total = self.wall.total
            leaf: dict[str, float] = {}
            for key, count, _err in self.wall.items():
                fr = key.rsplit(";", 1)[-1]
                leaf[fr] = leaf.get(fr, 0.0) + count
            top_share = max(leaf.values()) / total if total and leaf else 0.0
            tagged_share = (
                self.op_tagged / self.engine_samples
                if self.engine_samples
                else 0.0
            )
            return {
                "samples_total": float(self.samples_total),
                "engine_samples_total": float(self.engine_samples),
                "errors_total": float(self.errors_total),
                "distinct_frames": float(len(self.wall)),
                "top_frame_share": round(top_share, 4),
                "op_tagged_share": round(tagged_share, 4),
            }

    def _deposit_flight(self) -> None:
        """Top-K collapsed stacks into the mmap flight ring — crash
        bundles then carry what the worker was executing when it died."""
        from .flightrecorder import get_recorder

        rec = get_recorder()
        if rec is None:
            return
        with self._lock:
            top = [
                [_trim_stack(key), round(count, 3)]
                for key, count, _err in self.wall.items()[:_FLIGHT_TOP_K]
            ]
            samples = self.samples_total
        if top:
            rec.record(
                "profile.top",
                process=self.process_id,
                samples=samples,
                top=top,
            )


def _is_parked(frame: Any) -> bool:
    """True when a label-less engine thread's leaf frame is a scheduler
    wait (``threading.Event``/``Condition`` wait, selector poll) or
    blocking transport socket I/O: the executor parks in the former
    between ticks, and stalls in the latter on peer backpressure —
    neither is *executing* Python-level work. Parked wall time still
    shows in the flamegraph (the ``wait``/``_send_vectored`` frames rank
    by self-time like any other); it just doesn't count against the
    op-tag coverage denominator, which answers "of the engine's executed
    samples, how many carried an operator label"."""
    code = frame.f_code
    fn = os.path.basename(code.co_filename)
    return (
        (fn == "threading.py" and code.co_name == "wait")
        or (fn == "selectors.py" and code.co_name == "select")
        or (
            fn == "cluster.py"
            and code.co_name in ("_send_vectored", "_recv_into")
        )
    )


def _fold_stack(frame: Any, thread_name: str, op: str | None) -> str:
    """One thread's stack -> collapsed-stack key, root-first. Frame
    labels use ``co_firstlineno`` (the def site, stable across samples)
    so identical logical stacks fold to one table entry."""
    parts: list[str] = []
    f = frame
    while f is not None and len(parts) < _MAX_DEPTH:
        code = f.f_code
        parts.append(
            f"{code.co_name} "
            f"({os.path.basename(code.co_filename)}:{code.co_firstlineno})"
        )
        f = f.f_back
    parts.reverse()
    head = [f"thread:{thread_name}"]
    if op is not None:
        head.append(f"op:{op}")
    return ";".join(head + parts)


def _trim_stack(key: str, keep: int = 6) -> str:
    """Flight-ring form: thread/op head + the leaf-most frames — rings
    are small (256 KB default) and the leaf end is the forensic signal."""
    parts = key.split(";")
    head = [p for p in parts[:2] if p.startswith(("thread:", "op:"))]
    frames = parts[len(head):]
    if len(frames) > keep:
        frames = ["..."] + frames[-keep:]
    return ";".join(head + frames)


# -- on-demand heap snapshot (tracemalloc) ------------------------------


def heap_document(top: int = 25) -> dict:
    """The memory-plane companion: arm ``tracemalloc`` on first call
    (``PATHWAY_PROFILE_HEAP_FRAMES`` frames of allocation traceback) and
    return the top allocation sites. First call returns ``armed_now:
    true`` with near-empty stats — allocations are traced from arming
    onward; call again after the suspect workload."""
    import tracemalloc

    from ..internals.config import _env_int

    frames = max(1, _env_int("PATHWAY_PROFILE_HEAP_FRAMES", DEFAULT_HEAP_FRAMES))
    armed_now = False
    if not tracemalloc.is_tracing():
        tracemalloc.start(frames)
        armed_now = True
    current, peak = tracemalloc.get_traced_memory()
    entries = []
    try:
        snap = tracemalloc.take_snapshot()
        for st in snap.statistics("traceback")[: max(1, top)]:
            entries.append(
                {
                    "size_kb": round(st.size / 1024.0, 1),
                    "count": st.count,
                    "stack": [
                        f"{os.path.basename(fr.filename)}:{fr.lineno}"
                        for fr in st.traceback
                    ],
                }
            )
    except Exception:
        pass  # heap view is best-effort; never fail the endpoint
    return {
        "armed_now": armed_now,
        "frames": frames,
        "traced_current_kb": round(current / 1024.0, 1),
        "traced_peak_kb": round(peak / 1024.0, 1),
        "top": entries,
    }
