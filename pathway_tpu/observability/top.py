"""``pathway-tpu top`` — live terminal dashboard over ``/query``.

Polls the hub's merged windowed-signals endpoint (process 0 under
``spawn -n M``) and redraws a plain-text dashboard: per-worker tick
rate, row rates, frontier lag, tick/e2e latency percentiles, comm queue
depth + send MB/s, the current bottleneck operator, and firing alerts.
Plain ANSI redraw (no curses dependency): each frame repaints from the
home position, so it works in every terminal the test rig has — and
:func:`render_frame` is a pure function of the ``/query`` document, so
tests and the signals smoke assert rendering without a TTY.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.request
from typing import Any

__all__ = ["fetch_query", "render_frame", "run_top"]

_CLEAR = "\x1b[H\x1b[2J"


def fetch_query(url: str, timeout: float = 5.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def _fmt(v: Any, unit: str = "", nd: int = 1) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}{unit}"
    return f"{v}{unit}"


def _keyload_line(kl: dict | None) -> str | None:
    """The shard-skew line (observability/keyload.py skew_line)."""
    if not kl:
        return None
    from .keyload import skew_line

    return skew_line(kl)


def render_frame(doc: dict, now: float | None = None) -> str:
    """One dashboard frame from a ``/query`` document."""
    if now is None:
        now = time.time()
    lines: list[str] = []
    procs = doc.get("processes", [doc.get("process_id", 0)])
    lines.append(
        f"pathway-tpu top — {len(doc.get('workers', {}))} worker(s), "
        f"{len(procs)} process(es), window {_fmt(doc.get('window_s'), 's')}"
        f", sampled every {_fmt(doc.get('sample_s'), 's')}"
    )
    lines.append("")
    header = (
        f"{'WORKER':>6} {'TICK/S':>8} {'ROWS/S':>10} {'OUT/S':>10} "
        f"{'LAG MS':>9} {'TICK P95':>9} {'E2E P95':>9}"
    )
    lines.append(header)
    workers = doc.get("workers", {})
    for wid in sorted(workers, key=lambda w: int(w) if w.isdigit() else 0):
        w = workers[wid]
        lag = w.get("frontier_lag_vs_max_ms")
        if lag is None:
            lag = w.get("frontier_lag_ms")
        stale = w.get("stale_s")
        lines.append(
            f"{wid:>6} {_fmt(w.get('tick_rate')):>8} "
            f"{_fmt(w.get('row_rate')):>10} "
            f"{_fmt(w.get('output_rate')):>10} "
            f"{_fmt(lag):>9} "
            f"{_fmt(w.get('tick_p95_ms'), nd=2):>9} "
            f"{_fmt(w.get('e2e_p95_ms'), nd=2):>9}"
            + (f"  STALE {stale:.0f}s" if stale is not None else "")
        )
    if not workers:
        lines.append("  (no worker series yet — sampler warming up)")
    lines.append("")
    comm = doc.get("comm", {})
    # merged docs key comm by process; single-process docs are flat
    comm_by_proc = (
        comm
        if comm and all(isinstance(v, dict) for v in comm.values())
        else {str(doc.get("process_id", 0)): comm}
    )
    for proc in sorted(comm_by_proc):
        c = comm_by_proc[proc] or {}
        if not c:
            continue
        lines.append(
            f"comm p{proc}: send queue {_fmt(c.get('send_queue_depth'), nd=0)}"
            f" frames, {_fmt(c.get('send_mb_per_sec'), ' MB/s', 2)}, "
            f"inbox {_fmt(c.get('cluster_inbox_depth'), nd=0)}"
        )
    mem = doc.get("memory", {})
    # merged docs key memory by process; single-process docs are flat
    mem_by_proc = (
        mem
        if mem and all(isinstance(v, dict) for v in mem.values())
        else {str(doc.get("process_id", 0)): mem}
    )
    for proc in sorted(mem_by_proc):
        m = mem_by_proc[proc] or {}
        if not m:
            continue
        line = (
            f"mem p{proc}: rss {_fmt(m.get('rss_bytes', 0) / 1e6, ' MB', 0)}"
        )
        if m.get("state_budget_bytes"):
            line += (
                f", state {_fmt(m.get('state_resident_bytes', 0) / 1e6, nd=1)}"
                f"/{_fmt(m['state_budget_bytes'] / 1e6, ' MB', 1)} resident"
                f", {_fmt(m.get('state_spilled_bytes', 0) / 1e6, ' MB', 1)}"
                f" spilled ({_fmt(m.get('spill_events_total'), nd=0)} spills)"
            )
        entries = m.get("key_registry_entries", 0)
        if entries:
            line += f", registry {entries:.0f} key(s)"
            if m.get("key_registry_cold_entries"):
                line += f" ({m['key_registry_cold_entries']:.0f} cold)"
            if m.get("key_registry_frozen"):
                line += " FROZEN"
        lines.append(line)
    sinks = doc.get("sinks", {})
    # merged docs key sinks by process; single-process docs are flat
    # (sink name -> counters). Flat docs have dicts of floats one level
    # down, merged docs dicts of dicts.
    flat: dict[str, dict] = {}

    def _absorb(name: str, counters: dict) -> None:
        # a sink is constructed (with zeroed counters) on EVERY worker but
        # delivers on one — keep the copy that has actually moved, never
        # let a muted peer's zeros shadow the live series
        cur = flat.get(name)
        if cur is None or (counters or {}).get(
            "delivered_rows_total", 0
        ) >= (cur or {}).get("delivered_rows_total", 0):
            flat[name] = counters

    for k, v in (sinks or {}).items():
        if v and all(isinstance(x, dict) for x in v.values()):
            for name, counters in v.items():  # process-keyed: union
                _absorb(name, counters)
        elif isinstance(v, dict):
            _absorb(k, v)
    for sname in sorted(flat):
        s = flat[sname] or {}
        if not s:
            continue
        line = (
            f"sink {sname}: {_fmt(s.get('delivered_rows_total'), nd=0)} "
            f"row(s) delivered, queue {_fmt(s.get('queue_depth'), nd=0)}"
        )
        if s.get("retries_total"):
            line += f", {s['retries_total']:.0f} retr(ies)"
        if s.get("dlq_total"):
            line += f", DLQ {s['dlq_total']:.0f}"
        if s.get("breaker_open"):
            line += ", breaker OPEN"
        lines.append(line)
    udf = doc.get("udf", {})
    # merged docs key udf by process; single-process docs are flat
    udf_by_proc = (
        udf
        if udf and all(isinstance(v, dict) for v in udf.values())
        else {str(doc.get("process_id", 0)): udf}
    )
    for proc in sorted(udf_by_proc):
        u = udf_by_proc[proc] or {}
        if not any(u.values()):
            continue
        lines.append(
            f"udf p{proc}: {_fmt(u.get('lifted_total'), nd=0)} lifted, "
            f"{_fmt(u.get('traced_total'), nd=0)} traced, "
            f"{_fmt(u.get('perrow_rows_total'), nd=0)} row(s) per-row"
        )
    fus = doc.get("fusion", {})
    # merged docs key fusion by process; single-process docs are flat
    fus_by_proc = (
        fus
        if fus and all(isinstance(v, dict) for v in fus.values())
        else {str(doc.get("process_id", 0)): fus}
    )
    for proc in sorted(fus_by_proc):
        f = fus_by_proc[proc] or {}
        if not any(f.values()):
            continue
        line = (
            f"fusion p{proc}: {_fmt(f.get('chains_total'), nd=0)} chain(s) "
            f"({_fmt(f.get('fused_ops_total'), nd=0)} ops), "
            f"{_fmt(f.get('preambles_total'), nd=0)} preamble(s), "
            f"{_fmt(f.get('fallbacks_total'), nd=0)} fallback(s)"
        )
        if f.get("jit_chains_total"):
            line += f", {_fmt(f.get('jit_chains_total'), nd=0)} XLA"
        lines.append(line)
    srv = doc.get("serve", {})
    # merged docs key serve by process; single-process docs are flat
    srv_by_proc = (
        srv
        if srv and all(isinstance(v, dict) for v in srv.values())
        else {str(doc.get("process_id", 0)): srv}
    )
    for proc in sorted(srv_by_proc):
        s = srv_by_proc[proc] or {}
        if not any(s.values()):
            continue
        line = (
            f"serve p{proc}: {_fmt(s.get('queries_total'), nd=0)} "
            f"quer(ies), inflight {_fmt(s.get('inflight'), nd=0)}/"
            f"{_fmt(s.get('max_inflight'), nd=0)}, "
            f"queue {_fmt(s.get('queue_depth'), nd=0)}, "
            f"{_fmt(s.get('rejected_total'), nd=0)} rejected"
        )
        if s.get("degraded_total"):
            line += f", {s['degraded_total']:.0f} degraded"
        if s.get("deadline_dropped_total"):
            line += (
                f", {s['deadline_dropped_total']:.0f} deadline-dropped"
            )
        lines.append(line)
    ing = doc.get("ingest", {})
    # merged docs key ingest by process; single-process docs are flat
    ing_by_proc = (
        ing
        if ing and all(isinstance(v, dict) for v in ing.values())
        else {str(doc.get("process_id", 0)): ing}
    )
    for proc in sorted(ing_by_proc):
        g = ing_by_proc[proc] or {}
        if not any(g.values()):
            continue
        total = (
            g.get("parse_s", 0) + g.get("hash_s", 0) + g.get("delta_s", 0)
        )

        def _pct(v: float) -> str:
            return f"{v / total * 100:.0f}%" if total else "-"

        lines.append(
            f"ingest p{proc}: parse {_fmt(g.get('parse_s'), 's', 3)} "
            f"({_pct(g.get('parse_s', 0))}), "
            f"hash {_fmt(g.get('hash_s'), 's', 3)} "
            f"({_pct(g.get('hash_s', 0))}), "
            f"delta {_fmt(g.get('delta_s'), 's', 3)} "
            f"({_pct(g.get('delta_s', 0))}) over "
            f"{_fmt(g.get('rows_total'), nd=0)} row(s)/"
            f"{_fmt(g.get('flushes_total'), nd=0)} flush(es)"
        )
        # per-connector stage split, costliest first: names the
        # bottleneck connector instead of one anonymous ingest total
        conns = g.get("connectors") or {}

        def _conn_total(c: dict) -> float:
            return (
                c.get("parse_s", 0) + c.get("hash_s", 0) + c.get("delta_s", 0)
            )

        for cname in sorted(conns, key=lambda n: -_conn_total(conns[n])):
            c = conns[cname]
            lines.append(
                f"  {cname}: parse {_fmt(c.get('parse_s'), 's', 3)}, "
                f"hash {_fmt(c.get('hash_s'), 's', 3)}, "
                f"delta {_fmt(c.get('delta_s'), 's', 3)} over "
                f"{_fmt(c.get('rows_total'), nd=0)} row(s)"
            )
    prof = doc.get("profile", {})
    # merged docs key profile by process; single-process docs are flat
    prof_by_proc = (
        prof
        if prof and all(isinstance(v, dict) for v in prof.values())
        else {str(doc.get("process_id", 0)): prof}
    )
    for proc in sorted(prof_by_proc):
        p = prof_by_proc[proc] or {}
        if not any(p.values()):
            continue
        tagged = p.get("op_tagged_share")
        lines.append(
            f"profile p{proc}: {_fmt(p.get('samples_total'), nd=0)} "
            f"sample(s), {_fmt(p.get('distinct_frames'), nd=0)} frame(s)"
            + (
                f", {tagged * 100:.0f}% op-tagged"
                if tagged is not None
                else ""
            )
        )
    waves = doc.get("waves")
    if waves and waves.get("last"):
        last = waves["last"]
        share = waves.get("holder_share") or {}
        holder = last.get("holder")
        held = (
            f", w{holder} holds {share.get(str(holder), 0) * 100:.0f}% "
            "of waves"
            if holder is not None
            else ""
        )
        lines.append(
            f"waves: {_fmt(waves.get('waves'), nd=0)} recorded, last "
            f"{_fmt(last.get('duration_ms'), ' ms', 1)} "
            f"(critical {last.get('critical_stage')}, "
            f"holder w{holder if holder is not None else '?'}{held})"
        )
    kl_line = _keyload_line(doc.get("keyload"))
    if kl_line:
        lines.append(kl_line)
    sup = doc.get("supervisor")
    if sup is not None and sup.get("window_failures") is not None:
        budget = sup.get("window_budget")
        breaker = (
            "OPEN" if sup.get("circuit_open")
            else f"{sup['window_failures']}/{_fmt(budget, nd=0)} window"
        )
        lines.append(
            f"supervisor: {_fmt(sup.get('restarts'), nd=0)} restart(s), "
            f"breaker {breaker}"
            + (f" — last: {sup['reason']}" if sup.get("reason") else "")
        )
    auto = doc.get("autoscale")
    if auto is not None:
        line = (
            f"autoscale [{auto.get('range')}]: "
            f"{_fmt(auto.get('events'), nd=0)} scale event(s)"
        )
        if auto.get("last_decision"):
            line += f", last {auto['last_decision']}"
        if auto.get("last_pause_ms") is not None:
            line += f" (pause {auto['last_pause_ms']:.0f} ms)"
        lines.append(line)
    att = doc.get("attribution") or {}
    bottleneck = att.get("bottleneck")
    if bottleneck:
        ranked = att.get("ranked", [])
        share = ranked[0].get("share") if ranked else None
        lines.append(
            f"bottleneck: {bottleneck}"
            + (f" ({share * 100:.0f}% of busy time)" if share else "")
        )
    alerts = doc.get("alerts", {}) or {}
    active = alerts.get("active", [])
    if active:
        lines.append("")
        lines.append(f"ALERTS ({len(active)} firing):")
        for ev in active[-8:]:
            age = max(0.0, now - ev.get("t", now))
            lines.append(
                f"  [{ev.get('severity', '?'):>8}] {ev.get('rule')}: "
                f"{ev.get('expr')} {ev.get('op')} {ev.get('threshold')} "
                f"(value {_fmt(ev.get('value'), nd=3)}, {age:.0f}s ago)"
            )
    else:
        lines.append("alerts: none firing")
    return "\n".join(lines) + "\n"


def run_top(
    url: str,
    interval_s: float = 1.0,
    frames: int = 0,
    clear: bool = True,
    out=None,
) -> int:
    """Poll ``url`` and redraw; ``frames=0`` runs until interrupted.
    Returns a process exit code (0 on success, 1 when the endpoint never
    answered)."""
    out = out or sys.stdout
    drawn = 0
    ok = False
    while True:
        try:
            doc = fetch_query(url)
        except Exception as e:
            out.write(f"pathway-tpu top: {url} unreachable ({e})\n")
            out.flush()
            if frames and drawn + 1 >= frames:
                return 0 if ok else 1
            drawn += 1
            time.sleep(interval_s)
            continue
        ok = True
        frame = render_frame(doc)
        if clear:
            out.write(_CLEAR)
        out.write(frame)
        out.flush()
        drawn += 1
        if frames and drawn >= frames:
            return 0
        try:
            time.sleep(interval_s)
        except KeyboardInterrupt:  # pragma: no cover — interactive exit
            return 0
