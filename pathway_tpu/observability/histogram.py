"""Lock-cheap log-bucketed latency histogram.

The reference engine reports distribution-level operator latency through
its OTLP metrics pipeline (``src/engine/telemetry.rs:47-156``); the seed
only kept scalar sums and a last-value gauge, which cannot distinguish a
steady p99 regression from one slow outlier. ``LogHistogram`` fills that
gap with the classic HdrHistogram-style trick reduced to its cheapest
form: values are non-negative integer nanoseconds and the bucket index is
``value.bit_length()`` — one CPython int op, no float math, no search.
Bucket ``i`` therefore covers ``[2**(i-1), 2**i)`` ns, a ~2x resolution
geometric ladder spanning 1 ns to ~290 years in 64 buckets.

Thread-safety: the hot path (``observe``) deliberately takes no lock.
Under the GIL ``list[i] += 1`` can lose an increment when two executor
threads collide on the same bucket, which skews a count by at most the
collision rate — acceptable for telemetry, and the reason the executor
can afford to observe every tick. ``snapshot()`` copies the bucket array
and derives the total from it, so the buckets and ``count`` a reader
(the /metrics endpoint, the OTLP flusher, cluster roll-up) sees are
always mutually consistent even when taken mid-observe.

Snapshots are plain JSON dicts so mesh workers can ship them across
processes (``parallel/cluster.py`` frames or an HTTP scrape) and process
0 can :func:`merge` them into a cluster-level distribution.
"""

from __future__ import annotations

import threading

__all__ = ["LogHistogram", "merge_snapshots", "quantile_from_snapshot"]

N_BUCKETS = 64


class LogHistogram:
    """Log2-bucketed histogram of non-negative integer values (nanoseconds
    by convention for all engine latency series)."""

    __slots__ = ("_counts", "_sum", "_count", "_lock")

    def __init__(self) -> None:
        self._counts = [0] * N_BUCKETS
        self._sum = 0
        self._count = 0
        self._lock = threading.Lock()

    # -- hot path ------------------------------------------------------

    def observe(self, value_ns: int) -> None:
        """Record one value. No lock: a lost increment under thread
        collision is an accepted telemetry-grade error."""
        v = int(value_ns)
        if v < 0:
            v = 0
        i = v.bit_length()
        if i >= N_BUCKETS:
            i = N_BUCKETS - 1
        self._counts[i] += 1
        self._sum += v
        self._count += 1

    def __len__(self) -> int:
        return self._count

    # -- read side -----------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-serializable state: ``{"counts", "sum", "count"}`` (counts
        per log2 bucket, sum in ns). ``count`` is derived from the bucket
        array, not ``_count``: observe() is lock-free, so a snapshot taken
        mid-observe could otherwise see a bucket increment whose ``_count``
        update is still pending — and a cumulative ``_bucket`` series
        exceeding its ``+Inf``/``_count`` total is non-monotone exposition
        text. Deriving keeps buckets and total self-consistent by
        construction (``sum`` may trail by the in-flight value, which only
        skews the mean — telemetry-grade)."""
        with self._lock:
            counts = list(self._counts)
            return {
                "counts": counts,
                "sum": int(self._sum),
                "count": sum(counts),
            }

    def quantile(self, q: float) -> float:
        """Approximate q-quantile in nanoseconds (geometric bucket
        midpoint; ~±41% worst case, exact enough for p50/p95/p99 trend
        lines)."""
        return quantile_from_snapshot(self.snapshot(), q)

    def percentiles(self) -> dict[str, float]:
        snap = self.snapshot()
        return {
            "p50": quantile_from_snapshot(snap, 0.50),
            "p95": quantile_from_snapshot(snap, 0.95),
            "p99": quantile_from_snapshot(snap, 0.99),
        }


def merge_snapshots(snaps: list[dict]) -> dict:
    """Pointwise sum of histogram snapshots — the cluster roll-up merge.
    Log buckets share boundaries across workers, so merging is exact."""
    counts = [0] * N_BUCKETS
    total_sum = 0
    total_count = 0
    for s in snaps:
        for i, c in enumerate(s.get("counts", ())[:N_BUCKETS]):
            counts[i] += int(c)
        total_sum += int(s.get("sum", 0))
        total_count += int(s.get("count", 0))
    return {"counts": counts, "sum": total_sum, "count": total_count}


def quantile_from_snapshot(snap: dict, q: float) -> float:
    counts = snap["counts"]
    total = snap["count"]
    if total <= 0:
        return 0.0
    rank = max(1, int(q * total + 0.5))
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen >= rank:
            if i == 0:
                return 0.0
            lo = 1 << (i - 1)
            hi = 1 << i
            # geometric midpoint of the bucket
            return float((lo * hi) ** 0.5)
    return float(1 << (N_BUCKETS - 1))
