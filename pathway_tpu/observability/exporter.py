"""Periodic telemetry flusher.

The seed exported telemetry exactly once, at run exit — a crashed or
OOM-killed streaming run left nothing behind. This background thread
(the analog of the reference's batched OTLP export pipeline,
``src/engine/telemetry.rs:97-156``) flushes every N seconds:

- the local Chrome-trace file (``PATHWAY_TRACE_FILE``) is rewritten, so
  the newest window of spans survives a crash;
- tracer events appended since the last push go to the configured OTLP
  endpoints (incremental — the shared ``_otlp_mark`` cursor also keeps
  the end-of-run export from re-sending them);
- engine histograms (tick duration, per-operator processing time, output
  latency) ship as OTLP histogram data points.

Interval: ``PATHWAY_TELEMETRY_FLUSH_S`` (``internals/config.py``),
default 60, ``0`` disables. Export never raises into the run.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

__all__ = ["PeriodicFlusher", "start_periodic_flusher"]


class PeriodicFlusher:
    def __init__(
        self,
        interval_s: float,
        hub: Any = None,
        endpoints: list[str] | None = None,
    ):
        self.interval_s = interval_s
        self.hub = hub
        self._endpoints = endpoints or []
        self._exporters: list[Any] | None = None  # built lazily, once
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.flushes = 0

    def _make_exporters(self) -> list[Any]:
        if self._exporters is None:
            from ..internals.telemetry import OtlpExporter

            # one exporter per endpoint for the flusher's lifetime: every
            # push shares one run id / trace id, so the collector sees a
            # single coherent run instead of one per flush
            self._exporters = [OtlpExporter(ep) for ep in self._endpoints]
        return self._exporters

    def flush_once(self) -> None:
        """One flush cycle; swallows everything — telemetry must not fail
        (or slow down by raising into) the run it observes."""
        try:
            self._flush_inner()
            self.flushes += 1
        except Exception:
            pass

    def _flush_inner(self) -> None:
        from ..internals.tracing import get_tracer

        tracer = get_tracer()
        exporters = self._make_exporters()
        if tracer is not None:
            tracer.flush()  # crash-durable local trace file
            if exporters:
                events, mark = tracer.events_since(tracer._otlp_mark)
                if events:
                    origin_unix_ns = time.time_ns() - (
                        time.perf_counter_ns() - tracer._origin
                    )
                    for exp in exporters:
                        exp.export_events(events, origin_unix_ns)
                    tracer._otlp_mark = mark
        if exporters and self.hub is not None:
            points = self._histogram_points()
            if points:
                for exp in exporters:
                    exp.export_histograms(points, time.time_ns())

    def _histogram_points(self) -> list[tuple[str, dict, dict]]:
        points: list[tuple[str, dict, dict]] = []
        for snap in self.hub.local_snapshots():
            attrs = {"worker": snap.get("worker", 0)}
            points.append(
                ("pathway.tick_duration", attrs, snap["tick_duration"])
            )
            if snap.get("latency_hist", {}).get("count"):
                points.append(
                    ("pathway.output_latency", attrs, snap["latency_hist"])
                )
            for op, hsnap in snap.get("node_time_hist", {}).items():
                points.append(
                    (
                        "pathway.operator_processing",
                        {**attrs, "operator": op},
                        hsnap,
                    )
                )
        return points

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "PeriodicFlusher":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="pathway-telemetry-flush"
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.flush_once()

    def stop(self) -> None:
        """Stop the loop, then flush one last time: a run shorter than the
        interval would otherwise export zero histogram datapoints (the
        caller's export_from_env only ships tracer events), and even long
        runs would leave the collector's cumulative totals one interval
        stale. The shared ``_otlp_mark`` cursor keeps the span side
        incremental, so nothing double-exports."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.flush_once()


def start_periodic_flusher(hub: Any = None) -> PeriodicFlusher | None:
    """Env-gated: starts a flusher when a positive interval is configured
    AND there is something to flush (a trace file or an OTLP endpoint)."""
    from ..internals.config import get_pathway_config
    from ..internals.tracing import get_tracer

    try:
        cfg = get_pathway_config()
        interval = cfg.telemetry_flush_s
    except RuntimeError:
        interval = 60.0
    if interval <= 0:
        return None
    endpoints = sorted(
        {
            e
            for e in (
                os.environ.get("PATHWAY_TELEMETRY_SERVER"),
                os.environ.get("PATHWAY_MONITORING_SERVER"),
            )
            if e
        }
    )
    tracer = get_tracer()
    has_trace_file = tracer is not None and tracer.path is not None
    if not endpoints and not has_trace_file:
        return None
    return PeriodicFlusher(interval, hub=hub, endpoints=endpoints).start()
