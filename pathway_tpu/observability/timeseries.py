"""In-process windowed time-series store — the cluster's signal plane.

The `/metrics` + `/snapshot` surfaces built in the observability arc are
*point-in-time*: every derived signal (a rate, a trend, a sustained
breach, a latency percentile *over the last N seconds*) had to be
computed by an external scraper. This module keeps those derivations in
the cluster, in the Monarch/Prometheus in-process-aggregation lineage
(PAPERS.md): a background sampler snapshots every ``EngineStats``
gauge/counter/histogram plus the comm backend counters at a fixed
cadence (``PATHWAY_SIGNALS_SAMPLE_S``) into per-series ring buffers
bounded by the window (``PATHWAY_SIGNALS_WINDOW_S``), and the
:class:`Signals` API answers windowed queries over them:

- ``rate(name, window)`` / ``delta(name, window)`` for counters;
- ``avg/min/max/last`` for gauges;
- ``percentile(name, q, window)`` for log2-histogram series — the
  cumulative bucket counts at the window edges are differenced, which
  yields the *exact* distribution of observations inside the window
  (buckets share boundaries across samples, so the diff is lossless);
- ``sustained_above/below(name, threshold, for_s)`` — the predicate
  shape SLO rules (``observability/slo.py``) and the future traffic
  autoscaler consume.

Series are keyed ``(metric, worker)`` — ``worker=None`` holds
process-level series (comm backend counters). The store is the exact
input the autoscaler arc will read; over HTTP it backs the hub's
``/query`` endpoint (``engine/http_server.py``), which process 0 merges
across peers the same way it merges ``/snapshot``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable

from .histogram import N_BUCKETS, quantile_from_snapshot

__all__ = [
    "DEFAULT_SAMPLE_S",
    "DEFAULT_WINDOW_S",
    "Signals",
    "SignalsPlane",
    "TimeSeriesStore",
]

DEFAULT_SAMPLE_S = 0.5
DEFAULT_WINDOW_S = 60.0

#: metric-name prefixes of per-operator series (attribution input)
OP_TIME_PREFIX = "op_time_ns:"
OP_ROWS_PREFIX = "op_rows:"


class TimeSeriesStore:
    """Ring-buffered ``(metric, worker) -> [(t, value), ...]`` store.

    ``value`` is a float for counter/gauge series or a list of cumulative
    log2-bucket counts for histogram series. Appends come from the
    sampler thread; reads from HTTP handler threads and SLO evaluation —
    one lock, copies out."""

    def __init__(self, capacity: int):
        self.capacity = max(4, int(capacity))
        self._series: dict[tuple[str, int | None], deque] = {}
        self._appended: dict[tuple[str, int | None], int] = {}
        self._lock = threading.Lock()

    def record(
        self, metric: str, value: Any, worker: int | None = None,
        t: float | None = None,
    ) -> None:
        if t is None:
            t = time.time()
        key = (metric, worker)
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                ring = self._series[key] = deque(maxlen=self.capacity)
            ring.append((t, value))
            self._appended[key] = self._appended.get(key, 0) + 1

    def covers_birth(
        self, metric: str, worker: int | None, window_s: float,
    ) -> bool:
        """True when the window reaches back to the series' very first
        sample (nothing evicted, nothing older outside the window) — a
        cumulative-histogram diff may then use a zero baseline, so
        observations from before the first sample still count."""
        with self._lock:
            key = (metric, worker)
            ring = self._series.get(key)
            if not ring:
                return False
            if self._appended.get(key, 0) > len(ring):
                return False  # ring evicted older samples
            first_t = ring[0][0]
            last_t = ring[-1][0]
        return last_t - first_t <= window_s

    def points(
        self, metric: str, worker: int | None = None,
        window_s: float | None = None,
    ) -> list[tuple[float, Any]]:
        with self._lock:
            ring = self._series.get((metric, worker))
            pts = list(ring) if ring else []
        if window_s is None or not pts:
            return pts
        cutoff = pts[-1][0] - window_s
        # keep the last point at-or-before the cutoff too: a counter
        # delta over the window needs the value at the window's LEFT
        # edge, and a sustained-for check needs coverage of the FULL
        # horizon — with a jittered sample cadence no point lands
        # exactly on the cutoff, so the straddling sample is the edge
        i = len(pts) - 1
        while i > 0 and pts[i - 1][0] >= cutoff:
            i -= 1
        if i > 0 and pts[i][0] > cutoff:
            i -= 1
        return pts[i:]

    def workers(self) -> list[int]:
        with self._lock:
            return sorted({
                w for (_m, w) in self._series if w is not None
            })

    def metrics(self, worker: int | None = None) -> list[str]:
        with self._lock:
            return sorted({
                m for (m, w) in self._series if w == worker
            })


def _hist_window_snapshot(
    pts: list[tuple[float, Any]], zero_baseline: bool = False,
) -> dict:
    """Difference the cumulative bucket counts at the window edges into
    one histogram snapshot of the observations inside the window.
    ``zero_baseline`` means the window reaches the series' birth, so the
    left edge is an all-zero histogram (observations recorded before the
    first sample still count)."""
    if not pts:
        return {"counts": [0] * N_BUCKETS, "sum": 0, "count": 0}
    first = [0] * N_BUCKETS if zero_baseline else list(pts[0][1])
    last = list(pts[-1][1])
    counts = [
        max(0, int(b) - int(a))
        for a, b in zip(
            first + [0] * (len(last) - len(first)), last
        )
    ]
    if len(counts) < N_BUCKETS:
        counts = counts + [0] * (N_BUCKETS - len(counts))
    return {
        "counts": counts[:N_BUCKETS],
        "sum": 0,
        "count": sum(counts[:N_BUCKETS]),
    }


def _scalar(metric: str, v: Any) -> float:
    """A series value as a float — histogram series (list-of-bucket
    values) only support the percentile ops, and asking rate()/avg() of
    one must be a clean ValueError, not a TypeError out of a handler."""
    if isinstance(v, (list, tuple)):
        raise ValueError(
            f"{metric!r} is a histogram series — use p50/p95/p99, not a "
            "scalar op"
        )
    return float(v)


class Signals:
    """Windowed queries over a :class:`TimeSeriesStore` — the
    programmatic input for SLO rules, ``/query``, and the autoscaler.

    ``sample_s`` is the sampler cadence feeding the store, when known
    (the :class:`SignalsPlane` passes its own). It arms the sampler-gap
    guard on the sustained predicates: a hole in the samples is a hole
    in the evidence, not sustained coverage."""

    #: expression ops accepted by :meth:`eval` (``op(metric)`` strings)
    OPS = ("rate", "delta", "avg", "min", "max", "last",
           "p50", "p95", "p99")

    def __init__(
        self, store: TimeSeriesStore, sample_s: float | None = None
    ):
        self.store = store
        self.sample_s = sample_s

    # -- scalar queries -----------------------------------------------

    def last(self, metric: str, worker: int | None = None) -> float | None:
        pts = self.store.points(metric, worker)
        return _scalar(metric, pts[-1][1]) if pts else None

    def delta(
        self, metric: str, window_s: float, worker: int | None = None,
    ) -> float | None:
        """Counter increase over the window (clamped at 0 — a process
        restart resets counters; a negative delta is a reset, not
        regress)."""
        pts = self.store.points(metric, worker, window_s)
        if len(pts) < 2:
            return None
        return max(
            0.0, _scalar(metric, pts[-1][1]) - _scalar(metric, pts[0][1])
        )

    def rate(
        self, metric: str, window_s: float, worker: int | None = None,
    ) -> float | None:
        """Counter increase per second over the window."""
        pts = self.store.points(metric, worker, window_s)
        if len(pts) < 2:
            return None
        dt = pts[-1][0] - pts[0][0]
        if dt <= 0:
            return None
        return (
            max(0.0, _scalar(metric, pts[-1][1]) - _scalar(metric, pts[0][1]))
            / dt
        )

    def agg(
        self, metric: str, window_s: float, fn: Callable,
        worker: int | None = None,
    ) -> float | None:
        pts = self.store.points(metric, worker, window_s)
        if not pts:
            return None
        return float(fn(_scalar(metric, v) for _t, v in pts))

    def percentile(
        self, metric: str, q: float, window_s: float,
        worker: int | None = None,
    ) -> float | None:
        """q-quantile (ns by convention) of a histogram series over the
        window, or None when the window holds no observations."""
        pts = self.store.points(metric, worker, window_s)
        snap = _hist_window_snapshot(
            pts, self.store.covers_birth(metric, worker, window_s)
        )
        if snap["count"] <= 0:
            return None
        return quantile_from_snapshot(snap, q)

    # -- sustained predicates -----------------------------------------

    def _sustained(
        self, metric: str, threshold: float, for_s: float,
        worker: int | None, above: bool,
    ) -> bool:
        """True when every sample in the last ``for_s`` seconds breaches
        the threshold AND the samples actually cover ``for_s`` (a store
        younger than the horizon cannot claim a sustained breach; a
        sampler gap inside the horizon is missing evidence, not
        coverage)."""
        pts = self.store.points(metric, worker, for_s)
        if len(pts) < 2:
            return False
        if pts[-1][0] - pts[0][0] < for_s * 0.95:
            return False
        if self.sample_s:
            # two breaching samples with a dead sampler in between do not
            # prove the signal breached throughout — the metric may have
            # recovered and re-breached inside the hole. Tolerate a few
            # missed samples (scheduler jitter), refuse a real gap.
            gap_limit = self.sample_s * 4
            if any(
                t1 - t0 > gap_limit
                for (t0, _a), (t1, _b) in zip(pts, pts[1:])
            ):
                return False
        if above:
            return all(_scalar(metric, v) > threshold for _t, v in pts)
        return all(_scalar(metric, v) < threshold for _t, v in pts)

    def sustained_above(
        self, metric: str, threshold: float, for_s: float,
        worker: int | None = None,
    ) -> bool:
        return self._sustained(metric, threshold, for_s, worker, True)

    def sustained_below(
        self, metric: str, threshold: float, for_s: float,
        worker: int | None = None,
    ) -> bool:
        return self._sustained(metric, threshold, for_s, worker, False)

    # -- expression surface -------------------------------------------

    def eval(
        self, expr: str, window_s: float, worker: int | None = None,
    ) -> float | None:
        """Evaluate one ``op(metric)`` expression (or a bare metric name,
        = ``last``) for one worker. Histogram percentiles come back in
        MILLISECONDS (the unit every ``*_ms`` gauge already uses);
        everything else is in the series' native unit."""
        expr = expr.strip()
        op, metric = "last", expr
        if expr.endswith(")") and "(" in expr:
            op, _, rest = expr.partition("(")
            op = op.strip()
            metric = rest[:-1].strip()
        if op not in self.OPS:
            raise ValueError(
                f"unknown signal op {op!r} (expected one of {self.OPS})"
            )
        if op in ("p50", "p95", "p99"):
            q = {"p50": 0.50, "p95": 0.95, "p99": 0.99}[op]
            ns = self.percentile(metric, q, window_s, worker)
            return None if ns is None else ns / 1e6
        if op == "rate":
            return self.rate(metric, window_s, worker)
        if op == "delta":
            return self.delta(metric, window_s, worker)
        if op == "avg":
            return self.agg(
                metric, window_s, lambda it: _mean(list(it)), worker
            )
        if op == "min":
            return self.agg(metric, window_s, min, worker)
        if op == "max":
            return self.agg(metric, window_s, max, worker)
        return self.last(metric, worker)

    def eval_worst(
        self, expr: str, window_s: float, higher_is_worse: bool = True,
        max_age_s: float | None = None, now: float | None = None,
    ) -> tuple[float | None, int | None]:
        """Evaluate across every worker (falling back to the
        process-level series when no worker has the metric) and return
        (worst value, worker) — what a threshold rule compares.

        ``max_age_s`` is the staleness guard: a worker whose NEWEST
        sample for the metric is older than that is excluded entirely —
        its series froze (the worker died, or its peer scrape is being
        served from a cache), and a frozen value must not win a
        worst-worker comparison and drive a decision."""
        metric = expr
        if expr.endswith(")") and "(" in expr:
            metric = expr.partition("(")[2][:-1].strip()
        candidates: list[int | None] = [
            w for w in self.store.workers()
            if self.store.points(metric, w)
        ]
        if not candidates:
            candidates = [None]
        if max_age_s is not None:
            cutoff = (time.time() if now is None else now) - max_age_s
            candidates = [
                w for w in candidates
                if (pts := self.store.points(metric, w))
                and pts[-1][0] >= cutoff
            ]
        worst: float | None = None
        worst_w: int | None = None
        for w in candidates:
            v = self.eval(expr, window_s, w)
            if v is None:
                continue
            if (
                worst is None
                or (higher_is_worse and v > worst)
                or (not higher_is_worse and v < worst)
            ):
                worst, worst_w = v, w
        return worst, worst_w


def _mean(values: list[float]) -> float:
    return sum(values) / len(values)


class SignalsPlane:
    """Sampler thread + store + (optional) SLO engine for one process.

    Owned by the :class:`~pathway_tpu.observability.hub.ObservabilityHub`
    — the hub registers workers/comms, the plane samples them. Sampling
    never raises into the run it observes."""

    def __init__(
        self,
        hub: Any,
        sample_s: float = DEFAULT_SAMPLE_S,
        window_s: float = DEFAULT_WINDOW_S,
        slo_engine: Any = None,
    ):
        self.hub = hub
        self.sample_s = max(0.05, float(sample_s))
        self.window_s = max(self.sample_s * 4, float(window_s))
        # capacity covers the window plus slack for the left-edge sample
        self.store = TimeSeriesStore(
            int(self.window_s / self.sample_s) + 8
        )
        self.signals = Signals(self.store, sample_s=self.sample_s)
        self.slo = slo_engine
        self.samples_taken = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- sampling ------------------------------------------------------

    def sample_once(self, t: float | None = None) -> None:
        try:
            self._sample_inner(t)
            self.samples_taken += 1
        except Exception:
            # the signal plane must not fail the run it observes
            pass
        if self.slo is not None:
            try:
                self.slo.evaluate(self.signals, t)
            except Exception:
                pass

    def _sample_inner(self, t: float | None) -> None:
        if t is None:
            t = time.time()
        with self.hub._lock:
            workers = sorted(self.hub._workers.items())
        now_ms = t * 1000.0
        for wid, stats in workers:
            rec = lambda m, v: self.store.record(m, v, wid, t)
            rec("engine_ticks", float(stats.ticks))
            rec("rows_total", float(stats.rows_total))
            rec("input_rows", float(stats.input_rows))
            rec("output_rows", float(stats.output_rows))
            rec("last_time", float(stats.last_time))
            if stats.latency_ms is not None:
                rec("latency_ms", float(stats.latency_ms))
            # frontier lag vs wall clock: streaming ticks are minted at
            # even wall-clock ms, so a worker keeping up shows a small
            # lag and a stalled/backpressured one grows linearly. Only
            # wall-scale logical times are comparable (scheduled test
            # streams use small ints).
            if stats.last_time > 1_000_000_000_000:
                rec(
                    "frontier_lag_ms",
                    max(0.0, now_ms - float(stats.last_time)),
                )
            self.store.record(
                "tick_duration", stats.tick_duration.snapshot()["counts"],
                wid, t,
            )
            e2e = getattr(stats, "e2e_latency_hist", None)
            if e2e is not None and len(e2e):
                self.store.record(
                    "e2e_latency", e2e.snapshot()["counts"], wid, t
                )
            # commit-wave critical path (async plane): wave counters +
            # per-phase cumulative seconds so SLO rules can watch e.g.
            # rate(wave.stage_settle_s) — the lineage behind an e2e p99
            waves_total = getattr(stats, "waves_total", 0)
            if waves_total:
                rec("wave.total", float(waves_total))
                for phase, ns in list(
                    (getattr(stats, "wave_stage_ns", None) or {}).items()
                ):
                    rec(f"wave.stage_{phase}_s", float(ns) / 1e9)
                last = None
                rec_ring = getattr(stats, "_waves", None)
                if rec_ring is not None and rec_ring.recent:
                    last = rec_ring.recent[-1]
                if last is not None:
                    rec("wave.last_duration_ms", float(last["duration_ms"]))
                    if last.get("holder") is not None:
                        rec("wave.last_holder", float(last["holder"]))
            # key-group load sketch: top share + skew vs uniform — the
            # rebalancer's (ROADMAP item 3) runtime input
            acct = getattr(stats, "keyload", None)
            if acct is not None and acct.rows_total:
                rec("keyload.rows_total", float(acct.rows_total))
                items = acct.sketch.items()
                if items:
                    top_share = items[0][1] / (acct.sketch.total or 1.0)
                    rec("keyload.top_share", top_share)
                    rec("keyload.top_group", float(items[0][0]))
                    rec("keyload.skew", top_share * acct.n_groups)
            # per-operator cumulative processing time + rows — the
            # attribution inputs (populated when stats.detailed is on,
            # which the hub enables alongside the metrics endpoint)
            for op, ns in list(stats.time_by_node.items()):
                rec(OP_TIME_PREFIX + op, float(ns))
            for op, n in list(stats.rows_by_node.items()):
                rec(OP_ROWS_PREFIX + op, float(n))
        for key, value in self.hub.comm_snapshot().items():
            self.store.record(f"comm.{key}", float(value), None, t)
        # memory/spill/key-registry gauges (engine/spill.py): process-
        # scoped like the comm series — SLO rules and the autoscale
        # decider can watch rss_bytes or state_spilled_bytes directly
        for key, value in self.hub.memory_stats_snapshot().items():
            self.store.record(f"mem.{key}", float(value), None, t)
        # output-plane delivery counters (io/delivery.py): per-sink series
        # — SLO rules can watch sink.out.dlq_total or queue_depth directly
        for sink, gauges in self.hub.sink_stats_snapshot().items():
            for key, value in gauges.items():
                self.store.record(
                    f"sink.{sink}.{key}", float(value), None, t
                )
        # UDF execution-path counters (expression_compiler): lifted /
        # traced plans + rows that ran per-row Python — an SLO rule can
        # watch udf.perrow_rows_total to catch a pipeline falling off
        # the columnar fast path after a deploy
        for key, value in self.hub.udf_stats_snapshot().items():
            self.store.record(f"udf.{key}", float(value), None, t)
        # kernel-fusion counters (engine/fusion.py): chains compiled,
        # member operators fused, per-batch fallbacks — an SLO rule can
        # watch fusion.fallbacks_total to catch a stream that fell off
        # the fused path after a schema/dtype change
        for key, value in self.hub.fusion_stats_snapshot().items():
            self.store.record(f"fusion.{key}", float(value), None, t)
        # serve-plane counters + admission gauges (serve/stats.py): the
        # autoscale decider watches serve.queue_depth / serve.inflight
        # against their bounds, and an SLO rule can watch
        # serve.rejected_total or serve.degraded_total directly
        for key, value in self.hub.serve_stats_snapshot().items():
            self.store.record(f"serve.{key}", float(value), None, t)
        # staged ingest cost split (io/python.INGEST_STAGE_STATS): an SLO
        # rule can watch ingest.hash_s grow faster than ingest.parse_s —
        # the columnar-ingest arc's regression tripwire (ROADMAP item 2)
        for key, value in self.hub.ingest_stats_snapshot().items():
            if key == "connectors":
                # per-connector stage split rides as nested dicts:
                # ingest.conn.<name>.<stage> series name the bottleneck
                # connector instead of one anonymous ingest total
                for cname, gauges in value.items():
                    for ckey, cval in gauges.items():
                        self.store.record(
                            f"ingest.conn.{cname}.{ckey}", float(cval), None, t
                        )
                continue
            self.store.record(f"ingest.{key}", float(value), None, t)
        # continuous-profiling scalars (observability/profiler.py):
        # samples_total proves the sampler is alive; op_tagged_share
        # dropping means profiles stopped joining against /attribution
        for key, value in self.hub.profile_stats_snapshot().items():
            self.store.record(f"profile.{key}", float(value), None, t)

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "SignalsPlane":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="pathway-signals-sampler"
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.sample_s):
            self.sample_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
