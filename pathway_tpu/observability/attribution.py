"""Per-operator bottleneck attribution over the signals window.

Answers "which operator is the pipeline slow *in* right now": for every
operator the sampler tracked (``op_time_ns:<Op#id>`` series,
``observability/timeseries.py``), the windowed delta of its cumulative
processing time is its share of the tick sweep's busy time over the
window. The top share — weighted up when the worker's frontier lag is
*growing*, i.e. the slowness is backing real input up rather than just
burning idle headroom — is named ``pathway_bottleneck_operator`` on
``/metrics`` and ranked first in the ``/attribution`` view.

Rows/s per operator rides along so the view distinguishes "slow because
it does all the work" from "slow per row".

Exchange nodes rank like any other operator. Under frontier-driven
asynchronous execution (the default for sharded streaming —
``PATHWAY_ASYNC_EXEC``, engine/executor.py) their per-node time is
genuine work: bucketing, posting, and merging arrivals, with no
blocked-in-collective component — so it belongs in the ranking. Their
aggregate still rides along as ``exchange_wait_ms`` so a comm-bound
pipeline is visible at a glance. (Before async execution this module
EXCLUDED Exchange nodes: under the BSP tick barrier their time measured
waiting for the slowest peer — the symptom of another operator's
slowness, not a cause. ``PATHWAY_ASYNC_EXEC=0`` runs re-inherit that
caveat: read large Exchange shares there as barrier wait.)
"""

from __future__ import annotations

from typing import Any

from .timeseries import OP_ROWS_PREFIX, OP_TIME_PREFIX, Signals

__all__ = [
    "attribution_document",
    "bottleneck_operator",
    "merge_attribution_documents",
]


def _worker_attribution(
    signals: Signals, worker: int, window_s: float,
) -> list[dict[str, Any]]:
    store = signals.store
    out: list[dict[str, Any]] = []
    for metric in store.metrics(worker):
        if not metric.startswith(OP_TIME_PREFIX):
            continue
        op = metric[len(OP_TIME_PREFIX):]
        busy_ns = signals.delta(metric, window_s, worker)
        if busy_ns is None:
            continue
        rows_rate = signals.rate(OP_ROWS_PREFIX + op, window_s, worker)
        out.append(
            {
                "operator": op,
                "worker": worker,
                "busy_ms": busy_ns / 1e6,
                "rows_per_sec": rows_rate,
            }
        )
    return out


def attribution_document(
    signals: Signals, window_s: float,
) -> dict[str, Any]:
    """Ranked per-operator attribution across every local worker.

    ``share`` is each operator's fraction of the total busy time the
    window saw (summed across workers — an operator sharded over N
    workers aggregates, exactly like its wall-clock footprint).
    ``backlogged`` marks workers whose frontier lag GREW over the window
    — the signature separating "bottleneck holding back the stream" from
    "slow but keeping up"."""
    per_op: dict[str, dict[str, Any]] = {}
    backlogged: list[int] = []
    exchange_wait_ms = 0.0
    wave_stages: dict[str, float] = {}
    for worker in signals.store.workers():
        # commit-wave phase attribution (async plane): cumulative
        # per-phase seconds sampled by the signals plane — the
        # cluster-level complement of the per-operator ranking (which
        # stage of the wave pipeline the cluster's wall time went to)
        for metric in signals.store.metrics(worker):
            if metric.startswith("wave.stage_") and metric.endswith("_s"):
                v = signals.last(metric, worker)
                if v:
                    phase = metric[len("wave.stage_"):-2]
                    wave_stages[phase] = (
                        wave_stages.get(phase, 0.0) + float(v)
                    )
        lag_pts = signals.store.points("frontier_lag_ms", worker, window_s)
        if (
            len(lag_pts) >= 2
            and float(lag_pts[-1][1]) > float(lag_pts[0][1]) + 1.0
        ):
            backlogged.append(worker)
        for entry in _worker_attribution(signals, worker, window_s):
            if entry["operator"].startswith("Exchange#"):
                # ranked AND aggregated: async execution made this real
                # per-operator work (see module docstring)
                exchange_wait_ms += entry["busy_ms"]
            doc = per_op.setdefault(
                entry["operator"],
                {
                    "operator": entry["operator"],
                    "busy_ms": 0.0,
                    "rows_per_sec": 0.0,
                    "workers": {},
                },
            )
            doc["busy_ms"] += entry["busy_ms"]
            if entry["rows_per_sec"] is not None:
                doc["rows_per_sec"] += entry["rows_per_sec"]
            doc["workers"][str(worker)] = round(entry["busy_ms"], 3)
    return _finalize(
        per_op, exchange_wait_ms, backlogged, window_s,
        wave_stages=wave_stages,
    )


def _finalize(
    per_op: dict[str, dict[str, Any]],
    exchange_wait_ms: float,
    backlogged: list,
    window_s: Any,
    wave_stages: dict[str, float] | None = None,
) -> dict[str, Any]:
    """Rank, compute shares, round — THE one place the attribution
    document takes its final shape (single- and merged-process paths)."""
    total = sum(d["busy_ms"] for d in per_op.values())
    ranked = sorted(
        per_op.values(), key=lambda d: d["busy_ms"], reverse=True
    )
    for doc in ranked:
        doc["share"] = round(doc["busy_ms"] / total, 4) if total > 0 else 0.0
        doc["busy_ms"] = round(doc["busy_ms"], 3)
        doc["rows_per_sec"] = round(doc["rows_per_sec"], 1)
    out = {
        "window_s": window_s,
        "total_busy_ms": round(total, 3),
        "exchange_wait_ms": round(exchange_wait_ms, 3),
        "backlogged_workers": sorted(set(backlogged)),
        "bottleneck": ranked[0]["operator"] if ranked else None,
        "ranked": ranked,
    }
    if wave_stages:
        out["wave_stages_s"] = {
            p: round(v, 3) for p, v in sorted(wave_stages.items())
        }
        out["wave_critical_stage"] = max(
            wave_stages, key=lambda p: wave_stages[p]
        )
    return out


def merge_attribution_documents(docs: list[dict]) -> dict:
    """Merge per-process attribution documents (the process-0 ``/query``
    roll-up): an operator sharded over several processes aggregates its
    busy time, exactly like its wall-clock footprint, and the ranking is
    recomputed cluster-wide through the same :func:`_finalize`."""
    docs = [d for d in docs if d]
    if not docs:
        return _finalize({}, 0.0, [], None)
    if len(docs) == 1:
        return docs[0]
    per_op: dict[str, dict[str, Any]] = {}
    backlogged: list = []
    exchange_wait_ms = 0.0
    wave_stages: dict[str, float] = {}
    for doc in docs:
        backlogged.extend(doc.get("backlogged_workers", []))
        exchange_wait_ms += float(doc.get("exchange_wait_ms", 0.0))
        for p, v in (doc.get("wave_stages_s") or {}).items():
            wave_stages[p] = wave_stages.get(p, 0.0) + float(v)
        for entry in doc.get("ranked", []):
            agg = per_op.setdefault(
                entry["operator"],
                {
                    "operator": entry["operator"],
                    "busy_ms": 0.0,
                    "rows_per_sec": 0.0,
                    "workers": {},
                },
            )
            agg["busy_ms"] += float(entry.get("busy_ms", 0.0))
            agg["rows_per_sec"] += float(entry.get("rows_per_sec") or 0.0)
            agg["workers"].update(entry.get("workers", {}))
    return _finalize(
        per_op, exchange_wait_ms, backlogged, docs[0].get("window_s"),
        wave_stages=wave_stages,
    )


def bottleneck_operator(
    signals: Signals, window_s: float,
) -> str | None:
    """Just the top-ranked operator label (the /metrics gauge value)."""
    doc = attribution_document(signals, window_s)
    return doc["bottleneck"]
