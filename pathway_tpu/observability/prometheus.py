"""Prometheus/OpenMetrics exposition rendering for engine stats snapshots.

One renderer serves every surface: the per-process ``/metrics`` endpoint
(``engine/http_server.py``), the cluster-merged view on process 0, and
the smoke-test validator (``scripts/obs_smoke.py``). Everything renders
from plain snapshot dicts (``observability.hub.stats_snapshot``), never
live objects, so remote workers' metrics — shipped as JSON over the
cluster scrape — go through the identical code path as local ones.

Label values are escaped per the OpenMetrics text format ABNF
(``\\`` → ``\\\\``, ``"`` → ``\\"``, newline → ``\\n``); the seed emitted
raw operator labels, which produced invalid exposition text for any
operator name containing a quote or backslash.
"""

from __future__ import annotations

from .histogram import N_BUCKETS

__all__ = [
    "escape_label_value",
    "format_labels",
    "render_histogram",
    "render_snapshots",
    "parse_exposition",
]


def escape_label_value(v: str) -> str:
    """OpenMetrics label-value escaping (backslash first, then quote/NL)."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def format_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in labels.items()
    )
    return "{" + inner + "}"


def _fmt(v: float) -> str:
    if isinstance(v, float):
        # integral floats (byte/frame counters cast through comm_stats)
        # render exactly — %.6g would quantize past ~1e6 and make
        # Prometheus increase() read 0-then-jump; non-integral values get
        # 9 significant digits (sub-ms resolution on week-long uptimes)
        if v.is_integer() and abs(v) < 1e15:
            return str(int(v))
        return f"{v:.9g}"
    return str(v)


class _Renderer:
    """Accumulates families so each gets exactly one # TYPE line."""

    def __init__(self) -> None:
        self.lines: list[str] = []
        self._typed: set[str] = set()

    def add(self, name: str, mtype: str, value, labels: dict | None = None):
        if name not in self._typed:
            self._typed.add(name)
            self.lines.append(f"# TYPE {name} {mtype}")
        self.lines.append(f"{name}{format_labels(labels or {})} {_fmt(value)}")

    def text(self) -> str:
        return "\n".join(self.lines) + "\n"


def render_histogram(
    r: _Renderer, name: str, snap: dict, labels: dict[str, str]
) -> None:
    """Render one log-bucketed snapshot as a Prometheus histogram family
    (``_bucket``/``_sum``/``_count``), bounds in seconds.

    Only the occupied bucket range renders (cumulative counts stay
    monotone regardless), keeping series cardinality ~10 per histogram
    instead of 64."""
    counts = snap["counts"]
    nonzero = [i for i, c in enumerate(counts) if c]
    if name not in r._typed:
        r._typed.add(name)
        r.lines.append(f"# TYPE {name} histogram")
    cum = 0
    if nonzero:
        lo, hi = nonzero[0], min(nonzero[-1] + 1, N_BUCKETS - 1)
        cum = sum(counts[:lo])
        for i in range(lo, hi + 1):
            cum += counts[i]
            le = (1 << i) / 1e9  # bucket i upper bound: 2^i ns, in seconds
            ls = format_labels({**labels, "le": f"{le:.9g}"})
            r.lines.append(f"{name}_bucket{ls} {cum}")
    ls_inf = format_labels({**labels, "le": "+Inf"})
    r.lines.append(f"{name}_bucket{ls_inf} {snap['count']}")
    r.lines.append(
        f"{name}_sum{format_labels(labels)} {snap['sum'] / 1e9:.9g}"
    )
    r.lines.append(f"{name}_count{format_labels(labels)} {snap['count']}")


def render_snapshots(
    snapshots: list[dict],
    comm_stats: dict[str, dict[str, float]] | None = None,
    scrape_errors: int = 0,
    worker_labels: bool | None = None,
    supervisor: dict | None = None,
    trace_dropped: int | dict[str, int] | None = None,
    stale_workers: dict[str, float] | None = None,
    bottleneck: str | None = None,
    alerts_fired: dict[str, int] | None = None,
    alerts_active: int | None = None,
    autoscale: dict | None = None,
    memory_stats: dict[str, dict[str, float]] | None = None,
    sink_stats: dict[str, dict[str, dict[str, float]]] | None = None,
    udf_stats: dict[str, dict[str, float]] | None = None,
    fusion_stats: dict[str, dict[str, float]] | None = None,
    ingest_stats: dict[str, dict[str, float]] | None = None,
    profile_stats: dict[str, dict[str, float]] | None = None,
    serve_stats: dict[str, dict[str, float]] | None = None,
) -> str:
    """Exposition text for a set of worker stats snapshots.

    ``worker_labels=None`` (auto) omits the ``worker`` label for a single
    snapshot (the seed's single-process format, relied on by existing
    scrapers) and labels every series ``worker="N"`` for several — the
    cluster-merged view. Cluster callers pass an explicit ``True`` so
    series identity is stable even when a peer scrape transiently fails.
    ``comm_stats`` maps a process label to that process's comm-backend
    gauges (exchange queue depth etc.).
    """
    r = _Renderer()
    labeled = (
        worker_labels if worker_labels is not None else len(snapshots) > 1
    )
    max_last_time = max((s.get("last_time", 0) for s in snapshots), default=0)
    for s in snapshots:
        lab = {"worker": str(s.get("worker", 0))} if labeled else {}
        r.add("pathway_engine_ticks", "counter", s["ticks"], lab)
        r.add("pathway_engine_rows_total", "counter", s["rows_total"], lab)
        r.add("pathway_input_rows", "counter", s["input_rows"], lab)
        r.add("pathway_output_rows", "counter", s["output_rows"], lab)
        r.add("pathway_uptime_seconds", "gauge", s["uptime_s"], lab)
        if s.get("latency_ms") is not None:
            r.add("pathway_output_latency_ms", "gauge", s["latency_ms"], lab)
            # staleness companion: the latency gauge freezes at the last
            # commit's value; its age tells "fast" from "stalled"
            r.add(
                "pathway_output_latency_age_seconds",
                "gauge",
                s.get("latency_age_s", 0.0),
                lab,
            )
        if labeled:
            # frontier lag vs the most advanced worker: a worker whose
            # logical time trails its peers is the backpressured one
            r.add(
                "pathway_frontier_lag_ms",
                "gauge",
                max(0, max_last_time - s.get("last_time", 0)),
                lab,
            )
        r.add(
            "pathway_exchange_rows_total", "counter",
            s.get("exchange_rows_out", 0), {**lab, "direction": "out"},
        )
        r.add(
            "pathway_exchange_rows_total", "counter",
            s.get("exchange_rows_in", 0), {**lab, "direction": "in"},
        )
        r.add(
            "pathway_exchange_batches_total", "counter",
            s.get("exchange_batches", 0), lab,
        )
        for op, count in sorted(s.get("rows_by_node", {}).items()):
            r.add(
                "pathway_operator_rows_total", "counter", count,
                {**lab, "operator": op},
            )
        if s.get("tick_duration"):
            render_histogram(r, "pathway_tick_duration_seconds",
                             s["tick_duration"], lab)
        if s.get("latency_hist") and s["latency_hist"]["count"]:
            render_histogram(r, "pathway_output_latency_seconds",
                             s["latency_hist"], lab)
        if s.get("e2e_latency_hist") and s["e2e_latency_hist"]["count"]:
            # connector-ingest → output-emit latency (end-to-end through
            # the dataflow, stamped by the connectors)
            render_histogram(r, "pathway_ingest_to_emit_seconds",
                             s["e2e_latency_hist"], lab)
        for op, hsnap in sorted(s.get("node_time_hist", {}).items()):
            render_histogram(
                r, "pathway_operator_processing_seconds", hsnap,
                {**lab, "operator": op},
            )
        for stage, hsnap in sorted(s.get("stage_hists", {}).items()):
            # staged decomposition of ingest→emit (executor.E2E_STAGES):
            # every e2e observation lands once per stage, so the staged
            # sums add up to pathway_ingest_to_emit_seconds_sum and a p99
            # move decomposes into the stage that caused it
            if hsnap and hsnap.get("count"):
                render_histogram(
                    r, "pathway_ingest_to_emit_stage_seconds", hsnap,
                    {**lab, "stage": stage},
                )
        # commit-wave critical path (async plane, observability/critpath)
        if s.get("waves_total"):
            r.add("pathway_waves_total", "counter", s["waves_total"], lab)
            if s.get("wave_duration") and s["wave_duration"]["count"]:
                render_histogram(
                    r, "pathway_wave_duration_seconds",
                    s["wave_duration"], lab,
                )
            for stage, ns in sorted(s.get("wave_stage_ns", {}).items()):
                r.add(
                    "pathway_wave_stage_seconds_total", "counter",
                    int(ns) / 1e9, {**lab, "stage": stage},
                )
            for holder, n in sorted(s.get("wave_held_total", {}).items()):
                # which worker's frontier arrived last (held the wave)
                r.add(
                    "pathway_wave_held_total", "counter", int(n),
                    {**lab, "holder": str(holder)},
                )
        kl = s.get("keyload")
        if kl and kl.get("rows_total"):
            # key-group heavy hitters (observability/keyload.py): top
            # tracked groups' share of routed rows — bounded label
            # cardinality (top 8 of a capacity-bounded sketch)
            r.add(
                "pathway_keyload_rows_total", "counter",
                kl["rows_total"], lab,
            )
            for entry in (kl.get("top") or [])[:8]:
                r.add(
                    "pathway_key_group_share", "gauge",
                    round(float(entry.get("share", 0.0)), 4),
                    {**lab, "group": str(entry.get("group"))},
                )
    for proc, gauges in sorted((comm_stats or {}).items()):
        plab = {"process": str(proc)}
        for key, value in sorted(gauges.items()):
            # OpenMetrics convention: a `_total` suffix names a counter
            # (pathway_comm_bytes_total / frames_coalesced_total /
            # encode_seconds_total from the pipelined data plane);
            # everything else in comm_stats is a point-in-time gauge
            # (queue depths, broken flag)
            kind = "counter" if key.endswith("_total") else "gauge"
            r.add(f"pathway_comm_{key}", kind, value, plab)
    for proc, gauges in sorted((memory_stats or {}).items()):
        # memory-at-scale surface (engine/spill.py memory_snapshot):
        # process RSS, state-budget occupancy, spill counters and the
        # two-tier key registry — per process, like the comm gauges
        plab = {"process": str(proc)}
        for key, value in sorted(gauges.items()):
            if key.startswith("key_registry"):
                name = f"pathway_{key}"
            elif key == "rss_bytes":
                name = "pathway_process_rss_bytes"
            elif key.endswith("_total"):
                name = f"pathway_state_{key}"  # spill/load event counters
            else:
                name = f"pathway_{key}"  # state_*_bytes gauges
            kind = "counter" if name.endswith("_total") else "gauge"
            r.add(name, kind, value, plab)
    for proc, sinks in sorted((sink_stats or {}).items()):
        # output plane (io/delivery.py): per-sink delivery counters. The
        # process label keeps a muted worker's zeroed copy of a sink from
        # colliding with the delivering worker's live series
        for sink, gauges in sorted(sinks.items()):
            slab = {"process": str(proc), "sink": str(sink)}
            for key, value in sorted(gauges.items()):
                kind = "counter" if key.endswith("_total") else "gauge"
                r.add(f"pathway_sink_{key}", kind, value, slab)
    for proc, gauges in sorted((udf_stats or {}).items()):
        # UDF execution-path counters (internals/expression_compiler.py):
        # lifted / traced plans built and rows that ran per-row Python —
        # the rowwise-tax visibility surface. Process-scoped like the
        # memory gauges.
        plab = {"process": str(proc)}
        for key, value in sorted(gauges.items()):
            kind = "counter" if key.endswith("_total") else "gauge"
            r.add(f"pathway_udf_{key}", kind, value, plab)
    for proc, gauges in sorted((fusion_stats or {}).items()):
        # kernel-fusion counters (engine/fusion.py): chains compiled,
        # member operators they absorbed, batches that fell back to the
        # per-node path, whole-chain XLA compiles, key-reuse hits —
        # the pathway_fusion_{chains,fused_ops,fallbacks}_total surface
        plab = {"process": str(proc)}
        for key, value in sorted(gauges.items()):
            kind = "counter" if key.endswith("_total") else "gauge"
            r.add(f"pathway_fusion_{key}", kind, value, plab)
    for proc, gauges in sorted((ingest_stats or {}).items()):
        # staged ingest cost split (io/python.INGEST_STAGE_STATS): the
        # parse | hash | delta seconds per connector flush, as one
        # stage-labeled family so dashboards stack the split directly
        for key, value in sorted(gauges.items()):
            if key == "connectors":
                # nested per-connector split: one connector-labeled
                # family so the bottleneck connector is nameable from
                # the dashboard, not just "ingest is slow somewhere"
                for cname, cg in sorted(value.items()):
                    for ckey, cval in sorted(cg.items()):
                        if ckey.endswith("_s"):
                            r.add(
                                "pathway_ingest_connector_stage_seconds_total",
                                "counter",
                                cval,
                                {
                                    "process": str(proc),
                                    "connector": str(cname),
                                    "stage": ckey[:-2],
                                },
                            )
                        else:
                            kind = (
                                "counter" if ckey.endswith("_total")
                                else "gauge"
                            )
                            r.add(
                                f"pathway_ingest_connector_{ckey}",
                                kind,
                                cval,
                                {
                                    "process": str(proc),
                                    "connector": str(cname),
                                },
                            )
                continue
            if key.endswith("_s"):
                r.add(
                    "pathway_ingest_stage_seconds_total",
                    "counter",
                    value,
                    {"process": str(proc), "stage": key[:-2]},
                )
            else:
                kind = "counter" if key.endswith("_total") else "gauge"
                r.add(
                    f"pathway_ingest_{key}",
                    kind,
                    value,
                    {"process": str(proc)},
                )
    for proc, gauges in sorted((serve_stats or {}).items()):
        # serve-plane counters + gauges (serve/stats.py): queries
        # admitted / rejected / degraded, scatter posts, shard searches,
        # plus the live in-flight and queue-depth admission gauges — the
        # pathway_serve_* overload-visibility surface
        plab = {"process": str(proc)}
        for key, value in sorted(gauges.items()):
            kind = "counter" if key.endswith("_total") else "gauge"
            r.add(f"pathway_serve_{key}", kind, value, plab)
    for proc, gauges in sorted((profile_stats or {}).items()):
        # continuous-profiling scalars (observability/profiler.py):
        # samples taken, distinct collapsed stacks, top-frame share and
        # the op-tagged share of engine-thread samples — the health
        # gauges of the flamegraph plane (the flamegraph itself lives at
        # /profile, not in the exposition)
        plab = {"process": str(proc)}
        for key, value in sorted(gauges.items()):
            kind = "counter" if key.endswith("_total") else "gauge"
            r.add(f"pathway_profile_{key}", kind, value, plab)
    r.add("pathway_cluster_workers", "gauge", len(snapshots))
    if stale_workers:
        # a peer whose /snapshot scrape failed: its workers are reported
        # as STALE (last-seen age from the roll-up's cache) instead of
        # silently vanishing from the merged view
        for wid, age in sorted(stale_workers.items()):
            r.add(
                "pathway_worker_last_seen_seconds", "gauge",
                round(float(age), 3), {"worker": str(wid)},
            )
        r.add("pathway_cluster_stale_workers", "gauge", len(stale_workers))
    if bottleneck:
        # info-style gauge: which operator currently owns the largest
        # share of windowed tick processing time (signals plane)
        r.add(
            "pathway_bottleneck_operator", "gauge", 1,
            {"operator": str(bottleneck)},
        )
    if alerts_fired:
        for sev, n in sorted(alerts_fired.items()):
            r.add(
                "pathway_alerts_fired_total", "counter", int(n),
                {"severity": str(sev)},
            )
    if alerts_active is not None:
        r.add("pathway_alerts_active", "gauge", int(alerts_active))
    if scrape_errors:
        r.add("pathway_cluster_scrape_errors", "counter", scrape_errors)
    if trace_dropped is not None:
        # tracer ring-buffer overflow: a timeline missing its head is
        # distinguishable from one that was simply quiet. Cluster callers
        # pass a per-process dict — like the comm gauges, a transiently
        # unreachable peer must DROP its series, not decrease a summed
        # counter (which Prometheus would read as a reset)
        if isinstance(trace_dropped, dict):
            for proc, v in sorted(trace_dropped.items()):
                r.add(
                    "pathway_trace_dropped_events_total", "counter",
                    int(v), {"process": str(proc)},
                )
        else:
            r.add(
                "pathway_trace_dropped_events_total", "counter",
                int(trace_dropped),
            )
    if supervisor is not None:
        # self-healing surface (spawn --supervise): restart generation +
        # why the supervisor last bounced the ensemble (info-style series,
        # value always 1, reason as a label) + armed-chaos fire count.
        # A rescale-only snapshot carries no "restarts" key — an elastic
        # boot outside supervision must not mint pathway_restarts_total
        if supervisor.get("restarts") is not None:
            r.add(
                "pathway_restarts_total", "counter",
                int(supervisor["restarts"]),
            )
        reason = supervisor.get("reason")
        if reason:
            r.add(
                "pathway_last_restart_reason", "gauge", 1,
                {"reason": str(reason)},
            )
        if supervisor.get("chaos_injections") is not None:
            r.add(
                "pathway_chaos_injections_total", "counter",
                int(supervisor["chaos_injections"]),
            )
        if supervisor.get("flight_dumps") is not None:
            # crash-forensic bundles harvested by the supervisor so far
            # (flight recorder, stamped as PATHWAY_FLIGHT_DUMPS)
            r.add(
                "pathway_flight_recorder_dumps_total", "counter",
                int(supervisor["flight_dumps"]),
            )
        if supervisor.get("rescales") is not None:
            # elastic rescaling: state resharder runs completed in this
            # process (spawn --elastic boot) + cumulative wall time
            r.add(
                "pathway_rescale_total", "counter",
                int(supervisor["rescales"]),
            )
            r.add(
                "pathway_rescale_duration_seconds", "gauge",
                float(supervisor.get("rescale_duration_s", 0.0)),
            )
        if supervisor.get("upgrades") is not None:
            # graph-version migrations completed in this process
            # (pathway-tpu upgrade --apply / spawn --upgrade-to) +
            # cumulative wall time + per-verb operator counts
            r.add(
                "pathway_upgrade_total", "counter",
                int(supervisor["upgrades"]),
            )
            r.add(
                "pathway_upgrade_duration_seconds", "gauge",
                float(supervisor.get("upgrade_duration_s", 0.0)),
            )
            verbs = supervisor.get("upgrade_operators") or {}
            for verb in ("carried", "remapped", "new", "dropped"):
                if verbs.get(verb) is not None:
                    r.add(
                        "pathway_upgrade_operators_total", "counter",
                        int(verbs[verb]), {"verb": verb},
                    )
        if supervisor.get("window_failures") is not None:
            # circuit-breaker window position: failures inside the
            # sliding window at this generation's launch vs the restart
            # budget — a restart storm building reads as the failure
            # count climbing toward the budget BEFORE the breaker trips;
            # open=1 means the LAST-CHANCE generation is running (one
            # more failure and the supervisor gives up, exit 75)
            r.add(
                "pathway_restart_window_failures", "gauge",
                int(supervisor["window_failures"]),
            )
            if supervisor.get("window_budget") is not None:
                r.add(
                    "pathway_restart_window_budget", "gauge",
                    int(supervisor["window_budget"]),
                )
            r.add(
                "pathway_circuit_open", "gauge",
                1 if supervisor.get("circuit_open") else 0,
            )
    if autoscale is not None:
        # closed-loop autoscaler (spawn --autoscale MIN..MAX): scale
        # events executed so far and the latest event's pause — stamped
        # into child environments by the controller per generation
        r.add(
            "pathway_autoscale_events_total", "counter",
            int(autoscale.get("events", 0)),
            {"range": str(autoscale.get("range", ""))},
        )
        if autoscale.get("last_pause_ms") is not None:
            r.add(
                "pathway_autoscale_last_pause_ms", "gauge",
                float(autoscale["last_pause_ms"]),
            )
        if autoscale.get("last_decision"):
            # label only the bounded "from->to" head: the full reason
            # string embeds measured values (unique per event = unbounded
            # series cardinality) and already lives in the event log,
            # /query document and `top` line
            r.add(
                "pathway_autoscale_last_decision", "gauge", 1,
                {
                    "decision": str(autoscale["last_decision"])
                    .partition(":")[0].strip()
                },
            )
    return r.text()


def parse_exposition(text: str) -> dict[tuple[str, tuple], float]:
    """Minimal exposition-text parser for validation (obs_smoke + tests):
    returns {(metric_name, sorted label items): value}. Raises ValueError
    on malformed lines — the smoke test's correctness check."""
    out: dict[tuple[str, tuple], float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:
            raise ValueError(f"malformed exposition line: {line!r}")
        labels: dict[str, str] = {}
        name = name_part
        if "{" in name_part:
            if not name_part.endswith("}"):
                raise ValueError(f"malformed labels in line: {line!r}")
            name, _, rest = name_part.partition("{")
            body = rest[:-1]
            try:
                _parse_label_body(body, labels)
            except (IndexError, ValueError) as e:
                raise ValueError(
                    f"malformed labels in line: {line!r}"
                ) from e
        try:
            value = float(value_part)
        except ValueError:
            raise ValueError(f"non-numeric sample value: {line!r}") from None
        out[(name, tuple(sorted(labels.items())))] = value
    return out


def _parse_label_body(body: str, labels: dict[str, str]) -> None:
    i = 0
    while i < len(body):
        eq = body.index("=", i)
        key = body[i:eq]
        if body[eq + 1] != '"':
            raise ValueError("unquoted label value")
        j = eq + 2
        val: list[str] = []
        while True:
            c = body[j]
            if c == "\\":
                nxt = body[j + 1]
                val.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt, nxt))
                j += 2
            elif c == '"':
                j += 1
                break
            else:
                val.append(c)
                j += 1
        labels[key] = "".join(val)
        if j < len(body) and body[j] == ",":
            j += 1
        i = j
