"""Heavy-hitter key-load accounting over routed exchange buckets.

ROADMAP item 3 (skew rebalancing) needs a decider-visible answer to
"which key-groups make a shard hot" — provable-cardinality guesses from
the static planner (``analysis/passes.py`` shard-skew lint) cannot see
the actual data. This module measures it at the one place every routed
row passes: the Exchange node's bucketing step.

Design (SpaceSaving, Metwally et al. 2005; merge discipline from
"Mergeable Summaries", Agarwal et al. 2012):

- rows are coarsened to **key-groups** (``K.shard_of(route_keys, G)``
  with ``G = PATHWAY_KEYLOAD_GROUPS``): the same hash family that picks
  the destination shard, over more buckets — so a hot group maps to a
  unique destination and the future rebalancer can move *groups*, not
  individual keys;
- a bounded :class:`SpaceSaving` sketch (``PATHWAY_KEYLOAD_CAPACITY``
  counters) tracks per-group row counts with the classic guarantee
  ``true <= estimate <= true + err`` and ``err <= N / capacity``;
- per-destination row counts ride alongside for tracked groups only
  (bounded by capacity x n_workers), so the report reads "group 17:
  41% of rows, all landing on worker 3";
- sketches merge associatively while the union of tracked groups fits
  capacity (then exactly — the usual case, G is small); beyond it the
  SpaceSaving merge keeps the epsilon bound in any merge order;
- optional exponential decay (``PATHWAY_KEYLOAD_DECAY_S``): counts
  halve every interval, so the ranking reflects the recent window
  rather than the whole run.

The accounting is windowed OFF with ``PATHWAY_KEYLOAD=0`` — the bench's
accounting A/B (``bench.py`` sharded lanes) holds the on/off throughput
delta under 3%.

Everything here is pure (no threads, no comm): per-worker accounts live
on ``EngineStats.keyload``, ship in the hub snapshot like every other
counter, and merge cluster-wide on process 0 (``merge_snapshots``).
"""

from __future__ import annotations

from typing import Any

__all__ = [
    "SpaceSaving",
    "KeyLoadAccount",
    "maybe_account",
    "merge_snapshots",
    "skew_line",
]

DEFAULT_CAPACITY = 64
DEFAULT_GROUPS = 64


class SpaceSaving:
    """Bounded heavy-hitter sketch: at most ``capacity`` counters.

    ``observe(key, w)`` either bumps a tracked counter or evicts the
    minimum counter ``m`` and admits ``key`` at ``m + w`` with error
    ``m`` — the overestimate discipline that keeps every true heavy
    hitter tracked. ``items()`` returns ``(key, count, err)`` sorted by
    count descending; for any tracked key,
    ``count - err <= true <= count``, and ``err <= total / capacity``.
    """

    __slots__ = ("capacity", "_counts", "_errs", "total")

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._counts: dict[Any, float] = {}
        self._errs: dict[Any, float] = {}
        #: total observed weight (the N of the epsilon bound)
        self.total = 0.0

    def __len__(self) -> int:
        return len(self._counts)

    def observe(self, key: Any, weight: float = 1.0) -> None:
        if weight <= 0:
            return
        self.total += weight
        counts = self._counts
        if key in counts:
            counts[key] += weight
            return
        if len(counts) < self.capacity:
            counts[key] = weight
            self._errs[key] = 0.0
            return
        evict = min(counts, key=lambda k: (counts[k], str(k)))
        floor = counts.pop(evict)
        self._errs.pop(evict, None)
        counts[key] = floor + weight
        self._errs[key] = floor

    def _floor(self) -> float:
        """Estimate for an untracked key: 0 while the sketch has room
        (untracked really means unseen), else the minimum counter."""
        if len(self._counts) < self.capacity:
            return 0.0
        return min(self._counts.values())

    def estimate(self, key: Any) -> tuple[float, float]:
        """(count, err) for ``key`` — tracked or the untracked floor."""
        c = self._counts.get(key)
        if c is not None:
            return c, self._errs.get(key, 0.0)
        f = self._floor()
        return f, f

    def items(self) -> list[tuple[Any, float, float]]:
        """Tracked ``(key, count, err)`` sorted by count descending
        (ties broken by key string for determinism)."""
        return sorted(
            (
                (k, c, self._errs.get(k, 0.0))
                for k, c in self._counts.items()
            ),
            key=lambda t: (-t[1], str(t[0])),
        )

    def error_bound(self) -> float:
        """The sketch-wide overestimate bound: N / capacity."""
        return self.total / self.capacity

    def merge(self, other: "SpaceSaving") -> "SpaceSaving":
        """Combined sketch at ``min`` of the two capacities. Exact (and
        therefore associative in any grouping) while the union of
        tracked keys fits capacity; otherwise the SpaceSaving merge:
        untracked keys contribute the donor sketch's floor, the union is
        truncated to the top ``capacity`` counters, and the epsilon
        bound ``err <= (N1 + N2) / capacity`` holds in any order."""
        cap = min(self.capacity, other.capacity)
        out = SpaceSaving(cap)
        out.total = self.total + other.total
        keys = set(self._counts) | set(other._counts)
        merged: list[tuple[Any, float, float]] = []
        for k in keys:
            c1, e1 = self.estimate(k)
            c2, e2 = other.estimate(k)
            merged.append((k, c1 + c2, e1 + e2))
        merged.sort(key=lambda t: (-t[1], str(t[0])))
        for k, c, e in merged[:cap]:
            out._counts[k] = c
            out._errs[k] = e
        return out

    def decay(self, factor: float) -> None:
        """Scale every counter (window semantics: ``factor=0.5`` halves
        the influence of everything observed so far)."""
        if not 0.0 <= factor <= 1.0:
            raise ValueError(f"decay factor must be in [0,1], got {factor}")
        for k in self._counts:
            self._counts[k] *= factor
        for k in self._errs:
            self._errs[k] *= factor
        self.total *= factor

    # -- wire form (hub snapshot / cluster merge) -----------------------

    def snapshot(self) -> dict:
        return {
            "capacity": self.capacity,
            "total": self.total,
            "counts": {str(k): c for k, c in self._counts.items()},
            "errs": {str(k): e for k, e in self._errs.items()},
        }

    @classmethod
    def from_snapshot(cls, snap: dict) -> "SpaceSaving":
        out = cls(int(snap.get("capacity", DEFAULT_CAPACITY)))
        out.total = float(snap.get("total", 0.0))
        out._counts = {k: float(v) for k, v in (snap.get("counts") or {}).items()}
        out._errs = {k: float(v) for k, v in (snap.get("errs") or {}).items()}
        return out


def _env_knobs() -> tuple[int, int, float]:
    from ..internals.config import _env_float, _env_int

    cap = max(1, _env_int("PATHWAY_KEYLOAD_CAPACITY", DEFAULT_CAPACITY))
    groups = max(2, _env_int("PATHWAY_KEYLOAD_GROUPS", DEFAULT_GROUPS))
    decay_s = max(0.0, _env_float("PATHWAY_KEYLOAD_DECAY_S", 0.0))
    return cap, groups, decay_s


def enabled() -> bool:
    from ..internals.config import _env_bool

    return _env_bool("PATHWAY_KEYLOAD", True)


def maybe_account() -> "KeyLoadAccount | None":
    """One per-worker account when accounting is on (``PATHWAY_KEYLOAD``,
    default on), else None — the single branch the Exchange hot path
    pays when the operator is disabled."""
    return KeyLoadAccount() if enabled() else None


class KeyLoadAccount:
    """Per-worker key-group load ledger fed by Exchange routing."""

    def __init__(
        self,
        capacity: int | None = None,
        n_groups: int | None = None,
        decay_s: float | None = None,
    ):
        env_cap, env_groups, env_decay = _env_knobs()
        self.capacity = capacity if capacity is not None else env_cap
        self.n_groups = n_groups if n_groups is not None else env_groups
        self.decay_s = decay_s if decay_s is not None else env_decay
        self.sketch = SpaceSaving(self.capacity)
        #: group -> destination worker -> rows (tracked groups only)
        self.dest_rows: dict[int, dict[int, int]] = {}
        self.rows_total = 0
        self.bytes_total = 0
        self.batches = 0
        self._last_decay: float | None = None

    def observe_exchange(
        self, route_keys, shards, nbytes: int = 0, now: float | None = None
    ) -> None:
        """One routed Exchange batch: ``route_keys`` (uint64 per row) and
        ``shards`` (destination worker per row), plus the batch's
        approximate byte size. Vectorized per batch — the per-row cost is
        one extra hash pass over keys the router already materialized."""
        import numpy as np

        from ..engine import keys as K

        n = len(shards)
        if n == 0:
            return
        self._maybe_decay(now)
        self.batches += 1
        self.rows_total += n
        self.bytes_total += int(nbytes)
        groups = K.shard_of(route_keys, self.n_groups)
        per_group = np.bincount(groups, minlength=0)
        hot = np.nonzero(per_group)[0]
        for g in hot:
            self.sketch.observe(int(g), int(per_group[g]))
        # per-destination split, bounded to groups the sketch tracks
        tracked = self.sketch._counts
        for g in hot:
            gi = int(g)
            if gi not in tracked:
                continue
            dests = self.dest_rows.setdefault(gi, {})
            sel = shards[groups == g]
            for w in np.unique(sel):
                dests[int(w)] = dests.get(int(w), 0) + int((sel == w).sum())
        if len(self.dest_rows) > 2 * self.capacity:
            # evicted groups leave their per-dest split behind — prune to
            # what the sketch still tracks so memory stays bounded
            self.dest_rows = {
                g: d for g, d in self.dest_rows.items() if g in tracked
            }

    def _maybe_decay(self, now: float | None) -> None:
        if self.decay_s <= 0:
            return
        import time as _time

        if now is None:
            now = _time.monotonic()
        if self._last_decay is None:
            self._last_decay = now
            return
        while now - self._last_decay >= self.decay_s:
            self.sketch.decay(0.5)
            for dests in self.dest_rows.values():
                for w in dests:
                    dests[w] = int(dests[w] * 0.5)
            self._last_decay += self.decay_s

    def snapshot(self) -> dict:
        """JSON-serializable account (rides the hub /snapshot document
        under ``"keyload"``; ``merge_snapshots`` rebuilds and merges)."""
        bytes_per_row = (
            self.bytes_total / self.rows_total if self.rows_total else 0.0
        )
        top = []
        total = self.sketch.total or 1.0
        for g, c, e in self.sketch.items():
            top.append(
                {
                    "group": int(g) if not isinstance(g, str) else g,
                    "rows": c,
                    "err": e,
                    "share": c / total,
                    "bytes_est": int(c * bytes_per_row),
                    "dest_rows": {
                        str(w): n
                        for w, n in sorted(
                            self.dest_rows.get(
                                int(g) if not isinstance(g, str) else -1, {}
                            ).items()
                        )
                    },
                }
            )
        return {
            "groups": self.n_groups,
            "capacity": self.capacity,
            "rows_total": self.rows_total,
            "bytes_total": self.bytes_total,
            "batches": self.batches,
            "error_bound": self.sketch.error_bound(),
            "top": top,
            "sketch": self.sketch.snapshot(),
        }


def merge_snapshots(snaps: list[dict | None]) -> dict | None:
    """Cluster-wide ranking: merge per-worker account snapshots (the
    process-0 roll-up, same pull direction as /snapshot). Returns the
    same document shape as :meth:`KeyLoadAccount.snapshot` minus the
    raw sketch wire form, plus ``skew`` — the top group's share times
    the group count (1.0 == perfectly uniform)."""
    snaps = [s for s in snaps if s]
    if not snaps:
        return None
    merged: SpaceSaving | None = None
    dest: dict[str, dict[str, int]] = {}
    rows_total = bytes_total = batches = 0
    groups = max(int(s.get("groups", DEFAULT_GROUPS)) for s in snaps)
    for s in snaps:
        sk = s.get("sketch")
        if sk:
            one = SpaceSaving.from_snapshot(sk)
            merged = one if merged is None else merged.merge(one)
        rows_total += int(s.get("rows_total", 0))
        bytes_total += int(s.get("bytes_total", 0))
        batches += int(s.get("batches", 0))
        for entry in s.get("top") or []:
            d = dest.setdefault(str(entry.get("group")), {})
            for w, n in (entry.get("dest_rows") or {}).items():
                d[w] = d.get(w, 0) + int(n)
    if merged is None:
        return None
    total = merged.total or 1.0
    bytes_per_row = bytes_total / rows_total if rows_total else 0.0
    top = [
        {
            "group": g,
            "rows": c,
            "err": e,
            "share": c / total,
            "bytes_est": int(c * bytes_per_row),
            "dest_rows": dest.get(str(g), {}),
        }
        for g, c, e in merged.items()
    ]
    doc = {
        "groups": groups,
        "capacity": merged.capacity,
        "rows_total": rows_total,
        "bytes_total": bytes_total,
        "batches": batches,
        "error_bound": merged.error_bound(),
        "top": top,
        # the merged sketch's wire form rides along so process-level
        # documents re-merge into the cluster roll-up (associativity:
        # merging merges == merging the originals)
        "sketch": merged.snapshot(),
    }
    if top:
        doc["skew"] = round(top[0]["share"] * groups, 3)
    return doc


def skew_line(doc: dict | None) -> str | None:
    """One-line operator rendering for ``top`` (and the lint note): the
    hottest key-group, its row share, and where it lands."""
    if not doc or not doc.get("top"):
        return None
    head = doc["top"][0]
    dests = head.get("dest_rows") or {}
    where = (
        "->w" + max(dests, key=lambda w: dests[w]) if dests else "->?"
    )
    return (
        f"keyload: group {head['group']} {head['share'] * 100:.1f}% of "
        f"{doc['rows_total']} routed rows {where} "
        f"(x{doc.get('skew', 0):.1f} vs uniform, "
        f"±{doc['error_bound']:.0f} rows)"
    )
