"""asof_join / asof_now_join.

Re-design of ``python/pathway/stdlib/temporal/_asof_join.py:479`` (sortedness
via the reference's prev_next.rs operator) and ``_asof_now_join.py:176``.
asof_join rides the engine's GroupedRecompute (sort the key group, match
each left row to the latest/nearest right row); asof_now_join is the
engine Join with ``react_to_right=False`` — queries join the current right
state and are never retracted by later right-side changes.
"""

from __future__ import annotations

import enum
from typing import Any

import numpy as np

from ...engine import keys as K
from ...internals import dtype as dt
from ...internals.expression import ColumnExpression, ColumnReference, smart_coerce
from ...internals.joins import JoinMode
from ...internals.parse_graph import Universe
from ...internals.schema import ColumnSchema, schema_from_columns
from ...internals.table import Table
from ...internals.thisclass import left as pw_left, right as pw_right, substitute, this

__all__ = ["Direction", "asof_join", "asof_join_left", "asof_now_join", "AsofJoinResult"]


class Direction(enum.Enum):
    BACKWARD = "backward"
    FORWARD = "forward"
    NEAREST = "nearest"


class AsofJoinResult:
    def __init__(self, left_t, right_t, left_time, right_time, on, mode, direction, defaults):
        self._left = left_t
        self._right = right_t
        self._ltime = substitute(smart_coerce(left_time), {this: left_t, pw_left: left_t})
        self._rtime = substitute(smart_coerce(right_time), {this: right_t, pw_right: right_t})
        self._on = on
        self._mode = mode
        self._direction = direction
        self._defaults = defaults or {}

    def select(self, *args: Any, **kwargs: Any) -> Table:
        from ...engine import operators as ops
        from ...internals.expression_compiler import ColumnEnv, compile_expr
        from ...internals.graph_runner import _colref

        lt, rt = self._left, self._right
        lcols, rcols = lt.column_names(), rt.column_names()
        combined_cols = (
            [f"l.{c}" for c in lcols] + ["l.__id__"]
            + [f"r.{c}" for c in rcols] + ["r.__id__"]
        )
        mode, direction = self._mode, self._direction
        on = self._on
        ltime_e, rtime_e = self._ltime, self._rtime

        def make_lower(out_exprs):
            def lower(runner, tbl):
                # per side: group key col, time col, payload
                def side(table, time_e, conds_side, prefix):
                    exprs = {"__t": time_e}
                    node, env = runner._zip_env(table, {**exprs, **{f"__c{i}": c for i, c in enumerate(conds_side)}})
                    rw = {f"{prefix}.{c}": _colref(c) for c in table.column_names()}
                    rw[f"{prefix}.__id__"] = lambda cols_, keys_: keys_
                    rw["__t"] = compile_expr(time_e, env).fn
                    cond_fns = [compile_expr(c, env).fn for c in conds_side]

                    def g_fn(cols_, keys_):
                        if not cond_fns:
                            return np.zeros(len(keys_), dtype=np.uint64)
                        from ...internals.expression_compiler import _materialize

                        vals = [np.asarray(_materialize(f(cols_, keys_), len(keys_))) for f in cond_fns]
                        return K.mix_columns(vals, len(keys_))

                    rw["__g"] = g_fn
                    return runner._add(ops.Rowwise(node, rw))

                lconds = [substitute(c._left, {pw_left: lt, this: lt}) for c in on]
                rconds = [substitute(c._right, {pw_right: rt, this: rt}) for c in on]
                lnode = side(lt, ltime_e, lconds, "l")
                rnode = side(rt, rtime_e, rconds, "r")
                n_l = len(lcols)
                lt_ix = n_l + 1  # l cols, l.__id__, then __t, __g
                n_r = len(rcols)

                def compute(gk, lrows, rrows, time):
                    # row layouts: left = (l.*, l.__id__, __t, __g);
                    #              right = (r.*, r.__id__, __t, __g)
                    rs = sorted(rrows.items(), key=lambda kv: (kv[1][n_r + 1], kv[0]))
                    rtimes = [r[1][n_r + 1] for r in rs]
                    out = []
                    import bisect

                    for lrk, lrow in sorted(lrows.items(), key=lambda kv: (kv[1][lt_ix], kv[0])):
                        t = lrow[lt_ix]
                        match = None
                        if rs:
                            if direction == Direction.BACKWARD:
                                i = bisect.bisect_right(rtimes, t) - 1
                                match = rs[i] if i >= 0 else None
                            elif direction == Direction.FORWARD:
                                i = bisect.bisect_left(rtimes, t)
                                match = rs[i] if i < len(rs) else None
                            else:  # NEAREST
                                i = bisect.bisect_left(rtimes, t)
                                cands = []
                                if i > 0:
                                    cands.append(rs[i - 1])
                                if i < len(rs):
                                    cands.append(rs[i])
                                match = min(
                                    cands, key=lambda kv: abs(kv[1][n_r + 1] - t)
                                ) if cands else None
                        if match is None:
                            if mode == JoinMode.INNER:
                                continue
                            rpart = (None,) * (n_r + 1)
                            okey = K.derive_scalar(lrk, 0xA50F)
                        else:
                            rrk, rrow = match
                            rpart = rrow[: n_r + 1]
                            okey = K.derive_pair_scalar(lrk, rrk)
                        out.append((okey, lrow[: n_l + 1] + rpart))
                    return out

                gr = runner._add(ops.GroupedRecompute(
                    [lnode, rnode], ["__g", "__g"], combined_cols, compute,
                ))
                env = ColumnEnv()
                l_opt = False
                r_opt = mode in (JoinMode.LEFT, JoinMode.OUTER)
                for c, cs in lt.schema.columns().items():
                    env.add(lt, c, f"l.{c}", cs.dtype)
                env.add(lt, "id", "l.__id__", dt.POINTER)
                for c, cs in rt.schema.columns().items():
                    env.add(rt, c, f"r.{c}", dt.Optional(cs.dtype) if r_opt else cs.dtype)
                env.add(rt, "id", "r.__id__", dt.Optional(dt.POINTER) if r_opt else dt.POINTER)
                post = {name: compile_expr(e, env).fn for name, e in out_exprs.items()}
                return runner._add(ops.Rowwise(gr, post))

            return lower

        out_exprs: dict[str, ColumnExpression] = {}
        for arg in args:
            resolved = self._resolve(arg)
            if not isinstance(resolved, ColumnReference):
                raise ValueError("positional args must be column references")
            out_exprs[resolved.name] = resolved
        for name, e in kwargs.items():
            out_exprs[name] = self._resolve(e)

        cols = {}
        from ...internals.expression_compiler import ColumnEnv, infer_dtype

        env = ColumnEnv()
        env.add_table(lt, prefix="l.")
        env.add_table(rt, prefix="r.")
        for name, e in out_exprs.items():
            try:
                cols[name] = ColumnSchema(name=name, dtype=infer_dtype(e, env))
            except Exception:
                cols[name] = ColumnSchema(name=name, dtype=dt.ANY)
        schema = schema_from_columns(cols, name="AsofJoined")
        return Table(
            "custom", [lt, rt], {"lower": make_lower(out_exprs)}, schema, Universe()
        )

    def _resolve(self, e):
        e = smart_coerce(e)

        def rewrite(x):
            import copy

            if isinstance(x, ColumnReference):
                if x.table is pw_left:
                    return ColumnReference(self._left, x.name)
                if x.table is pw_right:
                    return ColumnReference(self._right, x.name)
                if x.table is this:
                    from ._shared import this_side as _this_side

                    side = _this_side(
                        x.name, self._left, self._right, "asof_join"
                    )
                    return ColumnReference(
                        self._left if side == "l" else self._right, x.name
                    )
                return x
            if not getattr(x, "_deps", ()):
                return x
            clone = copy.copy(x)
            for attr, value in list(vars(clone).items()):
                if isinstance(value, ColumnExpression):
                    setattr(clone, attr, rewrite(value))
                elif isinstance(value, tuple) and any(isinstance(v, ColumnExpression) for v in value):
                    setattr(clone, attr, tuple(
                        rewrite(v) if isinstance(v, ColumnExpression) else v for v in value
                    ))
            return clone

        return rewrite(e)


def asof_join(
    self: Table, other: Table, self_time, other_time, *on: Any,
    how: JoinMode = JoinMode.LEFT, defaults: dict | None = None,
    direction: Direction = Direction.BACKWARD, behavior=None,
) -> AsofJoinResult:
    return AsofJoinResult(self, other, self_time, other_time, on, how, direction, defaults)


def asof_join_left(self, other, self_time, other_time, *on, **kw):
    return asof_join(self, other, self_time, other_time, *on, how=JoinMode.LEFT, **kw)


def asof_now_join(self: Table, other: Table, *on: Any, how: JoinMode = JoinMode.INNER, **kwargs):
    """Join each (query) row of self against other's CURRENT state; later
    changes to `other` never retract past outputs (reference
    ``_asof_now_join.py:176`` / UseExternalIndexAsOfNow semantics)."""
    from ...internals.joins import JoinResult

    jr = JoinResult(self, other, on, mode=how)
    jr._asof_now = True
    return jr
