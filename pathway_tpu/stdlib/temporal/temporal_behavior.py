"""Temporal behaviors (reference ``temporal_behavior.py:21-99``).

- ``common_behavior(delay, cutoff, keep_results)``: delay buffers window
  updates until the watermark reaches window_start + delay; cutoff ignores
  updates arriving after window_end + cutoff; keep_results=False frees and
  retracts a window's contribution once it is past its cutoff.
- ``exactly_once_behavior(shift)``: each window emits exactly one output, at
  window_end + shift (buffer-to-close + ignore-late).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

__all__ = [
    "CommonBehavior",
    "ExactlyOnceBehavior",
    "common_behavior",
    "exactly_once_behavior",
]


@dataclass(frozen=True)
class CommonBehavior:
    delay: Any = None
    cutoff: Any = None
    keep_results: bool = True


@dataclass(frozen=True)
class ExactlyOnceBehavior:
    shift: Any = None


def common_behavior(
    delay: Any = None, cutoff: Any = None, keep_results: bool = True
) -> CommonBehavior:
    return CommonBehavior(delay=delay, cutoff=cutoff, keep_results=keep_results)


def exactly_once_behavior(shift: Any = None) -> ExactlyOnceBehavior:
    return ExactlyOnceBehavior(shift=shift)
