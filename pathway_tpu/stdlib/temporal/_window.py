"""Windows: tumbling / sliding / session / intervals_over + windowby.

Re-design of ``python/pathway/stdlib/temporal/_window.py`` (Window ABC :42,
windowby :595-865). Tumbling/sliding windows are stateless row expansions
(flatten) followed by an ordinary incremental groupby — no dedicated window
operator needed; the engine's retraction machinery maintains window results.
Session windows need cross-row grouping and ride GroupedRecompute.
"""

from __future__ import annotations

import math
from abc import ABC, abstractmethod
from typing import Any

import numpy as np

from ...internals import dtype as dt
from ...internals.expression import (
    ApplyExpression,
    ColumnReference,
    MakeTupleExpression,
    smart_coerce,
)
from ...internals.parse_graph import Universe
from ...internals.schema import ColumnSchema, schema_from_columns
from ...internals.table import Table
from ...internals.thisclass import substitute, this
from .temporal_behavior import CommonBehavior, ExactlyOnceBehavior

__all__ = ["Window", "tumbling", "sliding", "session", "intervals_over", "windowby"]


class Window(ABC):
    @abstractmethod
    def _assign(self, table: Table, time_expr, instance_expr, behavior) -> Table:
        """Return the expanded table with _pw_window_start/_pw_window_end
        (+ _pw_instance) columns, one row per (row, window) membership."""


def _to_number(v: Any) -> Any:
    import datetime

    if isinstance(v, datetime.timedelta):
        return v
    return v


class _FixedWindow(Window):
    """Common machinery for tumbling/sliding: per-row window assignment."""

    def _windows_of(self, t):
        raise NotImplementedError

    def _assign(self, table, time_expr, instance_expr, behavior):
        win_fn = self._windows_of
        first_cols = {
            "_pw_windows": ApplyExpression(
                lambda t: tuple(win_fn(t)), dt.List(dt.ANY), (time_expr,), {}
            )
        }
        if instance_expr is not None:
            # instance references the source table — compute before flatten
            first_cols["_pw_instance"] = instance_expr
        expanded = table.with_columns(**first_cols).flatten(this._pw_windows)
        expanded = expanded.with_columns(
            _pw_window_start=ApplyExpression(
                lambda w: w[0], dt.ANY, (this._pw_windows,), {}
            ),
            _pw_window_end=ApplyExpression(
                lambda w: w[1], dt.ANY, (this._pw_windows,), {}
            ),
        ).without("_pw_windows")
        return _apply_behavior(expanded, behavior)


class TumblingWindow(_FixedWindow):
    def __init__(self, duration, origin=None):
        self.duration = duration
        self.origin = origin

    def _windows_of(self, t):
        d = self.duration
        origin = self.origin if self.origin is not None else (t - t) if not isinstance(t, (int, float)) else 0
        if self.origin is None and not isinstance(t, (int, float)):
            import datetime

            origin = datetime.datetime(1970, 1, 1, tzinfo=getattr(t, "tzinfo", None))
        k = math.floor((t - origin) / d)
        if self.origin is not None and k < 0:
            # an explicit origin is the FIRST window's start (reference
            # temporal/_window.py): earlier times belong to no window
            return ()
        start = origin + k * d
        return ((start, start + d),)


class SlidingWindow(_FixedWindow):
    def __init__(self, hop, duration, origin=None):
        self.hop = hop
        self.duration = duration
        self.origin = origin

    def _windows_of(self, t):
        h, d = self.hop, self.duration
        origin = self.origin
        if origin is None:
            if isinstance(t, (int, float)):
                origin = 0
            else:
                import datetime

                origin = datetime.datetime(1970, 1, 1, tzinfo=getattr(t, "tzinfo", None))
        # latest window start <= t
        s = origin + math.floor((t - origin) / h) * h
        out = []
        while s + d > t:
            if s <= t and (self.origin is None or s >= origin):
                # explicit origin truncates: no window starts before it
                # (reference sliding origin semantics, test_windows.py:430)
                out.append((s, s + d))
            s = s - h
        out.reverse()
        return tuple(out)


class SessionWindow(Window):
    def __init__(self, predicate=None, max_gap=None):
        if (predicate is None) == (max_gap is None):
            raise ValueError("session window needs exactly one of predicate / max_gap")
        self.predicate = predicate
        self.max_gap = max_gap

    def _assign(self, table, time_expr, instance_expr, behavior):
        from ...engine import keys as K
        from ...engine import operators as ops
        from ...internals.expression_compiler import compile_expr

        base_cols = table.column_names()
        out_cols = base_cols + ["_pw_window_start", "_pw_window_end"] + (
            ["_pw_instance"] if instance_expr is not None else []
        )
        cols = {
            **{n: c for n, c in table.schema.columns().items()},
            "_pw_window_start": ColumnSchema(name="_pw_window_start", dtype=dt.ANY),
            "_pw_window_end": ColumnSchema(name="_pw_window_end", dtype=dt.ANY),
        }
        if instance_expr is not None:
            cols["_pw_instance"] = ColumnSchema(name="_pw_instance", dtype=dt.ANY)
        schema = schema_from_columns(cols, name="SessionAssigned")
        predicate, max_gap = self.predicate, self.max_gap
        has_instance = instance_expr is not None

        def lower(runner, tbl):
            exprs = {"__t": time_expr}
            if has_instance:
                exprs["__i"] = instance_expr
            node, env = runner._zip_env(table, exprs)
            rw_cols = {c: (lambda cols_, keys_, n=c: cols_[n]) for c in base_cols}
            rw_cols["__t"] = compile_expr(time_expr, env).fn
            if has_instance:
                inst_fn = compile_expr(instance_expr, env).fn

                def g_fn(cols_, keys_, f=inst_fn):
                    vals = f(cols_, keys_)
                    if not isinstance(vals, np.ndarray):
                        arr = np.empty(len(keys_), dtype=object)
                        arr[:] = [vals] * len(keys_)
                        vals = arr
                    return K.mix_columns([vals], len(keys_))

                rw_cols["__g"] = g_fn
                rw_cols["__i"] = inst_fn
            pre = runner._add(ops.Rowwise(node, rw_cols))
            t_ix = len(base_cols)  # position of __t in rows
            i_ix = t_ix + 2 if has_instance else None

            def compute(gk, rows, time):
                # rows: {row_key: (base..., __t, [__g, __i])}
                entries = sorted(rows.items(), key=lambda kv: (kv[1][t_ix], kv[0]))
                out = []
                cluster: list = []

                def flush():
                    if not cluster:
                        return
                    start = cluster[0][1][t_ix]
                    end = cluster[-1][1][t_ix]
                    for rk, row in cluster:
                        base = row[:t_ix]
                        extra = (start, end)
                        if has_instance:
                            extra = extra + (row[i_ix],)
                        out.append((K.derive_scalar(rk, 0x5E55), base + extra))
                    cluster.clear()

                for rk, row in entries:
                    if cluster:
                        prev_t = cluster[-1][1][t_ix]
                        t = row[t_ix]
                        joined = (
                            predicate(prev_t, t)
                            if predicate is not None
                            else (t - prev_t) <= max_gap
                        )
                        if not joined:
                            flush()
                    cluster.append((rk, row))
                flush()
                return out

            gr = runner._add(ops.GroupedRecompute(
                [pre], ["__g" if has_instance else None], out_cols, compute,
            ))
            return gr

        expanded = Table("custom", [table], {"lower": lower}, schema, Universe())
        return _apply_behavior(expanded, behavior)


class IntervalsOverWindow(Window):
    def __init__(self, at, lower_bound, upper_bound, is_outer=True):
        self.at = at
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.is_outer = is_outer

    def _assign(self, table, time_expr, instance_expr, behavior):
        from ._interval_join import _expand_buckets

        at_ref = self.at
        if not isinstance(at_ref, ColumnReference):
            raise ValueError("intervals_over(at=...) takes a column reference")
        anchors = at_ref.table.groupby(at_ref).reduce(
            **{"_pw_anchor": at_ref}
        )
        lo, up = self.lower_bound, self.upper_bound
        # anchor a matches rows with time in [a+lo, a+up]
        from ._interval_join import interval, interval_join_inner

        expanded = interval_join_inner(
            anchors, table, anchors._pw_anchor, time_expr, interval(lo, up)
        ).select(
            *[ColumnReference(table, c) for c in table.column_names()],
            _pw_window_start=anchors._pw_anchor + lo,
            _pw_window_end=anchors._pw_anchor + up,
            _pw_instance=anchors._pw_anchor,
            # the probe point itself (reference intervals_over exposes
            # `_pw_window_location`, temporal/test_windows.py:961)
            _pw_window_location=anchors._pw_anchor,
        )
        return _apply_behavior(expanded, behavior)


def tumbling(duration, origin=None) -> TumblingWindow:
    return TumblingWindow(duration, origin)


def sliding(hop, duration=None, ratio=None, origin=None) -> SlidingWindow:
    if duration is None and ratio is not None:
        duration = hop * ratio
    return SlidingWindow(hop, duration, origin)


def session(*, predicate=None, max_gap=None) -> SessionWindow:
    return SessionWindow(predicate=predicate, max_gap=max_gap)


def intervals_over(*, at, lower_bound, upper_bound, is_outer=True) -> IntervalsOverWindow:
    return IntervalsOverWindow(at, lower_bound, upper_bound, is_outer)


class WindowedTable:
    """Result of windowby — reduce() aggregates per (instance, window)."""

    def __init__(self, table: Table, expanded: Table, has_instance: bool):
        self._table = table
        self._expanded = expanded
        self._has_instance = has_instance

    def reduce(self, *args: Any, **kwargs: Any) -> Table:
        exp = self._expanded
        group_cols = [exp._pw_window_start, exp._pw_window_end]
        if self._has_instance:
            group_cols.append(exp._pw_instance)
        if "_pw_window_location" in exp.column_names():
            # intervals_over: the probe point is constant per window and
            # selectable in reduce (reference _pw_window_location)
            group_cols.append(exp._pw_window_location)
        grouped = exp.groupby(*group_cols)
        # rewrite pw.this references against the expanded table; synthesize
        # the _pw_window tuple from the grouping columns
        new_kwargs = {}
        for name, e in kwargs.items():
            e = _rewrite_window_tuple(smart_coerce(e), exp, self._has_instance)
            new_kwargs[name] = substitute(e, {this: exp})
        new_args = [
            substitute(_rewrite_window_tuple(smart_coerce(a), exp, self._has_instance), {this: exp})
            for a in args
        ]
        return grouped.reduce(*new_args, **new_kwargs)


def _rewrite_window_tuple(expr, exp, has_instance):
    if isinstance(expr, ColumnReference) and expr.name == "_pw_window":
        parts = [ColumnReference(exp, "_pw_window_start"), ColumnReference(exp, "_pw_window_end")]
        if has_instance:
            parts = [ColumnReference(exp, "_pw_instance")] + parts
        return MakeTupleExpression(*parts)
    import copy

    if not getattr(expr, "_deps", ()):
        return expr
    clone = copy.copy(expr)
    from ...internals.expression import ColumnExpression

    for attr, value in list(vars(clone).items()):
        if isinstance(value, ColumnExpression):
            setattr(clone, attr, _rewrite_window_tuple(value, exp, has_instance))
        elif isinstance(value, tuple) and any(isinstance(v, ColumnExpression) for v in value):
            setattr(clone, attr, tuple(
                _rewrite_window_tuple(v, exp, has_instance) if isinstance(v, ColumnExpression) else v
                for v in value
            ))
    return clone


def windowby(
    table: Table,
    time_expr: Any,
    *,
    window: Window,
    instance: Any = None,
    behavior: Any = None,
) -> WindowedTable:
    time_expr = substitute(smart_coerce(time_expr), {this: table})
    if behavior is not None:
        # carry the event time as a column: behaviors' buffer/forget
        # watermark is the max TIME-COLUMN value seen (reference
        # time_column.rs frontier), not the engine's processing time
        table = table.with_columns(_pw_t=time_expr)
        time_expr = ColumnReference(table, "_pw_t")
    instance_expr = (
        substitute(smart_coerce(instance), {this: table}) if instance is not None else None
    )
    expanded = window._assign(table, time_expr, instance_expr, behavior)
    return WindowedTable(table, expanded, instance_expr is not None)


def _apply_behavior(expanded: Table, behavior) -> Table:
    """Wrap the expanded window-membership stream with buffer/forget engine
    nodes per the behavior (reference: engine buffer/forget/freeze). The
    watermark is the max EVENT time seen (the ``_pw_t`` column threaded
    through by windowby)."""
    if behavior is None:
        return expanded
    from ._shared import apply_behavior_nodes

    if isinstance(behavior, ExactlyOnceBehavior):
        shift = behavior.shift or 0
        buffer_expr = this._pw_window_end + shift
        # lateness is inclusive at the threshold (ForgetAfter keeps
        # thr >= watermark), so the released batch itself passes through
        cutoff_expr = this._pw_window_end + shift
        keep_results = True
    else:
        buffer_expr = (
            this._pw_window_start + behavior.delay if behavior.delay is not None else None
        )
        cutoff_expr = (
            this._pw_window_end + behavior.cutoff
            if behavior.cutoff is not None
            else None
        )
        keep_results = behavior.keep_results

    return apply_behavior_nodes(
        expanded, buffer_expr, cutoff_expr, "_pw_t", keep_results
    )
