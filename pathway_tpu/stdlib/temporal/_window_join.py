"""window_join — join rows that fall into the same window
(reference ``_window_join.py:156``): both sides expand to their window
memberships, then equi-join on (window_start, window_end) + extra conditions.
"""

from __future__ import annotations

from typing import Any

from ...internals.expression import ColumnReference, smart_coerce
from ...internals.joins import JoinMode
from ...internals.table import Table
from ...internals.thisclass import left as pw_left, right as pw_right, substitute, this

__all__ = [
    "window_join", "window_join_inner", "window_join_left",
    "window_join_right", "window_join_outer", "WindowJoinResult",
]


def _refs_table(expr: Any, table: Table) -> bool:
    """True when the expression contains a direct ColumnReference to the
    given concrete table (pw.left/pw.right placeholders do not count)."""
    if isinstance(expr, ColumnReference):
        return expr.table is table
    return any(
        _refs_table(d, table) for d in getattr(expr, "_deps", ())
    )


class WindowJoinResult:
    def __init__(self, left_t, right_t, left_time, right_time, window, on, mode):
        self._left = left_t
        self._right = right_t
        self._lexp = window._assign(
            left_t,
            substitute(smart_coerce(left_time), {this: left_t, pw_left: left_t}),
            None, None,
        )
        self._rexp = window._assign(
            right_t,
            substitute(
                smart_coerce(right_time), {this: right_t, pw_right: right_t}
            ),
            None, None,
        )
        self._on = on
        self._mode = mode

    def select(self, *args: Any, **kwargs: Any) -> Table:
        le, re_ = self._lexp, self._rexp
        conditions = [
            le._pw_window_start == re_._pw_window_start,
            le._pw_window_end == re_._pw_window_end,
        ]
        # conditions may reference pw.left/pw.right OR the original
        # tables directly (reference t1.k == t2.k style)
        if self._left is self._right and any(
            _refs_table(c, self._left) for c in self._on
        ):
            # a self-join collapses both table keys to one mapping entry,
            # which would silently rewrite a direct reference to one side;
            # pw.left/pw.right conditions stay unambiguous and allowed
            raise ValueError(
                "window self-join conditions must use pw.left/pw.right "
                "(direct table references are ambiguous)"
            )
        cond_map = {
            pw_left: le, pw_right: re_,
            self._left: le, self._right: re_,
        }
        for cond in self._on:
            lexpr = substitute(cond._left, cond_map)
            rexpr = substitute(cond._right, cond_map)
            conditions.append(lexpr == rexpr)
        jr = {
            JoinMode.INNER: le.join,
            JoinMode.LEFT: le.join_left,
            JoinMode.RIGHT: le.join_right,
            JoinMode.OUTER: le.join_outer,
        }[self._mode](re_, *conditions)

        def rewrite(e):
            import copy

            from ...internals.expression import ColumnExpression

            e = smart_coerce(e)
            if isinstance(e, ColumnReference):
                if e.table is self._left or e.name == "_pw_window" and e.table is this:
                    return ColumnReference(le, e.name)
                if e.table is self._right:
                    return ColumnReference(re_, e.name)
                return e
            if not getattr(e, "_deps", ()):
                return e
            clone = copy.copy(e)
            for attr, value in list(vars(clone).items()):
                if isinstance(value, ColumnExpression):
                    setattr(clone, attr, rewrite(value))
                elif isinstance(value, tuple) and any(isinstance(v, ColumnExpression) for v in value):
                    setattr(clone, attr, tuple(
                        rewrite(v) if isinstance(v, ColumnExpression) else v for v in value
                    ))
            return clone

        new_args = [rewrite(substitute(smart_coerce(a), {pw_left: le, pw_right: re_})) for a in args]
        new_kwargs = {
            n: rewrite(substitute(smart_coerce(e), {pw_left: le, pw_right: re_}))
            for n, e in kwargs.items()
        }
        return jr.select(*new_args, **new_kwargs)


def window_join(
    self: Table, other: Table, self_time, other_time, window,
    *on: Any, how: JoinMode = JoinMode.INNER,
) -> WindowJoinResult:
    return WindowJoinResult(self, other, self_time, other_time, window, on, how)


def window_join_inner(self, other, self_time, other_time, window, *on):
    return window_join(self, other, self_time, other_time, window, *on,
                       how=JoinMode.INNER)


def window_join_left(self, other, self_time, other_time, window, *on):
    return window_join(self, other, self_time, other_time, window, *on,
                       how=JoinMode.LEFT)


def window_join_right(self, other, self_time, other_time, window, *on):
    return window_join(self, other, self_time, other_time, window, *on,
                       how=JoinMode.RIGHT)


def window_join_outer(self, other, self_time, other_time, window, *on):
    return window_join(self, other, self_time, other_time, window, *on,
                       how=JoinMode.OUTER)
