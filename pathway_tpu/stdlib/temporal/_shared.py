"""Shared helpers for the temporal family (joins + window behaviors)."""

from __future__ import annotations

from typing import Any

from ...internals.table import Table

__all__ = ["this_side", "apply_behavior_nodes"]


def apply_behavior_nodes(
    table: Table,
    buffer_expr: Any,
    cutoff_expr: Any,
    watermark_col: str,
    keep_results: bool,
) -> Table:
    """Wrap ``table`` with the engine's temporal behavior nodes: rows whose
    ``cutoff_expr`` lies before the event-time watermark (max value of
    ``watermark_col`` seen) are dropped (and, with ``keep_results=False``,
    retracted once passed); rows are buffered until the watermark reaches
    ``buffer_expr``. Shared scaffold for windowby behaviors and the
    per-side interval_join behaviors."""
    from ...engine import operators as ops
    from ...internals.expression_compiler import compile_expr
    from ...internals.parse_graph import Universe
    from ...internals.expression import smart_coerce
    from ...internals.thisclass import substitute, this

    if buffer_expr is None and cutoff_expr is None:
        return table
    base_cols = table.column_names()
    schema = table.schema

    def lower(runner, tbl):
        inner = table
        exprs = {}
        if buffer_expr is not None:
            exprs["__buf"] = substitute(smart_coerce(buffer_expr), {this: inner})
        if cutoff_expr is not None:
            exprs["__cut"] = substitute(smart_coerce(cutoff_expr), {this: inner})
        node, env = runner._zip_env(inner, exprs)
        rw = {c: (lambda cols_, keys_, n=c: cols_[n]) for c in base_cols}
        for name, e in exprs.items():
            rw[name] = compile_expr(e, env).fn
        node = runner._add(ops.Rowwise(node, rw))
        # cutoff BEFORE buffer: lateness is judged at arrival time, and
        # buffered rows released later must still pass through
        if cutoff_expr is not None:
            node = runner._add(ops.ForgetAfter(
                node, "__cut", forget_state=not keep_results,
                watermark_col=watermark_col,
            ))
        if buffer_expr is not None:
            node = runner._add(ops.BufferUntil(
                node, "__buf", watermark_col=watermark_col
            ))
        return runner._add(ops.Rowwise(
            node, {c: (lambda cols_, keys_, n=c: cols_[n]) for c in base_cols}
        ))

    return Table("custom", [table], {"lower": lower}, schema, Universe())


def this_side(name: str, lt: Table, rt: Table, ctx: str) -> str:
    """Which side a ``pw.this.name`` reference means in a two-sided join
    result: 'l' or 'r' by column-name lookup, refusing ambiguity (the
    plain-join model: joins.py ``_lookup``)."""
    in_l = name in lt.column_names()
    in_r = name in rt.column_names()
    if in_l and in_r:
        raise ValueError(
            f"column {name!r} exists on both sides of the {ctx}; "
            "use pw.left / pw.right to disambiguate"
        )
    if in_l:
        return "l"
    if in_r:
        return "r"
    raise AttributeError(f"{ctx} result has no column {name!r}")
