"""Shared helpers for the temporal join family."""

from __future__ import annotations

from ...internals.table import Table

__all__ = ["this_side"]


def this_side(name: str, lt: Table, rt: Table, ctx: str) -> str:
    """Which side a ``pw.this.name`` reference means in a two-sided join
    result: 'l' or 'r' by column-name lookup, refusing ambiguity (the
    plain-join model: joins.py ``_lookup``)."""
    in_l = name in lt.column_names()
    in_r = name in rt.column_names()
    if in_l and in_r:
        raise ValueError(
            f"column {name!r} exists on both sides of the {ctx}; "
            "use pw.left / pw.right to disambiguate"
        )
    if in_l:
        return "l"
    if in_r:
        return "r"
    raise AttributeError(f"{ctx} result has no column {name!r}")
