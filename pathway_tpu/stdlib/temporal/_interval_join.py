"""interval_join — band joins on time columns.

Re-design of ``python/pathway/stdlib/temporal/_interval_join.py:577``.
TPU-first shape: instead of the reference's dedicated engine operator, the
band condition compiles to *bucketized equi-joins* over the existing
incremental Join — each left row expands to the (≤2 when the band fits one
bucket width) time buckets its band overlaps, right rows live in their own
bucket, and an exact post-filter trims the band edges. Outer modes derive
pads with an anti-join (difference) against the matched side.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any

from ...internals import dtype as dt
from ...internals.expression import (
    ApplyExpression,
    ColumnExpression,
    ColumnReference,
    smart_coerce,
)
from ...internals.joins import JoinMode
from ...internals.table import Table
from ...internals.thisclass import left as pw_left, right as pw_right, substitute, this
from ._shared import this_side as _this_side

__all__ = [
    "interval",
    "interval_join",
    "interval_join_inner",
    "interval_join_left",
    "interval_join_right",
    "interval_join_outer",
]


@dataclass(frozen=True)
class Interval:
    lower_bound: Any
    upper_bound: Any


def interval(lower_bound, upper_bound) -> Interval:
    if lower_bound > upper_bound:
        # reference temporal/test_interval_joins.py:286 — an empty interval
        # is a build-time error, not a silent never-matching join
        raise ValueError(
            "interval: lower_bound has to be less than or equal to "
            "upper_bound"
        )
    return Interval(lower_bound, upper_bound)


def _bucket_of(value, width):
    return int(math.floor(value / width))


def _expand_buckets(table: Table, time_expr, lo, up, col: str) -> Table:
    """Add a flattened bucket column covering [t+lo, t+up] per row."""
    width = _bucket_width(lo, up)

    def buckets(t):
        b0 = _bucket_of(t + lo, width)
        b1 = _bucket_of(t + up, width)
        return tuple(range(b0, b1 + 1))

    return table.with_columns(
        **{col: ApplyExpression(buckets, dt.List(dt.INT), (time_expr,), {})}
    ).flatten(this[col])


def _bucket_width(lo, up):
    span = up - lo
    if hasattr(span, "total_seconds"):
        span = span.total_seconds()
    return max(float(span), 1.0) if isinstance(span, float) else max(int(span), 1)


def _apply_side_behavior(table: Table, time_col: str, behavior) -> Table:
    """Per-side temporal behavior for interval joins (reference
    ``_interval_join.py`` behavior param): judged against each side's OWN
    event-time watermark — ``cutoff`` drops rows arriving after
    ``t + cutoff`` has passed (and with ``keep_results=False`` retracts
    them from join state, bounding memory); ``delay`` buffers rows until
    the watermark reaches ``t + delay``."""
    if behavior is None:
        return table
    from ._shared import apply_behavior_nodes
    from .temporal_behavior import CommonBehavior

    if not isinstance(behavior, CommonBehavior):
        raise TypeError(
            "interval_join behavior must be pw.temporal.common_behavior(...)"
        )
    return apply_behavior_nodes(
        table,
        this[time_col] + behavior.delay if behavior.delay is not None else None,
        this[time_col] + behavior.cutoff if behavior.cutoff is not None else None,
        time_col,
        behavior.keep_results,
    )


class IntervalJoinResult:
    def __init__(self, left_t: Table, right_t: Table, left_time, right_time,
                 iv: Interval, on: tuple, mode: JoinMode, behavior=None):
        self._left = left_t
        self._right = right_t
        self._left_time = substitute(smart_coerce(left_time), {this: left_t, pw_left: left_t, pw_right: right_t})
        self._right_time = substitute(smart_coerce(right_time), {this: right_t, pw_left: left_t, pw_right: right_t})
        self._iv = iv
        self._on = on
        self._mode = mode
        self._behavior = behavior

    def select(self, *args: Any, **kwargs: Any) -> Table:
        lt, rt = self._left, self._right
        lo, up = self._iv.lower_bound, self._iv.upper_bound
        width = _bucket_width(lo, up)

        # working copies with private time/bucket columns; behavior wraps
        # apply BEFORE expansion and are ALSO the pad sources — a row the
        # behavior dropped/forgot must not resurface as an outer pad
        lb = lt.with_columns(_pw_lt=self._left_time, _pw_lid=this.id)
        lb = _apply_side_behavior(lb, "_pw_lt", self._behavior)
        lt2 = _expand_buckets(lb, this._pw_lt, lo, up, "_pw_b")
        rb = rt.with_columns(_pw_rt=self._right_time, _pw_rid=this.id)
        rb = _apply_side_behavior(rb, "_pw_rt", self._behavior)
        rt2 = rb.with_columns(
            _pw_b=ApplyExpression(
                lambda t: _bucket_of(t, width), dt.INT, (this._pw_rt,), {}
            ),
        )
        conditions = [lt2._pw_b == rt2._pw_b]
        for cond in self._on:
            lexpr = substitute(cond._left, {pw_left: lt2, pw_right: rt2, this: lt2})
            rexpr = substitute(cond._right, {pw_left: lt2, pw_right: rt2, this: rt2})
            conditions.append(lexpr == rexpr)
        joined = lt2.join(rt2, *conditions)
        inner_sel: dict[str, ColumnExpression] = {
            "_pw_lid": ColumnReference(lt2, "_pw_lid"),
            "_pw_rid": ColumnReference(rt2, "_pw_rid"),
            "_pw_lt": ColumnReference(lt2, "_pw_lt"),
            "_pw_rt": ColumnReference(rt2, "_pw_rt"),
        }
        for c in lt.column_names():
            inner_sel[f"l.{c}"] = ColumnReference(lt2, c)
        for c in rt.column_names():
            inner_sel[f"r.{c}"] = ColumnReference(rt2, c)
        matched = joined.select(**inner_sel).filter(
            (this._pw_rt - this._pw_lt >= lo) & (this._pw_rt - this._pw_lt <= up)
        )

        # user select expressions over matched rows
        def out_of(matched_t, l_prefix=True, r_prefix=True):
            exprs = {}
            for arg in args:
                resolved = self._resolve(arg, matched_t, lt, rt)
                if not isinstance(resolved, tuple):
                    raise ValueError("positional args must be column references")
                name, e = resolved
                exprs[name] = e
            for name, e in kwargs.items():
                exprs[name] = self._resolve_expr(e, matched_t, lt, rt)
            return exprs

        result = matched.select(**out_of(matched))

        # pad keys are salt-derived from the unmatched side's row keys and
        # can never collide with the pair-derived match keys
        if self._mode in (JoinMode.LEFT, JoinMode.OUTER):
            pads = self._pads(matched, lt, rt, "left", args, kwargs, src=lb)
            result = result.promise_universes_are_disjoint(pads).concat(pads)
        if self._mode in (JoinMode.RIGHT, JoinMode.OUTER):
            pads = self._pads(matched, lt, rt, "right", args, kwargs, src=rb)
            result = result.promise_universes_are_disjoint(pads).concat(pads)
        return result

    # -- helpers --------------------------------------------------------

    def _resolve(self, arg, matched_t, lt, rt):
        e = self._resolve_expr(arg, matched_t, lt, rt)
        if isinstance(arg, ColumnReference):
            return arg.name, e
        raise ValueError("positional args must be column references")

    def _resolve_expr(self, e, matched_t, lt, rt):
        e = smart_coerce(e)

        def rewrite(x):
            import copy

            if isinstance(x, ColumnReference):
                if x.table is lt or x.table is pw_left or (isinstance(x.table, type(pw_left)) and x.table is pw_left):
                    return ColumnReference(matched_t, f"l.{x.name}")
                if x.table is rt or x.table is pw_right:
                    return ColumnReference(matched_t, f"r.{x.name}")
                if x.table is this:
                    # pw.this desugars by column-name side lookup, exactly
                    # like the plain-join result (joins.py _lookup)
                    side = _this_side(x.name, lt, rt, "interval_join")
                    return ColumnReference(matched_t, f"{side}.{x.name}")
                return x
            if not getattr(x, "_deps", ()):
                return x
            clone = copy.copy(x)
            for attr, value in list(vars(clone).items()):
                if isinstance(value, ColumnExpression):
                    setattr(clone, attr, rewrite(value))
                elif isinstance(value, tuple) and any(isinstance(v, ColumnExpression) for v in value):
                    setattr(clone, attr, tuple(
                        rewrite(v) if isinstance(v, ColumnExpression) else v for v in value
                    ))
            return clone

        return rewrite(substitute(e, {pw_left: lt, pw_right: rt}))

    def _pads(self, matched, lt, rt, side, args, kwargs, src=None):
        """Unmatched rows of one side, padded with None on the other side.
        ``src`` is the behavior-wrapped side (defaults to the raw table
        when no behavior is set)."""
        if src is None:
            src = lt if side == "left" else rt
        id_col = "_pw_lid" if side == "left" else "_pw_rid"
        # anti-join: source rows whose id is not among matched ids
        unmatched = _anti_join_by_pointer(src, matched, id_col)
        exprs = {}
        for arg in args:
            if not isinstance(arg, ColumnReference):
                raise ValueError("positional args must be column references")
            exprs[arg.name] = self._pad_expr(arg, unmatched, src, side, lt, rt)
        for name, e in kwargs.items():
            exprs[name] = self._pad_expr(e, unmatched, src, side, lt, rt)
        pads = unmatched.select(**exprs)
        # rekey with a side marker: pad rows keep their source row key
        # otherwise, so a row unmatched on BOTH sides of a self-join (or of
        # two tables sharing an ancestor) would collide between the left-pad
        # and right-pad concat inputs (reference derives distinct pad keys
        # the same way)
        return pads.with_id_from(pads.id, f"_pw_{side}_pad")

    def _pad_expr(self, e, unmatched, src, side, lt, rt):
        from ...internals.expression import ColumnConstExpression

        e = smart_coerce(e)

        def rewrite(x):
            import copy

            if isinstance(x, ColumnReference):
                if x.table is this:
                    # same side lookup as the matched path — an own-side
                    # pw.this column keeps its value in pad rows
                    this_side_ = _this_side(x.name, lt, rt, "interval_join")
                    own = (this_side_ == "l") == (side == "left")
                    if own:
                        return ColumnReference(unmatched, x.name)
                    return ColumnConstExpression(None)
                own = (x.table is lt or x.table is pw_left) if side == "left" else (
                    x.table is rt or x.table is pw_right
                )
                if own:
                    return ColumnReference(unmatched, x.name)
                return ColumnConstExpression(None)
            if not getattr(x, "_deps", ()):
                return x
            clone = copy.copy(x)
            for attr, value in list(vars(clone).items()):
                if isinstance(value, ColumnExpression):
                    setattr(clone, attr, rewrite(value))
                elif isinstance(value, tuple) and any(isinstance(v, ColumnExpression) for v in value):
                    setattr(clone, attr, tuple(
                        rewrite(v) if isinstance(v, ColumnExpression) else v for v in value
                    ))
            return clone

        return rewrite(e)


def _anti_join_by_pointer(src: Table, matched: Table, id_col: str) -> Table:
    """Rows of src whose id does not appear in matched[id_col]."""
    from ...engine import operators as ops
    from ...internals.parse_graph import Universe

    def lower(runner, tbl):
        src_node = runner.lower(src)
        m_node = runner.lower(matched)
        from ...internals.graph_runner import _colref

        m_ids = runner._add(ops.Rowwise(m_node, {"__p": _colref(id_col)}))
        cols = src.column_names()
        return runner._add(ops.Join(
            src_node, m_ids, None, "__p",
            left_cols=cols, right_cols=[], out_names=cols,
            mode="left", key_mode="left", emit_matched=False,
        ))

    return Table(
        "custom", [src, matched], {"lower": lower}, src.schema,
        Universe(parent=src._universe),
    )


def interval_join(
    self: Table, other: Table, self_time, other_time, interval: Interval,
    *on: Any, behavior=None, how: JoinMode = JoinMode.INNER,
) -> IntervalJoinResult:
    return IntervalJoinResult(self, other, self_time, other_time, interval, on, how, behavior)


def interval_join_inner(self, other, self_time, other_time, iv, *on, behavior=None):
    return IntervalJoinResult(self, other, self_time, other_time, iv, on, JoinMode.INNER, behavior)


def interval_join_left(self, other, self_time, other_time, iv, *on, behavior=None):
    return IntervalJoinResult(self, other, self_time, other_time, iv, on, JoinMode.LEFT, behavior)


def interval_join_right(self, other, self_time, other_time, iv, *on, behavior=None):
    return IntervalJoinResult(self, other, self_time, other_time, iv, on, JoinMode.RIGHT, behavior)


def interval_join_outer(self, other, self_time, other_time, iv, *on, behavior=None):
    return IntervalJoinResult(self, other, self_time, other_time, iv, on, JoinMode.OUTER, behavior)
