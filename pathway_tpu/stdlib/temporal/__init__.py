"""``pw.temporal`` — windows, temporal joins, behaviors.

Re-design of ``python/pathway/stdlib/temporal`` (windows ``_window.py:42-865``,
interval_join ``_interval_join.py:577``, window_join ``_window_join.py:156``,
asof joins ``_asof_join.py:479`` / ``_asof_now_join.py:176``, behaviors
``temporal_behavior.py:29,83``). Tumbling/sliding windows compile to a
flatten+groupby pipeline over the existing engine ops; session windows and
asof joins ride the GroupedRecompute operator; behaviors map to the engine's
BufferUntil/ForgetAfter nodes (the ``time_column.rs`` analogs).
"""

from ._window import (
    Window,
    intervals_over,
    session,
    sliding,
    tumbling,
    windowby,
)
from ._interval_join import (
    interval,
    interval_join,
    interval_join_inner,
    interval_join_left,
    interval_join_outer,
    interval_join_right,
)
from ._window_join import (
    window_join,
    window_join_inner,
    window_join_left,
    window_join_outer,
    window_join_right,
)
from ._asof_join import Direction, asof_join, asof_join_left, asof_now_join
from .temporal_behavior import (
    CommonBehavior,
    ExactlyOnceBehavior,
    common_behavior,
    exactly_once_behavior,
)

__all__ = [
    "Window",
    "tumbling",
    "sliding",
    "session",
    "intervals_over",
    "windowby",
    "interval",
    "interval_join",
    "interval_join_inner",
    "interval_join_left",
    "interval_join_right",
    "interval_join_outer",
    "window_join",
    "window_join_inner",
    "window_join_left",
    "window_join_right",
    "window_join_outer",
    "asof_join",
    "asof_join_left",
    "asof_now_join",
    "Direction",
    "CommonBehavior",
    "ExactlyOnceBehavior",
    "common_behavior",
    "exactly_once_behavior",
]
