"""pw.temporal — windows, interval/asof joins, behaviors (reference
python/pathway/stdlib/temporal). Implementations land incrementally."""


def windowby(table, time_expr, *, window, instance=None, behavior=None):
    raise NotImplementedError("temporal.windowby is not implemented yet")
