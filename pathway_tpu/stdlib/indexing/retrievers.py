"""Retriever factory surface (reference ``stdlib/indexing/retrievers.py``)."""

from __future__ import annotations

from .data_index import InnerIndexFactory

__all__ = ["AbstractRetrieverFactory", "InnerIndexFactory"]

# the reference exposes the factory protocol under this name for xpack configs
AbstractRetrieverFactory = InnerIndexFactory
