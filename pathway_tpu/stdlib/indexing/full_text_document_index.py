"""Default full-text document index (reference
``stdlib/indexing/full_text_document_index.py``)."""

from __future__ import annotations

from ...internals.expression import ColumnExpression, ColumnReference
from ...internals.table import Table
from .bm25 import TantivyBM25
from .data_index import DataIndex

__all__ = ["default_full_text_document_index"]


def default_full_text_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    metadata_column: ColumnExpression | None = None,
) -> DataIndex:
    inner = TantivyBM25(
        data_column=data_column,
        metadata_column=metadata_column,
    )
    return DataIndex(data_table, inner)
