"""Hybrid index — reciprocal-rank fusion over inner indexes
(reference ``stdlib/indexing/hybrid_index.py``)."""

from __future__ import annotations

from dataclasses import dataclass, field

from ...internals.expression import ColumnExpression, ColumnReference
from ...ops.index_engines import HybridEngine
from .data_index import InnerIndex, InnerIndexFactory

__all__ = ["HybridIndex", "HybridIndexFactory"]


@dataclass(kw_only=True)
class HybridIndex(InnerIndex):
    """Fuses the rankings of several inner indexes with reciprocal rank
    fusion: score(doc) = Σ_i 1 / (k + rank_i(doc))."""

    inner_indexes: list[InnerIndex] = field(default_factory=list)
    k: int = 60

    def __post_init__(self):
        if not self.inner_indexes:
            raise ValueError("HybridIndex needs at least one inner index")

    def _make_engine(self):
        return HybridEngine(
            [ix._make_engine() for ix in self.inner_indexes], rrf_k=self.k
        )


@dataclass
class HybridIndexFactory(InnerIndexFactory):
    retriever_factories: list[InnerIndexFactory]
    k: int = 60

    def build_inner_index(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnExpression | None = None,
    ) -> InnerIndex:
        inner = [
            f.build_inner_index(data_column, metadata_column)
            for f in self.retriever_factories
        ]
        return HybridIndex(
            data_column=data_column,
            metadata_column=metadata_column,
            inner_indexes=inner,
            k=self.k,
        )
