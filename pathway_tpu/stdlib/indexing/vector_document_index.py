"""Default vector document indexes (reference
``stdlib/indexing/vector_document_index.py:34-160``): convenience builders
producing a ``DataIndex`` with a KNN inner index over a text column, using an
embedder to map text → vectors. On TPU the embedder itself can be the
flax/JAX model in ``models/embedder.py`` so the whole retrieve path
(embed → score → top-k) stays on device."""

from __future__ import annotations

from typing import Any

from ...internals.expression import ColumnExpression, ColumnReference
from ...internals.table import Table
from .data_index import DataIndex
from .bm25 import TantivyBM25
from .nearest_neighbors import BruteForceKnn, LshKnn, USearchKnn

__all__ = [
    "default_vector_document_index",
    "default_brute_force_knn_document_index",
    "default_lsh_knn_document_index",
    "default_usearch_knn_document_index",
]


def _as_callable(embedder: Any):
    """Accept a pw.UDF or a plain callable as the text→vector embedder."""
    if embedder is None:
        return None
    for attr in ("func", "__wrapped__"):
        f = getattr(embedder, attr, None)
        if callable(f):
            return f
    if callable(embedder):
        return embedder
    raise TypeError(f"embedder must be callable or a UDF, got {type(embedder)}")


def default_vector_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    dimensions: int,
    embedder: Any | None = None,
    metadata_column: ColumnExpression | None = None,
) -> DataIndex:
    """An arbitrary good-default vector index (reference picks LSH; on TPU
    the exact brute-force kernel is both faster and exact at the default
    scale, so it is the default here)."""
    return default_brute_force_knn_document_index(
        data_column,
        data_table,
        dimensions=dimensions,
        embedder=embedder,
        metadata_column=metadata_column,
    )


def default_brute_force_knn_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    dimensions: int,
    embedder: Any | None = None,
    metadata_column: ColumnExpression | None = None,
) -> DataIndex:
    inner = BruteForceKnn(
        data_column=data_column,
        metadata_column=metadata_column,
        dimensions=dimensions,
        reserved_space=1024,
        embedder=_as_callable(embedder),
    )
    return DataIndex(data_table, inner)


def default_lsh_knn_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    dimensions: int,
    embedder: Any | None = None,
    metadata_column: ColumnExpression | None = None,
) -> DataIndex:
    inner = LshKnn(
        data_column=data_column,
        metadata_column=metadata_column,
        dimensions=dimensions,
        reserved_space=1024,
        embedder=_as_callable(embedder),
    )
    return DataIndex(data_table, inner)


def default_usearch_knn_document_index(
    data_column: ColumnReference,
    data_table: Table,
    dimensions: int,
    *,
    embedder: Any | None = None,
    metadata_column: ColumnExpression | None = None,
) -> DataIndex:
    inner = USearchKnn(
        data_column=data_column,
        metadata_column=metadata_column,
        dimensions=dimensions,
        reserved_space=1024,
        embedder=_as_callable(embedder),
    )
    return DataIndex(data_table, inner)
