"""``pw.indexing`` — live KNN / BM25 / hybrid indexes and sortedness
(reference ``python/pathway/stdlib/indexing``). The KNN scoring path runs
as XLA kernels on the TPU MXU (``ops/knn.py``, ``ops/index_engines.py``)
replacing the reference's native USearch/Tantivy integrations
(``src/external_integration/``)."""

from __future__ import annotations

from .bm25 import BM25, TantivyBM25, TantivyBM25Factory
from .data_index import DataIndex, InnerIndex, InnerIndexFactory
from .full_text_document_index import default_full_text_document_index
from .hybrid_index import HybridIndex, HybridIndexFactory
from .nearest_neighbors import (
    BruteForceKnn,
    BruteForceKnnFactory,
    BruteForceKnnMetricKind,
    LshKnn,
    LshKnnFactory,
    USearchKnn,
    USearchMetricKind,
    UsearchKnnFactory,
)
from .retrievers import AbstractRetrieverFactory
from .sorting import (
    SortedIndex,
    build_sorted_index,
    retrieve_prev_next_values,
    sort_from_index,
)
from .vector_document_index import (
    default_brute_force_knn_document_index,
    default_lsh_knn_document_index,
    default_usearch_knn_document_index,
    default_vector_document_index,
)

__all__ = [
    "AbstractRetrieverFactory",
    "DataIndex",
    "InnerIndex",
    "InnerIndexFactory",
    "USearchKnn",
    "UsearchKnnFactory",
    "USearchMetricKind",
    "BruteForceKnn",
    "BruteForceKnnFactory",
    "BruteForceKnnMetricKind",
    "LshKnn",
    "LshKnnFactory",
    "TantivyBM25",
    "TantivyBM25Factory",
    "BM25",
    "HybridIndex",
    "HybridIndexFactory",
    "SortedIndex",
    "default_vector_document_index",
    "default_lsh_knn_document_index",
    "default_usearch_knn_document_index",
    "default_brute_force_knn_document_index",
    "default_full_text_document_index",
    "retrieve_prev_next_values",
    "sort_from_index",
    "build_sorted_index",
]
