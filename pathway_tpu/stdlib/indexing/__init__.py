"""pw.indexing — KNN / BM25 / hybrid live indexes (reference
python/pathway/stdlib/indexing). TPU-native XLA kernels live in ops/knn.py."""
