"""``pw.indexing.DataIndex`` / ``InnerIndex`` — live index query surface.

Re-design of the reference ``python/pathway/stdlib/indexing/data_index.py``
(``InnerIndex`` :206, ``DataIndex`` :278). An ``InnerIndex`` wires an
indexed-data column and a query column into the engine's
``ExternalIndexNode`` (our analog of ``UseExternalIndexAsOfNow``,
``src/engine/dataflow/operators/external_index.rs:38``); ``DataIndex``
repacks the raw ``(id, score)`` replies into a JoinResult against the data
table — collapsed (one row per query, tuple-valued columns, best-first) or
flat (one row per match) — mirroring ``_extract_data_collapsed_rows`` /
``_extract_data_flat`` (data_index.py:46,91).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Any, Callable

from ... import reducers
from ...internals import dtype as dt
from ...internals.expression import (
    ColumnExpression,
    ColumnReference,
    apply_with_type,
    smart_coerce,
)
from ...internals.joins import JoinMode, JoinResult
from ...internals.parse_graph import Universe
from ...internals.schema import ColumnSchema, schema_from_columns
from ...internals.table import Table
from ...internals.thisclass import this, substitute

__all__ = [
    "DataIndex",
    "InnerIndex",
    "InnerIndexFactory",
    "_INDEX_REPLY",
    "_QUERY_ID",
    "_MATCHED_ID",
    "_SCORE",
]

# special column names, kept verbatim for parity (indexing/colnames.py)
_INDEX_REPLY = "_pw_index_reply"
_QUERY_ID = "_pw_query_id"
_MATCHED_ID = "_pw_index_reply_id"
_SCORE = "_pw_index_reply_score"


@dataclass(kw_only=True)
class InnerIndex(ABC):
    """Base of index implementations over ``data_column``
    (reference data_index.py:206)."""

    data_column: ColumnReference
    metadata_column: ColumnExpression | None = None

    @abstractmethod
    def _make_engine(self) -> Any:
        """Fresh host/TPU index engine (engine.external_index.IndexEngine)."""

    def _prep_data(self) -> Table:
        t = self.data_column.table
        exprs: dict[str, Any] = {"__data__": self.data_column}
        if self.metadata_column is not None:
            exprs["__filter_data__"] = self.metadata_column
        return t.select(**exprs)

    def _raw(
        self,
        query_column: ColumnReference,
        number_of_matches: ColumnExpression | int,
        metadata_filter: ColumnExpression | None,
        asof_now: bool,
    ) -> Table:
        """Reply table keyed by query id with one tuple column
        ``_pw_index_reply`` of (id, score) pairs, best first."""
        from ...engine.external_index import ExternalIndexNode

        qt = query_column.table
        qexprs: dict[str, Any] = {
            "__query__": query_column,
            "__limit__": smart_coerce(number_of_matches),
        }
        if metadata_filter is not None:
            qexprs["__filter__"] = metadata_filter
        prep_q = qt.select(**qexprs)
        prep_d = self._prep_data()
        make_engine = self._make_engine

        def lower(runner, tbl):
            from ...engine import operators as ops

            # query chain first: source ownership round-robins in lowering
            # order, so this keeps a REST query edge on worker 0 — the same
            # worker the serve plane's scatter origin, the response sink's
            # gather and the degraded-status side channel all live on
            query_node = runner.lower(prep_q)
            data_node = runner.lower(prep_d)
            return runner._add(
                ExternalIndexNode(
                    data_node, query_node, make_engine(), asof_now=asof_now
                )
            )

        schema = schema_from_columns(
            {_INDEX_REPLY: ColumnSchema(name=_INDEX_REPLY, dtype=dt.List(dt.ANY))},
            name="IndexReply",
        )
        return Table("custom", [prep_d, prep_q], {"lower": lower}, schema, Universe())

    def query(
        self,
        query_column: ColumnReference,
        *,
        number_of_matches: ColumnExpression | int = 3,
        metadata_filter: ColumnExpression | None = None,
    ) -> Table:
        return self._raw(query_column, number_of_matches, metadata_filter, False)

    def query_as_of_now(
        self,
        query_column: ColumnReference,
        *,
        number_of_matches: ColumnExpression | int = 3,
        metadata_filter: ColumnExpression | None = None,
    ) -> Table:
        return self._raw(query_column, number_of_matches, metadata_filter, True)


class InnerIndexFactory(ABC):
    """Builds an InnerIndex given the data columns
    (reference retrievers.py InnerIndexFactory)."""

    @abstractmethod
    def build_inner_index(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnExpression | None = None,
    ) -> InnerIndex: ...

    def build_index(
        self,
        data_column: ColumnReference,
        data_table: Table,
        metadata_column: ColumnExpression | None = None,
    ) -> "DataIndex":
        return DataIndex(
            data_table, self.build_inner_index(data_column, metadata_column)
        )


@dataclass
class DataIndex:
    """Augments InnerIndex replies with the data table's columns
    (reference data_index.py:278)."""

    data_table: Table
    inner_index: InnerIndex

    def query(
        self,
        query_column: ColumnReference,
        *,
        number_of_matches: ColumnExpression | int = 3,
        collapse_rows: bool = True,
        metadata_filter: ColumnExpression | None = None,
    ) -> JoinResult:
        """Maintained matches: answers update when the index data changes."""
        raw = self.inner_index.query(
            query_column,
            number_of_matches=number_of_matches,
            metadata_filter=metadata_filter,
        )
        return self._repack(raw, query_column.table, collapse_rows)

    def query_as_of_now(
        self,
        query_column: ColumnReference,
        *,
        number_of_matches: ColumnExpression | int = 3,
        collapse_rows: bool = True,
        metadata_filter: ColumnExpression | None = None,
    ) -> JoinResult:
        """Answers reflect the index at query arrival and are not revisited."""
        raw = self.inner_index.query_as_of_now(
            query_column,
            number_of_matches=number_of_matches,
            metadata_filter=metadata_filter,
        )
        return self._repack(raw, query_column.table, collapse_rows)

    # ------------------------------------------------------------------

    def _matching(self, raw: Table) -> Table:
        """One row per (query, match): _pw_query_id, _pw_index_reply_id,
        _pw_index_reply_score (reference's flatten+unpack,
        data_index.py:294-345)."""
        flat = raw.flatten(this[_INDEX_REPLY], origin_id=_QUERY_ID)
        return flat.select(
            **{
                _QUERY_ID: this[_QUERY_ID],
                _MATCHED_ID: apply_with_type(
                    lambda p: int(p[0]), dt.POINTER, this[_INDEX_REPLY]
                ),
                _SCORE: apply_with_type(
                    lambda p: float(p[1]), dt.FLOAT, this[_INDEX_REPLY]
                ),
            }
        )

    def _repack(
        self, raw: Table, query_table: Table, collapse_rows: bool
    ) -> JoinResult:
        from ...internals.thisclass import left as l_, right as r_

        data_cols = self.data_table.column_names()
        matching = self._matching(raw)
        docs = JoinResult(
            matching,
            self.data_table,
            (ColumnReference(matching, _MATCHED_ID) == _id_of(self.data_table),),
            JoinMode.INNER,
        ).select(
            *(getattr(r_, c) for c in data_cols),
            **{
                _QUERY_ID: getattr(l_, _QUERY_ID),
                _SCORE: getattr(l_, _SCORE),
                _MATCHED_ID: getattr(l_, _MATCHED_ID),
            },
        )
        if not collapse_rows:
            jr = JoinResult(
                query_table,
                docs,
                (_id_of(query_table) == ColumnReference(docs, _QUERY_ID),),
                JoinMode.LEFT,
            )
            return jr

        order = -ColumnReference(docs, _SCORE)
        grouped = docs.groupby(id=ColumnReference(docs, _QUERY_ID)).reduce(
            **{
                _SCORE: reducers.tuple_by(order, ColumnReference(docs, _SCORE)),
                _MATCHED_ID: reducers.tuple_by(
                    order, ColumnReference(docs, _MATCHED_ID)
                ),
                **{
                    c: reducers.tuple_by(order, ColumnReference(docs, c))
                    for c in data_cols
                },
            }
        )
        # every query gets a row; unmatched queries carry empty tuples
        defaults = query_table.select(
            **{
                _SCORE: (),
                _MATCHED_ID: (),
                **{c: () for c in data_cols},
            }
        )
        collapsed = defaults.update_rows(grouped)
        return JoinResult(
            query_table,
            collapsed,
            (_id_of(query_table) == _id_of(collapsed),),
            JoinMode.LEFT,
            # output rows keep the QUERY row ids (reference: a maintained
            # query() result is keyed by its query table, so
            # `queries + index.get_nearest_items(...)` zips directly)
            id=_id_of(query_table),
        )


def _id_of(table: Table):
    from ...internals.expression import IdReference

    return IdReference(table)
