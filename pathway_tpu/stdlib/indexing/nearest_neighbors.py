"""KNN inner indexes (reference ``stdlib/indexing/nearest_neighbors.py``).

``BruteForceKnn`` — exact KNN; the reference scores on CPU
(``brute_force_knn_integration.rs``), here scoring is one bf16 matmul on the
TPU MXU + ``lax.top_k`` (``ops/index_engines.BruteForceKnnEngine``).
``USearchKnn`` — the reference wraps the USearch HNSW graph
(``usearch_integration.rs``); on TPU an HNSW pointer-chase is the wrong
shape for the hardware, and exact MXU scoring is faster than HNSW up to
millions of rows — so this class keeps the USearch API surface (metric
kinds, reserved space) over the same exact TPU kernel.
``LshKnn`` — random-hyperplane LSH bucketing with exact scoring of the
candidate set (reference ``LshKnn``; classic impl ``stdlib/ml/index.py``).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable

from ...internals.expression import ColumnExpression, ColumnReference
from ...ops.index_engines import BruteForceKnnEngine, LshKnnEngine
from .data_index import DataIndex, InnerIndex, InnerIndexFactory

__all__ = [
    "BruteForceKnnMetricKind",
    "USearchMetricKind",
    "BruteForceKnn",
    "BruteForceKnnFactory",
    "USearchKnn",
    "UsearchKnnFactory",
    "LshKnn",
    "LshKnnFactory",
]


class BruteForceKnnMetricKind(enum.Enum):
    """Metric for brute-force KNN (reference engine BruteForceKnnMetricKind)."""

    COS = "cos"
    L2SQ = "l2"


class USearchMetricKind(enum.Enum):
    """Metric kinds mirroring the USearch surface (reference USearchMetricKind)."""

    IP = "ip"  # raw inner product — inputs are NOT normalized
    COS = "cos"
    L2SQ = "l2"


def _metric_str(metric) -> str:
    return metric.value if isinstance(metric, enum.Enum) else str(metric)


@dataclass(kw_only=True)
class BruteForceKnn(InnerIndex):
    """Exact nearest neighbors over ``data_column`` vectors — MXU matmul +
    top-k per query batch (reference nearest_neighbors.py:170)."""

    dimensions: int
    reserved_space: int = 1024
    metric: BruteForceKnnMetricKind | str = BruteForceKnnMetricKind.COS
    embedder: Callable | None = None

    def _make_engine(self):
        return BruteForceKnnEngine(
            self.dimensions,
            metric=_metric_str(self.metric),
            reserved_space=self.reserved_space,
            embedder=self.embedder,
        )


@dataclass(kw_only=True)
class USearchKnn(InnerIndex):
    """USearch-surface KNN (reference nearest_neighbors.py:65). Exact TPU
    scoring stands in for the HNSW graph — see module docstring."""

    dimensions: int
    reserved_space: int = 1024
    metric: USearchMetricKind | str = USearchMetricKind.COS
    connectivity: int = 0  # accepted for API parity; no-op on the exact kernel
    expansion_add: int = 0
    expansion_search: int = 0
    embedder: Callable | None = None

    def _make_engine(self):
        return BruteForceKnnEngine(
            self.dimensions,
            metric=_metric_str(self.metric),
            reserved_space=self.reserved_space,
            embedder=self.embedder,
        )


@dataclass(kw_only=True)
class LshKnn(InnerIndex):
    """Locality-sensitive-hashing approximate KNN
    (reference nearest_neighbors.py:262)."""

    dimensions: int
    reserved_space: int = 1024
    metric: BruteForceKnnMetricKind | str = BruteForceKnnMetricKind.COS
    n_or: int = 4
    n_and: int = 8
    bucket_length: float = 10.0
    seed: int = 0
    embedder: Callable | None = None

    def _make_engine(self):
        return LshKnnEngine(
            self.dimensions,
            metric=_metric_str(self.metric),
            reserved_space=self.reserved_space,
            n_or=self.n_or,
            n_and=self.n_and,
            seed=self.seed,
            embedder=self.embedder,
        )


@dataclass
class BruteForceKnnFactory(InnerIndexFactory):
    dimensions: int
    reserved_space: int = 1024
    metric: BruteForceKnnMetricKind | str = BruteForceKnnMetricKind.COS
    embedder: Callable | None = None

    def build_inner_index(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnExpression | None = None,
    ) -> InnerIndex:
        return BruteForceKnn(
            data_column=data_column,
            metadata_column=metadata_column,
            dimensions=self.dimensions,
            reserved_space=self.reserved_space,
            metric=self.metric,
            embedder=self.embedder,
        )


@dataclass
class UsearchKnnFactory(InnerIndexFactory):
    dimensions: int
    reserved_space: int = 1024
    metric: USearchMetricKind | str = USearchMetricKind.COS
    connectivity: int = 0
    expansion_add: int = 0
    expansion_search: int = 0
    embedder: Callable | None = None

    def build_inner_index(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnExpression | None = None,
    ) -> InnerIndex:
        return USearchKnn(
            data_column=data_column,
            metadata_column=metadata_column,
            dimensions=self.dimensions,
            reserved_space=self.reserved_space,
            metric=self.metric,
            connectivity=self.connectivity,
            expansion_add=self.expansion_add,
            expansion_search=self.expansion_search,
            embedder=self.embedder,
        )


@dataclass
class LshKnnFactory(InnerIndexFactory):
    dimensions: int
    reserved_space: int = 1024
    metric: BruteForceKnnMetricKind | str = BruteForceKnnMetricKind.COS
    n_or: int = 4
    n_and: int = 8
    seed: int = 0
    embedder: Callable | None = None

    def build_inner_index(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnExpression | None = None,
    ) -> InnerIndex:
        return LshKnn(
            data_column=data_column,
            metadata_column=metadata_column,
            dimensions=self.dimensions,
            reserved_space=self.reserved_space,
            metric=self.metric,
            n_or=self.n_or,
            n_and=self.n_and,
            seed=self.seed,
            embedder=self.embedder,
        )
