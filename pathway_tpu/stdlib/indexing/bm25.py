"""BM25 full-text inner index (reference ``stdlib/indexing/bm25.py``).

The reference delegates to the Tantivy library
(``src/external_integration/tantivy_integration.rs``); here the inverted
index + Okapi BM25 scoring is the in-process host engine
``ops/index_engines.BM25Engine`` — text scoring is branchy and string-heavy,
the wrong shape for the MXU, so it stays on host exactly as the reference
keeps it off its dataflow threads. Class names keep the reference surface.
"""

from __future__ import annotations

from dataclasses import dataclass

from ...internals.expression import ColumnExpression, ColumnReference
from ...ops.index_engines import BM25Engine
from .data_index import InnerIndex, InnerIndexFactory

__all__ = ["TantivyBM25", "TantivyBM25Factory", "BM25"]


@dataclass(kw_only=True)
class TantivyBM25(InnerIndex):
    """BM25 ranking over ``data_column`` text (reference bm25.py:41)."""

    ram_budget: int = 50_000_000  # accepted for parity; in-memory engine
    in_memory_index: bool = True
    k1: float = 1.2
    b: float = 0.75

    def _make_engine(self):
        return BM25Engine(
            ram_budget=self.ram_budget,
            in_memory_index=self.in_memory_index,
            k1=self.k1,
            b=self.b,
        )


BM25 = TantivyBM25


@dataclass
class TantivyBM25Factory(InnerIndexFactory):
    ram_budget: int = 50_000_000
    in_memory_index: bool = True

    def build_inner_index(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnExpression | None = None,
    ) -> InnerIndex:
        return TantivyBM25(
            data_column=data_column,
            metadata_column=metadata_column,
            ram_budget=self.ram_budget,
            in_memory_index=self.in_memory_index,
        )
