"""Sortedness utilities (reference ``stdlib/indexing/sorting.py``).

The reference maintains sorted order with the prev-next pointer operator
(``src/engine/dataflow/operators/prev_next.rs:770``) and a distributed
treap for ``build_sorted_index``. Here sorted order per instance is computed
by the engine's grouped-recompute machinery (``stdlib/_sorted.py``) — a
host-side sort per group feeding pointer columns; chain walks
(``retrieve_prev_next_values``) recompute incrementally per tick.
"""

from __future__ import annotations

from typing import Any, TypedDict

from ...internals import dtype as dt
from ...internals.expression import ColumnExpression, ColumnReference
from ...internals.table import Table
from ...internals.thisclass import this
from .._sorted import sorted_group_transform

__all__ = [
    "SortedIndex",
    "build_sorted_index",
    "sort_from_index",
    "retrieve_prev_next_values",
]


class SortedIndex(TypedDict):
    index: Table
    oriented_index: Table


def sort_from_index(
    table: Table,
    key: ColumnExpression | None = None,
    instance: ColumnExpression | None = None,
) -> Table:
    """``prev``/``next`` pointer columns ordering ``table`` by ``key``
    (reference sorting.py:137 / Table.sort table.py:2157)."""
    key_expr = table._sub(key) if key is not None else this.id
    key_expr = table._sub(key_expr)
    inst = table._sub(instance) if instance is not None else None

    def fn(entries):
        out = []
        n = len(entries)
        for i, (rk, _o, _p) in enumerate(entries):
            prev_k = entries[i - 1][0] if i > 0 else None
            next_k = entries[i + 1][0] if i < n - 1 else None
            out.append((rk, (prev_k, next_k)))
        return out

    return sorted_group_transform(
        table,
        key_expr,
        [],
        inst,
        {"prev": dt.Optional(dt.POINTER), "next": dt.Optional(dt.POINTER)},
        fn,
    )


def build_sorted_index(
    nodes: Table, key: ColumnExpression | None = None,
    instance: ColumnExpression | None = None,
) -> SortedIndex:
    """Reference sorting.py:92 — builds the sorted index structure. The
    treap internals are an implementation detail there; the public payload
    is the prev/next orientation, which is what this returns."""
    if key is None and "key" in nodes.column_names():
        key = nodes.key
    if instance is None and "instance" in nodes.column_names():
        instance = nodes.instance
    idx = nodes + sort_from_index(nodes, key, instance)
    return SortedIndex(index=idx, oriented_index=idx)


def retrieve_prev_next_values(
    ordered_table: Table, value: ColumnReference | None = None
) -> Table:
    """For each row of a prev/next-chained table: the nearest non-None
    ``value`` looking backward (``prev_value``) and forward (``next_value``)
    (reference sorting.py:195; backs ``statistical.interpolate``)."""
    from ...engine import operators as ops
    from ...internals.expression_compiler import compile_expr
    from ...internals.parse_graph import Universe
    from ...internals.schema import ColumnSchema, schema_from_columns

    if value is None:
        value = ordered_table.value
    value_expr = ordered_table._sub(value)
    val_dt = dt.Optional(dt.ANY)
    schema = schema_from_columns(
        {
            "prev_value": ColumnSchema(name="prev_value", dtype=val_dt),
            "next_value": ColumnSchema(name="next_value", dtype=val_dt),
        },
        name="PrevNextValues",
    )

    def lower(runner, tbl):
        exprs = {
            "__prev": ordered_table._sub(this.prev),
            "__next": ordered_table._sub(this.next),
            "__val": value_expr,
        }
        node, env = runner._zip_env(ordered_table, exprs)
        rw = {n: compile_expr(e, env).fn for n, e in exprs.items()}
        pre = runner._add(ops.Rowwise(node, rw))

        def compute(gk, rows, time):
            # rows: rk -> (prev, next, val); walk chains to nearest non-None
            def walk(rk, port):
                seen = set()
                cur = rows.get(rk)
                cur = cur[port] if cur else None
                while cur is not None and cur not in seen:
                    seen.add(cur)
                    row = rows.get(int(cur))
                    if row is None:
                        return None
                    if row[2] is not None:
                        return row[2]
                    cur = row[port]
                return None

            return [(rk, (walk(rk, 0), walk(rk, 1))) for rk in rows]

        return runner._add(
            ops.GroupedRecompute(
                [pre], [None], ["prev_value", "next_value"], compute
            )
        )

    return Table(
        "custom", [ordered_table], {"lower": lower}, schema, ordered_table._universe
    )
