"""``pw.ordered`` — order-based transforms (reference
``python/pathway/stdlib/ordered/diff.py:10``)."""

from __future__ import annotations

from typing import Any

from ...internals import dtype as dt
from ...internals.expression import ColumnReference, smart_coerce
from ...internals.table import Table
from ...internals.thisclass import substitute, this
from .._sorted import sorted_group_transform

__all__ = ["diff"]


def diff(
    self: Table,
    timestamp: Any,
    *values: Any,
    instance: Any = None,
) -> Table:
    """Per-row difference of `values` columns vs the previous row ordered by
    `timestamp` (first row per instance gets None)."""
    ts = substitute(smart_coerce(timestamp), {this: self})
    vals = [substitute(smart_coerce(v), {this: self}) for v in values]
    names = []
    for v in vals:
        if not isinstance(v, ColumnReference):
            raise ValueError("diff values must be column references")
        names.append(f"diff_{v.name}")
    inst = substitute(smart_coerce(instance), {this: self}) if instance is not None else None

    def fn(entries):
        out = []
        prev = None
        for rk, order, payload in entries:
            if prev is None:
                out.append((rk, tuple([None] * len(payload))))
            else:
                out.append((rk, tuple(
                    None if (a is None or b is None) else a - b
                    for a, b in zip(payload, prev)
                )))
            prev = payload
        return out

    env_types = {
        n: dt.Optional(self.schema.columns()[v.name].dtype)
        for n, v in zip(names, vals)
    }
    return sorted_group_transform(self, ts, vals, inst, env_types, fn)
