"""pw.ordered (reference python/pathway/stdlib/ordered)."""


def diff(table, timestamp, *values):
    raise NotImplementedError("ordered.diff arrives with the sort/prev-next operator")
