"""pw.stateful (reference python/pathway/stdlib/stateful/deduplicate.py:9)."""

from __future__ import annotations

from typing import Any, Callable


def deduplicate(
    table,
    *,
    col=None,
    value=None,
    instance=None,
    acceptor: Callable[[Any, Any], bool],
    persistent_id: str | None = None,
):
    """``col=`` is the reference keyword (deduplicate.py:9); ``value=``
    is kept as an alias matching ``Table.deduplicate``."""
    if (col is None) == (value is None):
        raise TypeError("deduplicate needs exactly one of col= / value=")
    return table.deduplicate(
        value=col if col is not None else value,
        instance=instance,
        acceptor=acceptor,
    )
