"""pw.stateful (reference python/pathway/stdlib/stateful/deduplicate.py:9)."""

from __future__ import annotations

from typing import Any, Callable


def deduplicate(
    table,
    *,
    value,
    instance=None,
    acceptor: Callable[[Any, Any], bool],
    persistent_id: str | None = None,
):
    return table.deduplicate(value=value, instance=instance, acceptor=acceptor)
