"""``pw.viz`` — live Bokeh/Panel plots (reference
``python/pathway/stdlib/viz/plotting.py``). Gated: bokeh/panel are not in
this environment; ``table.plot``/``show`` raise with guidance."""

from __future__ import annotations

from typing import Any, Callable

__all__ = ["plot", "show", "table_viz"]


def _require_panel():
    try:
        import bokeh  # type: ignore[import-not-found]  # noqa: F401
        import panel  # type: ignore[import-not-found]
        return panel
    except ImportError as e:
        raise ImportError(
            "pw.viz requires the 'bokeh' and 'panel' packages (not installed "
            "in this environment); use pw.debug.compute_and_print or "
            "pw.io.subscribe for textual inspection"
        ) from e


def plot(table: Any, plotting_function: Callable, sorting_col: str | None = None):
    """Live-updating Bokeh plot of a table (reference plotting.py:plot)."""
    _require_panel()
    raise NotImplementedError


def show(obj: Any) -> None:
    _require_panel()
    raise NotImplementedError


def table_viz(table: Any, **kwargs: Any):
    _require_panel()
    raise NotImplementedError
