"""``pw.viz`` — live visualization of streaming tables.

Re-design of the reference's Bokeh/Panel integration
(``python/pathway/stdlib/viz/plotting.py``): a table is mirrored into a
live columnar snapshot (insertions/retractions applied per commit tick,
optional sort column), and every update pushes the fresh columns to the
attached render target. The mirror + update machinery is complete and
locally tested (``tests/test_viz.py``); only the Bokeh/Panel render
objects are gated on those packages being installed — without them,
``plot``/``table_viz`` return the live source itself, which exposes the
same column data the plot would show.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

__all__ = ["plot", "show", "table_viz", "LiveTableSource"]


class LiveTableSource:
    """A live, subscribe-fed mirror of a table: ``columns()`` returns the
    current column arrays (sorted by ``sorting_col`` when given); listeners
    fire after every applied commit tick — the ColumnDataSource-updating
    role of the reference's plotting callback."""

    def __init__(self, table: Any, sorting_col: str | None = None):
        from ... import io as pw_io

        self.table = table
        self.names = list(table.column_names())
        self.sorting_col = sorting_col
        if sorting_col is not None and sorting_col not in self.names:
            raise ValueError(
                f"sorting_col {sorting_col!r} is not a column of the table "
                f"(columns: {self.names})"
            )
        self._sort_ix = (
            self.names.index(sorting_col) if sorting_col is not None else None
        )
        self._rows: dict[int, tuple] = {}
        self._lock = threading.Lock()
        self._listeners: list[Callable[[dict[str, list]], None]] = []
        # a viz mirrors STATE, not an external sink: after a persistence
        # restart it must see the replayed history, not suppress it
        pw_io.subscribe(
            table, on_batch=self._on_batch, skip_persisted_batch=False
        )

    def _on_batch(self, time: int, batch: Any) -> None:
        from ...engine.delta import rows_equal

        with self._lock:
            # deletions first: a tick updating key K carries (K, old, -1)
            # and (K, new, +1) in arbitrary order, and the retraction must
            # not clobber the freshly-inserted row
            pending = list(batch.iter_rows())
            appended: list[tuple] | None = []
            for key, row, diff in pending:
                if diff < 0:
                    appended = None  # retraction: not an append-only tick
                    if key in self._rows and rows_equal(self._rows[key], row):
                        self._rows.pop(key, None)
            for key, row, diff in pending:
                if diff > 0:
                    if appended is not None and key in self._rows:
                        appended = None  # in-place update, not an append
                    self._rows[key] = row
                    if appended is not None:
                        appended.append(row)
            cols = self._columns_locked()
            # append-only ticks on an unsorted mirror carry the new rows
            # as an incremental hint: renderers stream JUST those to the
            # browser (reference plotting.py ColumnDataSource.stream)
            # instead of re-sending the whole snapshot
            inc = None
            if appended and self._sort_ix is None:
                inc = {
                    name: [r[i] for r in appended]
                    for i, name in enumerate(self.names)
                }
        for fn in list(self._listeners):
            fn(cols, inc)

    def _columns_locked(self) -> dict[str, list]:
        rows = list(self._rows.values())
        if self._sort_ix is not None:
            ix = self._sort_ix
            rows.sort(key=lambda r: r[ix])
        return {
            name: [r[i] for r in rows] for i, name in enumerate(self.names)
        }

    def columns(self) -> dict[str, list]:
        with self._lock:
            return self._columns_locked()

    def on_update(
        self, fn: Callable[[dict[str, list], dict[str, list] | None], None]
    ) -> None:
        """``fn(columns, appended)``: full snapshot columns plus, for
        append-only ticks on an unsorted mirror, just the appended rows
        (None otherwise) — the incremental-update channel."""
        self._listeners.append(fn)

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)


def _try_panel():
    try:
        import bokeh.models  # type: ignore[import-not-found]  # noqa: F401
        import panel  # type: ignore[import-not-found]

        return panel
    except ImportError:
        return None


def plot(table: Any, plotting_function: Callable, sorting_col: str | None = None):
    """Live-updating plot (reference plotting.py ``plot``): builds a
    ColumnDataSource over the table mirror, hands it to
    ``plotting_function(source) -> figure``, and streams updates into it.
    Without bokeh/panel installed, returns the LiveTableSource (same data,
    no rendering)."""
    source = LiveTableSource(table, sorting_col)
    panel = _try_panel()
    if panel is None:
        return source
    from bokeh.models import ColumnDataSource  # type: ignore[import-not-found]

    cds = ColumnDataSource(data=source.columns())
    fig = plotting_function(cds)

    def push(cols: dict[str, list], appended: dict[str, list] | None) -> None:
        # updates arrive on the engine thread; a served Bokeh document owns
        # its state on the session thread and requires next-tick callbacks
        # for cross-thread mutation. Append-only ticks stream JUST the new
        # rows (browser-side append, reference plotting.py:99); anything
        # with retractions/updates swaps the full snapshot.
        if appended is not None:
            apply = lambda: cds.stream(appended)  # noqa: E731
        else:
            apply = lambda: setattr(cds, "data", cols)  # noqa: E731
        doc = getattr(cds, "document", None)
        if doc is not None:
            doc.add_next_tick_callback(apply)
        else:
            apply()

    source.on_update(push)
    return panel.pane.Bokeh(fig)


def table_viz(table: Any, sorting_col: str | None = None, **kwargs: Any):
    """Live table widget (reference ``viz.table_viz``). Without panel,
    returns the LiveTableSource."""
    source = LiveTableSource(table, sorting_col)
    panel = _try_panel()
    if panel is None:
        return source
    import pandas as pd

    widget = panel.widgets.Tabulator(
        pd.DataFrame(source.columns()), **kwargs
    )

    def push(cols: dict[str, list], appended: dict[str, list] | None) -> None:
        if appended is not None and hasattr(widget, "stream"):
            apply = lambda: widget.stream(  # noqa: E731
                pd.DataFrame(appended), follow=True
            )
        else:
            apply = lambda: setattr(  # noqa: E731
                widget, "value", pd.DataFrame(cols)
            )
        doc = getattr(widget, "document", None)
        if doc is not None:
            doc.add_next_tick_callback(apply)
        else:
            apply()

    source.on_update(push)
    return widget


def show(obj: Any) -> None:
    """Open a Panel server for the visualization (reference ``show``)."""
    panel = _try_panel()
    if panel is None:
        raise ImportError(
            "pw.viz.show requires the 'bokeh' and 'panel' packages (not "
            "installed in this environment); plot()/table_viz() without "
            "them return a LiveTableSource whose .columns() holds the data"
        )
    panel.panel(obj).show()
