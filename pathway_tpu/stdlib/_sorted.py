"""Shared sorted-group transform backing Table.sort / ordered.diff /
statistical.interpolate (the reference implements these on the prev-next
pointer operator, ``src/engine/dataflow/operators/prev_next.rs:770``).

``sorted_group_transform`` groups rows (by optional instance), sorts each
group by an order expression, and lets a host function emit one output row
per input row — keyed by the input row's key, so the result shares the
source universe and composes with ``with_columns`` / ``+``.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from ..engine import keys as K
from ..internals import dtype as dt
from ..internals.expression import ColumnExpression
from ..internals.parse_graph import Universe
from ..internals.schema import ColumnSchema, schema_from_columns
from ..internals.table import Table


def sorted_group_transform(
    table: Table,
    order_expr: ColumnExpression,
    payload_exprs: list[ColumnExpression],
    instance_expr: ColumnExpression | None,
    out_cols: dict[str, dt.DType],
    fn: Callable[[list[tuple[int, Any, tuple]]], list[tuple[int, tuple]]],
) -> Table:
    """fn receives [(row_key, order_value, payload_tuple)] sorted by
    (order_value, row_key) and returns [(row_key, out_row_tuple)]."""
    from ..engine import operators as ops
    from ..internals.expression_compiler import compile_expr

    out_names = list(out_cols.keys())
    schema = schema_from_columns(
        {n: ColumnSchema(name=n, dtype=t) for n, t in out_cols.items()},
        name="SortedTransform",
    )

    def lower(runner, tbl):
        exprs = {"__o": order_expr}
        for i, p in enumerate(payload_exprs):
            exprs[f"__p{i}"] = p
        if instance_expr is not None:
            exprs["__i"] = instance_expr
        node, env = runner._zip_env(table, exprs)
        rw = {}
        rw["__o"] = compile_expr(order_expr, env).fn
        for i, p in enumerate(payload_exprs):
            rw[f"__p{i}"] = compile_expr(p, env).fn
        if instance_expr is not None:
            inst_fn = compile_expr(instance_expr, env).fn

            def g_fn(cols_, keys_, f=inst_fn):
                from ..internals.expression_compiler import _materialize

                vals = np.asarray(_materialize(f(cols_, keys_), len(keys_)))
                return K.mix_columns([vals], len(keys_))

            rw["__g"] = g_fn
        pre = runner._add(ops.Rowwise(node, rw))
        n_payload = len(payload_exprs)

        def compute(gk, rows, time):
            entries = sorted(
                (
                    (rk, row[0], tuple(row[1 : 1 + n_payload]))
                    for rk, row in rows.items()
                ),
                key=lambda e: (e[1], e[0]),
            )
            return fn(entries)

        gr = runner._add(ops.GroupedRecompute(
            [pre], ["__g" if instance_expr is not None else None], out_names, compute,
        ))
        return gr

    return Table(
        "custom", [table], {"lower": lower}, schema, table._universe
    )
