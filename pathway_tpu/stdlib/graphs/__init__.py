"""pw.graphs (reference python/pathway/stdlib/graphs) — needs pw.iterate."""
