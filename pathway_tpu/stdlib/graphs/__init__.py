"""``pw.graphs`` — graph schemas + algorithms (reference
``stdlib/graphs/``): Graph/WeightedGraph with clustering contraction,
Bellman–Ford, PageRank, Louvain communities. Iterative algorithms ride
``pw.iterate`` (host-driven fixpoint over batched XLA rounds)."""

from __future__ import annotations

from . import bellman_ford, louvain_communities, pagerank
from .common import Cluster, Clustering, Edge, Vertex, Weight
from .graph import Graph, WeightedGraph

__all__ = [
    "bellman_ford",
    "pagerank",
    "louvain_communities",
    "Edge",
    "Graph",
    "Vertex",
    "Weight",
    "Cluster",
    "Clustering",
    "WeightedGraph",
]
