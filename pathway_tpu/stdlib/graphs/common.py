"""Shared graph schemas (reference ``stdlib/graphs/common.py``)."""

from __future__ import annotations

from typing import Any

from ...internals import dtype as dt
from ...internals.schema import Schema


class Vertex(Schema):
    pass


class Edge(Schema):
    """Directed edge: pointers to the endpoint vertices."""

    u: dt.Pointer[Any]
    v: dt.Pointer[Any]


class Weight(Schema):
    """Weight extension for vertices / edges."""

    weight: float


class Cluster(Vertex):
    pass


class Clustering(Schema):
    """Cluster membership: vertex (row id) belongs to cluster ``c``."""

    c: dt.Pointer[Any]
