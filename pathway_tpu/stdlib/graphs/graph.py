"""Graph / WeightedGraph with clustering contraction.

Re-design of reference ``stdlib/graphs/graph.py:77-150``: a Graph is a pair
of tables (V, E); contracting by a ``Clustering`` relabels edge endpoints to
their cluster pointer and makes clusters the new vertex set. All operations
are incremental Table ops (relabeling is two key-joins; dedup/weight merge is
a groupby) — on TPU these lower to batched hash-join / segment-reduce
kernels.
"""

from __future__ import annotations

from dataclasses import dataclass

from ...internals.table import Table
from ...internals.thisclass import this
from ... import reducers
from .common import Clustering  # noqa: F401 (re-exported concept)


def _extended_to_full_clustering(
    vertices: Table, clustering: Table
) -> Table:
    """Every vertex gets a cluster: its assigned one, or itself as a
    singleton cluster (reference ``graph.py:61``)."""
    own = vertices.select(c=vertices.id)
    # a Clustering is keyed by vertices by contract (reference common.py)
    sub = clustering.select(clustering.c).promise_universe_is_subset_of(own)
    return own.update_cells(sub)


def _relabel_edges(edges: Table, full_clustering: Table) -> Table:
    return edges.select(
        u=full_clustering.ix(edges.u).c,
        v=full_clustering.ix(edges.v).c,
    )


def _cluster_vertices(full_clustering: Table) -> Table:
    return full_clustering.groupby(id=full_clustering.c).reduce()


@dataclass
class Graph:
    """Undirected, unweighted (multi)graph."""

    V: Table
    E: Table

    def contracted_to_multi_graph(self, clustering: Table) -> "Graph":
        full = _extended_to_full_clustering(self.V, clustering)
        return Graph(V=_cluster_vertices(full), E=_relabel_edges(self.E, full))

    def contracted_to_unweighted_simple_graph(
        self, clustering: Table, **reducer_expressions
    ) -> "Graph":
        g = self.contracted_to_multi_graph(clustering)
        simple = g.E.groupby(g.E.u, g.E.v).reduce(g.E.u, g.E.v)
        return Graph(V=g.V, E=simple)

    def contracted_to_weighted_simple_graph(
        self, clustering: Table, **reducer_expressions
    ) -> "WeightedGraph":
        g = self.contracted_to_multi_graph(clustering)
        we = g.E.groupby(g.E.u, g.E.v).reduce(g.E.u, g.E.v, **reducer_expressions)
        return WeightedGraph.from_vertices_and_weighted_edges(g.V, we)

    def without_self_loops(self) -> "Graph":
        return Graph(V=self.V, E=self.E.filter(this.u != this.v))


@dataclass
class WeightedGraph(Graph):
    """Graph whose edges carry weights (``WE``: u, v, weight)."""

    WE: Table = None  # type: ignore[assignment]

    @staticmethod
    def from_vertices_and_weighted_edges(V: Table, WE: Table) -> "WeightedGraph":
        return WeightedGraph(V=V, E=WE, WE=WE)

    def contracted_to_multi_graph(self, clustering: Table) -> "WeightedGraph":
        full = _extended_to_full_clustering(self.V, clustering)
        we = self.WE.select(
            u=full.ix(this.u).c,
            v=full.ix(this.v).c,
            weight=this.weight,
        )
        return WeightedGraph(V=_cluster_vertices(full), E=we, WE=we)

    def contracted_to_weighted_simple_graph(
        self, clustering: Table, **reducer_expressions
    ) -> "WeightedGraph":
        g = self.contracted_to_multi_graph(clustering)
        if not reducer_expressions:
            reducer_expressions = {"weight": reducers.sum(g.WE.weight)}
        we = g.WE.groupby(g.WE.u, g.WE.v).reduce(
            g.WE.u, g.WE.v, **reducer_expressions
        )
        return WeightedGraph.from_vertices_and_weighted_edges(g.V, we)

    def without_self_loops(self) -> "WeightedGraph":
        return WeightedGraph.from_vertices_and_weighted_edges(
            self.V, self.WE.filter(this.u != this.v)
        )
