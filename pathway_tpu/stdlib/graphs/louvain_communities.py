"""Louvain community detection.

Counterpart of reference ``stdlib/graphs/louvain_communities/impl.py``
(`_louvain_level`, `louvain_communities_fixed_iterations`,
`exact_modularity`). The local-move phase is irregular, data-dependent
control flow — a poor fit for per-step dataflow kernels — so one Louvain
level runs as a *host-recomputed* operator (engine Iterate node with a
single-round driver): on any change of the weighted edge table the whole
level is recomputed vectorized in numpy and diffed against the previous
clustering. ``exact_modularity`` is fully declarative (joins + segment
sums → XLA).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ...internals import dtype as dt
from ...internals.parse_graph import Universe
from ...internals.schema import schema_from_types
from ...internals.table import Table
from ...internals.thisclass import this
from ... import reducers
from .graph import WeightedGraph


def _louvain_level_numpy(
    us: np.ndarray, vs: np.ndarray, ws: np.ndarray
) -> dict[int, int]:
    """One Louvain level: greedy modularity local moves until stable.
    Deterministic (vertices scanned in sorted key order)."""
    verts = np.unique(np.concatenate([us, vs]))
    index = {int(k): i for i, k in enumerate(verts)}
    n = len(verts)
    ui = np.array([index[int(k)] for k in us], dtype=np.int64)
    vi = np.array([index[int(k)] for k in vs], dtype=np.int64)
    w = ws.astype(np.float64)

    # undirected: accumulate both directions; self-loops count once
    deg = np.zeros(n)
    np.add.at(deg, ui, w)
    np.add.at(deg, vi, w)
    total = w.sum()
    if total <= 0:
        return {int(k): int(k) for k in verts}

    # adjacency in CSR-ish dict form (host side; n is the number of
    # *vertices*, typically ≪ rows of the stream)
    nbrs: list[dict[int, float]] = [dict() for _ in range(n)]
    for a, b, x in zip(ui, vi, w):
        a, b = int(a), int(b)
        if a == b:
            continue
        nbrs[a][b] = nbrs[a].get(b, 0.0) + float(x)
        nbrs[b][a] = nbrs[b].get(a, 0.0) + float(x)

    comm = np.arange(n)
    comm_deg = deg.copy()
    two_m = 2.0 * total
    improved = True
    rounds = 0
    while improved and rounds < 64:
        improved = False
        rounds += 1
        for i in range(n):
            ci = comm[i]
            # weights from i to each neighboring community
            to_comm: dict[int, float] = {}
            for j, x in nbrs[i].items():
                to_comm[comm[j]] = to_comm.get(comm[j], 0.0) + x
            comm_deg[ci] -= deg[i]
            best_c, best_gain = ci, to_comm.get(ci, 0.0) - comm_deg[ci] * deg[i] / two_m
            for c, k_in in to_comm.items():
                gain = k_in - comm_deg[c] * deg[i] / two_m
                if gain > best_gain + 1e-12 or (
                    abs(gain - best_gain) <= 1e-12 and c < best_c
                ):
                    best_c, best_gain = c, gain
            comm[i] = best_c
            comm_deg[best_c] += deg[i]
            if best_c != ci:
                improved = True

    # canonical cluster representative: smallest vertex key in the community
    rep: dict[int, int] = {}
    for i in range(n):
        c = int(comm[i])
        k = int(verts[i])
        if c not in rep or k < rep[c]:
            rep[c] = k
    return {int(verts[i]): rep[int(comm[i])] for i in range(n)}


class _LouvainDriver:
    """Single-round driver for the engine Iterate node: full recompute of
    one Louvain level on every change of the weighted edge table."""

    def __call__(
        self, snapshots: dict[str, dict[int, tuple]]
    ) -> dict[str, dict[int, tuple]]:
        rows = list(snapshots["edges"].values())
        if not rows:
            return {"clustering": {}}
        us = np.array([int(r[0]) for r in rows], dtype=np.uint64)
        vs = np.array([int(r[1]) for r in rows], dtype=np.uint64)
        ws = np.array([float(r[2]) for r in rows])
        assignment = _louvain_level_numpy(us, vs, ws)
        return {
            "clustering": {
                np.uint64(k).item(): (np.uint64(c).item(),)
                for k, c in assignment.items()
            }
        }


def _louvain_level(G: WeightedGraph) -> Table:
    """One level of Louvain: Clustering table keyed by vertex pointer with
    column ``c`` = cluster pointer."""
    from ...engine.iterate import Iterate, IterateOutput

    edges = G.WE.select(u=this.u, v=this.v, weight=this.weight)
    driver = _LouvainDriver()
    schema = schema_from_types(c=dt.Pointer)

    def lower(runner, _table):
        node = runner._add(
            Iterate(
                [runner._project(runner.lower(edges), edges, ["u", "v", "weight"])],
                ["edges"],
                driver,
                {"clustering": ["c"]},
            )
        )
        return runner._add(IterateOutput(node, "clustering"))

    return Table("custom", [edges], {"lower": lower}, schema, Universe())


louvain_level = _louvain_level


def louvain_communities(G: WeightedGraph, levels: int = 1) -> Table:
    """Hierarchical Louvain: repeatedly cluster and contract ``levels``
    times; returns the flattened Clustering of original vertices."""
    clustering = _louvain_level(G)
    for _ in range(levels - 1):
        G = G.contracted_to_weighted_simple_graph(clustering)
        higher = _louvain_level(G)
        # compose: vertex -> cluster -> higher cluster
        clustering = clustering.select(
            c=higher.ix(clustering.c, optional=True).c
        ).select(c=_coalesce_ptr(this.c, clustering.c))
    return clustering


def _coalesce_ptr(a, b):
    from ...internals.expression import coalesce

    return coalesce(a, b)


def exact_modularity(G: WeightedGraph, C: Table, round_digits: int = 16) -> Table:
    """Modularity Q of clustering ``C`` on graph ``G`` (reference
    ``impl.py:340``): sum over clusters of within-weight/total minus
    (degree/2·total)²; computed with joins + segment sums."""
    edges = G.WE
    labeled = edges.select(
        cu=C.ix(edges.u).c,
        cv=C.ix(edges.v).c,
        weight=edges.weight,
    )
    total = labeled.groupby().reduce(m=reducers.sum(labeled.weight))

    internal = labeled.filter(this.cu == this.cv)
    per_cluster_internal = internal.groupby(id=internal.cu).reduce(
        internal_w=reducers.sum(internal.weight)
    )
    # degree of a cluster: sum of weights of edges incident to it
    half_u = labeled.select(c=this.cu, w=this.weight)
    half_v = labeled.select(c=this.cv, w=this.weight)
    halves = half_u.concat_reindex(half_v)
    per_cluster_deg = halves.groupby(id=halves.c).reduce(
        degree=reducers.sum(halves.w)
    )
    from ...internals.expression import apply_with_type, coalesce

    m = total.ix(total.pointer_from(), context=per_cluster_deg).m
    internal_w = coalesce(
        per_cluster_internal.ix(per_cluster_deg.id, optional=True).internal_w, 0.0
    )
    scored = per_cluster_deg.select(
        q=internal_w / m - (per_cluster_deg.degree / (2.0 * m)) ** 2
    )
    summed = scored.groupby().reduce(modularity=reducers.sum(scored.q))
    return summed.select(
        modularity=apply_with_type(
            lambda q: round(q, round_digits), float, summed.modularity
        )
    )
