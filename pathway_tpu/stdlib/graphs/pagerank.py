"""PageRank (reference ``stdlib/graphs/pagerank/impl.py:18``).

Same API (``pagerank(edges, steps=5) -> Table[Result]``, integer ranks so the
fixpoint is exact). Rank flow per step is a key-join (edge source lookup) +
segment-sum per target — two batched kernels per step on TPU; steps are
driven by the engine's Iterate node with ``iteration_limit=steps``.
"""

from __future__ import annotations

from ...internals.expression import coalesce, if_else
from ...internals.iterate import iterate
from ...internals.schema import Schema
from ...internals.table import Table
from ... import reducers


class Result(Schema):
    rank: int


def pagerank(edges: Table, steps: int = 5) -> Table:
    # vertex set = all edge endpoints, keyed by their pointer; out-degree 0
    # for pure sinks
    out_deg = edges.groupby(id=edges.u).reduce(degree=reducers.count())
    sinks = edges.groupby(id=edges.v).reduce(degree=0)
    degrees = sinks.update_rows(out_deg)

    init = degrees.select(rank=6_000, degree=degrees.degree)

    def step(ranks: Table, edges: Table) -> Table:
        # each vertex sends rank*5/6 split over its out-edges; everyone keeps
        # a 1000 base (the damping term, integer arithmetic keeps it exact)
        outflow = ranks.select(
            flow=if_else(
                ranks.degree == 0, 0, (ranks.rank * 5) // (ranks.degree * 6)
            )
        )
        inflow = edges.groupby(id=edges.v).reduce(
            received=reducers.sum(outflow.ix(edges.u).flow)
        )
        return ranks.select(
            rank=coalesce(inflow.ix(ranks.id, optional=True).received, 0) + 1_000,
            degree=ranks.degree,
        )

    result = iterate(step, iteration_limit=steps, ranks=init, edges=edges)
    return result.select(rank=result.rank)
