"""Bellman–Ford shortest paths over ``pw.iterate``.

Same API as reference ``stdlib/graphs/bellman_ford/impl.py:14-52``
(``Vertex{is_source}``, ``Dist{dist}``, ``DistFromSource{dist_from_source}``,
``bellman_ford(vertices, edges)``); the relaxation step is expressed as one
key-join plus one segment-min per round, each round a batched XLA kernel, and
the fixpoint is driven by the engine's Iterate node.
"""

from __future__ import annotations

import math

from ...internals.expression import coalesce, if_else
from ...internals.iterate import iterate
from ...internals.schema import Schema
from ...internals.table import Table
from ... import reducers


class Vertex(Schema):
    is_source: bool


class Dist(Schema):
    dist: float


class DistFromSource(Schema):
    dist_from_source: float


def _relax(vertices_dist: Table, edges: Table) -> Table:
    # candidate distance for edge target v: dist(u) + len(u→v)
    candidates = edges.select(
        dist_from_source=vertices_dist.ix(edges.u).dist_from_source + edges.dist
    )
    best = candidates.groupby(id=edges.v).reduce(
        dist_from_source=reducers.min(candidates.dist_from_source)
    )
    improved = best.ix(vertices_dist.id, optional=True).dist_from_source
    return vertices_dist.select(
        dist_from_source=if_else(
            coalesce(improved, math.inf) < vertices_dist.dist_from_source,
            coalesce(improved, math.inf),
            vertices_dist.dist_from_source,
        )
    )


def bellman_ford(vertices: Table, edges: Table) -> Table:
    """Distances from the ``is_source`` vertices; unreachable = inf."""
    init = vertices.select(
        dist_from_source=if_else(vertices.is_source, 0.0, math.inf)
    )
    return iterate(_relax, vertices_dist=init, edges=edges)
