"""``AsyncTransformer`` (reference
``python/pathway/stdlib/utils/async_transformer.py:282``).

Subclass with an ``async def invoke(**input_row) -> dict`` and an
``output_schema``; ``.successful`` is the table of completed results.
The reference runs a connector thread + event loop and re-ingests results
as-of their completion time; here invocation rides the engine's async
apply machinery (rows of a batch are awaited concurrently, results land
at the batch's logical time), with the same retry/cache options.
"""

from __future__ import annotations

import asyncio
from abc import ABC, abstractmethod
from typing import Any, ClassVar

from ...internals import dtype as dt
from ...internals.expression import AsyncApplyExpression, apply_with_type
from ...internals.schema import SchemaMetaclass
from ...internals.table import Table
from ...internals.thisclass import this
from ...udfs import (
    AsyncRetryStrategy,
    CacheStrategy,
    with_cache_strategy,
    with_capacity,
    with_retry_strategy,
    with_timeout,
)

__all__ = ["AsyncTransformer"]

_FAILED = object()


class AsyncTransformer(ABC):
    output_schema: ClassVar[SchemaMetaclass]

    def __init_subclass__(cls, output_schema: Any = None, **kw: Any) -> None:
        # reference form: class X(pw.AsyncTransformer, output_schema=Schema)
        super().__init_subclass__(**kw)
        if output_schema is not None:
            if not isinstance(output_schema, SchemaMetaclass):
                raise TypeError(
                    f"output_schema must be a pw.Schema subclass, got "
                    f"{output_schema!r}"
                )
            cls.output_schema = output_schema

    def __init__(self, input_table: Table, *, instance: Any = None, **kwargs: Any):
        if not hasattr(self, "output_schema"):
            raise ValueError("AsyncTransformer subclass must set output_schema")
        self._input_table = input_table
        self._retry_strategy: AsyncRetryStrategy | None = None
        self._cache_strategy: CacheStrategy | None = None
        self._capacity: int | None = None
        self._timeout: float | None = None
        self._result: Table | None = None
        self._failed: Table | None = None

    @abstractmethod
    async def invoke(self, *args: Any, **kwargs: Any) -> dict: ...

    # -- reference fluent config (with_options) --

    def with_options(
        self,
        capacity: int | None = None,
        timeout: float | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
        cache_strategy: CacheStrategy | None = None,
    ) -> "AsyncTransformer":
        self._capacity = capacity
        self._timeout = timeout
        self._retry_strategy = retry_strategy
        self._cache_strategy = cache_strategy
        return self

    # -- execution --

    def _wrapped_invoke(self):
        names = self._input_table.column_names()

        expected_cols = set(self.output_schema.column_names())

        async def call(*values):
            result = dict(await self.invoke(**dict(zip(names, values))))
            if set(result) != expected_cols:
                # reference asserts the result matches output_schema
                # (test_async_transformer.py:188) — the row lands in
                # .failed, not in .successful with nulls
                raise ValueError(
                    f"AsyncTransformer.invoke returned columns "
                    f"{sorted(result)}, expected {sorted(expected_cols)}"
                )
            return result

        # exceptions must still RAISE through cache/retry (retry fires on
        # exceptions; the cache must not memoize failures) — only the
        # outermost wrapper converts a final failure into the _FAILED row
        fn = call
        if self._cache_strategy is not None:
            fn = self._cache_strategy.wrap(fn)
        if self._retry_strategy is not None:
            fn = with_retry_strategy(fn, self._retry_strategy)
        if self._timeout is not None:
            fn = with_timeout(fn, self._timeout)
        if self._capacity is not None:
            fn = with_capacity(fn, self._capacity)

        async def safe(*values):
            try:
                return await fn(*values)
            except Exception:
                return _FAILED

        return safe

    def _run(self) -> None:
        if self._result is not None:
            return
        names = self._input_table.column_names()
        cols = [self._input_table[n] for n in names]
        raw = self._input_table.select(
            __res=AsyncApplyExpression(self._wrapped_invoke(), dt.ANY, tuple(cols), {}),
        )
        ok = raw.filter(
            apply_with_type(lambda r: r is not _FAILED, dt.BOOL, this["__res"])
        )
        out_names = self.output_schema.column_names()
        self._result = ok.select(**{
            n: apply_with_type(lambda r, n=n: r.get(n), dt.ANY, this["__res"])
            for n in out_names
        })
        self._failed = raw.filter(
            apply_with_type(lambda r: r is _FAILED, dt.BOOL, this["__res"])
        ).select()

    @property
    def successful(self) -> Table:
        """Table of completed invocations (reference .successful)."""
        self._run()
        assert self._result is not None
        return self._result

    @property
    def failed(self) -> Table:
        """Rows whose invocation raised (reference .failed)."""
        self._run()
        assert self._failed is not None
        return self._failed

    @property
    def output_table(self) -> Table:
        return self.successful
