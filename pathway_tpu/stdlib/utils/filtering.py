"""Row filters (reference ``python/pathway/stdlib/utils/filtering.py``):
``argmax_rows`` (:8) / ``argmin_rows`` (:20) — keep, per group, the row
extremizing a column."""

from __future__ import annotations

from ... import reducers
from ...internals.expression import ColumnReference
from ...internals.table import Table
from ...internals.thisclass import this

__all__ = ["argmax_rows", "argmin_rows"]


def _extreme_rows(table: Table, *on: ColumnReference, what: ColumnReference, reducer) -> Table:
    winners = (
        table.groupby(*on)
        .reduce(__winner=reducer(what))
        .with_id(this["__winner"])
    )
    # argmax/argmin values are keys of `table` by construction — promised,
    # since the solver cannot prove it across the reindex
    return table.restrict(winners.promise_universe_is_subset_of(table))


def argmax_rows(table: Table, *on: ColumnReference, what: ColumnReference) -> Table:
    return _extreme_rows(table, *on, what=what, reducer=reducers.argmax)


def argmin_rows(table: Table, *on: ColumnReference, what: ColumnReference) -> Table:
    return _extreme_rows(table, *on, what=what, reducer=reducers.argmin)
