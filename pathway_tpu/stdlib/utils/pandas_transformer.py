"""``pandas_transformer`` (reference
``python/pathway/stdlib/utils/pandas_transformer.py:124``): run a
pandas-DataFrame function over live tables.

The engine node keeps the consolidated state of every input table; on any
change it rebuilds the input DataFrames (indexed by row key), re-runs the
user function, and emits the diff between the new and previous output —
so the pandas computation behaves incrementally at table granularity.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from ...engine.delta import Delta, rows_to_columns
from ...engine.executor import Node
from ...engine.state import RowState
from ...internals.parse_graph import Universe
from ...internals.schema import SchemaMetaclass
from ...internals.table import Table

__all__ = ["pandas_transformer"]


class _PandasRecomputeNode(Node):
    def __init__(self, inputs: list[Node], fn: Callable, out_names: list[str]):
        super().__init__(inputs, list(out_names))
        self._states = [RowState(inp.column_names) for inp in inputs]
        self._fn = fn
        self._prev: dict[int, tuple] = {}

    def _frames(self):
        import pandas as pd

        frames = []
        for st in self._states:
            keys = list(st._rows.keys())
            keys = [k for k in keys if st._counts.get(k, 0) > 0]
            data = {
                c: [st.get(k)[i] for k in keys]
                for i, c in enumerate(st.columns)
            }
            frames.append(pd.DataFrame(data, index=pd.Index(keys, dtype=np.uint64)))
        return frames

    def process(self, time: int, ins: list[Delta | None]) -> Delta | None:
        changed = False
        for st, d in zip(self._states, ins):
            if d is not None and len(d):
                st.apply(d.consolidated())
                changed = True
        if not changed:
            return None
        out_df = self._fn(*self._frames())
        current: dict[int, tuple] = {}
        for key, row in zip(out_df.index, out_df.itertuples(index=False, name=None)):
            current[int(key)] = tuple(
                row[out_df.columns.get_loc(c)] for c in self.column_names
            )
        events: list[tuple[int, tuple, int]] = []
        for key, row in current.items():
            old = self._prev.get(key)
            if old is None:
                events.append((key, row, 1))
            elif old != row:
                events.append((key, old, -1))
                events.append((key, row, 1))
        for key, old in self._prev.items():
            if key not in current:
                events.append((key, old, -1))
        self._prev = current
        if not events:
            return None
        keys = np.array([k for k, _, _ in events], dtype=np.uint64)
        diffs = np.array([d for _, _, d in events], dtype=np.int64)
        rows = [r for _, r, _ in events]
        return Delta(
            keys=keys, data=rows_to_columns(rows, self.column_names), diffs=diffs
        )


def pandas_transformer(
    output_schema: SchemaMetaclass,
    output_universe: Any = None,
) -> Callable:
    """Decorator: a function of DataFrames (indexed by row key) becomes a
    function of Tables returning a Table (reference :124). The returned
    DataFrame's index determines output row keys — keep the input index to
    stay aligned with an input universe."""

    def wrapper(fn: Callable) -> Callable:
        def wrapped(*tables: Table) -> Table:
            out_names = output_schema.column_names()

            def lower(runner, tbl):
                in_nodes = [runner.lower(t) for t in tables]
                return runner._add(
                    _PandasRecomputeNode(in_nodes, fn, out_names)
                )

            return Table(
                "custom", list(tables), {"lower": lower}, output_schema, Universe()
            )

        return wrapped

    return wrapper
