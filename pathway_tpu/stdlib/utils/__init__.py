"""``pw.utils`` stdlib (reference ``python/pathway/stdlib/utils``):
column helpers, row filters, AsyncTransformer, pandas_transformer."""

from . import col, filtering  # noqa: F401
from .async_transformer import AsyncTransformer  # noqa: F401
from .col import (  # noqa: F401
    apply_all_rows,
    flatten_column,
    groupby_reduce_majority,
    multiapply_all_rows,
    unpack_col,
)
from .filtering import argmax_rows, argmin_rows  # noqa: F401
from .pandas_transformer import pandas_transformer  # noqa: F401

__all__ = [
    "col",
    "filtering",
    "AsyncTransformer",
    "pandas_transformer",
    "unpack_col",
    "flatten_column",
    "apply_all_rows",
    "multiapply_all_rows",
    "groupby_reduce_majority",
    "argmax_rows",
    "argmin_rows",
]
