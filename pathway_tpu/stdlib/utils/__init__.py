"""pw.utils (reference python/pathway/stdlib/utils)."""
