"""Column utilities (reference ``python/pathway/stdlib/utils/col.py``):
``unpack_col`` (:60), ``flatten_column`` (:16), ``apply_all_rows`` (:276),
``multiapply_all_rows`` (:211), ``groupby_reduce_majority`` (:326).
"""

from __future__ import annotations

from typing import Any, Callable

from ... import reducers
from ...internals import dtype as dt
from ...internals.expression import ColumnReference, apply_with_type
from ...internals.table import Table
from ...internals.thisclass import this

__all__ = [
    "unpack_col",
    "flatten_column",
    "apply_all_rows",
    "multiapply_all_rows",
    "groupby_reduce_majority",
]


def unpack_col(column: ColumnReference, *unpacked_columns: Any, schema: Any = None) -> Table:
    """Tuple column -> one column per element (reference col.py:60)."""
    if schema is not None:
        names = schema.column_names()
    else:
        names = [c if isinstance(c, str) else c.name for c in unpacked_columns]
    table = column.table
    return table.select(**{
        n: apply_with_type(lambda v, i=i: v[i], dt.ANY, column)
        for i, n in enumerate(names)
    })


def flatten_column(column: ColumnReference, origin_id: str | None = "origin_id") -> Table:
    """One row per element of an iterable column (reference col.py:16 —
    deprecated there in favor of Table.flatten, kept for parity)."""
    table = column.table
    if origin_id is None:
        return table.flatten(column)
    return table.flatten(column, origin_id=origin_id)


def multiapply_all_rows(
    *cols: ColumnReference,
    fun: Callable[..., tuple[list, ...]],
    result_col_names: list[str],
) -> Table:
    """Apply a function to ALL rows at once: ``fun(col1_values, ...)``
    returns one result list per output column, positionally aligned with
    the input rows (reference col.py:211). Runs as a global gather +
    per-row re-keying back onto the source universe."""
    table = cols[0].table
    gathered = table.reduce(
        __keys=reducers.tuple(table.id),
        **{f"__c{i}": reducers.tuple(c) for i, c in enumerate(cols)},
    )
    n = len(cols)

    def explode(keys, *col_lists):
        results = fun(*[list(c) for c in col_lists])
        return tuple(zip(keys, zip(*results)))

    exploded = gathered.select(
        __pairs=apply_with_type(
            explode, dt.ANY,
            this["__keys"], *[this[f"__c{i}"] for i in range(n)],
        )
    ).flatten(this["__pairs"])
    return exploded.select(
        __newkey=apply_with_type(lambda p: p[0], dt.POINTER, this["__pairs"]),
        **{
            name: apply_with_type(lambda p, i=i: p[1][i], dt.ANY, this["__pairs"])
            for i, name in enumerate(result_col_names)
        },
    ).with_id(this["__newkey"]).select(
        **{name: this[name] for name in result_col_names}
    )


def apply_all_rows(
    *cols: ColumnReference,
    fun: Callable[..., list],
    result_col_name: str,
) -> Table:
    """Like multiapply_all_rows with a single result column
    (reference col.py:276)."""
    return multiapply_all_rows(
        *cols, fun=lambda *a: (fun(*a),), result_col_names=[result_col_name]
    )


def groupby_reduce_majority(
    column_group: ColumnReference, column_val: ColumnReference
) -> Table:
    """Per group, the most frequent value (reference col.py:326)."""
    table = column_group.table
    counted = table.groupby(column_group, column_val).reduce(
        group=column_group, val=column_val, cnt=reducers.count()
    )
    ranked = counted.groupby(this.group).reduce(
        group=this.group,
        __ordered=reducers.tuple_by(-this.cnt, this.val),
    )
    return ranked.select(
        group=this.group,
        majority=apply_with_type(lambda t: t[0], dt.ANY, this["__ordered"]),
    )
