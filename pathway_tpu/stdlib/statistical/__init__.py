"""``pw.statistical`` — interpolation (reference
``python/pathway/stdlib/statistical/_interpolate.py:33``)."""

from __future__ import annotations

import enum
from typing import Any

from ...internals import dtype as dt
from ...internals.expression import ColumnReference, smart_coerce
from ...internals.table import Table
from ...internals.thisclass import substitute, this
from .._sorted import sorted_group_transform

__all__ = ["interpolate", "InterpolateMode"]


class InterpolateMode(enum.Enum):
    LINEAR = "linear"


def interpolate(
    self: Table,
    timestamp: Any,
    *values: Any,
    mode: InterpolateMode = InterpolateMode.LINEAR,
) -> Table:
    """Fill None values by linear interpolation between the previous and next
    non-None values ordered by `timestamp`; boundary Nones stay None."""
    if mode != InterpolateMode.LINEAR:
        raise ValueError("only InterpolateMode.LINEAR is supported")
    ts = substitute(smart_coerce(timestamp), {this: self})
    vals = [substitute(smart_coerce(v), {this: self}) for v in values]
    names = []
    for v in vals:
        if not isinstance(v, ColumnReference):
            raise ValueError("interpolate values must be column references")
        names.append(v.name)

    def fn(entries):
        n = len(entries)
        cols = list(zip(*[p for _, _, p in entries])) if n else []
        times = [order for _, order, _ in entries]
        out_cols = []
        for series in cols:
            series = list(series)
            known = [i for i, v in enumerate(series) if v is not None]
            for i, v in enumerate(series):
                if v is not None:
                    continue
                import bisect

                j = bisect.bisect_left(known, i)
                lo = known[j - 1] if j > 0 else None
                hi = known[j] if j < len(known) else None
                if lo is not None and hi is not None:
                    t0, t1, t = times[lo], times[hi], times[i]
                    v0, v1 = series[lo], series[hi]
                    series[i] = v0 + (v1 - v0) * (t - t0) / (t1 - t0)
            out_cols.append(series)
        ts_col = [order for _, order, _ in entries]
        out = []
        for i, (rk, order, _) in enumerate(entries):
            out.append((rk, (order,) + tuple(c[i] for c in out_cols)))
        return out

    ts_name = ts.name if isinstance(ts, ColumnReference) else "timestamp"
    out_types = {ts_name: self.schema.columns()[ts_name].dtype if ts_name in self.schema.__columns__ else dt.ANY}
    for nm, v in zip(names, vals):
        t = self.schema.columns()[v.name].dtype
        u = dt.unoptionalize(t)
        out_types[nm] = dt.Optional(dt.FLOAT if u in (dt.INT, dt.FLOAT) else u)
    return sorted_group_transform(self, ts, vals, None, out_types, fn)
