"""pw.statistical (reference python/pathway/stdlib/statistical)."""
