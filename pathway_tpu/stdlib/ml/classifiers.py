"""kNN-LSH classifiers (reference
``python/pathway/stdlib/ml/classifiers/_knn_lsh.py``):
``knn_lsh_classifier_train`` builds an index over training points,
``knn_lsh_classify`` labels queries by majority vote of their k nearest
training points. The distance kernels run on TPU via ``pw.ml.index``.
"""

from __future__ import annotations

from typing import Any

from ...internals import dtype as dt
from ...internals.expression import ColumnReference, apply_with_type
from ...internals.table import Table
from ...internals.thisclass import this
from .index import KNNIndex

__all__ = [
    "knn_lsh_classifier_train",
    "knn_lsh_train",
    "knn_lsh_classify",
    "knn_lsh_generic_classifier_train",
    "knn_lsh_euclidean_classifier_train",
]


def knn_lsh_classifier_train(
    data: Table,
    L: int = 20,
    type: str = "euclidean",  # noqa: A002 - reference parameter name
    **kwargs: Any,
) -> KNNIndex:
    """Index training vectors (column ``data``); returns the queryable
    model (reference _knn_lsh.py knn_lsh_classifier_train)."""
    d = kwargs.get("d")
    if d is None:
        raise ValueError("pass d= (embedding dimensionality)")
    return KNNIndex(
        ColumnReference(data, "data"),
        data,
        n_dimensions=d,
        n_or=L,
        n_and=kwargs.get("M", 10),
        bucket_length=kwargs.get("A", 10.0),
        distance_type=type,
    )


knn_lsh_train = knn_lsh_classifier_train
knn_lsh_generic_classifier_train = knn_lsh_classifier_train


def knn_lsh_euclidean_classifier_train(data: Table, d: int, M: int = 10, L: int = 20, A: float = 10.0) -> KNNIndex:
    return knn_lsh_classifier_train(data, L=L, type="euclidean", d=d, M=M, A=A)


def knn_lsh_classify(
    knn_model: KNNIndex, data_labels: Table, queries: Table, k: int = 3
) -> Table:
    """Majority label among the k nearest training points
    (reference _knn_lsh.py knn_lsh_classify)."""
    from ..indexing.data_index import _MATCHED_ID
    from ...internals.thisclass import left as l_, right as r_

    # collapsed matches with the training row ids (the classify path needs
    # ids, which the user-facing get_nearest_items projection drops)
    hits = knn_model._index.query(
        ColumnReference(queries, "data"),
        number_of_matches=k,
        collapse_rows=True,
    ).select(**{"__ids": getattr(r_, _MATCHED_ID)})

    label_col = data_labels.column_names()[0]
    id_to_label = data_labels.reduce(
        __pairs=_tuple_of_pairs(data_labels, label_col),
    )

    tagged = hits.with_columns(__one=0)
    lookup = id_to_label.select(__one=0, __pairs=this["__pairs"])
    joined = tagged.join_left(
        lookup, l_["__one"] == r_["__one"]
    ).select(
        __ids=l_["__ids"],
        __pairs=r_["__pairs"],
    )

    def vote(ids, pairs):
        from collections import Counter

        mapping = dict(pairs or ())
        votes = Counter(
            mapping[i] for i in (ids or ()) if i in mapping
        )
        if not votes:
            return None
        return votes.most_common(1)[0][0]

    return joined.select(
        predicted_label=apply_with_type(
            vote, dt.ANY, this["__ids"], this["__pairs"]
        )
    )


def _tuple_of_pairs(table: Table, label_col: str):
    from ... import reducers

    return reducers.tuple(
        apply_with_type(
            lambda i, v: (int(i), v), dt.ANY, table.id, table[label_col]
        )
    )
