"""Dataset fetchers (reference ``python/pathway/stdlib/ml/datasets``):
downloads public classification datasets. Gated — this environment has no
network egress; pass local files to the parse helpers instead."""

from __future__ import annotations

from typing import Any

__all__ = ["load_mnist_stream", "parse_svm_file"]


def parse_svm_file(path: str, n_features: int) -> list[tuple]:
    """Parse an svmlight-format file into (vector, label) rows."""
    import numpy as np

    rows = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            label = int(float(parts[0]))
            vec = np.zeros(n_features)
            for tok in parts[1:]:
                idx, val = tok.split(":")
                vec[int(idx) - 1] = float(val)
            rows.append((vec, label))
    return rows


def load_mnist_stream(*args: Any, **kwargs: Any):
    raise RuntimeError(
        "dataset download requires network egress, unavailable in this "
        "environment; load a local copy with parse_svm_file"
    )
