"""Hidden-Markov-Model decoding reducer (reference
``python/pathway/stdlib/ml/hmm.py:11`` ``create_hmm_reducer``).

The HMM is a networkx DiGraph whose edges carry transition log-probability
functions of the observation; the reducer maintains per-state best
log-likelihood (online Viterbi) and emits the most likely current state
(optionally the decoded trail). Stateful, append-only — matches the
reference's stateful-reducer semantics.
"""

from __future__ import annotations

from typing import Any

from ... import reducers as _reducers

__all__ = ["create_hmm_reducer"]


def create_hmm_reducer(
    graph: Any,
    beam_size: int | None = None,
    num_results_kept: int | None = None,
):
    """Returns a reducer expression factory: ``reducer(observation_col)``
    decodes the observation stream per group (reference hmm.py:11).

    Graph contract (reference parity): nodes are states; ``graph.nodes[s]``
    may carry ``initial_log_ppb``; each edge (u, v) carries
    ``calc_log_ppb(observation) -> float`` (emission+transition log prob).
    """
    import math

    states = list(graph.nodes)
    initial = {
        s: float(graph.nodes[s].get("initial_log_ppb", 0.0)) for s in states
    }
    edges = {
        (u, v): data["calc_log_ppb"] for u, v, data in graph.edges(data=True)
    }

    def combine(state, values, diff=1):
        # state: (scores: dict state->logppb, trail: tuple) | None;
        # called once per row (engine StatefulReducer — append-only)
        (obs,) = values
        if state is None:
            scores = dict(initial)
            trail: tuple = ()
        else:
            scores, trail = dict(state[0]), state[1]
        new_scores: dict[Any, float] = {}
        for (u, v), calc in edges.items():
            if u not in scores:
                continue
            cand = scores[u] + float(calc(obs))
            if v not in new_scores or cand > new_scores[v]:
                new_scores[v] = cand
        if not new_scores:
            new_scores = dict(initial)
        if beam_size is not None:
            kept = sorted(new_scores, key=new_scores.get, reverse=True)[:beam_size]
            new_scores = {s: new_scores[s] for s in kept}
        scores = new_scores
        best = max(scores, key=scores.get) if scores else None
        trail = trail + (best,)
        if num_results_kept is not None:
            trail = trail[-num_results_kept:]
        return (scores, trail)

    def reducer(observation_col):
        expr = _reducers.stateful_many(combine, observation_col)
        return _extract_last(expr)

    return reducer


def _extract_last(state_expr):
    from ...internals import dtype as dt
    from ...internals.expression import apply_with_type

    return apply_with_type(
        lambda st: st[1][-1] if st and st[1] else None, dt.ANY, state_expr
    )
