"""``pw.ml.index.KNNIndex`` (reference
``python/pathway/stdlib/ml/index.py:9`` — the classic LSH-based KNN
surface). Wraps the TPU KNN engines in ``pathway_tpu/stdlib/indexing``:
the distance math runs as batched XLA kernels on the MXU instead of the
reference's pure-python LSH bucket scans.
"""

from __future__ import annotations

from typing import Any

from ...internals.expression import ColumnExpression, ColumnReference
from ...internals.table import Table
from ..indexing.data_index import DataIndex
from ..indexing.nearest_neighbors import BruteForceKnn, LshKnn

__all__ = ["KNNIndex"]


class KNNIndex:
    """K nearest neighbours over an embedding column (reference index.py:9).

    ``bucketing_params`` selects the LSH engine (reference parity); without
    it the exact brute-force TPU kernel is used — at reference scales the
    exact kernel is faster than approximate bucketing.
    """

    def __init__(
        self,
        data_embedding: ColumnReference,
        data: Table,
        n_dimensions: int,
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
        distance_type: str = "euclidean",
        metadata: ColumnExpression | None = None,
    ):
        metric = {"euclidean": "l2sq", "cosine": "cos"}.get(
            distance_type, distance_type
        )
        if n_or != 20 or n_and != 10:  # explicit LSH request
            inner = LshKnn(
                data_column=data_embedding,
                metadata_column=metadata,
                dimensions=n_dimensions,
                metric=metric,
                n_or=n_or,
                n_and=n_and,
            )
        else:
            inner = BruteForceKnn(
                data_column=data_embedding,
                metadata_column=metadata,
                dimensions=n_dimensions,
                metric=metric,
            )
        self._index = DataIndex(data, inner)
        self._data = data

    def get_nearest_items(
        self,
        query_embedding: ColumnReference,
        k: ColumnExpression | int = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: ColumnExpression | None = None,
    ) -> Table:
        """Maintained KNN answers (reference index.py:54)."""
        return self._package(
            self._index.query(
                query_embedding,
                number_of_matches=k,
                collapse_rows=collapse_rows,
                metadata_filter=metadata_filter,
            ),
            collapse_rows,
            with_distances,
        )

    def get_nearest_items_asof_now(
        self,
        query_embedding: ColumnReference,
        k: ColumnExpression | int = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: ColumnExpression | None = None,
    ) -> Table:
        """As-of-now answers: not revisited when data changes later
        (reference index.py:194)."""
        return self._package(
            self._index.query_as_of_now(
                query_embedding,
                number_of_matches=k,
                collapse_rows=collapse_rows,
                metadata_filter=metadata_filter,
            ),
            collapse_rows,
            with_distances,
        )

    def _package(self, join_result, collapse_rows: bool, with_distances: bool) -> Table:
        from ...internals.thisclass import right as r_
        from ..indexing.data_index import _SCORE

        cols = {c: getattr(r_, c) for c in self._data.column_names()}
        if with_distances:
            from ...internals import dtype as dt
            from ...internals.expression import apply_with_type

            if collapse_rows:
                cols["dist"] = apply_with_type(
                    lambda scores: tuple(-float(s) for s in (scores or ())),
                    dt.ANY, getattr(r_, _SCORE),
                )
            else:
                cols["dist"] = apply_with_type(
                    lambda s: -float(s) if s is not None else None,
                    dt.Optional(dt.FLOAT), getattr(r_, _SCORE),
                )
        # the collapsed DataIndex result is a LEFT join keyed by
        # pw.left.id, so join_select already carries the queries' universe
        # (joins.py) — `queries + result` zips with no promise needed here
        return join_result.select(**cols)
