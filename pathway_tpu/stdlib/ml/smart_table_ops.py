"""Fuzzy joins (reference
``python/pathway/stdlib/ml/smart_table_ops/_fuzzy_join.py``:
``fuzzy_match`` :265, ``fuzzy_self_match`` :249, ``fuzzy_match_tables``
:106, ``smart_fuzzy_match`` :199, ``fuzzy_match_with_hint`` :282).

Own construction, same contract: tokenize both sides into features,
weight features by inverse global frequency, score candidate pairs by
shared-feature weight, and keep mutually-best pairs. Everything is
ordinary incremental dataflow (flatten + join + groupby), so matches
update live as either side changes.
"""

from __future__ import annotations

import enum
import math
import re
from typing import Any, Callable

import pathway_tpu as pw
from ...internals import dtype as dt
from ...internals.expression import ColumnReference, apply_with_type
from ...internals.table import Table
from ...internals.thisclass import left as l_, right as r_, this

__all__ = [
    "FuzzyJoinFeatureGeneration",
    "FuzzyJoinNormalization",
    "fuzzy_match",
    "fuzzy_self_match",
    "fuzzy_match_tables",
    "smart_fuzzy_match",
    "fuzzy_match_with_hint",
]


class FuzzyJoinFeatureGeneration(enum.IntEnum):
    AUTO = 0
    TOKENIZE = 1
    LETTERS = 2


class FuzzyJoinNormalization(enum.IntEnum):
    WEIGHT = 0
    LOGWEIGHT = 1
    NONE = 2


def _features(value: Any, generation: FuzzyJoinFeatureGeneration) -> tuple[str, ...]:
    text = str(value).lower()
    if generation == FuzzyJoinFeatureGeneration.LETTERS:
        return tuple(ch for ch in text if not ch.isspace())
    return tuple(re.findall(r"\w+", text))


def _edges(
    column: ColumnReference,
    generation: FuzzyJoinFeatureGeneration,
    side: str,
) -> Table:
    """(node_id, feature) rows — one per (row, distinct feature)."""
    table = column.table
    flat = table.select(
        __feats=apply_with_type(
            lambda v: tuple(set(_features(v, generation))), dt.ANY, column
        ),
    ).flatten(this["__feats"], origin_id="__node")
    return flat.select(
        feature=this["__feats"],
        node=this["__node"],
    )


def _normalizer(normalization: FuzzyJoinNormalization) -> Callable[[float], float]:
    if normalization == FuzzyJoinNormalization.WEIGHT:
        return lambda cnt: 1.0 / cnt
    if normalization == FuzzyJoinNormalization.LOGWEIGHT:
        return lambda cnt: 1.0 / (1.0 + math.log(cnt))
    return lambda cnt: 1.0


def fuzzy_match(
    left_col: ColumnReference,
    right_col: ColumnReference,
    *,
    feature_generation: FuzzyJoinFeatureGeneration = FuzzyJoinFeatureGeneration.TOKENIZE,
    normalization: FuzzyJoinNormalization = FuzzyJoinNormalization.WEIGHT,
) -> Table:
    """Table(left, right, weight): mutually-best fuzzy pairs between the
    two text columns (reference _fuzzy_join.py:265)."""
    left_edges = _edges(left_col, feature_generation, "l")
    right_edges = _edges(right_col, feature_generation, "r")

    # global feature frequency (both sides) -> weight
    all_edges = left_edges.concat_reindex(right_edges)
    counts = all_edges.groupby(this.feature).reduce(
        feature=this.feature, cnt=pw.reducers.count()
    )
    norm = _normalizer(normalization)
    weights = counts.select(
        feature=this.feature,
        weight=apply_with_type(lambda c: norm(float(c)), dt.FLOAT, this.cnt),
    )

    # candidate pairs sharing a feature, scored by summed feature weight
    pairs = (
        left_edges.join(right_edges, l_.feature == r_.feature)
        .select(feature=l_.feature, left=l_.node, right=r_.node)
    )
    pairs_w = (
        pairs.join(weights, l_.feature == r_.feature)
        .select(left=l_.left, right=l_.right, weight=r_.weight)
    )
    scored = pairs_w.groupby(this.left, this.right).reduce(
        left=this.left, right=this.right, weight=pw.reducers.sum(this.weight)
    )

    # mutually-best: the heaviest pair for its left AND for its right
    best_left = scored.groupby(this.left).reduce(
        left=this.left,
        best=pw.reducers.argmax(this.weight),
    )
    best_right = scored.groupby(this.right).reduce(
        right=this.right,
        best=pw.reducers.argmax(this.weight),
    )
    # argmax values ARE keys of `scored`, so both reindexed winner tables
    # are subsets of it by construction — promised, since the solver can't
    # prove it across the reindex. A best-for-right row need NOT be
    # best-for-left, so the second cut is an intersection, not a restrict.
    keep_l = scored.restrict(
        best_left.with_id(this.best).promise_universe_is_subset_of(scored)
    )
    mutual = keep_l.intersect(
        best_right.with_id(this.best).promise_universe_is_subset_of(scored)
    )
    return mutual


def fuzzy_self_match(
    values: ColumnReference,
    **kwargs: Any,
) -> Table:
    """Fuzzy pairs within one column, excluding self-pairs
    (reference :249)."""
    matched = fuzzy_match(values, values, **kwargs)
    return matched.filter(
        apply_with_type(
            lambda a, b: a != b, dt.BOOL, this.left, this.right
        )
    )


def _concat_row_text(table: Table) -> Table:
    cols = [table[c] for c in table.column_names()]
    return table.select(
        __text=apply_with_type(
            lambda *vs: " ".join(str(v) for v in vs if v is not None),
            dt.STR, *cols,
        )
    )


def fuzzy_match_tables(
    left_table: Table,
    right_table: Table,
    *,
    by_hand_match: Table | None = None,
    left_projection: dict[str, str] | None = None,
    right_projection: dict[str, str] | None = None,
    **kwargs: Any,
) -> Table:
    """Fuzzy-match whole rows (all columns concatenated to text,
    reference :106)."""
    lcols = list(left_projection) if left_projection else left_table.column_names()
    rcols = list(right_projection) if right_projection else right_table.column_names()
    lt = _concat_row_text(left_table.select(**{c: left_table[c] for c in lcols}))
    rt = _concat_row_text(right_table.select(**{c: right_table[c] for c in rcols}))
    matched = fuzzy_match(
        ColumnReference(lt, "__text"), ColumnReference(rt, "__text"), **kwargs
    )
    if by_hand_match is not None:
        # hand matches override: drop computed pairs whose left appears
        hand_lefts = by_hand_match.with_id(this.left)
        matched = matched.with_id(this.left).difference(hand_lefts).concat_reindex(
            by_hand_match
        )
    return matched


def smart_fuzzy_match(
    left_col: ColumnReference,
    right_col: ColumnReference,
    **kwargs: Any,
) -> Table:
    """reference :199 — fuzzy_match with the default heuristics."""
    kwargs.setdefault("normalization", FuzzyJoinNormalization.LOGWEIGHT)
    return fuzzy_match(left_col, right_col, **kwargs)


def fuzzy_match_with_hint(
    left_col: ColumnReference,
    right_col: ColumnReference,
    by_hand_match: Table,
    **kwargs: Any,
) -> Table:
    """reference :282 — hand-made (left, right, weight) rows override the
    computed matching for their left keys."""
    matched = fuzzy_match(left_col, right_col, **kwargs)
    hand_keyed = by_hand_match.with_id(this.left)
    auto_keyed = matched.with_id(this.left)
    return auto_keyed.difference(hand_keyed).concat_reindex(by_hand_match)
