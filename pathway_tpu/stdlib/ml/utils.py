"""ML helpers (reference ``python/pathway/stdlib/ml/utils.py``)."""

from __future__ import annotations

import pathway_tpu as pw
from ...internals import dtype as dt
from ...internals.expression import ColumnReference, apply_with_type
from ...internals.table import Table
from ...internals.thisclass import left as l_, right as r_, this

__all__ = ["classifier_accuracy"]


def classifier_accuracy(
    predicted_labels: ColumnReference, exact_labels: ColumnReference
) -> Table:
    """Count of correct vs incorrect predictions
    (reference ml/utils.py:13)."""
    pt = predicted_labels.table
    joined = pt.select(
        __pred=predicted_labels,
        __exact=exact_labels,
    )
    flagged = joined.select(
        ok=apply_with_type(
            lambda p, e: bool(p == e), dt.BOOL, this["__pred"], this["__exact"]
        )
    )
    return flagged.groupby(this.ok).reduce(
        cnt=pw.reducers.count(), value=this.ok
    )
