"""``pw.ml`` (reference ``python/pathway/stdlib/ml``): KNN index,
LSH classifiers, fuzzy joins, HMM decoding, dataset helpers."""

from . import classifiers, datasets, hmm, index, smart_table_ops, utils  # noqa: F401
from .classifiers import (  # noqa: F401
    knn_lsh_classifier_train,
    knn_lsh_classify,
    knn_lsh_euclidean_classifier_train,
    knn_lsh_generic_classifier_train,
    knn_lsh_train,
)
from .hmm import create_hmm_reducer  # noqa: F401
from .index import KNNIndex  # noqa: F401
from .smart_table_ops import (  # noqa: F401
    fuzzy_match,
    fuzzy_match_tables,
    fuzzy_self_match,
    smart_fuzzy_match,
)
from .utils import classifier_accuracy  # noqa: F401

__all__ = [
    "index",
    "classifiers",
    "smart_table_ops",
    "hmm",
    "datasets",
    "utils",
    "KNNIndex",
    "create_hmm_reducer",
    "classifier_accuracy",
    "knn_lsh_classifier_train",
    "knn_lsh_train",
    "knn_lsh_classify",
    "knn_lsh_generic_classifier_train",
    "knn_lsh_euclidean_classifier_train",
    "fuzzy_match",
    "fuzzy_self_match",
    "fuzzy_match_tables",
    "smart_fuzzy_match",
]
