"""pw.ml (reference python/pathway/stdlib/ml)."""
