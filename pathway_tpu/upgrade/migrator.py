"""Staged graph-version cutover behind ``pathway-tpu upgrade --apply``.

Rides the rescale substrate (``rescale/resharder.py``): the migrated
layout is staged under ``upgrade-tmp/`` as a COMPLETE next-epoch layout
— carried snapshots copied verbatim, remapped state rewritten through
``split_state``/``merge_states``, new operators backfilled by replaying
the retained input log through just their ancestor subgraph, the live
input tail + per-source offsets + delivery ack cursors carried exactly
as rescale carries them — then promoted with ONE atomic ``cluster``
marker put. A crash at any earlier instant leaves the old code version
bootable against the old, untouched layout; after the marker flip the
new version boots with exactly-once output intact across the code flip.

Every phase boundary (plan / stage / backfill / carry / promote /
cleanup) is an ``upgrade`` chaos site and an ``upgrade.*`` trace span.
The ``torn`` chaos action lands a truncated blob under the staging
prefix before raising — proving half-written staging never contaminates
a bootable layout.

Unlike rescale, the worker count is UNCHANGED: per-worker namespaces map
1:1, so tail chunks and ack cursors copy verbatim per worker and keyed
state never crosses shard boundaries (remap rewrites are per-worker
normalizations, not reshuffles).
"""

from __future__ import annotations

import json
import pickle
import sys
import time as _time
from typing import Any, Callable

from ..internals.config import _env_bool
from ..internals.tracing import span as _span
from ..persistence import layout as _layout
from ..persistence.backends import PersistenceBackend, open_backend
from ..persistence.manager import MANIFEST_KEY
from ..persistence.snapshots import (
    MetadataAccessor,
    OperatorSnapshots,
    SnapshotReader,
    read_op_state,
)
from ..rescale.resharder import (
    _merge_offsets,
    _node_class,
    _pick_snapshot_time,
    _worker_view,
)
from .planner import UpgradeError, classify, load_new_graph

__all__ = [
    "UpgradeError",
    "NoStoreManifest",
    "NoStoreMarker",
    "plan_upgrade",
    "apply_upgrade",
    "stats",
]


class NoStoreManifest(UpgradeError):
    """The store predates fingerprint manifests (or was never booted):
    there is nothing to match the new script against. Boot once with the
    CURRENT code version — attach_nodes persists the manifest — then
    plan the upgrade."""


class NoStoreMarker(UpgradeError):
    """The store has no cluster marker: nothing was ever persisted.
    ``spawn --upgrade-to`` catches THIS (not a message substring) and
    boots fresh — an empty store needs no migration."""


#: process-local counters surfaced as ``pathway_upgrade_total`` /
#: ``pathway_upgrade_duration_seconds`` on /metrics (observability/hub.py)
_STATS: dict[str, Any] = {
    "total": 0, "duration_s": 0.0, "planned": 0,
    "carried": 0, "remapped": 0, "new": 0, "dropped": 0,
    "last": None,
}


def stats() -> dict[str, Any]:
    return dict(_STATS)


def _default_log(msg: str) -> None:
    print(f"[upgrade] {msg}", file=sys.stderr)


def _open_root(backend: Any) -> tuple[PersistenceBackend, bool]:
    if isinstance(backend, PersistenceBackend):
        return backend, False
    return open_backend(backend), True


def _load_store(
    root: PersistenceBackend, log: Callable[[str], Any]
) -> dict[str, Any]:
    """Read marker + per-worker metadata + the persisted manifest."""
    marker = _layout.read_marker(root)
    if marker is None:
        raise NoStoreMarker(
            f"no cluster marker at {root.describe()}: nothing was ever "
            "persisted, so there is no state to upgrade (the new version "
            "can simply boot)"
        )
    n_workers, epoch = marker
    views: list[PersistenceBackend] = []
    metas: list[dict] = []
    missing: list[int] = []
    for i in range(n_workers):
        ns = _layout.worker_namespace(epoch, n_workers, i)
        view = _worker_view(root, ns)
        views.append(view)
        cur = MetadataAccessor(view).current
        if cur is None:
            missing.append(i)
        metas.append(cur or {})
    if missing and len(missing) < n_workers:
        raise UpgradeError(
            f"worker(s) {missing} have no committed metadata while others "
            "do — the store is torn mid-first-commit; boot the old version "
            "once, then upgrade"
        )
    try:
        manifest = json.loads(views[0].get_value(MANIFEST_KEY))
    except (KeyError, FileNotFoundError):
        manifest = None
    except Exception as e:
        raise UpgradeError(f"corrupt graph manifest in store: {e}")
    return {
        "n_workers": n_workers,
        "epoch": epoch,
        "views": views,
        "metas": metas,
        "empty": len(missing) == n_workers,
        "manifest": manifest,
    }


def plan_upgrade(
    backend: Any, script: str, *,
    script_args: tuple = (),
    allow_drop: bool = False, log: Callable[[str], Any] | None = None,
) -> tuple[dict[str, Any], BaseException | None]:
    """Classify every stateful operator of the store's persisted graph
    version against a build-only compile of ``script``. Returns
    ``(plan, crash)`` — ``crash`` is the exception the new script raised
    while building (plan is then empty; exit code 3). Writes nothing."""
    log = log or _default_log
    allow_drop = allow_drop or _env_bool("PATHWAY_UPGRADE_ALLOW_DROP")
    root, close_after = _open_root(backend)
    try:
        store = _load_store(root, log)
        plan = _build_plan(root, store, script, script_args, allow_drop)
    finally:
        if close_after:
            root.close()
    _STATS["planned"] += 1
    plan.pop("_new_doc", None)
    return plan, plan.pop("_crash", None)


def _build_plan(
    root: PersistenceBackend, store: dict, script: str,
    script_args: tuple, allow_drop: bool
) -> dict[str, Any]:
    head = {
        "store": root.describe(),
        "script": script,
        "epoch": store["epoch"],
        "n_workers": store["n_workers"],
        "snapshot_time": None,
    }
    if store["empty"]:
        # marker without committed state: the new version boots fresh
        return {
            **head, "operators": [], "carried": 0, "remapped": 0,
            "new": 0, "dropped": 0, "warnings": [], "errors": [],
            "noop": True, "_crash": None,
        }
    if store["manifest"] is None:
        raise NoStoreManifest(
            f"store at {root.describe()} carries no graph manifest "
            f"({MANIFEST_KEY}) — boot it once with the CURRENT code "
            "version (any committed run persists the manifest), then "
            "plan the upgrade"
        )
    new_doc = load_new_graph(script, tuple(script_args))
    if new_doc.get("crash") is not None:
        return {
            **head, "operators": [], "carried": 0, "remapped": 0,
            "new": 0, "dropped": 0, "warnings": [],
            "errors": [f"new script failed to build: {new_doc['crash']}"],
            "_crash": new_doc["crash"],
        }
    snap_time = _pick_snapshot_time(store["metas"])
    plan = classify(store["manifest"], new_doc, allow_drop=allow_drop)
    plan.update(head)
    plan["snapshot_time"] = snap_time
    plan["_crash"] = None
    plan["_new_doc"] = new_doc
    backfill_on = _env_bool("PATHWAY_UPGRADE_BACKFILL", True)
    plan["backfill"] = backfill_on
    if plan["new"] and snap_time >= 0:
        if not backfill_on:
            plan["warnings"].append(
                f"{plan['new']} new stateful operator(s) start from "
                "INITIAL state (PATHWAY_UPGRADE_BACKFILL=0)"
            )
        elif any(
            int(m.get("first_chunk", 0)) > 0 for m in store["metas"]
        ):
            plan["warnings"].append(
                "input history was already truncated: new operators "
                "backfill from the RETAINED log only — rows persisted "
                "before the oldest retained chunk are not replayed "
                "into them"
            )
    return plan


def apply_upgrade(
    backend: Any, script: str, *,
    script_args: tuple = (),
    allow_drop: bool = False, log: Callable[[str], Any] | None = None,
) -> dict[str, Any]:
    """Migrate the store to the graph version built by ``script`` and
    promote it atomically. Raises :class:`UpgradeError` (with the plan's
    errors) instead of ever applying a refused plan."""
    log = log or _default_log
    t0 = _time.monotonic()
    root, close_after = _open_root(backend)
    try:
        report = _apply_root(root, script, script_args, allow_drop, log)
    finally:
        if close_after:
            root.close()
    dt = _time.monotonic() - t0
    report["duration_s"] = round(dt, 6)
    if not report.get("noop"):
        _STATS["total"] += 1
        _STATS["duration_s"] += dt
        for verb in ("carried", "remapped", "new", "dropped"):
            _STATS[verb] += report.get(verb, 0)
        _STATS["last"] = {
            k: v for k, v in report.items() if k != "operators"
        }
    return report


def _apply_root(
    root: PersistenceBackend, script: str, script_args: tuple,
    allow_drop: bool, log: Callable[[str], Any],
) -> dict[str, Any]:
    from ..chaos import injector as _chaos

    try:
        from ..parallel.exchange import shard_rows
    except ImportError:
        from ..engine.keys import shard_of as shard_rows

    import numpy as np

    allow_drop = allow_drop or _env_bool("PATHWAY_UPGRADE_ALLOW_DROP")
    armed = _chaos.current()
    fault = armed.upgrade_faults() if armed is not None else None

    def torn() -> None:
        # half-written staging blob: must never contaminate the old
        # layout (it lives under the staging prefix, swept on retry)
        root.put_value(
            _layout.UPGRADE_STAGING_PREFIX + "torn-blob", b'{"half": '
        )

    def fire(phase: str) -> None:
        if fault is not None:
            fault.fire(phase, torn=torn)

    with _span("upgrade.plan", script=script):
        store = _load_store(root, log)
        plan = _build_plan(root, store, script, script_args, allow_drop)
        crash = plan.pop("_crash", None)
        if crash is not None:
            raise UpgradeError(
                f"new script failed to build: {crash}"
            ) from crash
        if plan.get("errors"):
            raise UpgradeError(
                "refusing to apply a plan with errors:\n  "
                + "\n  ".join(plan["errors"])
            )
    fire("plan")
    if plan.get("noop"):
        return plan

    new_doc = plan.pop("_new_doc")
    new_manifest = {
        k: new_doc[k] for k in ("version", "stateful", "sources")
    }
    if json.dumps(new_manifest, sort_keys=True) == json.dumps(
        store["manifest"], sort_keys=True
    ):
        # identical graph version: every operator carried at its own rank
        # — the store already matches, flipping epochs would only churn
        plan["noop"] = True
        log(
            f"store at {root.describe()} already matches {script} — "
            "nothing to migrate"
        )
        return plan
    n_workers, epoch = store["n_workers"], store["epoch"]
    views, metas = store["views"], store["metas"]
    snap_time = plan["snapshot_time"]
    new_epoch = epoch + 1

    # stale staging from a previously crashed attempt is garbage
    for key in root.list_keys():
        if key.startswith(_layout.UPGRADE_STAGING_PREFIX):
            root.remove_key(key)

    staged = [
        _worker_view(
            root,
            _layout.UPGRADE_STAGING_PREFIX
            + _layout.worker_namespace(new_epoch, n_workers, i),
        )
        for i in range(n_workers)
    ]

    def mask_for(i: int):
        def mask(keys: np.ndarray) -> np.ndarray:
            return (
                shard_rows(np.asarray(keys, dtype=np.uint64), n_workers) == i
            )

        return mask

    # per-worker snapshot descriptors at the chosen time
    entries: list[dict] = []
    if snap_time >= 0:
        for m in metas:
            entry = next(
                (
                    e for e in m.get("op_snapshots", [])
                    if int(e["time"]) == snap_time
                ),
                None,
            )
            assert entry is not None  # snap_time is the common time
            entries.append(entry["ops"])

    fire("stage")
    ops_per_worker: list[dict] = [{} for _ in range(n_workers)]
    moved = [
        op for op in plan["operators"]
        if op["verb"] in ("carried", "remapped")
    ]
    with _span("upgrade.stage", ops=len(moved), at=snap_time):
        for op in moved:
            if snap_time < 0:
                continue  # nothing snapshotted yet; tail replay covers it
            cls = _node_class(op["cls"])
            for i in range(n_workers):
                desc = entries[i].get(str(op["old_rank"])) or entries[i].get(
                    op["old_rank"]
                )
                if desc is None:
                    raise UpgradeError(
                        f"operator snapshot is missing rank "
                        f"{op['old_rank']} on worker {i}"
                    )
                piece = read_op_state(
                    OperatorSnapshots(views[i]), op["old_rank"], desc, cls
                )
                if op["verb"] == "remapped":
                    # normalize through the operator's own reshard
                    # protocol: the signature drifted, so the state is
                    # re-expressed rather than byte-copied
                    piece = cls.merge_states(
                        [cls.split_state(piece, mask_for(i))]
                    )
                n_chunks = OperatorSnapshots(staged[i]).write(
                    op["rank"], snap_time, piece
                )
                ops_per_worker[i][str(op["rank"])] = {
                    "cls": op["cls"], "at": snap_time, "chunks": n_chunks,
                }

    fire("backfill")
    new_ops = [op for op in plan["operators"] if op["verb"] == "new"]
    if snap_time >= 0 and new_ops:
        with _span("upgrade.backfill", ops=len(new_ops), upto=snap_time):
            states = _backfill_states(
                new_doc, new_ops, views, metas, snap_time,
                enabled=plan["backfill"], log=log,
            )
            for op in new_ops:
                initial, final = states[op["rank"]]
                cls = type(new_doc["stateful_nodes"][op["rank"]])
                mode = op.get("reshard", "keyed")
                for i in range(n_workers):
                    if mode == "keyed":
                        state = cls.split_state(final, mask_for(i))
                    elif mode == "pinned":
                        # single-owner composite: worker 0 owns it
                        state = final if i == 0 else initial
                    else:  # replicate
                        state = final
                    n_chunks = OperatorSnapshots(staged[i]).write(
                        op["rank"], snap_time, state
                    )
                    ops_per_worker[i][str(op["rank"])] = {
                        "cls": op["cls"], "at": snap_time,
                        "chunks": n_chunks,
                    }

    fire("carry")
    offsets = _merge_offsets(metas, log)
    carried_cursors = 0
    with _span("upgrade.carry", workers=n_workers):
        for i in range(n_workers):
            view, m = views[i], metas[i]
            # the live input tail copies VERBATIM: worker count (and so
            # key sharding) is unchanged across an upgrade
            for key in view.list_keys():
                if key.startswith("chunks/"):
                    staged[i].put_value(key, view.get_value(key))
            meta = {
                "last_time": int(m.get("last_time", -1)),
                "n_chunks": int(m.get("n_chunks", 0)),
                "first_chunk": int(m.get("first_chunk", 0)),
                "chunk_spans": m.get("chunk_spans", {}),
                "offsets": offsets,
                "n_workers": n_workers,
                "op_snapshots": (
                    [{"time": snap_time, "ops": ops_per_worker[i]}]
                    if snap_time >= 0
                    else []
                ),
            }
            staged[i].put_value(
                "meta/meta-00000000", json.dumps(meta).encode()
            )
            # delivery ack cursors: same worker owns the same sinks on
            # both sides of the flip — dropping them would reset the
            # recovery floor and re-deliver the replayed tail (duplicate
            # external output across the code-version boundary)
            for key in view.list_keys():
                if key.startswith("delivery/"):
                    staged[i].put_value(key, view.get_value(key))
                    carried_cursors += 1
            # the NEW graph version's manifest: the store self-describes
            # before the new code ever boots
            staged[i].put_value(
                MANIFEST_KEY,
                json.dumps(
                    {
                        k: new_doc[k]
                        for k in ("version", "stateful", "sources")
                    },
                    sort_keys=True,
                ).encode(),
            )
    plan["delivery_cursors"] = carried_cursors

    staged_keys = [
        k for k in root.list_keys()
        if k.startswith(_layout.UPGRADE_STAGING_PREFIX)
    ]
    with _span("upgrade.promote", staged_keys=len(staged_keys)):
        # leftovers of a crashed attempt under the target epoch would
        # survive next to the fresh copy as unreferenced orphans
        tgt = _layout.epoch_prefix(new_epoch)
        for key in root.list_keys():
            if tgt and key.startswith(tgt):
                root.remove_key(key)
        for key in staged_keys:
            root.put_value(
                key[len(_layout.UPGRADE_STAGING_PREFIX):],
                root.get_value(key),
            )
        fire("promote")
        # THE commit point: one atomic marker rewrite flips the cluster
        # to the new graph version's layout; everything before this line
        # left the old version's layout untouched
        _layout.write_marker(root, n_workers, new_epoch)
    fire("cleanup")
    with _span("upgrade.cleanup"):
        tgt = _layout.epoch_prefix(new_epoch)
        for key in root.list_keys():
            if key == _layout.MARKER_KEY or (tgt and key.startswith(tgt)):
                continue
            if key.startswith(
                (_layout.STAGING_PREFIX, _layout.UPGRADE_STAGING_PREFIX)
            ) or key.startswith(
                ("epoch-", "meta/", "chunks/", "ops/", "worker-",
                 "delivery/", "graph/")
            ):
                root.remove_key(key)
    plan["epoch"] = new_epoch
    log(
        f"upgraded store at {root.describe()} to {script} "
        f"(snapshot time {snap_time}, {plan['carried']} carried / "
        f"{plan['remapped']} remapped / {plan['new']} new / "
        f"{plan['dropped']} dropped, epoch {new_epoch})"
    )
    return plan


def _backfill_states(
    new_doc: dict, new_ops: list[dict], views: list, metas: list[dict],
    snap_time: int, *, enabled: bool, log: Callable[[str], Any],
) -> dict[int, tuple[Any, Any]]:
    """rank -> (initial_state, final_state) for every NEW stateful
    operator: replay the retained input log (entries at or before the
    carried snapshot time — the post-snapshot tail replays live at boot)
    through just the new operators' ancestor subgraph of the offline
    compile. History before the oldest retained chunk is gone; the plan
    already warned about that."""
    import numpy as np  # noqa: F401

    from ..engine.delta import concat_deltas
    from ..engine.executor import SourceNode, _topological

    nodes = new_doc["nodes"]
    stateful = new_doc["stateful_nodes"]
    targets = [stateful[op["rank"]] for op in new_ops]

    def snap(node: Any) -> Any:
        return pickle.loads(pickle.dumps(node.snapshot_state()))

    initials = {id(n): snap(n) for n in targets}
    if not enabled:
        return {
            op["rank"]: (initials[id(t)], initials[id(t)])
            for op, t in zip(new_ops, targets)
        }

    # ancestor closure of the new operators, in topological order
    wanted: set[int] = set()
    stack = list(targets)
    while stack:
        n = stack.pop()
        if id(n) in wanted:
            continue
        wanted.add(id(n))
        stack.extend(n.inputs)
    subgraph = [n for n in _topological(nodes) if id(n) in wanted]

    # the boot-time pid assignment: declared persistent ids, positional
    # src-{i} fallback in source order (executor._recover)
    sources = [n for n in subgraph if isinstance(n, SourceNode)]
    all_sources = [
        n for n in sorted(nodes, key=lambda x: x.node_id)
        if isinstance(n, SourceNode)
    ]
    pid_of: dict[int, str] = {}
    for i, src in enumerate(all_sources):
        pid_of[id(src)] = getattr(src, "persistent_id", None) or f"src-{i}"
    by_pid = {pid_of[id(s)]: s for s in sources}

    # union of every worker's retained entries up to the snapshot time
    entries: list[tuple[int, str, Any]] = []
    for view, m in zip(views, metas):
        reader = SnapshotReader(
            view, int(m.get("n_chunks", 0)), int(m.get("first_chunk", 0))
        )
        for t, pid, delta in reader.batches(after_time=-1):
            if int(t) <= snap_time and pid in by_pid:
                entries.append((int(t), pid, delta))
    entries.sort(key=lambda e: e[0])

    replayed = 0
    ticks: dict[int, dict[str, list]] = {}
    for t, pid, delta in entries:
        ticks.setdefault(t, {}).setdefault(pid, []).append(delta)
        replayed += 1
    for t in sorted(ticks):
        seeded = ticks[t]
        outputs: dict[int, Any] = {}
        for node in subgraph:
            parts: list[Any] = []
            released = node.advance_to(t)
            if released is not None and len(released):
                parts.append(released)
            if isinstance(node, SourceNode):
                for d in seeded.get(pid_of.get(id(node), ""), []):
                    if len(d):
                        parts.append(d)
            else:
                ins = [outputs.get(id(inp)) for inp in node.inputs]
                if any(x is not None for x in ins) or node.always_run:
                    out = node.process(t, ins)
                    if out is not None and len(out):
                        parts.append(out)
            outputs[id(node)] = (
                concat_deltas(parts, list(node.column_names))
                if parts
                else None
            )
    log(
        f"backfilled {len(new_ops)} new operator(s) from {replayed} "
        f"retained input entr(ies) up to snapshot time {snap_time}"
    )
    return {
        op["rank"]: (initials[id(t)], snap(t))
        for op, t in zip(new_ops, targets)
    }
