"""Human-readable rendering shared by ``pathway-tpu rescale --dry-run``
and ``pathway-tpu upgrade --plan``.

Both verbs preview a store migration as a per-operator table; keeping one
renderer means operators read the same vocabulary in both reports — rank,
class, reshard mode, structural fingerprint, pinned name, state bytes —
and a fingerprint printed by a dry run can be grepped verbatim in an
upgrade plan for the same store.
"""

from __future__ import annotations

from typing import Any

__all__ = ["render_dry_run", "render_plan", "op_label"]


def op_label(op: dict[str, Any]) -> str:
    """``rank <r> <Cls>`` plus the identity a human can match across
    reports: the pinned name when one exists, else the fingerprint."""
    ident = []
    if op.get("name"):
        ident.append(f"name={op['name']!r}")
    if op.get("fingerprint"):
        ident.append(f"fp={op['fingerprint']}")
    tail = f" ({', '.join(ident)})" if ident else ""
    return f"rank {op['rank']} {op['cls']}{tail}"


def render_dry_run(report: dict[str, Any]) -> list[str]:
    """The rescale dry-run preview (previously inlined in cli.py), now
    fingerprint-aware: operators are identifiable, not just numbered."""
    lines = [
        f"dry run: would rescale {report['from']} -> {report['to']} "
        f"worker(s) at snapshot time {report['snapshot_time']} "
        f"(epoch {report['epoch']} -> {report['epoch'] + 1}):"
    ]
    for op in report.get("operators", []):
        mb = op.get("state_bytes", 0) / 1e6
        lines.append(
            f"  {op_label(op)} [{op['mode']}]: {op['action']} "
            f"(source snapshot chunks: {op['chunks_per_source']}, "
            f"state {mb:.2f} MB = {op.get('state_bytes_per_source')} B "
            "per source, incl. spilled)"
        )
    if not report.get("operators"):
        lines.append("  (no stateful operator snapshots at that time)")
    total_mb = report.get("state_bytes_total", 0) / 1e6
    lines.append(
        f"  total stateful-operator bytes to redistribute: "
        f"{total_mb:.2f} MB across {report['to']} target worker(s) "
        f"(~{total_mb / max(1, report['to']):.2f} MB/worker)"
    )
    lines.append(
        "  input tail chunks to re-route per source worker: "
        f"{report.get('tail_chunks_per_source')}"
    )
    return lines


_VERB_GLOSS = {
    "carried": "snapshot reused verbatim",
    "remapped": "state rewritten via split_state/merge_states",
    "new": "backfilled from the retained input log",
    "dropped": "persisted state discarded",
}


def render_plan(plan: dict[str, Any]) -> list[str]:
    """The upgrade plan: every old/new stateful operator with its verb
    (carried / remapped / new / dropped), then warnings and errors."""
    lines = [
        f"upgrade plan: {plan['store']} (epoch {plan['epoch']}, "
        f"{plan['n_workers']} worker(s), snapshot time "
        f"{plan['snapshot_time']}) -> {plan['script']}:"
    ]
    for op in plan.get("operators", []):
        gloss = _VERB_GLOSS.get(op["verb"], "")
        detail = f" — {op['detail']}" if op.get("detail") else ""
        lines.append(
            f"  [{op['verb']:>8}] {op_label(op)}: {gloss}{detail}"
        )
    if not plan.get("operators"):
        lines.append("  (no stateful operators on either side)")
    counts = ", ".join(
        f"{plan.get(v, 0)} {v}" for v in ("carried", "remapped", "new", "dropped")
    )
    lines.append(f"  operators: {counts}")
    for w in plan.get("warnings", []):
        lines.append(f"  warning: {w}")
    for e in plan.get("errors", []):
        lines.append(f"  error: {e}")
    return lines
