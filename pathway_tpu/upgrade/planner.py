"""Graph-version diff/plan engine behind ``pathway-tpu upgrade --plan``.

Matches the fingerprint manifest a running pipeline persisted into its
store (``persistence/manager.py`` ``graph/manifest``) against a fresh
build-only compile of the NEW script (the same lint-mode execution
``pathway-tpu lint`` uses: imports and table building run for real,
``pw.run`` is stubbed — nothing external opens). Every stateful operator
gets one verb:

- **carried** — identical structural fingerprint, or pinned ``name=``
  with an unchanged signature: the persisted snapshot is reused verbatim.
- **remapped** — pinned name matches but the construction signature
  drifted compatibly: state is rewritten through the operator's
  ``split_state``/``merge_states`` protocol.
- **new** — no match: state is backfilled by replaying the retained
  input log through just that operator's ancestor subgraph.
- **dropped** — an old stateful operator with no successor: refused
  (exit code 2, operator named) unless ``--allow-drop``.

Exit codes mirror ``pathway-tpu lint``: 0 clean, 1 warnings, 2 errors,
3 the new script crashed while building.
"""

from __future__ import annotations

import os
import runpy
import sys
from typing import Any

from ..internals import lintmode
from ..internals.parse_graph import G

__all__ = [
    "UpgradeError",
    "load_new_graph",
    "classify",
    "plan_exit_code",
]


class UpgradeError(RuntimeError):
    pass


def load_new_graph(
    script: str, script_args: tuple[str, ...] = ()
) -> dict[str, Any]:
    """Build-only compile of ``script``: run it with ``pw.run`` stubbed
    (lint mode), lower every registered sink, fingerprint the nodes.
    ``script_args`` becomes ``sys.argv[1:]`` for scripts that parse
    their command line while building. Returns ``{"crash": exc}`` when
    the script itself failed, else a manifest-shaped doc plus the live
    node objects (``"stateful_nodes"``, ``"nodes"``) the migrator needs
    for remap/backfill."""
    from ..analysis.graph import fingerprint_nodes, lower_current_graph
    from ..persistence.manager import build_manifest

    script = os.path.abspath(script)
    saved_graph = dict(G.__dict__)
    saved_argv = list(sys.argv)
    G.clear()
    lintmode.arm(script)
    crash: BaseException | None = None
    nodes: list[Any] = []
    try:
        sys.argv = [script, *script_args]
        try:
            runpy.run_path(script, run_name="__main__")
        except SystemExit as e:
            # argparse --help / sys.exit(0) is not a crash; nonzero is
            if e.code not in (None, 0):
                crash = e
        except BaseException as e:
            crash = e
        if crash is None:
            runner = lower_current_graph()
            nodes = list(runner._nodes)
    finally:
        lintmode.disarm()
        sys.argv = saved_argv
        G.__dict__.clear()
        G.__dict__.update(saved_graph)
    if crash is not None:
        return {"crash": crash}
    fps = fingerprint_nodes(nodes)
    ordered = sorted(nodes, key=lambda n: n.node_id)
    stateful = [n for n in ordered if n.has_state()]
    # the EXACT manifest a boot of this script would persist — matching
    # against anything else would let plan and runtime disagree
    doc = build_manifest(stateful, nodes, fps)
    doc["crash"] = None
    doc["nodes"] = nodes
    doc["stateful_nodes"] = stateful
    return doc


def classify(
    old_manifest: dict[str, Any],
    new_doc: dict[str, Any],
    *,
    allow_drop: bool = False,
) -> dict[str, Any]:
    """The migration plan: one entry per stateful operator (old or new),
    with counts, warnings and errors. Pure function of the two manifests
    — the migrator executes exactly what this returns."""
    from .render import op_label

    old_ops = list(old_manifest.get("stateful", []))
    new_ops = list(new_doc.get("stateful", []))
    matched_old: set[int] = set()
    by_fp: dict[tuple[str, str], list[dict]] = {}
    for e in old_ops:
        by_fp.setdefault((e["fingerprint"], e["cls"]), []).append(e)
    by_name = {e["name"]: e for e in old_ops if e.get("name")}

    entries: list[dict[str, Any]] = []
    errors: list[str] = []
    warnings: list[str] = []
    for e in new_ops:
        entry = {
            "rank": e["rank"],
            "old_rank": None,
            "cls": e["cls"],
            "fingerprint": e["fingerprint"],
            "name": e.get("name"),
            "reshard": e.get("reshard", "keyed"),
            "verb": "new",
            "detail": None,
        }
        # 1. exact structural identity: two compiles of unchanged code
        cands = [
            c for c in by_fp.get((e["fingerprint"], e["cls"]), [])
            if c["rank"] not in matched_old
        ]
        if cands:
            old = cands[0]
            matched_old.add(old["rank"])
            entry.update(verb="carried", old_rank=old["rank"])
            entries.append(entry)
            continue
        # 2. pinned identity survives structural drift
        name = e.get("name")
        old = by_name.get(name) if name else None
        if old is not None and old["rank"] not in matched_old:
            if old["cls"] != e["cls"]:
                errors.append(
                    f"pinned name {name!r} is {old['cls']} in the store "
                    f"but {e['cls']} in the new script — state cannot "
                    "migrate across operator classes"
                )
                entry["detail"] = (
                    f"name {name!r} reused for a different class "
                    f"({old['cls']} -> {e['cls']})"
                )
            elif old.get("signature") == e.get("signature"):
                matched_old.add(old["rank"])
                entry.update(
                    verb="carried", old_rank=old["rank"],
                    detail="pinned name; upstream drift only",
                )
            else:
                matched_old.add(old["rank"])
                entry.update(
                    verb="remapped", old_rank=old["rank"],
                    detail=(
                        f"signature drifted under pinned name {name!r}"
                    ),
                )
        entries.append(entry)

    for e in old_ops:
        if e["rank"] in matched_old:
            continue
        entry = {
            "rank": None,
            "old_rank": e["rank"],
            "cls": e["cls"],
            "fingerprint": e["fingerprint"],
            "name": e.get("name"),
            "reshard": e.get("reshard", "keyed"),
            "verb": "dropped",
            "detail": None,
        }
        label = op_label({**entry, "rank": e["rank"]})
        if allow_drop:
            warnings.append(
                f"stateful operator {label} is dropped: its persisted "
                "state is discarded (--allow-drop)"
            )
        else:
            errors.append(
                f"stateful operator {label} would be DROPPED and its "
                "persisted state discarded — rerun with --allow-drop to "
                "accept, or pin it in the new script via .named(...)"
            )
        entries.append(entry)

    old_pids = {
        s.get("pid") for s in old_manifest.get("sources", []) if s.get("pid")
    }
    new_pids = {
        s.get("pid") for s in new_doc.get("sources", []) if s.get("pid")
    }
    gone = sorted(old_pids - new_pids)
    if gone:
        warnings.append(
            f"persisted source id(s) {gone} have no matching source in "
            "the new script — their recorded tail rows cannot replay"
        )

    counts = {"carried": 0, "remapped": 0, "new": 0, "dropped": 0}
    for entry in entries:
        counts[entry["verb"]] += 1
    return {
        "operators": entries,
        **counts,
        "warnings": warnings,
        "errors": errors,
    }


def plan_exit_code(plan: dict[str, Any]) -> int:
    """lint-style severity exit code: 0 clean, 1 warnings, 2 errors
    (3 — script crash — is decided by the caller, which holds the
    exception)."""
    if plan.get("errors"):
        return 2
    if plan.get("warnings"):
        return 1
    return 0
