"""Zero-downtime graph-version upgrades (``pathway-tpu upgrade``).

Snapshots key on operator identities, so historically ANY edit to a
pipeline script orphaned its persisted store (``restore_operators``:
"the dataflow changed since the snapshot was taken"). This package turns
the structural-fingerprint + atomic-marker + ack-cursor machinery into a
migration path instead:

- ``planner`` — diff the store's persisted fingerprint manifest against
  a build-only compile of the new script; classify every stateful
  operator as carried / remapped / new / dropped.
- ``migrator`` — stage the migrated layout under ``upgrade-tmp/``,
  backfill new operators from the retained input log, carry offsets and
  delivery ack cursors, promote with one atomic marker put.
- ``render`` — the human-readable plan renderer, shared with
  ``pathway-tpu rescale --dry-run``.
"""

from .migrator import (
    NoStoreManifest,
    NoStoreMarker,
    UpgradeError,
    apply_upgrade,
    plan_upgrade,
    stats,
)
from .planner import classify, load_new_graph, plan_exit_code
from .render import render_dry_run, render_plan

__all__ = [
    "UpgradeError",
    "NoStoreManifest",
    "NoStoreMarker",
    "plan_upgrade",
    "apply_upgrade",
    "classify",
    "load_new_graph",
    "plan_exit_code",
    "render_dry_run",
    "render_plan",
    "stats",
]
