"""Ring attention — sequence-parallel attention over a device mesh.

Long documents embed as one sequence sharded across devices on a ``seq``
mesh axis: each device holds its Q/K/V block, K/V blocks rotate around the
ring via ``lax.ppermute`` (ICI neighbor hops, overlapping compute with
transfer), and softmax is accumulated online (flash-attention style
running max/normalizer), so no device ever materializes the full S×S score
matrix. This is the long-context capability the framework treats as
first-class; the reference has no attention kernels at all (SURVEY §5.7) —
its "long sequence" machinery is temporal windowing.

Numerics: scores and accumulators in float32, inputs may be bf16.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from ..internals.jax_compat import shard_map

__all__ = ["ring_attention", "full_attention"]

_NEG = -1e30


def full_attention(q, k, v, mask, scale: float):
    """Reference single-device attention (correctness oracle for the ring).

    q,k,v: [B, S, H, D]; mask: [B, S] bool (key-side padding mask).
    """
    qh = q.transpose(0, 2, 1, 3).astype(jnp.float32)
    kh = k.transpose(0, 2, 1, 3).astype(jnp.float32)
    vh = v.transpose(0, 2, 1, 3).astype(jnp.float32)
    scores = (qh @ kh.transpose(0, 1, 3, 2)) * jnp.float32(scale)
    scores = jnp.where(mask[:, None, None, :], scores, jnp.float32(_NEG))
    att = jax.nn.softmax(scores, axis=-1)
    out = att @ vh
    return out.transpose(0, 2, 1, 3)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array,
    mesh: Mesh,
    axis: str,
    scale: float,
) -> jax.Array:
    """Sequence-parallel attention.

    q,k,v: [B, S, H, D] sharded over S on mesh axis ``axis``;
    mask: [B, S] bool, sharded the same way. Returns [B, S, H, D] f32,
    sharded over S.
    """
    n = mesh.shape[axis]
    perm = [(j, (j + 1) % n) for j in range(n)]

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(None, axis, None, None),
            P(None, axis, None, None),
            P(None, axis, None, None),
            P(None, axis),
        ),
        out_specs=P(None, axis, None, None),
        check_vma=False,
    )
    def inner(qb, kb, vb, mb):
        b, s, h, d = qb.shape
        qh = qb.transpose(0, 2, 1, 3).astype(jnp.float32)  # [B,H,s,D]

        def accumulate(carry_olm, kb, vb, mb):
            o, m, l = carry_olm
            kh = kb.transpose(0, 2, 1, 3).astype(jnp.float32)
            vh = vb.transpose(0, 2, 1, 3).astype(jnp.float32)
            scores = (qh @ kh.transpose(0, 1, 3, 2)) * jnp.float32(scale)  # [B,H,s,s_blk]
            scores = jnp.where(mb[:, None, None, :], scores, jnp.float32(_NEG))
            m_new = jnp.maximum(m, scores.max(-1))
            p = jnp.exp(scores - m_new[..., None])
            corr = jnp.exp(m - m_new)
            l_new = l * corr + p.sum(-1)
            o_new = o * corr[..., None] + p @ vh
            return (o_new, m_new, l_new)

        def step(_, carry):
            olm, kb, vb, mb = carry
            olm = accumulate(olm, kb, vb, mb)
            # rotate the K/V/mask blocks one hop around the ring (ICI)
            kb = lax.ppermute(kb, axis, perm)
            vb = lax.ppermute(vb, axis, perm)
            mb = lax.ppermute(mb, axis, perm)
            return (olm, kb, vb, mb)

        o0 = jnp.zeros((b, h, s, d), jnp.float32)
        m0 = jnp.full((b, h, s), jnp.float32(_NEG), jnp.float32)
        l0 = jnp.zeros((b, h, s), jnp.float32)
        # n-1 rotations suffice: the last block is consumed without another
        # round of collectives
        olm, kb, vb, mb = lax.fori_loop(
            0, n - 1, step, ((o0, m0, l0), kb, vb, mb)
        )
        o, m, l = accumulate(olm, kb, vb, mb)
        out = o / jnp.maximum(l, jnp.float32(1e-30))[..., None]
        return out.transpose(0, 2, 1, 3)

    return inner(q, k, v, mask)


def ring_encoder_block(
    x: jax.Array,
    mask: jax.Array,
    layer: dict[str, Any],
    cfg: Any,
    mesh: Mesh,
    axis: str,
) -> jax.Array:
    """One transformer encoder block with sequence-parallel attention —
    the long-context variant of ``models.embedder._block`` (same params)."""
    from .embedder import _layernorm

    h = _layernorm(x, layer["ln1_scale"], layer["ln1_bias"])
    b, s, d = h.shape
    qkv = h @ layer["qkv"].astype(cfg.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(b, s, cfg.n_heads, cfg.head_dim)

    att = ring_attention(
        heads(q), heads(k), heads(v), mask, mesh, axis,
        scale=1.0 / float(cfg.head_dim) ** 0.5,
    )
    out = att.reshape(b, s, d).astype(cfg.dtype)
    x = x + out @ layer["proj"].astype(cfg.dtype)
    h = _layernorm(x, layer["ln2_scale"], layer["ln2_bias"])
    h = jax.nn.gelu(h @ layer["mlp_in"].astype(cfg.dtype))
    x = x + h @ layer["mlp_out"].astype(cfg.dtype)
    return x


def embed_tokens_long(
    params: dict,
    token_ids: jax.Array,
    cfg: Any,
    mesh: Mesh,
    axis: str = "data",
) -> jax.Array:
    """Long-context embedding forward: the sequence dimension is sharded
    over `axis`, attention runs as a ring, pooling reduces with a psum-style
    global mean. token_ids int32 [B, S] (0 = pad), S % mesh.shape[axis] == 0.
    Positions use modular position embeddings for S beyond cfg.max_len."""
    from .embedder import _layernorm

    mask = token_ids > 0
    s = token_ids.shape[1]
    pos = jnp.arange(s) % params["pos_emb"].shape[0]
    x = params["tok_emb"].astype(cfg.dtype)[token_ids] + params["pos_emb"].astype(
        cfg.dtype
    )[pos][None, :, :]
    for layer in params["layers"]:
        x = ring_encoder_block(x, mask, layer, cfg, mesh, axis)
    x = _layernorm(x, params["ln_f_scale"], params["ln_f_bias"])
    m = mask[:, :, None].astype(jnp.float32)
    pooled = (x.astype(jnp.float32) * m).sum(1) / jnp.maximum(m.sum(1), 1.0)
    return pooled / jnp.linalg.norm(pooled, axis=-1, keepdims=True).clip(1e-9)
