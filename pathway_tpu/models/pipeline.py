"""The flagship distributed step: embed → exchange → index → retrieve → learn.

This is the framework's "training step" analog — one tick of the Adaptive-RAG
north-star pipeline (BASELINE.json) jitted over a 2D (data, model) mesh:

- **dp**: token batches sharded over ``data``;
- **tp**: embedder QKV/MLP weights sharded over ``model`` (XLA inserts the
  psum/all-gather for the split matmuls);
- **index sharding (the sp/ep analog)**: KNN index rows sharded over
  ``data``; queries hit every shard, local top-k, all-gather merge;
- **record exchange**: embeddings routed to owner shards by key low bits via
  bucketed all-to-all (the timely exchange analog, parallel/exchange.py);
- a contrastive gradient step on the embedder params (SGD) so the whole
  backward pass also compiles under the same shardings.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.knn import sharded_knn_search
from .embedder import EmbedderConfig, embed_tokens, init_params


def param_shardings(mesh: Mesh, params: dict) -> dict:
    """Tensor-parallel layout: split QKV/MLP hidden over the model axis."""

    def spec_for(path: str):
        if path in ("qkv", "mlp_in"):
            return P(None, "model")
        if path in ("proj", "mlp_out"):
            return P("model", None)
        return P()

    def map_tree(p):
        out = {}
        for k, v in p.items():
            if k == "layers":
                out[k] = [
                    {kk: NamedSharding(mesh, spec_for(kk)) for kk in layer}
                    for layer in v
                ]
            else:
                out[k] = NamedSharding(mesh, P())
        return out

    return map_tree(params)


def make_step(mesh: Mesh, cfg: EmbedderConfig, k: int = 4, lr: float = 1e-3):
    """Build the jitted full step over the mesh."""

    def loss_fn(params, tokens_a, tokens_b):
        ea = embed_tokens(params, tokens_a, cfg)
        eb = embed_tokens(params, tokens_b, cfg)
        logits = (ea @ eb.T) / 0.07
        labels = jnp.arange(ea.shape[0])
        loss = (
            -jax.nn.log_softmax(logits, axis=-1)[labels, labels].mean()
            - jax.nn.log_softmax(logits.T, axis=-1)[labels, labels].mean()
        )
        return loss, ea

    def step(params, tokens, tokens_aug, index, insert_at, queries):
        (loss, emb), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, tokens, tokens_aug
        )
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        # ingest: write the fresh embeddings into the sharded index
        index = jax.lax.dynamic_update_slice(
            index, emb.astype(index.dtype), (insert_at, 0)
        )
        # retrieve: sharded brute-force KNN with all-gather merge
        qe = embed_tokens(params, queries, cfg)
        scores, ids = sharded_knn_search(mesh, "data", qe, index, k)
        return params, index, loss, scores, ids

    in_shardings = (
        param_shardings(mesh, init_params(cfg, 0)),
        NamedSharding(mesh, P("data", None)),
        NamedSharding(mesh, P("data", None)),
        NamedSharding(mesh, P("data", None)),
        None,
        NamedSharding(mesh, P()),
    )
    return jax.jit(step, in_shardings=in_shardings, donate_argnums=(3,))


def run_one_step(mesh: Mesh, cfg: EmbedderConfig | None = None, batch: int = 8, seq: int = 16, k: int = 2):
    """Build tiny inputs and run one full distributed step (dryrun path)."""
    data_size = mesh.shape["data"]
    cfg = cfg or EmbedderConfig(
        vocab_size=1024, dim=64, n_layers=2, n_heads=4, max_len=seq
    )
    batch = max(batch, data_size)
    batch -= batch % data_size
    capacity = max(4 * batch, data_size * 8)
    capacity -= capacity % data_size

    params = init_params(cfg, 0)
    params = jax.device_put(params, param_shardings(mesh, params))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(1, cfg.vocab_size, (batch, seq)), jnp.int32)
    tokens_aug = jnp.where(tokens % 7 == 0, 1, tokens)
    index = jax.device_put(
        jnp.zeros((capacity, cfg.dim), jnp.float32),
        NamedSharding(mesh, P("data", None)),
    )
    queries = jnp.asarray(rng.integers(1, cfg.vocab_size, (4, seq)), jnp.int32)

    step = make_step(mesh, cfg, k=k)
    params, index, loss, scores, ids = step(
        params, tokens, tokens_aug, index, 0, queries
    )
    jax.block_until_ready((params, index, loss, scores, ids))
    return float(loss), np.asarray(scores), np.asarray(ids)
