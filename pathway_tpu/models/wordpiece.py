"""WordPiece tokenizer — the real vocab-driven tokenizer for pretrained
MiniLM/BERT-class embedders.

Re-implements BERT's tokenization pipeline (basic tokenization: lowercase /
accent stripping / punctuation splitting / CJK spacing, then greedy
longest-match-first WordPiece with ``##`` continuations) so pretrained
checkpoints see exactly the token ids they were trained with. Verified
against ``transformers.BertTokenizer`` over a shared vocab in
``tests/test_embedder_pretrained.py``. Replaces the hashing stand-in that
``models/embedder.py`` shipped before pretrained weights existed
(reference: ``python/pathway/xpacks/llm/embedders.py:217``
SentenceTransformerEmbedder's underlying tokenizer).
"""

from __future__ import annotations

import unicodedata

import numpy as np

__all__ = ["WordPieceTokenizer"]


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    # ASCII ranges BERT treats as punctuation even when unicodedata does not
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (
        0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
        or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
        or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
        or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F
    )


def _is_control(ch: str) -> bool:
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


class WordPieceTokenizer:
    def __init__(
        self,
        vocab: dict[str, int],
        *,
        lowercase: bool = True,
        unk_token: str = "[UNK]",
        cls_token: str = "[CLS]",
        sep_token: str = "[SEP]",
        pad_token: str = "[PAD]",
        max_chars_per_word: int = 100,
    ):
        self.vocab = vocab
        self.lowercase = lowercase
        self.unk_id = vocab[unk_token]
        self.cls_id = vocab[cls_token]
        self.sep_id = vocab[sep_token]
        self.pad_id = vocab.get(pad_token, 0)
        self.max_chars_per_word = max_chars_per_word

    @classmethod
    def from_vocab_file(cls, path: str, **kwargs) -> "WordPieceTokenizer":
        vocab: dict[str, int] = {}
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                tok = line.rstrip("\n")
                if tok:
                    vocab[tok] = i
        return cls(vocab, **kwargs)

    # -- basic tokenization (BERT BasicTokenizer) --------------------------

    def _clean(self, text: str) -> str:
        out = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or _is_control(ch):
                continue
            if ch.isspace():
                out.append(" ")
            elif _is_cjk(cp):
                out.append(f" {ch} ")
            else:
                out.append(ch)
        return "".join(out)

    def _split_word(self, word: str) -> list[str]:
        if self.lowercase:
            word = word.lower()
            word = "".join(
                ch for ch in unicodedata.normalize("NFD", word)
                if unicodedata.category(ch) != "Mn"  # strip accents
            )
        pieces: list[str] = []
        current: list[str] = []
        for ch in word:
            if _is_punctuation(ch):
                if current:
                    pieces.append("".join(current))
                    current = []
                pieces.append(ch)
            else:
                current.append(ch)
        if current:
            pieces.append("".join(current))
        return pieces

    def basic_tokenize(self, text: str) -> list[str]:
        out: list[str] = []
        for word in self._clean(text).split():
            out.extend(self._split_word(word))
        return out

    # -- WordPiece (greedy longest-match-first) ----------------------------

    def wordpiece(self, token: str) -> list[int]:
        if len(token) > self.max_chars_per_word:
            return [self.unk_id]
        ids: list[int] = []
        start = 0
        while start < len(token):
            end = len(token)
            cur: int | None = None
            while start < end:
                piece = token[start:end]
                if start > 0:
                    piece = "##" + piece
                pid = self.vocab.get(piece)
                if pid is not None:
                    cur = pid
                    break
                end -= 1
            if cur is None:
                return [self.unk_id]  # whole word becomes [UNK]
            ids.append(cur)
            start = end
        return ids

    # -- public API --------------------------------------------------------

    def encode(self, text: str, max_len: int | None = None) -> list[int]:
        """[CLS] pieces [SEP], truncated to max_len total."""
        ids = [self.cls_id]
        for token in self.basic_tokenize(text):
            ids.extend(self.wordpiece(token))
        limit = (max_len - 1) if max_len is not None else len(ids) + 1
        ids = ids[:limit]
        ids.append(self.sep_id)
        return ids

    def encode_batch(self, texts: list[str], max_len: int = 128) -> np.ndarray:
        """int32 [batch, max_len], right-padded with pad_id."""
        out = np.full((len(texts), max_len), self.pad_id, dtype=np.int32)
        for i, t in enumerate(texts):
            ids = self.encode(t, max_len)
            out[i, : len(ids)] = ids
        return out
