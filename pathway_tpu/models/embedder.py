"""TPU-native text embedder — the flagship on-device model.

Replaces the reference LLM xpack's CPU-bound ``SentenceTransformerEmbedder``
(``python/pathway/xpacks/llm/embedders.py:217``) with a pure-JAX transformer
encoder that runs on the MXU in bf16: mean-pooled, L2-normalized sentence
embeddings. Weights can be tensor-parallel sharded over a mesh "model" axis
(attention heads + MLP hidden split), with batch data-parallel over "data".

Deterministic init (seeded) so the framework is self-contained; loading
pretrained MiniLM-class weights is a straight param-tree mapping.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class EmbedderConfig:
    vocab_size: int = 30528
    dim: int = 384
    n_layers: int = 6
    n_heads: int = 12
    mlp_ratio: int = 4
    max_len: int = 512
    dtype: Any = jnp.bfloat16
    #: "preln" — the self-contained deterministic-init encoder;
    #: "bert" — post-layernorm with biases, numerically matching HF
    #: BertModel so MiniLM-class pretrained checkpoints load verbatim
    arch: str = "preln"
    ln_eps: float = 1e-6

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


def init_params(cfg: EmbedderConfig, seed: int = 0) -> dict:
    """Initialize a parameter pytree (dense f32 master weights)."""
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, 4 + 8 * cfg.n_layers)
    k = iter(keys)

    def dense(kk, fan_in, shape):
        return (jax.random.normal(kk, shape, jnp.float32) / np.sqrt(fan_in)).astype(
            jnp.float32
        )

    params: dict = {
        "tok_emb": dense(next(k), cfg.dim, (cfg.vocab_size, cfg.dim)),
        "pos_emb": dense(next(k), cfg.dim, (cfg.max_len, cfg.dim)),
        "ln_f_scale": jnp.ones((cfg.dim,), jnp.float32),
        "ln_f_bias": jnp.zeros((cfg.dim,), jnp.float32),
        "layers": [],
    }
    hidden = cfg.dim * cfg.mlp_ratio
    for _ in range(cfg.n_layers):
        layer = {
            "qkv": dense(next(k), cfg.dim, (cfg.dim, 3 * cfg.dim)),
            "proj": dense(next(k), cfg.dim, (cfg.dim, cfg.dim)),
            "mlp_in": dense(next(k), cfg.dim, (cfg.dim, hidden)),
            "mlp_out": dense(next(k), hidden, (hidden, cfg.dim)),
            "ln1_scale": jnp.ones((cfg.dim,), jnp.float32),
            "ln1_bias": jnp.zeros((cfg.dim,), jnp.float32),
            "ln2_scale": jnp.ones((cfg.dim,), jnp.float32),
            "ln2_bias": jnp.zeros((cfg.dim,), jnp.float32),
        }
        params["layers"].append(layer)
        for _ in range(4):
            next(k, None)
    return params


def _layernorm(x, scale, bias, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps) * scale + bias).astype(x.dtype)


def _block(x, layer, cfg: EmbedderConfig, mask):
    # attention — bf16 matmuls land on the MXU; softmax in f32
    h = _layernorm(x, layer["ln1_scale"], layer["ln1_bias"])
    b, s, d = h.shape
    qkv = h @ layer["qkv"].astype(cfg.dtype)
    q, kk, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(b, s, cfg.n_heads, cfg.head_dim).transpose(0, 2, 1, 3)

    q, kk, v = heads(q), heads(kk), heads(v)
    scores = (q @ kk.transpose(0, 1, 3, 2)).astype(jnp.float32) / np.sqrt(cfg.head_dim)
    scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    att = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
    out = (att @ v).transpose(0, 2, 1, 3).reshape(b, s, d)
    x = x + out @ layer["proj"].astype(cfg.dtype)
    # MLP
    h = _layernorm(x, layer["ln2_scale"], layer["ln2_bias"])
    h = jax.nn.gelu(h @ layer["mlp_in"].astype(cfg.dtype))
    x = x + h @ layer["mlp_out"].astype(cfg.dtype)
    return x


def _bert_block(x, layer, cfg: EmbedderConfig, mask):
    """Post-layernorm encoder block matching HF BertLayer exactly (dense
    biases, residual-then-LN, exact erf GELU). bf16/f32 matmuls on the MXU,
    softmax + layernorm statistics in f32."""
    b, s, d = x.shape
    dt = cfg.dtype

    def dense(t, name):
        return t @ layer[f"{name}_w"].astype(dt) + layer[f"{name}_b"].astype(dt)

    def heads(t):
        return t.reshape(b, s, cfg.n_heads, cfg.head_dim).transpose(0, 2, 1, 3)

    q, kk, v = heads(dense(x, "q")), heads(dense(x, "k")), heads(dense(x, "v"))
    scores = (q @ kk.transpose(0, 1, 3, 2)).astype(jnp.float32) / np.sqrt(cfg.head_dim)
    scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    att = jax.nn.softmax(scores, axis=-1).astype(dt)
    out = (att @ v).transpose(0, 2, 1, 3).reshape(b, s, d)
    x = _layernorm(
        x + dense(out, "proj"), layer["ln1_scale"], layer["ln1_bias"], cfg.ln_eps
    )
    h = jax.nn.gelu(dense(x, "mlp_in").astype(jnp.float32), approximate=False)
    x = _layernorm(
        x + dense(h.astype(dt), "mlp_out"),
        layer["ln2_scale"], layer["ln2_bias"], cfg.ln_eps,
    )
    return x


def embed_tokens(params: dict, token_ids: jax.Array, cfg: EmbedderConfig) -> jax.Array:
    """token_ids int32 [batch, seq] (0 = pad) -> f32 [batch, dim], L2-normed
    (mean pooling + normalize — the sentence-transformers MiniLM head)."""
    mask = token_ids > 0
    s = token_ids.shape[1]
    x = params["tok_emb"].astype(cfg.dtype)[token_ids] + params["pos_emb"].astype(
        cfg.dtype
    )[:s][None, :, :]
    if cfg.arch == "bert":
        x = x + params["type_emb"].astype(cfg.dtype)[0][None, None, :]
        x = _layernorm(
            x, params["emb_ln_scale"], params["emb_ln_bias"], cfg.ln_eps
        )
        for layer in params["layers"]:
            x = _bert_block(x, layer, cfg, mask)
    else:
        for layer in params["layers"]:
            x = _block(x, layer, cfg, mask)
        x = _layernorm(x, params["ln_f_scale"], params["ln_f_bias"])
    # masked mean pool
    m = mask[:, :, None].astype(jnp.float32)
    pooled = (x.astype(jnp.float32) * m).sum(1) / jnp.maximum(m.sum(1), 1.0)
    return pooled / jnp.linalg.norm(pooled, axis=-1, keepdims=True).clip(1e-9)


def _np(v) -> np.ndarray:
    """Tensor-library-agnostic ndarray view (torch tensors or arrays)."""
    if hasattr(v, "detach"):
        v = v.detach().cpu().numpy()
    return np.asarray(v, dtype=np.float32)


def load_hf_state_dict(
    state_dict: dict, *, n_heads: int | None = None
) -> tuple[dict, EmbedderConfig]:
    """Map a HF ``BertModel``/MiniLM checkpoint (the param tree
    ``models/embedder.py`` has promised since round 1; reference
    ``xpacks/llm/embedders.py:217`` wraps the same family) onto the
    TPU encoder. HF Linear weights are (out, in) — transposed here to the
    (in, out) matmul layout. Accepts torch tensors or arrays; tolerates the
    ``bert.``-prefixed naming some exports use."""
    sd = {k.removeprefix("bert."): v for k, v in state_dict.items()}
    tok = _np(sd["embeddings.word_embeddings.weight"])
    pos = _np(sd["embeddings.position_embeddings.weight"])
    n_layers = 1 + max(
        int(k.split(".")[2]) for k in sd if k.startswith("encoder.layer.")
    )
    inter = _np(sd["encoder.layer.0.intermediate.dense.weight"]).shape[0]
    dim = tok.shape[1]
    if n_heads is None:
        # the head count is NOT derivable from tensor shapes, and the head
        # partition changes attention output — it must come from the
        # checkpoint's config.json (from_pretrained reads it) or the caller
        raise ValueError(
            "load_hf_state_dict: pass n_heads= (attention output depends on "
            "the head partition; it cannot be inferred from tensor shapes — "
            "see num_attention_heads in the checkpoint's config.json)"
        )
    cfg = EmbedderConfig(
        vocab_size=tok.shape[0], dim=dim, n_layers=n_layers,
        n_heads=n_heads, mlp_ratio=max(1, inter // dim),
        max_len=pos.shape[0], arch="bert", ln_eps=1e-12,
    )
    params: dict = {
        "tok_emb": jnp.asarray(tok),
        "pos_emb": jnp.asarray(pos),
        "type_emb": jnp.asarray(_np(sd["embeddings.token_type_embeddings.weight"])),
        "emb_ln_scale": jnp.asarray(_np(sd["embeddings.LayerNorm.weight"])),
        "emb_ln_bias": jnp.asarray(_np(sd["embeddings.LayerNorm.bias"])),
        "layers": [],
    }
    for i in range(n_layers):
        p = f"encoder.layer.{i}."
        layer = {}
        for ours, theirs in (
            ("q", "attention.self.query"),
            ("k", "attention.self.key"),
            ("v", "attention.self.value"),
            ("proj", "attention.output.dense"),
            ("mlp_in", "intermediate.dense"),
            ("mlp_out", "output.dense"),
        ):
            layer[f"{ours}_w"] = jnp.asarray(_np(sd[p + theirs + ".weight"]).T)
            layer[f"{ours}_b"] = jnp.asarray(_np(sd[p + theirs + ".bias"]))
        layer["ln1_scale"] = jnp.asarray(_np(sd[p + "attention.output.LayerNorm.weight"]))
        layer["ln1_bias"] = jnp.asarray(_np(sd[p + "attention.output.LayerNorm.bias"]))
        layer["ln2_scale"] = jnp.asarray(_np(sd[p + "output.LayerNorm.weight"]))
        layer["ln2_bias"] = jnp.asarray(_np(sd[p + "output.LayerNorm.bias"]))
        params["layers"].append(layer)
    return params, cfg


class Embedder:
    """Host-facing embedder with a cached jitted forward per shape bucket."""

    def __init__(self, cfg: EmbedderConfig | None = None, seed: int = 0,
                 params: dict | None = None, tokenizer: Any = None):
        self.cfg = cfg or EmbedderConfig()
        self.params = params if params is not None else init_params(self.cfg, seed)
        self.tokenizer = tokenizer
        self._fwd = jax.jit(functools.partial(embed_tokens, cfg=self.cfg))

    @classmethod
    def from_pretrained(
        cls, source: Any, *, tokenizer: Any = None, dtype: Any = None,
        n_heads: int | None = None,
    ) -> "Embedder":
        """Build from a pretrained MiniLM/BERT checkpoint.

        ``source``: a HF state dict (pass ``n_heads=`` — the head partition
        is not derivable from tensor shapes), or a local directory with
        ``pytorch_model.bin`` + ``config.json`` (``num_attention_heads`` is
        read from it) and optionally ``vocab.txt``, which becomes the
        WordPiece tokenizer. No network access is attempted."""
        import json as _json
        import os

        if isinstance(source, (str, os.PathLike)):
            path = os.fspath(source)
            import torch  # baked in; state dicts are torch-serialized

            state_dict = torch.load(
                os.path.join(path, "pytorch_model.bin"),
                map_location="cpu", weights_only=True,
            )
            cfg_file = os.path.join(path, "config.json")
            if n_heads is None and os.path.exists(cfg_file):
                with open(cfg_file) as f:
                    n_heads = int(_json.load(f)["num_attention_heads"])
            vocab_file = os.path.join(path, "vocab.txt")
            if tokenizer is None and os.path.exists(vocab_file):
                from .wordpiece import WordPieceTokenizer

                tokenizer = WordPieceTokenizer.from_vocab_file(vocab_file)
        else:
            state_dict = source
        params, cfg = load_hf_state_dict(state_dict, n_heads=n_heads)
        if dtype is not None:
            cfg = dataclasses.replace(cfg, dtype=dtype)
        return cls(cfg, params=params, tokenizer=tokenizer)

    def __call__(self, token_ids: np.ndarray) -> np.ndarray:
        return np.asarray(self._fwd(self.params, jnp.asarray(token_ids, jnp.int32)))

    def embed_texts_device(self, texts: list[str], max_len: int = 128) -> jax.Array:
        """Embeddings as a device-resident array (no host fetch): consumers
        that feed another device computation (the KNN scorer) pipeline the
        dispatches and pay ONE host roundtrip for the whole chain — the
        serve-path latency win on remote/tunneled accelerators.

        The sequence is bucketed to the smallest power of two covering the
        longest REAL token run (min 16): pad columns are masked out of
        attention and the mean pool, so truncating them is numerically
        equivalent (differences ~1e-4 from the finite -1e9 attention mask
        vs absent columns), and a 4-token serve query pays a 16-token
        forward instead of a ``max_len`` one (the dominant slice of REST
        p50 off-TPU). One jit cache entry per bucket."""
        max_len = min(max_len, self.cfg.max_len)  # position-table bound
        if self.tokenizer is not None:
            toks = self.tokenizer.encode_batch(texts, max_len)
        else:
            if self.cfg.arch == "bert":
                raise RuntimeError(
                    "pretrained (arch='bert') embedder has no tokenizer: the "
                    "hashing stand-in would feed token ids the checkpoint was "
                    "never trained on — load with a vocab.txt (WordPiece) or "
                    "pass tokenizer="
                )
            toks = tokenize_batch(texts, self.cfg.vocab_size, max_len)
        toks = np.asarray(toks, dtype=np.int32)
        n, width = toks.shape
        if n == 0:
            return self._fwd(self.params, jnp.asarray(toks))
        # PER-TEXT buckets: each text's embedding is a pure function of
        # (text, its own bucket) — never of the other texts in the batch
        # (batch-derived buckets would make a re-embedded document's
        # vector drift with batch composition and churn the maintained
        # index; review finding). Texts group by bucket and each group
        # runs one forward; results reassemble device-side.
        lengths = (toks > 0).sum(axis=1)
        buckets = np.maximum(
            16, 2 ** np.ceil(np.log2(np.maximum(lengths, 1))).astype(np.int64)
        )
        buckets = np.minimum(buckets, width)
        uniq = np.unique(buckets)
        if len(uniq) == 1:
            b = int(uniq[0])
            return self._fwd(self.params, jnp.asarray(toks[:, :b]))
        out = None
        for b in uniq.tolist():
            ix = np.flatnonzero(buckets == b)
            part = self._fwd(self.params, jnp.asarray(toks[ix, :b]))
            if out is None:
                out = jnp.zeros((n, part.shape[1]), part.dtype)
            out = out.at[jnp.asarray(ix)].set(part)
        return out

    def embed_texts(self, texts: list[str], max_len: int = 128) -> np.ndarray:
        return np.asarray(self.embed_texts_device(texts, max_len))


def tokenize_batch(texts: list[str], vocab_size: int, max_len: int) -> np.ndarray:
    """Deterministic hashing tokenizer (feature-hashing — a self-contained
    stand-in for a learned vocab; swap with a real WordPiece for pretrained
    weights)."""
    out = np.zeros((len(texts), max_len), dtype=np.int32)
    for i, t in enumerate(texts):
        words = t.lower().split()[: max_len]
        for j, w in enumerate(words):
            out[i, j] = (hash_word(w) % (vocab_size - 2)) + 2
    return out


def hash_word(w: str) -> int:
    h = 2166136261
    for ch in w.encode("utf-8"):
        h = ((h ^ ch) * 16777619) & 0xFFFFFFFF
    return h
