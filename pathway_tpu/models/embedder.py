"""TPU-native text embedder — the flagship on-device model.

Replaces the reference LLM xpack's CPU-bound ``SentenceTransformerEmbedder``
(``python/pathway/xpacks/llm/embedders.py:217``) with a pure-JAX transformer
encoder that runs on the MXU in bf16: mean-pooled, L2-normalized sentence
embeddings. Weights can be tensor-parallel sharded over a mesh "model" axis
(attention heads + MLP hidden split), with batch data-parallel over "data".

Deterministic init (seeded) so the framework is self-contained; loading
pretrained MiniLM-class weights is a straight param-tree mapping.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class EmbedderConfig:
    vocab_size: int = 30528
    dim: int = 384
    n_layers: int = 6
    n_heads: int = 12
    mlp_ratio: int = 4
    max_len: int = 512
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


def init_params(cfg: EmbedderConfig, seed: int = 0) -> dict:
    """Initialize a parameter pytree (dense f32 master weights)."""
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, 4 + 8 * cfg.n_layers)
    k = iter(keys)

    def dense(kk, fan_in, shape):
        return (jax.random.normal(kk, shape, jnp.float32) / np.sqrt(fan_in)).astype(
            jnp.float32
        )

    params: dict = {
        "tok_emb": dense(next(k), cfg.dim, (cfg.vocab_size, cfg.dim)),
        "pos_emb": dense(next(k), cfg.dim, (cfg.max_len, cfg.dim)),
        "ln_f_scale": jnp.ones((cfg.dim,), jnp.float32),
        "ln_f_bias": jnp.zeros((cfg.dim,), jnp.float32),
        "layers": [],
    }
    hidden = cfg.dim * cfg.mlp_ratio
    for _ in range(cfg.n_layers):
        layer = {
            "qkv": dense(next(k), cfg.dim, (cfg.dim, 3 * cfg.dim)),
            "proj": dense(next(k), cfg.dim, (cfg.dim, cfg.dim)),
            "mlp_in": dense(next(k), cfg.dim, (cfg.dim, hidden)),
            "mlp_out": dense(next(k), hidden, (hidden, cfg.dim)),
            "ln1_scale": jnp.ones((cfg.dim,), jnp.float32),
            "ln1_bias": jnp.zeros((cfg.dim,), jnp.float32),
            "ln2_scale": jnp.ones((cfg.dim,), jnp.float32),
            "ln2_bias": jnp.zeros((cfg.dim,), jnp.float32),
        }
        params["layers"].append(layer)
        for _ in range(4):
            next(k, None)
    return params


def _layernorm(x, scale, bias):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + 1e-6) * scale + bias).astype(x.dtype)


def _block(x, layer, cfg: EmbedderConfig, mask):
    # attention — bf16 matmuls land on the MXU; softmax in f32
    h = _layernorm(x, layer["ln1_scale"], layer["ln1_bias"])
    b, s, d = h.shape
    qkv = h @ layer["qkv"].astype(cfg.dtype)
    q, kk, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(b, s, cfg.n_heads, cfg.head_dim).transpose(0, 2, 1, 3)

    q, kk, v = heads(q), heads(kk), heads(v)
    scores = (q @ kk.transpose(0, 1, 3, 2)).astype(jnp.float32) / np.sqrt(cfg.head_dim)
    scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    att = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
    out = (att @ v).transpose(0, 2, 1, 3).reshape(b, s, d)
    x = x + out @ layer["proj"].astype(cfg.dtype)
    # MLP
    h = _layernorm(x, layer["ln2_scale"], layer["ln2_bias"])
    h = jax.nn.gelu(h @ layer["mlp_in"].astype(cfg.dtype))
    x = x + h @ layer["mlp_out"].astype(cfg.dtype)
    return x


def embed_tokens(params: dict, token_ids: jax.Array, cfg: EmbedderConfig) -> jax.Array:
    """token_ids int32 [batch, seq] (0 = pad) -> f32 [batch, dim], L2-normed."""
    mask = token_ids > 0
    s = token_ids.shape[1]
    x = params["tok_emb"].astype(cfg.dtype)[token_ids] + params["pos_emb"].astype(
        cfg.dtype
    )[:s][None, :, :]
    for layer in params["layers"]:
        x = _block(x, layer, cfg, mask)
    x = _layernorm(x, params["ln_f_scale"], params["ln_f_bias"])
    # masked mean pool
    m = mask[:, :, None].astype(jnp.float32)
    pooled = (x.astype(jnp.float32) * m).sum(1) / jnp.maximum(m.sum(1), 1.0)
    return pooled / jnp.linalg.norm(pooled, axis=-1, keepdims=True).clip(1e-9)


class Embedder:
    """Host-facing embedder with a cached jitted forward per shape bucket."""

    def __init__(self, cfg: EmbedderConfig | None = None, seed: int = 0):
        self.cfg = cfg or EmbedderConfig()
        self.params = init_params(self.cfg, seed)
        self._fwd = jax.jit(functools.partial(embed_tokens, cfg=self.cfg))

    def __call__(self, token_ids: np.ndarray) -> np.ndarray:
        return np.asarray(self._fwd(self.params, jnp.asarray(token_ids, jnp.int32)))

    def embed_texts(self, texts: list[str], max_len: int = 128) -> np.ndarray:
        toks = tokenize_batch(texts, self.cfg.vocab_size, max_len)
        return self(toks)


def tokenize_batch(texts: list[str], vocab_size: int, max_len: int) -> np.ndarray:
    """Deterministic hashing tokenizer (feature-hashing — a self-contained
    stand-in for a learned vocab; swap with a real WordPiece for pretrained
    weights)."""
    out = np.zeros((len(texts), max_len), dtype=np.int32)
    for i, t in enumerate(texts):
        words = t.lower().split()[: max_len]
        for j, w in enumerate(words):
            out[i, j] = (hash_word(w) % (vocab_size - 2)) + 2
    return out


def hash_word(w: str) -> int:
    h = 2166136261
    for ch in w.encode("utf-8"):
        h = ((h ^ ch) * 16777619) & 0xFFFFFFFF
    return h
