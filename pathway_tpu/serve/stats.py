"""Serve-plane counters — the ``serve.*`` observability surface.

Module-global like ``engine/fusion.py``'s FUSION_STATS and
``io/python.py``'s INGEST_STAGE_STATS: every component of the serve
plane bumps these under a lock, and the observability hub snapshots
them into ``/snapshot`` / ``/query`` documents, the
``pathway_serve_*`` prometheus families, the ``serve.*`` signals
series (which the autoscale decider consumes) and the ``pathway-tpu
top`` serve line.

The snapshot is EMPTY until the serve plane has actually done
something, so expositions of pipelines that never serve stay
byte-identical to the seed's.
"""

from __future__ import annotations

import threading
from typing import Callable

__all__ = [
    "SERVE_STATS",
    "bump",
    "serve_stats_snapshot",
    "register_gauge_provider",
    "unregister_gauge_provider",
    "reset_serve_stats",
]

#: monotone counters; every key ends ``_total`` (the serve_metrics gate
#: checks this — prometheus renders _total keys as counters)
SERVE_STATS: dict[str, int] = {
    #: queries admitted at the edge (one per accepted REST request)
    "queries_total": 0,
    #: queries refused with 429 (saturated: queue at bound)
    "rejected_total": 0,
    #: queries that waited in the admission queue before a slot freed
    "queued_total": 0,
    #: queries dropped at ANY hop because their deadline had passed
    "deadline_dropped_total": 0,
    #: gathers that completed with at least one shard missing
    "degraded_total": 0,
    #: cross-worker scatter posts (one per remote shard per query batch)
    "scatter_posts_total": 0,
    #: per-shard searches executed (local + remote responders)
    "shard_searches_total": 0,
    #: gathers merged into a final result (degraded or not)
    "results_merged_total": 0,
    #: duplicate shard results discarded by correlation-id dedup
    "duplicate_results_total": 0,
    #: admission slots cancelled by client disconnect
    "cancelled_total": 0,
    #: shard responder errors surfaced as failed shards
    "errors_total": 0,
}

_lock = threading.Lock()

#: live-gauge providers (admission controllers, routers) — each returns
#: a {name: value} dict merged into the snapshot; names must NOT end
#: ``_total`` (they are gauges: in-flight, queue depth, pending gathers)
_gauge_providers: list[Callable[[], dict[str, float]]] = []


def bump(key: str, n: int = 1) -> None:
    with _lock:
        SERVE_STATS[key] += n


def register_gauge_provider(fn: Callable[[], dict[str, float]]) -> None:
    with _lock:
        if fn not in _gauge_providers:
            _gauge_providers.append(fn)


def unregister_gauge_provider(fn: Callable[[], dict[str, float]]) -> None:
    with _lock:
        try:
            _gauge_providers.remove(fn)
        except ValueError:
            pass


def serve_stats_snapshot() -> dict[str, float]:
    """Counters + live gauges, or ``{}`` when the serve plane never ran
    (keeps non-serving expositions byte-identical)."""
    with _lock:
        counters = dict(SERVE_STATS)
        providers = list(_gauge_providers)
    if not any(counters.values()) and not providers:
        return {}
    out = {k: float(v) for k, v in counters.items()}
    for fn in providers:
        try:
            for k, v in fn().items():
                out[k] = float(v)
        except Exception:
            # telemetry must not fail the plane it observes
            continue
    return out


def reset_serve_stats() -> None:
    """Test hook: zero the counters and drop gauge providers."""
    with _lock:
        for k in SERVE_STATS:
            SERVE_STATS[k] = 0
        _gauge_providers.clear()
