"""Shard registry: which local worker holds which index shard.

Process-local directory from worker id to the index engine that holds
that worker's hash shard (ownership follows the engine's row-hash
exchange — the same ``shard_rows`` assignment that routes ``("key",)``
exchanges, so rescale/upgrade epochs carry index shards for free).
Each entry pairs the engine with an RLock: the engine node takes it
while mutating (inserts/removals inside a tick), the serve responder
takes it while searching — searches never observe a half-applied tick.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterator

__all__ = ["ShardHandle", "ShardRegistry"]


class ShardHandle:
    __slots__ = ("worker_id", "lock", "_search")

    def __init__(self, worker_id: int, search: Callable):
        self.worker_id = worker_id
        self.lock = threading.RLock()
        self._search = search

    def search(
        self, queries: list[Any], limits: list[int], filters: list[Any]
    ) -> list:
        """Per-query [(key, score), ...] best-first, under the shard
        lock so a concurrent tick's mutation can't interleave."""
        with self.lock:
            return self._search(queries, limits, filters)


class ShardRegistry:
    """One per process (module global via :func:`registry`); keyed by
    (node fingerprint, worker id) so several sharded index nodes in one
    graph don't collide."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._shards: dict[tuple[Any, int], ShardHandle] = {}

    def register(
        self, node_key: Any, worker_id: int, search: Callable
    ) -> ShardHandle:
        """(Re-)register a worker's shard; re-registration (a restarted
        generation, a re-run graph in the same process) replaces the
        stale handle."""
        handle = ShardHandle(worker_id, search)
        with self._lock:
            self._shards[(node_key, worker_id)] = handle
        return handle

    def unregister(self, node_key: Any, worker_id: int) -> None:
        with self._lock:
            self._shards.pop((node_key, worker_id), None)

    def get(self, node_key: Any, worker_id: int) -> ShardHandle | None:
        with self._lock:
            return self._shards.get((node_key, worker_id))

    def local_workers(self, node_key: Any) -> Iterator[int]:
        with self._lock:
            return iter(
                sorted(w for (nk, w) in self._shards if nk == node_key)
            )

    def clear(self) -> None:
        with self._lock:
            self._shards.clear()


_REGISTRY = ShardRegistry()


def registry() -> ShardRegistry:
    return _REGISTRY
