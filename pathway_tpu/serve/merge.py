"""Pure scatter/gather bookkeeping for the serve plane.

A query batch scattered to N shards gathers N per-shard top-k answers;
this module owns the merge and the accounting — no comm, no threads,
no clocks beyond the ``time.time_ns`` deadline arguments it is handed.
The router wraps a :class:`GatherState` per in-flight correlation id
and waits on its event; unit tests drive the same object directly
(duplicate delivery, partial gathers, deadline expiry).

Merging generalizes the single-host gather in ``ops/knn.py``'s
``sharded_knn_search`` (local top-k per shard → global top-k over the
union) to shards that answer over the wire: each shard's candidate
list is already best-first, so the merge is a heap-free concat + sort
over at most ``n_shards * k`` pairs.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Iterable, Sequence

from .stats import bump

__all__ = [
    "merge_topk",
    "GatherState",
    "deadline_from_ms",
    "default_deadline_ms",
    "expired",
]


def default_deadline_ms() -> float:
    """Per-query budget when the client sent no deadline header —
    defaults to the REST edge's historical 120 s wait."""
    from ..internals.config import _env_float

    return max(1.0, _env_float("PATHWAY_SERVE_DEADLINE_MS", 120000.0))


def deadline_from_ms(deadline_ms: float, now_ns: int | None = None) -> int:
    """Absolute wall-clock deadline (ns) a relative budget away."""
    base = time.time_ns() if now_ns is None else now_ns
    return base + int(deadline_ms * 1e6)


def expired(deadline_ns: int | None, now_ns: int | None = None) -> bool:
    if deadline_ns is None:
        return False
    return (time.time_ns() if now_ns is None else now_ns) >= deadline_ns


def merge_topk(
    parts: Iterable[Sequence[tuple[Any, float]]], k: int
) -> list[tuple[Any, float]]:
    """Merge per-shard (key, score) candidate lists into a global
    best-first top-k. Scores compare higher-is-better (the engines
    negate distances). Duplicate keys — a rescale replaying a row into
    two shards' epochs — keep their best score only."""
    best: dict[Any, float] = {}
    for part in parts:
        for key, score in part:
            prev = best.get(key)
            if prev is None or score > prev:
                best[key] = score
    ranked = sorted(best.items(), key=lambda kv: (-kv[1], kv[0]))
    return [(key, score) for key, score in ranked[:k]]


class GatherState:
    """One in-flight scatter: per-shard answers for a batch of queries.

    Thread-safe; the router's dispatcher threads call :meth:`add` /
    :meth:`fail` while the origin blocks on :meth:`wait`. Duplicate
    delivery of a (qid, shard) answer — the serve seam inherits the
    async plane's at-least-once chaos duplication — is dropped by
    correlation-id dedup and counted.
    """

    def __init__(
        self,
        qid: tuple,
        shards: Iterable[int],
        limits: Sequence[int],
        deadline_ns: int | None = None,
    ):
        self.qid = qid
        self.expected = frozenset(shards)
        self.limits = list(limits)
        self.n_queries = len(self.limits)
        self.deadline_ns = deadline_ns
        self._lock = threading.Lock()
        self._event = threading.Event()
        #: shard -> list (per query) of [(key, score), ...] best-first
        self._answers: dict[int, list] = {}
        self._failed: set[int] = set()

    # -- responder side ------------------------------------------------

    def add(self, shard: int, per_query_hits: list) -> bool:
        """Record one shard's answer; returns False on duplicate or
        unexpected shard (dropped, counted)."""
        with self._lock:
            if shard not in self.expected or shard in self._answers:
                bump("duplicate_results_total")
                return False
            self._failed.discard(shard)
            self._answers[shard] = per_query_hits
            done = self._done_locked()
        if done:
            self._event.set()
        return True

    def fail(self, shard: int) -> None:
        """A shard reported an error (or the router knows it is gone):
        the gather completes without it rather than hanging."""
        with self._lock:
            if shard not in self.expected or shard in self._answers:
                return
            self._failed.add(shard)
            done = self._done_locked()
        if done:
            self._event.set()

    def _done_locked(self) -> bool:
        return len(self._answers) + len(self._failed) >= len(self.expected)

    # -- origin side ---------------------------------------------------

    def wait(self, timeout_s: float | None) -> bool:
        """Block until every shard answered/failed, the deadline passed,
        or ``timeout_s`` elapsed; True iff the gather is complete."""
        if timeout_s is not None and self.deadline_ns is not None:
            timeout_s = min(
                timeout_s, max(0.0, (self.deadline_ns - time.time_ns()) / 1e9)
            )
        elif self.deadline_ns is not None:
            timeout_s = max(0.0, (self.deadline_ns - time.time_ns()) / 1e9)
        return self._event.wait(timeout=timeout_s)

    def result(self) -> dict:
        """Merge whatever arrived. Never blocks, never raises: a shard
        that stayed silent is reported in ``missing_shards`` and flips
        ``degraded`` — partial answers over hung gathers."""
        with self._lock:
            answers = dict(self._answers)
            failed = set(self._failed)
        missing = sorted((self.expected - set(answers)) | failed)
        hits = [
            merge_topk(
                (
                    answers[s][q] if q < len(answers[s]) else []
                    for s in answers
                ),
                self.limits[q],
            )
            for q in range(self.n_queries)
        ]
        degraded = bool(missing)
        bump("results_merged_total")
        if degraded:
            bump("degraded_total")
        return {
            "hits": hits,
            "degraded": degraded,
            "missing_shards": missing,
            "deadline_exceeded": expired(self.deadline_ns),
        }
