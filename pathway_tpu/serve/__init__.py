"""Scale-out serve plane: sharded-index query fan-out.

The distributed query subsystem behind ``rest_connector`` serving: each
worker holds an index shard (ownership follows the engine's row-hash
exchange), a query is scattered to every shard as a fire-and-forget
post with a correlation id over the comm serve seam, per-shard top-k
results gather back at the origin and merge best-first. On top sits an
admission controller (bounded in-flight + bounded queue, 429 with
Retry-After on saturation), per-query deadline propagation (expired
queries are dropped at every hop, not just the edge), and graceful
shard-loss degradation (a dead shard yields a partial result flagged
``degraded`` with the missing shard set — never a hung gather).

Modules:

- :mod:`.stats` — ``serve.*`` counters/gauges (hub → prometheus →
  timeseries → top);
- :mod:`.admission` — the bounded in-flight admission controller;
- :mod:`.merge` — pure scatter/gather bookkeeping (top-k merge,
  correlation-id dedup, partial-gather accounting);
- :mod:`.registry` — which local worker holds which index shard;
- :mod:`.router` — the per-process scatter/gather router over the comm
  serve seam;
- :mod:`.status` — process-local per-query degraded/deadline side
  channel between the engine node and the HTTP edge.
"""

from __future__ import annotations

from .admission import AdmissionController
from .merge import GatherState, merge_topk
from .registry import ShardRegistry
from .stats import SERVE_STATS, bump, serve_stats_snapshot

__all__ = [
    "AdmissionController",
    "GatherState",
    "merge_topk",
    "ShardRegistry",
    "SERVE_STATS",
    "bump",
    "serve_stats_snapshot",
]
