"""Process-local per-query side channel between the HTTP edge and the
sharded index node.

The engine's reply column is shape-locked (a tuple of (key, score)
pairs — ``stdlib/indexing/data_index.py`` flattens and repacks it), so
degraded-gather metadata can't ride the dataflow value. But the scatter
origin (worker 0, a ``("gather",)`` query exchange) lives in the SAME
process as the REST edge, and the request key survives unchanged from
``rest_connector`` row to index-node query (``.select`` preserves the
universe). So: the node deposits per-key status here at merge time, the
edge reads it after the future resolves and turns it into the
``X-Pathway-Degraded`` header / ``degraded`` body field; the edge
deposits per-key deadline hints here at admission time, the node reads
them at scatter time. Bounded, self-evicting — an abandoned entry (a
query whose edge died) can't leak.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any

__all__ = [
    "note_deadline",
    "take_deadline",
    "note_status",
    "take_status",
]

_MAX_ENTRIES = 4096

_lock = threading.Lock()
_deadlines: "OrderedDict[Any, int]" = OrderedDict()
_status: "OrderedDict[Any, dict]" = OrderedDict()


def _put(table: OrderedDict, key: Any, value: Any) -> None:
    with _lock:
        table.pop(key, None)
        table[key] = value
        while len(table) > _MAX_ENTRIES:
            table.popitem(last=False)


def _take(table: OrderedDict, key: Any) -> Any:
    with _lock:
        return table.pop(key, None)


def note_deadline(key: Any, deadline_ns: int) -> None:
    """Edge → node: this query's absolute wall-clock deadline (ns)."""
    _put(_deadlines, key, int(deadline_ns))


def take_deadline(key: Any) -> int | None:
    return _take(_deadlines, key)


def note_status(key: Any, status: dict) -> None:
    """Node → edge: gather outcome for this query key —
    ``{"degraded": bool, "missing_shards": [...],
    "deadline_exceeded": bool}``."""
    _put(_status, key, status)


def take_status(key: Any) -> dict | None:
    return _take(_status, key)
