"""The per-process query router: scatter/gather over the comm serve seam.

One router per comm backend (module singleton keyed by the live comm,
weakly — a dead comm's dispatchers unwind on their next poll). The
router runs one dispatcher thread per LOCAL worker, draining that
worker's serve inbox and handling three event kinds, all
fire-and-forget posts with a correlation id:

- ``("q", qid, origin, shard, deadline_ns, limits, node_key)`` —
  a scatter: search shard ``shard``'s registered index, post the
  answer back to ``origin``;
- ``("r", qid, shard)`` — a shard's answer arriving at the origin:
  feed the pending :class:`~pathway_tpu.serve.merge.GatherState`;
- ``("f", qid, shard)`` — a shard declining (error, missing
  registration, expired deadline): the gather completes without it.

Every hop is a ``serve.query`` chaos site (phases scatter / search /
result); a lost event at any hop degrades exactly one gather — the
origin's bounded wait plus :class:`GatherState`'s partial-result
accounting guarantee no query ever hangs on a dead shard.

Query payloads ride the columnar wire codec when they can: a batch of
same-dim vector queries is posted as one ``(n, {"q": stacked})``
PT_COLS frame; anything else (text queries, metadata filters) falls
back to the pickle section, exactly like exchange frames.
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref
from collections import OrderedDict
from typing import Any

import numpy as np

from .merge import GatherState, expired
from .registry import registry
from .stats import bump, register_gauge_provider

__all__ = ["QueryRouter", "get_router", "gather_timeout_s"]

#: dispatcher poll period — also the close()-latency bound
_POLL_S = 0.2

#: responder-side seen-correlation-id window (duplicate scatter drops)
_SEEN_MAX = 4096


def gather_timeout_s() -> float:
    from ..internals.config import _env_float

    return max(
        0.01, _env_float("PATHWAY_SERVE_GATHER_TIMEOUT_MS", 5000.0) / 1e3
    )


def _encode_queries(queries: list, filters: list) -> Any:
    """Columnar when possible: same-dim ndarray batch + no filters →
    the PT_COLS 2-tuple shape frames.py auto-detects."""
    if (
        queries
        and all(f is None for f in filters)
        and all(isinstance(q, np.ndarray) and q.ndim == 1 for q in queries)
        and len({q.shape[0] for q in queries}) == 1
    ):
        return (len(queries), {"q": np.stack(queries)})
    return ("obj", list(queries), list(filters))


def _decode_queries(payload: Any) -> tuple[list, list]:
    if (
        isinstance(payload, tuple)
        and len(payload) == 2
        and isinstance(payload[1], dict)
    ):
        n, cols = payload
        qs = list(cols["q"])
        return qs, [None] * len(qs)
    _tag, queries, filters = payload
    return list(queries), list(filters)


class QueryRouter:
    def __init__(self, comm: Any, n_workers: int):
        self._comm_ref = weakref.ref(comm)
        self.n_workers = n_workers
        local = getattr(comm, "_local_workers", None)
        self.local_workers = (
            sorted(local) if local is not None else list(range(n_workers))
        )
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._pending: dict[tuple, GatherState] = {}
        #: per-worker seen scatter qids (duplicate-delivery dedup)
        self._seen: dict[int, OrderedDict] = {
            w: OrderedDict() for w in self.local_workers
        }
        self._closed = False
        from ..chaos import injector as _chaos

        armed = _chaos.current()
        self._chaos = armed.serve_faults() if armed is not None else None
        self._threads = [
            threading.Thread(
                target=self._dispatch_loop,
                args=(w,),
                daemon=True,
                name=f"pathway-serve-w{w}",
            )
            for w in self.local_workers
        ]
        for t in self._threads:
            t.start()
        register_gauge_provider(self._gauges)

    def _gauges(self) -> dict[str, float]:
        with self._lock:
            return {"pending_gathers": float(len(self._pending))}

    # -- origin side ---------------------------------------------------

    def scatter_search(
        self,
        node_key: Any,
        origin_worker: int,
        queries: list,
        limits: list,
        filters: list,
        deadline_ns: int | None = None,
        timeout_s: float | None = None,
    ) -> dict:
        """Fan a query batch out to every shard, gather, merge.

        Never raises and never hangs: shards that fail, stay silent
        past the gather timeout, or were never reachable come back in
        ``missing_shards`` with ``degraded=True``."""
        shards = list(range(self.n_workers))
        if expired(deadline_ns):
            # dropped at the first hop: the origin never scatters an
            # already-dead query
            bump("deadline_dropped_total")
            return {
                "hits": [[] for _ in queries],
                "degraded": True,
                "missing_shards": shards,
                "deadline_exceeded": True,
            }
        qid = (node_key, origin_worker, next(self._seq))
        g = GatherState(qid, shards, limits, deadline_ns)
        with self._lock:
            self._pending[qid] = g
        payload = _encode_queries(queries, filters)
        meta_base = (qid, origin_worker)
        comm = self._comm_ref()
        try:
            for shard in shards:
                if self._chaos is not None:
                    op = self._chaos.op_for("scatter", shard)
                    if op is not None:
                        action, delay_s = op
                        if action == "drop":
                            continue  # lost scatter: shard goes missing
                        if action == "fail":
                            g.fail(shard)
                            continue
                        if action == "delay":
                            time.sleep(delay_s)
                if comm is None:
                    g.fail(shard)
                    continue
                meta = (
                    "q", qid, origin_worker, shard, deadline_ns,
                    tuple(limits), node_key,
                )
                if comm.serve_post(shard, meta, payload):
                    bump("scatter_posts_total")
                else:
                    g.fail(shard)
        finally:
            del comm
        g.wait(timeout_s if timeout_s is not None else gather_timeout_s())
        with self._lock:
            self._pending.pop(qid, None)
        return g.result()

    # -- dispatcher (responder + gather feed) --------------------------

    def _dispatch_loop(self, worker_id: int) -> None:
        while not self._closed:
            comm = self._comm_ref()
            if comm is None:
                break
            try:
                events = comm.serve_recv(worker_id, timeout_s=_POLL_S)
            except RuntimeError:
                self._fail_all()
                break
            finally:
                del comm
            for meta, payload in events:
                try:
                    self._handle(worker_id, meta, payload)
                except Exception:
                    bump("errors_total")

    def _handle(self, worker_id: int, meta: tuple, payload: Any) -> None:
        kind = meta[0]
        if kind == "q":
            self._handle_query(worker_id, meta, payload)
        elif kind in ("r", "f"):
            _, qid, shard = meta[:3]
            with self._lock:
                g = self._pending.get(qid)
            if g is None:
                return  # late answer for a timed-out gather
            if kind == "r":
                g.add(shard, payload)
            else:
                g.fail(shard)

    def _handle_query(
        self, worker_id: int, meta: tuple, payload: Any
    ) -> None:
        _, qid, origin, shard, deadline_ns, limits, node_key = meta
        comm = self._comm_ref()
        if comm is None:
            return
        seen = self._seen[worker_id]
        if qid in seen:
            # the serve seam inherits the async plane's at-least-once
            # chaos duplication: a re-delivered scatter must not search
            # (or answer) twice
            bump("duplicate_results_total")
            return
        seen[qid] = True
        while len(seen) > _SEEN_MAX:
            seen.popitem(last=False)
        if self._chaos is not None:
            op = self._chaos.op_for("search", shard)
            if op is not None:
                action, delay_s = op
                if action == "drop":
                    return  # silent shard: the origin's timeout degrades
                if action == "fail":
                    bump("errors_total")
                    comm.serve_post(origin, ("f", qid, shard), None)
                    return
                if action == "delay":
                    time.sleep(delay_s)
        if expired(deadline_ns):
            # dropped at the interior hop: no search for a dead query
            bump("deadline_dropped_total")
            comm.serve_post(origin, ("f", qid, shard), None)
            return
        handle = registry().get(node_key, shard)
        if handle is None:
            comm.serve_post(origin, ("f", qid, shard), None)
            return
        try:
            queries, filters = _decode_queries(payload)
            hits = handle.search(queries, list(limits), filters)
            bump("shard_searches_total")
        except Exception:
            bump("errors_total")
            comm.serve_post(origin, ("f", qid, shard), None)
            return
        if self._chaos is not None:
            op = self._chaos.op_for("result", shard)
            if op is not None:
                action, delay_s = op
                if action == "drop":
                    return  # lost answer: origin degrades on timeout
                if action == "fail":
                    bump("errors_total")
                    comm.serve_post(origin, ("f", qid, shard), None)
                    return
                if action == "delay":
                    time.sleep(delay_s)
        comm.serve_post(origin, ("r", qid, shard), hits)

    # -- lifecycle -----------------------------------------------------

    def _fail_all(self) -> None:
        with self._lock:
            pending = list(self._pending.values())
        for g in pending:
            for shard in g.expected:
                g.fail(shard)

    def close(self) -> None:
        self._closed = True
        self._fail_all()


_lock = threading.Lock()
_routers: dict[int, QueryRouter] = {}


def get_router(comm: Any, n_workers: int) -> QueryRouter:
    """The process's router for ``comm``, created on first use. Weakly
    bound: the router never keeps a dead comm alive, and its dispatcher
    threads exit once the comm is collected or the mesh breaks."""
    key = id(comm)
    with _lock:
        r = _routers.get(key)
        if r is not None and r._comm_ref() is comm and not r._closed:
            return r
        r = QueryRouter(comm, n_workers)
        _routers[key] = r

        def _cleanup(_ref: Any, key: int = key) -> None:
            with _lock:
                stale = _routers.pop(key, None)
            if stale is not None:
                stale.close()

        weakref.finalize(comm, _cleanup, None)
        return r
