"""Admission control for the serve plane.

Two bounds, both knob-driven: at most ``PATHWAY_SERVE_MAX_INFLIGHT``
queries execute concurrently, and at most ``PATHWAY_SERVE_QUEUE_BOUND``
more may wait for a slot. A query arriving with the queue at its bound
is REJECTED immediately (the HTTP edge turns that into 429 with a
Retry-After computed from the measured service time), so the
accepted-query tail stays bounded instead of collapsing under overload
— load shedding at the door, not timeouts in the hall.

Pure component: no sockets, no event loop, no clocks it didn't take as
arguments beyond an EWMA of observed service times. The HTTP edge calls
it from executor threads; unit tests drive it directly.
"""

from __future__ import annotations

import threading
from typing import Optional

from .stats import bump

__all__ = ["AdmissionController", "Slot", "shared_controller"]


class Slot:
    """Opaque token for one admitted query (identity-compared)."""

    __slots__ = ("queued",)

    def __init__(self, queued: bool):
        #: whether this query waited for a slot before admission
        self.queued = queued


class AdmissionController:
    def __init__(
        self,
        max_inflight: int | None = None,
        queue_bound: int | None = None,
    ):
        from ..internals.config import _env_int

        self.max_inflight = max(
            1,
            max_inflight
            if max_inflight is not None
            else _env_int("PATHWAY_SERVE_MAX_INFLIGHT", 64),
        )
        self.queue_bound = max(
            0,
            queue_bound
            if queue_bound is not None
            else _env_int("PATHWAY_SERVE_QUEUE_BOUND", 256),
        )
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._inflight = 0
        self._queued = 0
        #: EWMA of observed service time (seconds); seeds Retry-After
        self._ewma_s: float | None = None

    # -- admission -----------------------------------------------------

    def try_admit(self, timeout_s: float | None = None) -> Optional[Slot]:
        """Admit one query, waiting up to ``timeout_s`` for a slot.

        Returns a :class:`Slot` on admission. Returns ``None`` — reject,
        the caller answers 429 — when the wait queue is already at its
        bound, or the wait timed out. ``timeout_s=0`` never queues.
        """
        with self._cond:
            if self._inflight < self.max_inflight:
                self._inflight += 1
                bump("queries_total")
                return Slot(queued=False)
            if self._queued >= self.queue_bound or (
                timeout_s is not None and timeout_s <= 0
            ):
                bump("rejected_total")
                return None
            self._queued += 1
            bump("queued_total")
            try:
                remaining = (
                    threading.TIMEOUT_MAX if timeout_s is None else timeout_s
                )
                import time as _time

                t0 = _time.monotonic()
                while self._inflight >= self.max_inflight:
                    if not self._cond.wait(timeout=remaining):
                        bump("rejected_total")
                        return None
                    if timeout_s is not None:
                        remaining = timeout_s - (_time.monotonic() - t0)
                        if remaining <= 0 and (
                            self._inflight >= self.max_inflight
                        ):
                            bump("rejected_total")
                            return None
                self._inflight += 1
                bump("queries_total")
                return Slot(queued=True)
            finally:
                self._queued -= 1

    def release(self, slot: Slot, service_s: float | None = None) -> None:
        """Return a slot; ``service_s`` feeds the Retry-After estimate."""
        with self._cond:
            self._inflight = max(0, self._inflight - 1)
            if service_s is not None and service_s >= 0:
                self._ewma_s = (
                    service_s
                    if self._ewma_s is None
                    else 0.8 * self._ewma_s + 0.2 * service_s
                )
            self._cond.notify()

    def cancel(self, slot: Slot) -> None:
        """Client disconnected mid-flight: free the slot, count it."""
        bump("cancelled_total")
        self.release(slot)

    # -- advice --------------------------------------------------------

    def retry_after_s(self) -> float:
        """How long a 429'd client should back off: the time for the
        current queue (plus itself) to drain at the measured service
        rate. Never below 50 ms so clients can't busy-retry."""
        with self._lock:
            ewma = self._ewma_s if self._ewma_s is not None else 0.05
            queued = self._queued
        per_slot = ewma / float(self.max_inflight)
        return max(0.05, (queued + 1) * per_slot)

    def gauges(self) -> dict[str, float]:
        with self._lock:
            return {
                "inflight": float(self._inflight),
                "queue_depth": float(self._queued),
                "max_inflight": float(self.max_inflight),
                "queue_bound": float(self.queue_bound),
            }


_shared_lock = threading.Lock()
_shared: AdmissionController | None = None


def shared_controller() -> AdmissionController:
    """The process's edge controller (every REST route shares one slot
    pool); created lazily so the knobs are read at first serve, and
    registered as a gauge provider so its in-flight / queue depth ride
    the ``serve.*`` snapshot."""
    global _shared
    from .stats import register_gauge_provider

    with _shared_lock:
        if _shared is None:
            _shared = AdmissionController()
        # idempotent, and re-arms after a reset_serve_stats() in tests
        register_gauge_provider(_shared.gauges)
        return _shared
