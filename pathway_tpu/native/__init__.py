"""Native runtime loader.

Compiles ``native.c`` (CPython C API — no pybind11 in this environment)
with the system compiler on first import and caches the shared object next
to the source; falls back to pure Python silently when no compiler is
available. The C and Python hash paths are bit-identical (enforced by
tests/test_native.py), so a cache hit/miss never changes key values.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sysconfig

__all__ = ["get_native", "native_available"]

_cached: object | None = None
_tried = False


def _build(src: str, out: str) -> bool:
    include = sysconfig.get_paths()["include"]
    cc = os.environ.get("CC", "gcc")
    cmd = [
        cc, "-O3", "-shared", "-fPIC", "-std=c11",
        f"-I{include}", src, "-o", out,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    return proc.returncode == 0 and os.path.exists(out)


def get_native():
    """The compiled module, or None when unavailable."""
    global _cached, _tried
    if _tried:
        return _cached
    _tried = True
    here = os.path.dirname(__file__)
    src = os.path.join(here, "native.c")
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    out = os.path.join(here, f"_pathway_native{suffix}")
    try:
        if not os.path.exists(out) or os.path.getmtime(out) < os.path.getmtime(src):
            if not _build(src, out):
                return None
        spec = importlib.util.spec_from_file_location("_pathway_native", out)
        assert spec is not None and spec.loader is not None
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        _cached = module
    except Exception:
        _cached = None
    return _cached


def native_available() -> bool:
    return get_native() is not None
