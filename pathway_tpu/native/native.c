/* Native runtime kernels for pathway_tpu.
 *
 * The reference engine's keyspace is native Rust (xxh3 u128 keys,
 * src/engine/value.rs:30-75); this module is our native equivalent for the
 * hot row-ingestion path: batch row hashing with EXACTLY the same scalar
 * semantics as the pure-Python implementation in engine/keys.py
 * (splitmix64 avalanche folds over per-scalar digests; strings/bytes via
 * BLAKE2b-64 as hashlib.blake2b(digest_size=8) produces). Python and C
 * paths are interchangeable bit-for-bit, so persisted state stays valid
 * whichever path built it (guarded by tests/test_native.py).
 *
 * Built with plain g++/gcc against the CPython C API (no pybind11 in this
 * environment) by pathway_tpu/native/__init__.py.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

/* ----------------------------------------------------------------- */
/* BLAKE2b (RFC 7693), fixed config: 8-byte digest, no key           */

static const uint64_t blake2b_iv[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
    0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
    0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL,
};

static const uint8_t blake2b_sigma[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
};

static inline uint64_t rotr64(uint64_t x, int n) {
    return (x >> n) | (x << (64 - n));
}

#define B2B_G(a, b, c, d, x, y)                 \
    do {                                        \
        v[a] = v[a] + v[b] + (x);               \
        v[d] = rotr64(v[d] ^ v[a], 32);         \
        v[c] = v[c] + v[d];                     \
        v[b] = rotr64(v[b] ^ v[c], 24);         \
        v[a] = v[a] + v[b] + (y);               \
        v[d] = rotr64(v[d] ^ v[a], 16);         \
        v[c] = v[c] + v[d];                     \
        v[b] = rotr64(v[b] ^ v[c], 63);         \
    } while (0)

static void blake2b_compress(uint64_t h[8], const uint8_t block[128],
                             uint64_t t, int last) {
    uint64_t v[16], m[16];
    int i, r;
    for (i = 0; i < 8; i++) v[i] = h[i];
    for (i = 0; i < 8; i++) v[i + 8] = blake2b_iv[i];
    v[12] ^= t; /* low counter word; inputs here are < 2^64 bytes */
    if (last) v[14] = ~v[14];
    for (i = 0; i < 16; i++) memcpy(&m[i], block + 8 * i, 8);
    for (r = 0; r < 12; r++) {
        const uint8_t *s = blake2b_sigma[r];
        B2B_G(0, 4, 8, 12, m[s[0]], m[s[1]]);
        B2B_G(1, 5, 9, 13, m[s[2]], m[s[3]]);
        B2B_G(2, 6, 10, 14, m[s[4]], m[s[5]]);
        B2B_G(3, 7, 11, 15, m[s[6]], m[s[7]]);
        B2B_G(0, 5, 10, 15, m[s[8]], m[s[9]]);
        B2B_G(1, 6, 11, 12, m[s[10]], m[s[11]]);
        B2B_G(2, 7, 8, 13, m[s[12]], m[s[13]]);
        B2B_G(3, 4, 9, 14, m[s[14]], m[s[15]]);
    }
    for (i = 0; i < 8; i++) h[i] ^= v[i] ^ v[i + 8];
}

/* 8-byte BLAKE2b digest of data, as little-endian uint64 (the exact value
 * of int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), 'little')) */
static uint64_t blake2b8(const uint8_t *data, Py_ssize_t len) {
    uint64_t h[8];
    uint8_t block[128];
    Py_ssize_t remaining = len, off = 0;
    memcpy(h, blake2b_iv, sizeof(h));
    h[0] ^= 0x01010000ULL ^ 8ULL; /* digest_size=8, no key, fanout=depth=1 */
    while (remaining > 128) {
        blake2b_compress(h, data + off, (uint64_t)(off + 128), 0);
        off += 128;
        remaining -= 128;
    }
    memset(block, 0, sizeof(block));
    if (remaining > 0) memcpy(block, data + off, (size_t)remaining);
    blake2b_compress(h, block, (uint64_t)len, 1);
    return h[0];
}

/* ----------------------------------------------------------------- */
/* splitmix64 finalizer — must match keys._splitmix exactly           */

static inline uint64_t splitmix(uint64_t x) {
    x += 0x9E3779B97F4A7C15ULL;
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
    x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
    return x ^ (x >> 31);
}

#define NONE_TAG 0x736E6F6E65736E6FULL
#define TUPLE_SEED 0x9E37ULL
#define ROW_SEED 0xA0761D6478BD642FULL

/* hash one scalar with keys._hash_scalar semantics; `fallback` is the
 * Python implementation used for types this C path doesn't know
 * (ndarrays, datetimes, Json wrappers, ...). Returns 0 + sets err on
 * failure. */
static int hash_scalar(PyObject *v, PyObject *fallback, uint64_t *out) {
    if (v == Py_None) {
        *out = NONE_TAG;
        return 0;
    }
    if (PyBool_Check(v)) {
        *out = splitmix((v == Py_True ? 1ULL : 0ULL) + 0xB001ULL);
        return 0;
    }
    if (PyLong_CheckExact(v)) {
        uint64_t x = PyLong_AsUnsignedLongLongMask(v); /* low 64 bits */
        if (x == (uint64_t)-1 && PyErr_Occurred()) return -1;
        *out = splitmix(x);
        return 0;
    }
    if (PyFloat_CheckExact(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        uint64_t bits;
        memcpy(&bits, &d, 8);
        *out = splitmix(bits);
        return 0;
    }
    if (PyUnicode_CheckExact(v)) {
        Py_ssize_t len;
        const char *utf8 = PyUnicode_AsUTF8AndSize(v, &len);
        if (utf8 == NULL) return -1;
        *out = blake2b8((const uint8_t *)utf8, len);
        return 0;
    }
    if (PyBytes_CheckExact(v)) {
        *out = blake2b8((const uint8_t *)PyBytes_AS_STRING(v),
                        PyBytes_GET_SIZE(v));
        return 0;
    }
    if (PyTuple_CheckExact(v)) {
        uint64_t acc = TUPLE_SEED, h;
        Py_ssize_t i, n = PyTuple_GET_SIZE(v);
        for (i = 0; i < n; i++) {
            if (hash_scalar(PyTuple_GET_ITEM(v, i), fallback, &h) < 0)
                return -1;
            acc = splitmix(acc ^ h);
        }
        *out = acc;
        return 0;
    }
    /* numpy scalars, ndarrays, datetimes, wrappers: defer to Python */
    {
        PyObject *res = PyObject_CallFunctionObjArgs(fallback, v, NULL);
        uint64_t x;
        if (res == NULL) return -1;
        x = PyLong_AsUnsignedLongLongMask(res);
        Py_DECREF(res);
        if (x == (uint64_t)-1 && PyErr_Occurred()) return -1;
        *out = x;
        return 0;
    }
}

/* hash_rows(rows: sequence of tuples, salt: int, fallback, out: writable
 * uint64 buffer of len(rows)) -> None */
static PyObject *py_hash_rows(PyObject *self, PyObject *args) {
    PyObject *rows, *fallback, *out_obj;
    unsigned long long salt;
    Py_buffer out;
    (void)self;
    if (!PyArg_ParseTuple(args, "OKOO", &rows, &salt, &fallback, &out_obj))
        return NULL;
    if (PyObject_GetBuffer(out_obj, &out, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0)
        return NULL;
    {
        PyObject *seq = PySequence_Fast(rows, "rows must be a sequence");
        Py_ssize_t n, i;
        uint64_t *dst = (uint64_t *)out.buf;
        if (seq == NULL) {
            PyBuffer_Release(&out);
            return NULL;
        }
        n = PySequence_Fast_GET_SIZE(seq);
        if ((Py_ssize_t)(out.len / 8) < n) {
            Py_DECREF(seq);
            PyBuffer_Release(&out);
            PyErr_SetString(PyExc_ValueError, "output buffer too small");
            return NULL;
        }
        for (i = 0; i < n; i++) {
            PyObject *row = PySequence_Fast_GET_ITEM(seq, i);
            uint64_t acc = ROW_SEED ^ (uint64_t)salt, h;
            Py_ssize_t j, m;
            PyObject *rowseq = PySequence_Fast(row, "row must be a sequence");
            if (rowseq == NULL) {
                Py_DECREF(seq);
                PyBuffer_Release(&out);
                return NULL;
            }
            m = PySequence_Fast_GET_SIZE(rowseq);
            for (j = 0; j < m; j++) {
                if (hash_scalar(PySequence_Fast_GET_ITEM(rowseq, j),
                                fallback, &h) < 0) {
                    Py_DECREF(rowseq);
                    Py_DECREF(seq);
                    PyBuffer_Release(&out);
                    return NULL;
                }
                acc = splitmix(acc ^ h);
            }
            Py_DECREF(rowseq);
            dst[i] = acc;
        }
        Py_DECREF(seq);
    }
    PyBuffer_Release(&out);
    Py_RETURN_NONE;
}

/* hash_scalars(values: sequence, fallback, out: writable uint64 buffer)
 * -> None — per-element hash_scalar (group-key/hash_column hot path) */
static PyObject *py_hash_scalars(PyObject *self, PyObject *args) {
    PyObject *values, *fallback, *out_obj;
    Py_buffer out;
    (void)self;
    if (!PyArg_ParseTuple(args, "OOO", &values, &fallback, &out_obj))
        return NULL;
    if (PyObject_GetBuffer(out_obj, &out, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0)
        return NULL;
    {
        PyObject *seq = PySequence_Fast(values, "values must be a sequence");
        Py_ssize_t n, i;
        uint64_t *dst = (uint64_t *)out.buf;
        if (seq == NULL) {
            PyBuffer_Release(&out);
            return NULL;
        }
        n = PySequence_Fast_GET_SIZE(seq);
        if ((Py_ssize_t)(out.len / 8) < n) {
            Py_DECREF(seq);
            PyBuffer_Release(&out);
            PyErr_SetString(PyExc_ValueError, "output buffer too small");
            return NULL;
        }
        for (i = 0; i < n; i++) {
            if (hash_scalar(PySequence_Fast_GET_ITEM(seq, i), fallback,
                            &dst[i]) < 0) {
                Py_DECREF(seq);
                PyBuffer_Release(&out);
                return NULL;
            }
        }
        Py_DECREF(seq);
    }
    PyBuffer_Release(&out);
    Py_RETURN_NONE;
}

/* blake2b8(data: bytes-like) -> int — exposed for parity tests */
static PyObject *py_blake2b8(PyObject *self, PyObject *arg) {
    Py_buffer buf;
    uint64_t h;
    (void)self;
    if (PyObject_GetBuffer(arg, &buf, PyBUF_C_CONTIGUOUS) < 0) return NULL;
    h = blake2b8((const uint8_t *)buf.buf, buf.len);
    PyBuffer_Release(&buf);
    return PyLong_FromUnsignedLongLong(h);
}

/* splitmix64(x: int) -> int — exposed for parity tests */
static PyObject *py_splitmix(PyObject *self, PyObject *arg) {
    unsigned long long x = PyLong_AsUnsignedLongLongMask(arg);
    (void)self;
    if (x == (unsigned long long)-1 && PyErr_Occurred()) return NULL;
    return PyLong_FromUnsignedLongLong(splitmix(x));
}

/* ----------------------------------------------------------------- */
/* KeyTable — open-addressing uint64 -> slot map with batch lookups.  */
/* Powers the dense groupby arena and join state: slot ids are dense  */
/* row indices into columnar (numpy) state arrays, so per-key state   */
/* updates become vectorized array ops instead of Python dict churn   */
/* (the role differential arrangements play in the reference).        */

typedef struct {
    PyObject_HEAD
    uint64_t *keys;
    int64_t *slots;
    uint8_t *used;
    Py_ssize_t capacity; /* power of two */
    Py_ssize_t size;
    int64_t next_slot;
} KeyTableObject;

static int keytable_grow(KeyTableObject *t, Py_ssize_t min_capacity) {
    Py_ssize_t new_cap = t->capacity ? t->capacity : 64;
    uint64_t *nk;
    int64_t *ns;
    uint8_t *nu;
    Py_ssize_t i;
    while (new_cap < min_capacity) new_cap <<= 1;
    nk = (uint64_t *)malloc((size_t)new_cap * 8);
    ns = (int64_t *)malloc((size_t)new_cap * 8);
    nu = (uint8_t *)calloc((size_t)new_cap, 1);
    if (!nk || !ns || !nu) {
        free(nk); free(ns); free(nu);
        PyErr_NoMemory();
        return -1;
    }
    for (i = 0; i < t->capacity; i++) {
        if (t->used[i]) {
            uint64_t h = splitmix(t->keys[i]);
            Py_ssize_t j = (Py_ssize_t)(h & (uint64_t)(new_cap - 1));
            while (nu[j]) j = (j + 1) & (new_cap - 1);
            nu[j] = 1;
            nk[j] = t->keys[i];
            ns[j] = t->slots[i];
        }
    }
    free(t->keys); free(t->slots); free(t->used);
    t->keys = nk; t->slots = ns; t->used = nu;
    t->capacity = new_cap;
    return 0;
}

/* lookup_or_insert(keys: uint64 buffer, out: int64 buffer) -> n_new */
static PyObject *keytable_lookup_or_insert(PyObject *self, PyObject *args) {
    KeyTableObject *t = (KeyTableObject *)self;
    PyObject *keys_obj, *out_obj;
    Py_buffer keys, out;
    Py_ssize_t n, i, n_new = 0;
    if (!PyArg_ParseTuple(args, "OO", &keys_obj, &out_obj)) return NULL;
    if (PyObject_GetBuffer(keys_obj, &keys, PyBUF_C_CONTIGUOUS) < 0) return NULL;
    if (PyObject_GetBuffer(out_obj, &out, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0) {
        PyBuffer_Release(&keys);
        return NULL;
    }
    n = keys.len / 8;
    if (out.len / 8 < n) {
        PyBuffer_Release(&keys); PyBuffer_Release(&out);
        PyErr_SetString(PyExc_ValueError, "output buffer too small");
        return NULL;
    }
    /* worst case inserts all n keys; keep load factor under 0.7 */
    if ((t->size + n) * 10 >= t->capacity * 7) {
        if (keytable_grow(t, (t->size + n) * 2) < 0) {
            PyBuffer_Release(&keys); PyBuffer_Release(&out);
            return NULL;
        }
    }
    {
        const uint64_t *src = (const uint64_t *)keys.buf;
        int64_t *dst = (int64_t *)out.buf;
        uint64_t mask = (uint64_t)(t->capacity - 1);
        for (i = 0; i < n; i++) {
            uint64_t k = src[i];
            Py_ssize_t j = (Py_ssize_t)(splitmix(k) & mask);
            while (t->used[j] && t->keys[j] != k) j = (j + 1) & mask;
            if (!t->used[j]) {
                t->used[j] = 1;
                t->keys[j] = k;
                t->slots[j] = t->next_slot++;
                t->size++;
                n_new++;
            }
            dst[i] = t->slots[j];
        }
    }
    PyBuffer_Release(&keys);
    PyBuffer_Release(&out);
    return PyLong_FromSsize_t(n_new);
}

/* lookup(keys: uint64 buffer, out: int64 buffer) -> None; missing = -1 */
static PyObject *keytable_lookup(PyObject *self, PyObject *args) {
    KeyTableObject *t = (KeyTableObject *)self;
    PyObject *keys_obj, *out_obj;
    Py_buffer keys, out;
    Py_ssize_t n, i;
    if (!PyArg_ParseTuple(args, "OO", &keys_obj, &out_obj)) return NULL;
    if (PyObject_GetBuffer(keys_obj, &keys, PyBUF_C_CONTIGUOUS) < 0) return NULL;
    if (PyObject_GetBuffer(out_obj, &out, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0) {
        PyBuffer_Release(&keys);
        return NULL;
    }
    n = keys.len / 8;
    if (out.len / 8 < n) {
        PyBuffer_Release(&keys); PyBuffer_Release(&out);
        PyErr_SetString(PyExc_ValueError, "output buffer too small");
        return NULL;
    }
    if (t->capacity == 0) {
        int64_t *dst = (int64_t *)out.buf;
        for (i = 0; i < n; i++) dst[i] = -1;
    } else {
        const uint64_t *src = (const uint64_t *)keys.buf;
        int64_t *dst = (int64_t *)out.buf;
        uint64_t mask = (uint64_t)(t->capacity - 1);
        for (i = 0; i < n; i++) {
            uint64_t k = src[i];
            Py_ssize_t j = (Py_ssize_t)(splitmix(k) & mask);
            while (t->used[j] && t->keys[j] != k) j = (j + 1) & mask;
            dst[i] = t->used[j] ? t->slots[j] : -1;
        }
    }
    PyBuffer_Release(&keys);
    PyBuffer_Release(&out);
    Py_RETURN_NONE;
}

static Py_ssize_t keytable_len(PyObject *self) {
    return ((KeyTableObject *)self)->size;
}

static void keytable_dealloc(PyObject *self) {
    KeyTableObject *t = (KeyTableObject *)self;
    free(t->keys); free(t->slots); free(t->used);
    Py_TYPE(self)->tp_free(self);
}

static PyObject *keytable_new(PyTypeObject *type, PyObject *args,
                              PyObject *kwds) {
    KeyTableObject *t;
    (void)args; (void)kwds;
    t = (KeyTableObject *)type->tp_alloc(type, 0);
    if (t == NULL) return NULL;
    t->keys = NULL; t->slots = NULL; t->used = NULL;
    t->capacity = 0; t->size = 0; t->next_slot = 0;
    return (PyObject *)t;
}

static PyMethodDef keytable_methods[] = {
    {"lookup_or_insert", keytable_lookup_or_insert, METH_VARARGS,
     "lookup_or_insert(keys_u64, out_i64) -> n_new"},
    {"lookup", keytable_lookup, METH_VARARGS,
     "lookup(keys_u64, out_i64); missing -> -1"},
    {NULL, NULL, 0, NULL},
};

static PySequenceMethods keytable_as_sequence = {
    keytable_len, /* sq_length */
};

static PyTypeObject KeyTableType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_pathway_native.KeyTable",
    .tp_basicsize = sizeof(KeyTableObject),
    .tp_dealloc = keytable_dealloc,
    .tp_as_sequence = &keytable_as_sequence,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "open-addressing uint64 -> dense slot map (batch API)",
    .tp_methods = keytable_methods,
    .tp_new = keytable_new,
};

static PyMethodDef methods[] = {
    {"hash_rows", py_hash_rows, METH_VARARGS,
     "hash_rows(rows, salt, fallback, out_uint64_buffer)"},
    {"hash_scalars", py_hash_scalars, METH_VARARGS,
     "hash_scalars(values, fallback, out_uint64_buffer)"},
    {"blake2b8", py_blake2b8, METH_O, "8-byte BLAKE2b digest as uint64"},
    {"splitmix64", py_splitmix, METH_O, "splitmix64 finalizer"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_pathway_native",
    "Native keyspace kernels for pathway_tpu", -1, methods,
    NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC PyInit__pathway_native(void) {
    PyObject *m;
    if (PyType_Ready(&KeyTableType) < 0) return NULL;
    m = PyModule_Create(&module);
    if (m == NULL) return NULL;
    Py_INCREF(&KeyTableType);
    if (PyModule_AddObject(m, "KeyTable", (PyObject *)&KeyTableType) < 0) {
        Py_DECREF(&KeyTableType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
