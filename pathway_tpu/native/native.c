/* Native runtime kernels for pathway_tpu.
 *
 * The reference engine's keyspace is native Rust (xxh3 u128 keys,
 * src/engine/value.rs:30-75); this module is our native equivalent for the
 * hot row-ingestion path: batch row hashing with EXACTLY the same scalar
 * semantics as the pure-Python implementation in engine/keys.py
 * (splitmix64 avalanche folds over per-scalar digests; strings/bytes via
 * BLAKE2b-64 as hashlib.blake2b(digest_size=8) produces). Python and C
 * paths are interchangeable bit-for-bit, so persisted state stays valid
 * whichever path built it (guarded by tests/test_native.py).
 *
 * Built with plain g++/gcc against the CPython C API (no pybind11 in this
 * environment) by pathway_tpu/native/__init__.py.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

/* ----------------------------------------------------------------- */
/* BLAKE2b (RFC 7693), fixed config: 8-byte digest, no key           */

static const uint64_t blake2b_iv[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
    0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
    0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL,
};

static const uint8_t blake2b_sigma[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
};

static inline uint64_t rotr64(uint64_t x, int n) {
    return (x >> n) | (x << (64 - n));
}

#define B2B_G(a, b, c, d, x, y)                 \
    do {                                        \
        v[a] = v[a] + v[b] + (x);               \
        v[d] = rotr64(v[d] ^ v[a], 32);         \
        v[c] = v[c] + v[d];                     \
        v[b] = rotr64(v[b] ^ v[c], 24);         \
        v[a] = v[a] + v[b] + (y);               \
        v[d] = rotr64(v[d] ^ v[a], 16);         \
        v[c] = v[c] + v[d];                     \
        v[b] = rotr64(v[b] ^ v[c], 63);         \
    } while (0)

static void blake2b_compress(uint64_t h[8], const uint8_t block[128],
                             uint64_t t, int last) {
    uint64_t v[16], m[16];
    int i, r;
    for (i = 0; i < 8; i++) v[i] = h[i];
    for (i = 0; i < 8; i++) v[i + 8] = blake2b_iv[i];
    v[12] ^= t; /* low counter word; inputs here are < 2^64 bytes */
    if (last) v[14] = ~v[14];
    for (i = 0; i < 16; i++) memcpy(&m[i], block + 8 * i, 8);
    for (r = 0; r < 12; r++) {
        const uint8_t *s = blake2b_sigma[r];
        B2B_G(0, 4, 8, 12, m[s[0]], m[s[1]]);
        B2B_G(1, 5, 9, 13, m[s[2]], m[s[3]]);
        B2B_G(2, 6, 10, 14, m[s[4]], m[s[5]]);
        B2B_G(3, 7, 11, 15, m[s[6]], m[s[7]]);
        B2B_G(0, 5, 10, 15, m[s[8]], m[s[9]]);
        B2B_G(1, 6, 11, 12, m[s[10]], m[s[11]]);
        B2B_G(2, 7, 8, 13, m[s[12]], m[s[13]]);
        B2B_G(3, 4, 9, 14, m[s[14]], m[s[15]]);
    }
    for (i = 0; i < 8; i++) h[i] ^= v[i] ^ v[i + 8];
}

/* 8-byte BLAKE2b digest of data, as little-endian uint64 (the exact value
 * of int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), 'little')) */
static uint64_t blake2b8(const uint8_t *data, Py_ssize_t len) {
    uint64_t h[8];
    uint8_t block[128];
    Py_ssize_t remaining = len, off = 0;
    memcpy(h, blake2b_iv, sizeof(h));
    h[0] ^= 0x01010000ULL ^ 8ULL; /* digest_size=8, no key, fanout=depth=1 */
    while (remaining > 128) {
        blake2b_compress(h, data + off, (uint64_t)(off + 128), 0);
        off += 128;
        remaining -= 128;
    }
    memset(block, 0, sizeof(block));
    if (remaining > 0) memcpy(block, data + off, (size_t)remaining);
    blake2b_compress(h, block, (uint64_t)len, 1);
    return h[0];
}

/* second 8 bytes (little-endian) of hashlib.blake2b(data, digest_size=16)
 * — the HI key lane for strings/bytes. A separate digest from blake2b8:
 * the blake2b parameter block folds the digest length into h[0], so the
 * 16-byte digest is independent of the 8-byte one (the lanes must not be
 * derivable from each other or low-lane collisions would always agree on
 * the high lane and conflation detection could never fire). */
static uint64_t blake2b16hi(const uint8_t *data, Py_ssize_t len) {
    uint64_t h[8];
    uint8_t block[128];
    Py_ssize_t remaining = len, off = 0;
    memcpy(h, blake2b_iv, sizeof(h));
    h[0] ^= 0x01010000ULL ^ 16ULL; /* digest_size=16 */
    while (remaining > 128) {
        blake2b_compress(h, data + off, (uint64_t)(off + 128), 0);
        off += 128;
        remaining -= 128;
    }
    memset(block, 0, sizeof(block));
    if (remaining > 0) memcpy(block, data + off, (size_t)remaining);
    blake2b_compress(h, block, (uint64_t)len, 1);
    return h[1];
}

/* ----------------------------------------------------------------- */
/* splitmix64 finalizer — must match keys._splitmix exactly           */

static inline uint64_t splitmix(uint64_t x) {
    x += 0x9E3779B97F4A7C15ULL;
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
    x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
    return x ^ (x >> 31);
}

#define NONE_TAG 0x736E6F6E65736E6FULL
#define TUPLE_SEED 0x9E37ULL
#define ROW_SEED 0xA0761D6478BD642FULL

/* HI key lane (the upper 64 bits of the 128-bit keyspace): same scalar
 * taxonomy as the LO lane but mixed with an independent finalizer
 * (moremur constants) so the lanes never co-collide. Must match
 * keys._hash_scalar_hi / keys._splitmix2 bit-for-bit. */
#define NONE_TAG_HI 0x6E6F6E655F686921ULL
#define TUPLE_SEED_HI 0xD1B5ULL
#define ROW_SEED_HI 0xE7037ED1A0B428DBULL

static inline uint64_t splitmix2(uint64_t x) {
    x += 0xD1B54A32D192ED03ULL;
    x = (x ^ (x >> 32)) * 0xAEF17502108EF2D9ULL;
    x = (x ^ (x >> 29)) * 0xD1342543DE82EF95ULL;
    return x ^ (x >> 32);
}

/* hash one scalar with keys._hash_scalar semantics; `fallback` is the
 * Python implementation used for types this C path doesn't know
 * (ndarrays, datetimes, Json wrappers, ...). Returns 0 + sets err on
 * failure. */
static int hash_scalar(PyObject *v, PyObject *fallback, uint64_t *out) {
    if (v == Py_None) {
        *out = NONE_TAG;
        return 0;
    }
    if (PyBool_Check(v)) {
        *out = splitmix((v == Py_True ? 1ULL : 0ULL) + 0xB001ULL);
        return 0;
    }
    if (PyLong_CheckExact(v)) {
        uint64_t x = PyLong_AsUnsignedLongLongMask(v); /* low 64 bits */
        if (x == (uint64_t)-1 && PyErr_Occurred()) return -1;
        *out = splitmix(x);
        return 0;
    }
    if (PyFloat_CheckExact(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        uint64_t bits;
        memcpy(&bits, &d, 8);
        *out = splitmix(bits);
        return 0;
    }
    if (PyUnicode_CheckExact(v)) {
        Py_ssize_t len;
        const char *utf8 = PyUnicode_AsUTF8AndSize(v, &len);
        if (utf8 == NULL) return -1;
        *out = blake2b8((const uint8_t *)utf8, len);
        return 0;
    }
    if (PyBytes_CheckExact(v)) {
        *out = blake2b8((const uint8_t *)PyBytes_AS_STRING(v),
                        PyBytes_GET_SIZE(v));
        return 0;
    }
    if (PyTuple_CheckExact(v)) {
        uint64_t acc = TUPLE_SEED, h;
        Py_ssize_t i, n = PyTuple_GET_SIZE(v);
        for (i = 0; i < n; i++) {
            if (hash_scalar(PyTuple_GET_ITEM(v, i), fallback, &h) < 0)
                return -1;
            acc = splitmix(acc ^ h);
        }
        *out = acc;
        return 0;
    }
    /* numpy scalars, ndarrays, datetimes, wrappers: defer to Python */
    {
        PyObject *res = PyObject_CallFunctionObjArgs(fallback, v, NULL);
        uint64_t x;
        if (res == NULL) return -1;
        x = PyLong_AsUnsignedLongLongMask(res);
        Py_DECREF(res);
        if (x == (uint64_t)-1 && PyErr_Occurred()) return -1;
        *out = x;
        return 0;
    }
}

/* hash one scalar on BOTH key lanes. fb_lo/fb_hi are the Python fallback
 * implementations for types this C path doesn't know. */
static int hash_scalar2(PyObject *v, PyObject *fb_lo, PyObject *fb_hi,
                        uint64_t *lo, uint64_t *hi) {
    if (v == Py_None) {
        *lo = NONE_TAG;
        *hi = NONE_TAG_HI;
        return 0;
    }
    if (PyBool_Check(v)) {
        uint64_t x = (v == Py_True ? 1ULL : 0ULL) + 0xB001ULL;
        *lo = splitmix(x);
        *hi = splitmix2(x);
        return 0;
    }
    if (PyLong_CheckExact(v)) {
        uint64_t x = PyLong_AsUnsignedLongLongMask(v);
        if (x == (uint64_t)-1 && PyErr_Occurred()) return -1;
        *lo = splitmix(x);
        *hi = splitmix2(x);
        return 0;
    }
    if (PyFloat_CheckExact(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        uint64_t bits;
        memcpy(&bits, &d, 8);
        *lo = splitmix(bits);
        *hi = splitmix2(bits);
        return 0;
    }
    if (PyUnicode_CheckExact(v)) {
        Py_ssize_t len;
        const char *utf8 = PyUnicode_AsUTF8AndSize(v, &len);
        if (utf8 == NULL) return -1;
        *lo = blake2b8((const uint8_t *)utf8, len);
        *hi = blake2b16hi((const uint8_t *)utf8, len);
        return 0;
    }
    if (PyBytes_CheckExact(v)) {
        *lo = blake2b8((const uint8_t *)PyBytes_AS_STRING(v),
                       PyBytes_GET_SIZE(v));
        *hi = blake2b16hi((const uint8_t *)PyBytes_AS_STRING(v),
                          PyBytes_GET_SIZE(v));
        return 0;
    }
    if (PyTuple_CheckExact(v)) {
        uint64_t acc_lo = TUPLE_SEED, acc_hi = TUPLE_SEED_HI, l, h;
        Py_ssize_t i, n = PyTuple_GET_SIZE(v);
        for (i = 0; i < n; i++) {
            if (hash_scalar2(PyTuple_GET_ITEM(v, i), fb_lo, fb_hi, &l, &h) < 0)
                return -1;
            acc_lo = splitmix(acc_lo ^ l);
            acc_hi = splitmix2(acc_hi ^ h);
        }
        *lo = acc_lo;
        *hi = acc_hi;
        return 0;
    }
    {
        PyObject *res = PyObject_CallFunctionObjArgs(fb_lo, v, NULL);
        uint64_t x;
        if (res == NULL) return -1;
        x = PyLong_AsUnsignedLongLongMask(res);
        Py_DECREF(res);
        if (x == (uint64_t)-1 && PyErr_Occurred()) return -1;
        *lo = x;
        res = PyObject_CallFunctionObjArgs(fb_hi, v, NULL);
        if (res == NULL) return -1;
        x = PyLong_AsUnsignedLongLongMask(res);
        Py_DECREF(res);
        if (x == (uint64_t)-1 && PyErr_Occurred()) return -1;
        *hi = x;
        return 0;
    }
}

#define STR_MEMO_CAP 65536

/* memoized two-lane hash of an exact str: the stream hot path hashes the
 * same (equal-valued) words every tick — a dict probe (~40ns) replaces
 * two BLAKE2b digests (~600ns). memo may be NULL. */
static int hash_scalar2_memo(PyObject *v, PyObject *fb_lo, PyObject *fb_hi,
                             PyObject *memo, uint64_t *lo, uint64_t *hi) {
    PyObject *hit, *pair, *plo, *phi;
    if (memo == NULL || !PyUnicode_CheckExact(v))
        return hash_scalar2(v, fb_lo, fb_hi, lo, hi);
    hit = PyDict_GetItemWithError(memo, v); /* borrowed */
    if (hit != NULL) {
        *lo = PyLong_AsUnsignedLongLongMask(PyTuple_GET_ITEM(hit, 0));
        *hi = PyLong_AsUnsignedLongLongMask(PyTuple_GET_ITEM(hit, 1));
        return 0;
    }
    if (PyErr_Occurred()) return -1;
    if (hash_scalar2(v, fb_lo, fb_hi, lo, hi) < 0) return -1;
    if (PyDict_GET_SIZE(memo) >= STR_MEMO_CAP) PyDict_Clear(memo);
    plo = PyLong_FromUnsignedLongLong(*lo);
    phi = PyLong_FromUnsignedLongLong(*hi);
    if (plo == NULL || phi == NULL) {
        Py_XDECREF(plo); Py_XDECREF(phi);
        return -1;
    }
    pair = PyTuple_Pack(2, plo, phi);
    Py_DECREF(plo); Py_DECREF(phi);
    if (pair == NULL) return -1;
    if (PyDict_SetItem(memo, v, pair) < 0) {
        Py_DECREF(pair);
        return -1;
    }
    Py_DECREF(pair);
    return 0;
}

/* hash_scalars2(values, fb_lo, fb_hi, memo_or_None,
 *               out_lo_u64, out_hi_u64) -> None */
static PyObject *py_hash_scalars2(PyObject *self, PyObject *args) {
    PyObject *values, *fb_lo, *fb_hi, *memo, *lo_obj, *hi_obj;
    Py_buffer lo, hi;
    (void)self;
    if (!PyArg_ParseTuple(args, "OOOOOO", &values, &fb_lo, &fb_hi, &memo,
                          &lo_obj, &hi_obj))
        return NULL;
    if (memo == Py_None) memo = NULL;
    if (PyObject_GetBuffer(lo_obj, &lo, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0)
        return NULL;
    if (PyObject_GetBuffer(hi_obj, &hi, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0) {
        PyBuffer_Release(&lo);
        return NULL;
    }
    {
        PyObject *seq = PySequence_Fast(values, "values must be a sequence");
        Py_ssize_t n, i;
        uint64_t *dlo = (uint64_t *)lo.buf, *dhi = (uint64_t *)hi.buf;
        if (seq == NULL) goto fail;
        n = PySequence_Fast_GET_SIZE(seq);
        if ((Py_ssize_t)(lo.len / 8) < n || (Py_ssize_t)(hi.len / 8) < n) {
            Py_DECREF(seq);
            PyErr_SetString(PyExc_ValueError, "output buffer too small");
            goto fail;
        }
        for (i = 0; i < n; i++) {
            if (hash_scalar2_memo(PySequence_Fast_GET_ITEM(seq, i), fb_lo,
                                  fb_hi, memo, &dlo[i], &dhi[i]) < 0) {
                Py_DECREF(seq);
                goto fail;
            }
        }
        Py_DECREF(seq);
    }
    PyBuffer_Release(&lo);
    PyBuffer_Release(&hi);
    Py_RETURN_NONE;
fail:
    PyBuffer_Release(&lo);
    PyBuffer_Release(&hi);
    return NULL;
}

/* mix_cols2(cols, n, salt_lo, salt_hi, fb_lo, fb_hi, memo_or_None,
 *           out_lo_u64, out_hi_u64) -> None
 * Fused column-key fold for the columnar ingest plane: accumulate every
 * OBJECT column of a batch into both key lanes in one C pass —
 * out[i] starts at ROW_SEED ^ salt and folds splitmix(acc ^ lane(v))
 * per column, which is keys.mix_columns' per-column _column_lanes fold
 * (and therefore hash_rows2 over the corresponding row tuples)
 * bit-for-bit, without materializing per-column lane arrays or row
 * tuples. Strings ride the same value-level memo as hash_rows2. */
static PyObject *py_mix_cols2(PyObject *self, PyObject *args) {
    PyObject *cols, *fb_lo, *fb_hi, *memo, *lo_obj, *hi_obj;
    unsigned long long salt_lo, salt_hi;
    Py_ssize_t n;
    Py_buffer lo, hi;
    (void)self;
    if (!PyArg_ParseTuple(args, "OnKKOOOOO", &cols, &n, &salt_lo, &salt_hi,
                          &fb_lo, &fb_hi, &memo, &lo_obj, &hi_obj))
        return NULL;
    if (memo == Py_None) memo = NULL;
    if (PyObject_GetBuffer(lo_obj, &lo, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0)
        return NULL;
    if (PyObject_GetBuffer(hi_obj, &hi, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0) {
        PyBuffer_Release(&lo);
        return NULL;
    }
    {
        PyObject *colseq = PySequence_Fast(cols, "cols must be a sequence");
        Py_ssize_t ncols, c, i;
        uint64_t *dlo = (uint64_t *)lo.buf, *dhi = (uint64_t *)hi.buf;
        if (colseq == NULL) goto fail;
        ncols = PySequence_Fast_GET_SIZE(colseq);
        if ((Py_ssize_t)(lo.len / 8) < n || (Py_ssize_t)(hi.len / 8) < n) {
            Py_DECREF(colseq);
            PyErr_SetString(PyExc_ValueError, "output buffer too small");
            goto fail;
        }
        for (i = 0; i < n; i++) {
            dlo[i] = ROW_SEED ^ (uint64_t)salt_lo;
            dhi[i] = ROW_SEED_HI ^ (uint64_t)salt_hi;
        }
        for (c = 0; c < ncols; c++) {
            PyObject *col = PySequence_Fast_GET_ITEM(colseq, c);
            PyObject *vals = PySequence_Fast(col, "column must be a sequence");
            uint64_t l, h;
            if (vals == NULL) {
                Py_DECREF(colseq);
                goto fail;
            }
            if (PySequence_Fast_GET_SIZE(vals) != n) {
                Py_DECREF(vals);
                Py_DECREF(colseq);
                PyErr_SetString(PyExc_ValueError,
                                "column length != row count");
                goto fail;
            }
            for (i = 0; i < n; i++) {
                if (hash_scalar2_memo(PySequence_Fast_GET_ITEM(vals, i),
                                      fb_lo, fb_hi, memo, &l, &h) < 0) {
                    Py_DECREF(vals);
                    Py_DECREF(colseq);
                    goto fail;
                }
                dlo[i] = splitmix(dlo[i] ^ l);
                dhi[i] = splitmix2(dhi[i] ^ h);
            }
            Py_DECREF(vals);
        }
        Py_DECREF(colseq);
    }
    PyBuffer_Release(&lo);
    PyBuffer_Release(&hi);
    Py_RETURN_NONE;
fail:
    PyBuffer_Release(&lo);
    PyBuffer_Release(&hi);
    return NULL;
}

/* hash_rows2(rows, salt_lo, salt_hi, fb_lo, fb_hi, memo_or_None,
 *            out_lo_u64, out_hi_u64) -> None — both key lanes per row */
static PyObject *py_hash_rows2(PyObject *self, PyObject *args) {
    PyObject *rows, *fb_lo, *fb_hi, *memo, *lo_obj, *hi_obj;
    unsigned long long salt_lo, salt_hi;
    Py_buffer lo, hi;
    (void)self;
    if (!PyArg_ParseTuple(args, "OKKOOOOO", &rows, &salt_lo, &salt_hi,
                          &fb_lo, &fb_hi, &memo, &lo_obj, &hi_obj))
        return NULL;
    if (memo == Py_None) memo = NULL;
    if (PyObject_GetBuffer(lo_obj, &lo, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0)
        return NULL;
    if (PyObject_GetBuffer(hi_obj, &hi, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0) {
        PyBuffer_Release(&lo);
        return NULL;
    }
    {
        PyObject *seq = PySequence_Fast(rows, "rows must be a sequence");
        Py_ssize_t n, i;
        uint64_t *dlo = (uint64_t *)lo.buf, *dhi = (uint64_t *)hi.buf;
        if (seq == NULL) goto fail;
        n = PySequence_Fast_GET_SIZE(seq);
        if ((Py_ssize_t)(lo.len / 8) < n || (Py_ssize_t)(hi.len / 8) < n) {
            Py_DECREF(seq);
            PyErr_SetString(PyExc_ValueError, "output buffer too small");
            goto fail;
        }
        for (i = 0; i < n; i++) {
            PyObject *row = PySequence_Fast_GET_ITEM(seq, i);
            uint64_t acc_lo = ROW_SEED ^ (uint64_t)salt_lo;
            uint64_t acc_hi = ROW_SEED_HI ^ (uint64_t)salt_hi;
            uint64_t l, h;
            Py_ssize_t j, m;
            PyObject *rowseq = PySequence_Fast(row, "row must be a sequence");
            if (rowseq == NULL) {
                Py_DECREF(seq);
                goto fail;
            }
            m = PySequence_Fast_GET_SIZE(rowseq);
            for (j = 0; j < m; j++) {
                if (hash_scalar2_memo(PySequence_Fast_GET_ITEM(rowseq, j),
                                      fb_lo, fb_hi, memo, &l, &h) < 0) {
                    Py_DECREF(rowseq);
                    Py_DECREF(seq);
                    goto fail;
                }
                acc_lo = splitmix(acc_lo ^ l);
                acc_hi = splitmix2(acc_hi ^ h);
            }
            Py_DECREF(rowseq);
            dlo[i] = acc_lo;
            dhi[i] = acc_hi;
        }
        Py_DECREF(seq);
    }
    PyBuffer_Release(&lo);
    PyBuffer_Release(&hi);
    Py_RETURN_NONE;
fail:
    PyBuffer_Release(&lo);
    PyBuffer_Release(&hi);
    return NULL;
}

/* splitmix64_2(x: int) -> int — HI-lane finalizer, for parity tests */
static PyObject *py_splitmix2(PyObject *self, PyObject *arg) {
    unsigned long long x = PyLong_AsUnsignedLongLongMask(arg);
    (void)self;
    if (x == (unsigned long long)-1 && PyErr_Occurred()) return NULL;
    return PyLong_FromUnsignedLongLong(splitmix2(x));
}

/* blake2b16hi(data) -> int — HI string lane, for parity tests */
static PyObject *py_blake2b16hi(PyObject *self, PyObject *arg) {
    Py_buffer buf;
    uint64_t h;
    (void)self;
    if (PyObject_GetBuffer(arg, &buf, PyBUF_C_CONTIGUOUS) < 0) return NULL;
    h = blake2b16hi((const uint8_t *)buf.buf, buf.len);
    PyBuffer_Release(&buf);
    return PyLong_FromUnsignedLongLong(h);
}

/* hash_rows(rows: sequence of tuples, salt: int, fallback, out: writable
 * uint64 buffer of len(rows)) -> None */
static PyObject *py_hash_rows(PyObject *self, PyObject *args) {
    PyObject *rows, *fallback, *out_obj;
    unsigned long long salt;
    Py_buffer out;
    (void)self;
    if (!PyArg_ParseTuple(args, "OKOO", &rows, &salt, &fallback, &out_obj))
        return NULL;
    if (PyObject_GetBuffer(out_obj, &out, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0)
        return NULL;
    {
        PyObject *seq = PySequence_Fast(rows, "rows must be a sequence");
        Py_ssize_t n, i;
        uint64_t *dst = (uint64_t *)out.buf;
        if (seq == NULL) {
            PyBuffer_Release(&out);
            return NULL;
        }
        n = PySequence_Fast_GET_SIZE(seq);
        if ((Py_ssize_t)(out.len / 8) < n) {
            Py_DECREF(seq);
            PyBuffer_Release(&out);
            PyErr_SetString(PyExc_ValueError, "output buffer too small");
            return NULL;
        }
        for (i = 0; i < n; i++) {
            PyObject *row = PySequence_Fast_GET_ITEM(seq, i);
            uint64_t acc = ROW_SEED ^ (uint64_t)salt, h;
            Py_ssize_t j, m;
            PyObject *rowseq = PySequence_Fast(row, "row must be a sequence");
            if (rowseq == NULL) {
                Py_DECREF(seq);
                PyBuffer_Release(&out);
                return NULL;
            }
            m = PySequence_Fast_GET_SIZE(rowseq);
            for (j = 0; j < m; j++) {
                if (hash_scalar(PySequence_Fast_GET_ITEM(rowseq, j),
                                fallback, &h) < 0) {
                    Py_DECREF(rowseq);
                    Py_DECREF(seq);
                    PyBuffer_Release(&out);
                    return NULL;
                }
                acc = splitmix(acc ^ h);
            }
            Py_DECREF(rowseq);
            dst[i] = acc;
        }
        Py_DECREF(seq);
    }
    PyBuffer_Release(&out);
    Py_RETURN_NONE;
}

/* memoized LO-lane hash of an exact str (see hash_scalar2_memo) */
static int hash_scalar_memo(PyObject *v, PyObject *fallback, PyObject *memo,
                            uint64_t *out) {
    PyObject *hit, *plo;
    if (memo == NULL || !PyUnicode_CheckExact(v))
        return hash_scalar(v, fallback, out);
    hit = PyDict_GetItemWithError(memo, v); /* borrowed */
    if (hit != NULL) {
        *out = PyLong_AsUnsignedLongLongMask(hit);
        return 0;
    }
    if (PyErr_Occurred()) return -1;
    if (hash_scalar(v, fallback, out) < 0) return -1;
    if (PyDict_GET_SIZE(memo) >= STR_MEMO_CAP) PyDict_Clear(memo);
    plo = PyLong_FromUnsignedLongLong(*out);
    if (plo == NULL) return -1;
    if (PyDict_SetItem(memo, v, plo) < 0) {
        Py_DECREF(plo);
        return -1;
    }
    Py_DECREF(plo);
    return 0;
}

/* hash_scalars(values: sequence, fallback, out: writable uint64 buffer
 * [, memo_dict]) -> None — per-element hash_scalar (group-key/hash_column
 * hot path; the optional memo caches string digests value-wise) */
static PyObject *py_hash_scalars(PyObject *self, PyObject *args) {
    PyObject *values, *fallback, *out_obj, *memo = NULL;
    Py_buffer out;
    (void)self;
    if (!PyArg_ParseTuple(args, "OOO|O", &values, &fallback, &out_obj, &memo))
        return NULL;
    if (memo == Py_None) memo = NULL;
    if (PyObject_GetBuffer(out_obj, &out, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0)
        return NULL;
    {
        PyObject *seq = PySequence_Fast(values, "values must be a sequence");
        Py_ssize_t n, i;
        uint64_t *dst = (uint64_t *)out.buf;
        if (seq == NULL) {
            PyBuffer_Release(&out);
            return NULL;
        }
        n = PySequence_Fast_GET_SIZE(seq);
        if ((Py_ssize_t)(out.len / 8) < n) {
            Py_DECREF(seq);
            PyBuffer_Release(&out);
            PyErr_SetString(PyExc_ValueError, "output buffer too small");
            return NULL;
        }
        for (i = 0; i < n; i++) {
            if (hash_scalar_memo(PySequence_Fast_GET_ITEM(seq, i), fallback,
                                 memo, &dst[i]) < 0) {
                Py_DECREF(seq);
                PyBuffer_Release(&out);
                return NULL;
            }
        }
        Py_DECREF(seq);
    }
    PyBuffer_Release(&out);
    Py_RETURN_NONE;
}

/* blake2b8(data: bytes-like) -> int — exposed for parity tests */
static PyObject *py_blake2b8(PyObject *self, PyObject *arg) {
    Py_buffer buf;
    uint64_t h;
    (void)self;
    if (PyObject_GetBuffer(arg, &buf, PyBUF_C_CONTIGUOUS) < 0) return NULL;
    h = blake2b8((const uint8_t *)buf.buf, buf.len);
    PyBuffer_Release(&buf);
    return PyLong_FromUnsignedLongLong(h);
}

/* splitmix64(x: int) -> int — exposed for parity tests */
static PyObject *py_splitmix(PyObject *self, PyObject *arg) {
    unsigned long long x = PyLong_AsUnsignedLongLongMask(arg);
    (void)self;
    if (x == (unsigned long long)-1 && PyErr_Occurred()) return NULL;
    return PyLong_FromUnsignedLongLong(splitmix(x));
}

/* ----------------------------------------------------------------- */
/* KeyTable — open-addressing uint64 -> slot map with batch lookups.  */
/* Powers the dense groupby arena and join state: slot ids are dense  */
/* row indices into columnar (numpy) state arrays, so per-key state   */
/* updates become vectorized array ops instead of Python dict churn   */
/* (the role differential arrangements play in the reference).        */

typedef struct {
    PyObject_HEAD
    uint64_t *keys;
    int64_t *slots;
    uint8_t *used;
    Py_ssize_t capacity; /* power of two */
    Py_ssize_t size;
    int64_t next_slot;
} KeyTableObject;

static int keytable_grow(KeyTableObject *t, Py_ssize_t min_capacity) {
    Py_ssize_t new_cap = t->capacity ? t->capacity : 64;
    uint64_t *nk;
    int64_t *ns;
    uint8_t *nu;
    Py_ssize_t i;
    while (new_cap < min_capacity) new_cap <<= 1;
    nk = (uint64_t *)malloc((size_t)new_cap * 8);
    ns = (int64_t *)malloc((size_t)new_cap * 8);
    nu = (uint8_t *)calloc((size_t)new_cap, 1);
    if (!nk || !ns || !nu) {
        free(nk); free(ns); free(nu);
        PyErr_NoMemory();
        return -1;
    }
    for (i = 0; i < t->capacity; i++) {
        if (t->used[i]) {
            uint64_t h = splitmix(t->keys[i]);
            Py_ssize_t j = (Py_ssize_t)(h & (uint64_t)(new_cap - 1));
            while (nu[j]) j = (j + 1) & (new_cap - 1);
            nu[j] = 1;
            nk[j] = t->keys[i];
            ns[j] = t->slots[i];
        }
    }
    free(t->keys); free(t->slots); free(t->used);
    t->keys = nk; t->slots = ns; t->used = nu;
    t->capacity = new_cap;
    return 0;
}

/* lookup_or_insert(keys: uint64 buffer, out: int64 buffer) -> n_new */
static PyObject *keytable_lookup_or_insert(PyObject *self, PyObject *args) {
    KeyTableObject *t = (KeyTableObject *)self;
    PyObject *keys_obj, *out_obj;
    Py_buffer keys, out;
    Py_ssize_t n, i, n_new = 0;
    if (!PyArg_ParseTuple(args, "OO", &keys_obj, &out_obj)) return NULL;
    if (PyObject_GetBuffer(keys_obj, &keys, PyBUF_C_CONTIGUOUS) < 0) return NULL;
    if (PyObject_GetBuffer(out_obj, &out, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0) {
        PyBuffer_Release(&keys);
        return NULL;
    }
    n = keys.len / 8;
    if (out.len / 8 < n) {
        PyBuffer_Release(&keys); PyBuffer_Release(&out);
        PyErr_SetString(PyExc_ValueError, "output buffer too small");
        return NULL;
    }
    /* worst case inserts all n keys; keep load factor under 0.7 */
    if ((t->size + n) * 10 >= t->capacity * 7) {
        if (keytable_grow(t, (t->size + n) * 2) < 0) {
            PyBuffer_Release(&keys); PyBuffer_Release(&out);
            return NULL;
        }
    }
    {
        const uint64_t *src = (const uint64_t *)keys.buf;
        int64_t *dst = (int64_t *)out.buf;
        uint64_t mask = (uint64_t)(t->capacity - 1);
        for (i = 0; i < n; i++) {
            uint64_t k = src[i];
            Py_ssize_t j = (Py_ssize_t)(splitmix(k) & mask);
            while (t->used[j] && t->keys[j] != k) j = (j + 1) & mask;
            if (!t->used[j]) {
                t->used[j] = 1;
                t->keys[j] = k;
                t->slots[j] = t->next_slot++;
                t->size++;
                n_new++;
            }
            dst[i] = t->slots[j];
        }
    }
    PyBuffer_Release(&keys);
    PyBuffer_Release(&out);
    return PyLong_FromSsize_t(n_new);
}

/* lookup(keys: uint64 buffer, out: int64 buffer) -> None; missing = -1 */
static PyObject *keytable_lookup(PyObject *self, PyObject *args) {
    KeyTableObject *t = (KeyTableObject *)self;
    PyObject *keys_obj, *out_obj;
    Py_buffer keys, out;
    Py_ssize_t n, i;
    if (!PyArg_ParseTuple(args, "OO", &keys_obj, &out_obj)) return NULL;
    if (PyObject_GetBuffer(keys_obj, &keys, PyBUF_C_CONTIGUOUS) < 0) return NULL;
    if (PyObject_GetBuffer(out_obj, &out, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0) {
        PyBuffer_Release(&keys);
        return NULL;
    }
    n = keys.len / 8;
    if (out.len / 8 < n) {
        PyBuffer_Release(&keys); PyBuffer_Release(&out);
        PyErr_SetString(PyExc_ValueError, "output buffer too small");
        return NULL;
    }
    if (t->capacity == 0) {
        int64_t *dst = (int64_t *)out.buf;
        for (i = 0; i < n; i++) dst[i] = -1;
    } else {
        const uint64_t *src = (const uint64_t *)keys.buf;
        int64_t *dst = (int64_t *)out.buf;
        uint64_t mask = (uint64_t)(t->capacity - 1);
        for (i = 0; i < n; i++) {
            uint64_t k = src[i];
            Py_ssize_t j = (Py_ssize_t)(splitmix(k) & mask);
            while (t->used[j] && t->keys[j] != k) j = (j + 1) & mask;
            dst[i] = t->used[j] ? t->slots[j] : -1;
        }
    }
    PyBuffer_Release(&keys);
    PyBuffer_Release(&out);
    Py_RETURN_NONE;
}

static Py_ssize_t keytable_len(PyObject *self) {
    return ((KeyTableObject *)self)->size;
}

static void keytable_dealloc(PyObject *self) {
    KeyTableObject *t = (KeyTableObject *)self;
    free(t->keys); free(t->slots); free(t->used);
    Py_TYPE(self)->tp_free(self);
}

static PyObject *keytable_new(PyTypeObject *type, PyObject *args,
                              PyObject *kwds) {
    KeyTableObject *t;
    (void)args; (void)kwds;
    t = (KeyTableObject *)type->tp_alloc(type, 0);
    if (t == NULL) return NULL;
    t->keys = NULL; t->slots = NULL; t->used = NULL;
    t->capacity = 0; t->size = 0; t->next_slot = 0;
    return (PyObject *)t;
}

static PyMethodDef keytable_methods[] = {
    {"lookup_or_insert", keytable_lookup_or_insert, METH_VARARGS,
     "lookup_or_insert(keys_u64, out_i64) -> n_new"},
    {"lookup", keytable_lookup, METH_VARARGS,
     "lookup(keys_u64, out_i64); missing -> -1"},
    {NULL, NULL, 0, NULL},
};

static PySequenceMethods keytable_as_sequence = {
    keytable_len, /* sq_length */
};

static PyTypeObject KeyTableType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_pathway_native.KeyTable",
    .tp_basicsize = sizeof(KeyTableObject),
    .tp_dealloc = keytable_dealloc,
    .tp_as_sequence = &keytable_as_sequence,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "open-addressing uint64 -> dense slot map (batch API)",
    .tp_methods = keytable_methods,
    .tp_new = keytable_new,
};

/* ----------------------------------------------------------------- */
/* KeyRegistry — process-wide LO->HI lane map for 128-bit key          */
/* conflation detection. Keys are created as 128-bit values (two       */
/* independent lanes); the engine transports the LO lane in its        */
/* vectorized uint64 arrays, and every key-creation batch registers    */
/* (lo, hi) here: a lo that re-registers with a DIFFERENT hi is two    */
/* distinct 128-bit keys colliding on the transport lane — fail-stop   */
/* instead of silent row conflation (reference keys by the full u128,  */
/* value.rs:30-47, so it never conflates; we detect at the same        */
/* probability scale). Bounded: at cap the registry freezes (existing  */
/* entries still detect; new keys pass unchecked) — callers log once.  */

typedef struct {
    PyObject_HEAD
    uint64_t *keys;
    uint64_t *his;
    uint8_t *used;
    Py_ssize_t capacity; /* power of two */
    Py_ssize_t size;
    Py_ssize_t max_entries;
    int frozen;
} KeyRegistryObject;

static int keyregistry_grow(KeyRegistryObject *t, Py_ssize_t min_capacity) {
    Py_ssize_t new_cap = t->capacity ? t->capacity : 1024;
    uint64_t *nk, *nh;
    uint8_t *nu;
    Py_ssize_t i;
    while (new_cap < min_capacity) new_cap <<= 1;
    nk = (uint64_t *)malloc((size_t)new_cap * 8);
    nh = (uint64_t *)malloc((size_t)new_cap * 8);
    nu = (uint8_t *)calloc((size_t)new_cap, 1);
    if (!nk || !nh || !nu) {
        free(nk); free(nh); free(nu);
        PyErr_NoMemory();
        return -1;
    }
    for (i = 0; i < t->capacity; i++) {
        if (t->used[i]) {
            uint64_t h = splitmix(t->keys[i]);
            Py_ssize_t j = (Py_ssize_t)(h & (uint64_t)(new_cap - 1));
            while (nu[j]) j = (j + 1) & (new_cap - 1);
            nu[j] = 1;
            nk[j] = t->keys[i];
            nh[j] = t->his[i];
        }
    }
    free(t->keys); free(t->his); free(t->used);
    t->keys = nk; t->his = nh; t->used = nu;
    t->capacity = new_cap;
    return 0;
}

/* register(lo_u64_buf, hi_u64_buf) -> first conflicting index or -1 */
static PyObject *keyregistry_register(PyObject *self, PyObject *args) {
    KeyRegistryObject *t = (KeyRegistryObject *)self;
    PyObject *lo_obj, *hi_obj;
    Py_buffer lo, hi;
    Py_ssize_t n, i, conflict = -1;
    if (!PyArg_ParseTuple(args, "OO", &lo_obj, &hi_obj)) return NULL;
    if (PyObject_GetBuffer(lo_obj, &lo, PyBUF_C_CONTIGUOUS) < 0) return NULL;
    if (PyObject_GetBuffer(hi_obj, &hi, PyBUF_C_CONTIGUOUS) < 0) {
        PyBuffer_Release(&lo);
        return NULL;
    }
    n = lo.len / 8;
    if (hi.len / 8 < n) {
        PyBuffer_Release(&lo); PyBuffer_Release(&hi);
        PyErr_SetString(PyExc_ValueError, "hi buffer too small");
        return NULL;
    }
    if (!t->frozen && (t->size + n) * 10 >= t->capacity * 7) {
        /* clamp to 2x the entry cap: the insert loop freezes at
         * max_entries, so load factor stays <= 0.5 in the frozen table */
        Py_ssize_t want = (t->size + n) * 2;
        if (want > t->max_entries * 2) want = t->max_entries * 2;
        if (want > t->capacity && keyregistry_grow(t, want) < 0) {
            PyBuffer_Release(&lo); PyBuffer_Release(&hi);
            return NULL;
        }
    }
    if (t->capacity) {
        const uint64_t *slo = (const uint64_t *)lo.buf;
        const uint64_t *shi = (const uint64_t *)hi.buf;
        uint64_t mask = (uint64_t)(t->capacity - 1);
        for (i = 0; i < n; i++) {
            uint64_t k = slo[i];
            Py_ssize_t j = (Py_ssize_t)(splitmix(k) & mask);
            while (t->used[j] && t->keys[j] != k) j = (j + 1) & mask;
            if (t->used[j]) {
                if (t->his[j] != shi[i]) {
                    conflict = i;
                    break;
                }
            } else if (!t->frozen) {
                t->used[j] = 1;
                t->keys[j] = k;
                t->his[j] = shi[i];
                t->size++;
                if (t->size >= t->max_entries) t->frozen = 1;
            }
        }
    }
    PyBuffer_Release(&lo);
    PyBuffer_Release(&hi);
    return PyLong_FromSsize_t(conflict);
}

/* register_overflow(lo_u64_buf, hi_u64_buf, miss_u8_buf)
 *   -> first conflicting index or -1
 * Two-tier variant of register(): identical insert/detect behavior for
 * the hot in-memory table, but once the table is FROZEN (cap reached),
 * keys absent from it are NOT silently passed — miss[i] is set to 1 and
 * the caller (engine/keys.py) probes/inserts them in the spilled cold
 * tier. miss must be a writable byte buffer of at least n entries; only
 * miss indexes of absent-while-frozen keys are written (caller zeroes). */
static PyObject *keyregistry_register_overflow(PyObject *self, PyObject *args) {
    KeyRegistryObject *t = (KeyRegistryObject *)self;
    PyObject *lo_obj, *hi_obj, *miss_obj;
    Py_buffer lo, hi, miss;
    Py_ssize_t n, i, conflict = -1;
    if (!PyArg_ParseTuple(args, "OOO", &lo_obj, &hi_obj, &miss_obj))
        return NULL;
    if (PyObject_GetBuffer(lo_obj, &lo, PyBUF_C_CONTIGUOUS) < 0) return NULL;
    if (PyObject_GetBuffer(hi_obj, &hi, PyBUF_C_CONTIGUOUS) < 0) {
        PyBuffer_Release(&lo);
        return NULL;
    }
    if (PyObject_GetBuffer(miss_obj, &miss,
                           PyBUF_C_CONTIGUOUS | PyBUF_WRITABLE) < 0) {
        PyBuffer_Release(&lo); PyBuffer_Release(&hi);
        return NULL;
    }
    n = lo.len / 8;
    if (hi.len / 8 < n || miss.len < n) {
        PyBuffer_Release(&lo); PyBuffer_Release(&hi); PyBuffer_Release(&miss);
        PyErr_SetString(PyExc_ValueError, "hi/miss buffer too small");
        return NULL;
    }
    if (!t->frozen && (t->size + n) * 10 >= t->capacity * 7) {
        Py_ssize_t want = (t->size + n) * 2;
        if (want > t->max_entries * 2) want = t->max_entries * 2;
        if (want > t->capacity && keyregistry_grow(t, want) < 0) {
            PyBuffer_Release(&lo); PyBuffer_Release(&hi);
            PyBuffer_Release(&miss);
            return NULL;
        }
    }
    if (t->capacity) {
        const uint64_t *slo = (const uint64_t *)lo.buf;
        const uint64_t *shi = (const uint64_t *)hi.buf;
        uint8_t *smiss = (uint8_t *)miss.buf;
        uint64_t mask = (uint64_t)(t->capacity - 1);
        for (i = 0; i < n; i++) {
            uint64_t k = slo[i];
            Py_ssize_t j = (Py_ssize_t)(splitmix(k) & mask);
            while (t->used[j] && t->keys[j] != k) j = (j + 1) & mask;
            if (t->used[j]) {
                if (t->his[j] != shi[i]) {
                    conflict = i;
                    break;
                }
            } else if (!t->frozen) {
                t->used[j] = 1;
                t->keys[j] = k;
                t->his[j] = shi[i];
                t->size++;
                if (t->size >= t->max_entries) t->frozen = 1;
            } else {
                smiss[i] = 1;
            }
        }
    } else {
        /* zero-capacity table (cap so small nothing was ever inserted):
         * every key is an overflow miss once frozen; pre-freeze the grow
         * above always allocates, so capacity==0 implies nothing stored */
        uint8_t *smiss = (uint8_t *)miss.buf;
        if (t->frozen)
            for (i = 0; i < n; i++) smiss[i] = 1;
    }
    PyBuffer_Release(&lo);
    PyBuffer_Release(&hi);
    PyBuffer_Release(&miss);
    return PyLong_FromSsize_t(conflict);
}

static PyObject *keyregistry_stats(PyObject *self, PyObject *noarg) {
    KeyRegistryObject *t = (KeyRegistryObject *)self;
    (void)noarg;
    return Py_BuildValue("(ni)", t->size, t->frozen);
}

static void keyregistry_dealloc(PyObject *self) {
    KeyRegistryObject *t = (KeyRegistryObject *)self;
    free(t->keys); free(t->his); free(t->used);
    Py_TYPE(self)->tp_free(self);
}

static PyObject *keyregistry_new(PyTypeObject *type, PyObject *args,
                                 PyObject *kwds) {
    KeyRegistryObject *t;
    Py_ssize_t max_entries = 1 << 22;
    (void)kwds;
    if (!PyArg_ParseTuple(args, "|n", &max_entries)) return NULL;
    t = (KeyRegistryObject *)type->tp_alloc(type, 0);
    if (t == NULL) return NULL;
    t->keys = NULL; t->his = NULL; t->used = NULL;
    t->capacity = 0; t->size = 0; t->frozen = 0;
    t->max_entries = max_entries > 0 ? max_entries : 1;
    return (PyObject *)t;
}

static PyMethodDef keyregistry_methods[] = {
    {"register", keyregistry_register, METH_VARARGS,
     "register(lo_u64, hi_u64) -> first conflicting index or -1"},
    {"register_overflow", keyregistry_register_overflow, METH_VARARGS,
     "register_overflow(lo_u64, hi_u64, miss_u8) -> first conflicting "
     "index or -1; frozen-table misses flagged for the cold tier"},
    {"stats", keyregistry_stats, METH_NOARGS, "stats() -> (size, frozen)"},
    {NULL, NULL, 0, NULL},
};

static PyTypeObject KeyRegistryType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_pathway_native.KeyRegistry",
    .tp_basicsize = sizeof(KeyRegistryObject),
    .tp_dealloc = keyregistry_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "lo->hi key-lane registry for 128-bit conflation detection",
    .tp_methods = keyregistry_methods,
    .tp_new = keyregistry_new,
};

/* all_unique_u64(uint64_contiguous_buffer) -> bool
 *
 * O(n) open-addressing duplicate probe over already-avalanched engine
 * keys (splitmix64 outputs distribute uniformly, so the slot is just
 * the masked key). The consolidation identity fast path
 * (engine/delta.py) uses it to prove an all-insertions batch is
 * already consolidated — the alternative is the full row-signature
 * hash + sort. */
static PyObject *py_all_unique_u64(PyObject *self, PyObject *arg) {
    Py_buffer buf;
    if (PyObject_GetBuffer(arg, &buf, PyBUF_C_CONTIGUOUS) < 0) return NULL;
    if (buf.itemsize != 8) {
        PyBuffer_Release(&buf);
        PyErr_SetString(PyExc_TypeError, "expected a uint64 buffer");
        return NULL;
    }
    Py_ssize_t n = buf.len / 8;
    const uint64_t *keys = (const uint64_t *)buf.buf;
    if (n < 2) {
        PyBuffer_Release(&buf);
        Py_RETURN_TRUE;
    }
    size_t cap = 64;
    while ((Py_ssize_t)cap < n * 2) cap <<= 1;
    uint64_t *table = (uint64_t *)calloc(cap, sizeof(uint64_t));
    if (table == NULL) {
        PyBuffer_Release(&buf);
        PyErr_NoMemory();
        return NULL;
    }
    size_t mask = cap - 1;
    int seen_zero = 0, unique = 1;
    for (Py_ssize_t i = 0; i < n; i++) {
        uint64_t k = keys[i];
        if (k == 0) { /* 0 marks empty slots: track it out-of-band */
            if (seen_zero) { unique = 0; break; }
            seen_zero = 1;
            continue;
        }
        size_t slot = (size_t)k & mask;
        for (;;) {
            uint64_t cur = table[slot];
            if (cur == 0) {
                table[slot] = k;
                break;
            }
            if (cur == k) {
                unique = 0;
                break;
            }
            slot = (slot + 1) & mask;
        }
        if (!unique) break;
    }
    free(table);
    PyBuffer_Release(&buf);
    if (unique) Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

static PyMethodDef methods[] = {
    {"all_unique_u64", py_all_unique_u64, METH_O,
     "all_unique_u64(uint64_buffer) -> bool (O(n) duplicate probe)"},
    {"hash_rows", py_hash_rows, METH_VARARGS,
     "hash_rows(rows, salt, fallback, out_uint64_buffer)"},
    {"hash_scalars", py_hash_scalars, METH_VARARGS,
     "hash_scalars(values, fallback, out_uint64_buffer[, memo])"},
    {"hash_rows2", py_hash_rows2, METH_VARARGS,
     "hash_rows2(rows, salt_lo, salt_hi, fb_lo, fb_hi, memo, out_lo, out_hi)"},
    {"mix_cols2", py_mix_cols2, METH_VARARGS,
     "mix_cols2(cols, n, salt_lo, salt_hi, fb_lo, fb_hi, memo, out_lo, out_hi)"},
    {"hash_scalars2", py_hash_scalars2, METH_VARARGS,
     "hash_scalars2(values, fb_lo, fb_hi, memo, out_lo, out_hi)"},
    {"blake2b8", py_blake2b8, METH_O, "8-byte BLAKE2b digest as uint64"},
    {"blake2b16hi", py_blake2b16hi, METH_O,
     "second word of the 16-byte BLAKE2b digest (HI string lane)"},
    {"splitmix64", py_splitmix, METH_O, "splitmix64 finalizer"},
    {"splitmix64_2", py_splitmix2, METH_O, "HI-lane finalizer"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_pathway_native",
    "Native keyspace kernels for pathway_tpu", -1, methods,
    NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC PyInit__pathway_native(void) {
    PyObject *m;
    if (PyType_Ready(&KeyTableType) < 0) return NULL;
    m = PyModule_Create(&module);
    if (m == NULL) return NULL;
    Py_INCREF(&KeyTableType);
    if (PyModule_AddObject(m, "KeyTable", (PyObject *)&KeyTableType) < 0) {
        Py_DECREF(&KeyTableType);
        Py_DECREF(m);
        return NULL;
    }
    if (PyType_Ready(&KeyRegistryType) < 0) return NULL;
    Py_INCREF(&KeyRegistryType);
    if (PyModule_AddObject(m, "KeyRegistry", (PyObject *)&KeyRegistryType) < 0) {
        Py_DECREF(&KeyRegistryType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
