"""Shared lint-mode state — armed by ``pathway_tpu.analysis.lint``.

When the static analyzer drives a user script (``pathway-tpu lint``), the
script must BUILD its dataflow without EXECUTING it, and diagnostics
should point at the script line that created each table. Both behaviors
live behind this tiny module so ``internals/run.py`` and
``internals/table.py`` can consult it without importing the analysis
package (no import cycles, zero cost when lint mode is off):

- ``ACTIVE`` — lint mode armed; ``pw.run()`` becomes a no-op that
  records its ``persistence_config`` into ``CAPTURE`` instead of
  executing, and ``Table.__init__`` records the creating script line.
- ``SCRIPT`` — absolute path of the script being linted; stack frames
  from this file are the ones recorded as creation sites.
- ``LOCATIONS`` — ``table_seq -> (filename, lineno)`` creation sites.
- ``CAPTURE`` — what the stubbed ``pw.run`` observed (persistence
  config, number of run calls).
"""

from __future__ import annotations

import sys
from typing import Any

ACTIVE: bool = False
SCRIPT: str | None = None
LOCATIONS: dict[int, tuple[str, int]] = {}
CAPTURE: dict[str, Any] = {"persistence_config": None, "runs": 0}


def arm(script: str | None) -> None:
    global ACTIVE, SCRIPT
    ACTIVE = True
    SCRIPT = script
    LOCATIONS.clear()
    CAPTURE.update(persistence_config=None, runs=0)


def disarm() -> None:
    global ACTIVE, SCRIPT
    ACTIVE = False
    SCRIPT = None


def script_location(start_depth: int = 2) -> tuple[str, int] | None:
    """(filename, lineno) of the first stack frame belonging to the
    linted SCRIPT, walking outward from ``start_depth`` (capped) — the
    one place that knows the sys._getframe walk."""
    if SCRIPT is None:
        return None
    frame = sys._getframe(start_depth)
    depth = 0
    while frame is not None and depth < 40:
        if frame.f_code.co_filename == SCRIPT:
            return (frame.f_code.co_filename, frame.f_lineno)
        frame = frame.f_back
        depth += 1
    return None


def note_table(table_seq: int) -> None:
    """Record the linted script's frame that created a table."""
    loc = script_location(start_depth=3)
    if loc is not None:
        LOCATIONS[table_seq] = loc


def note_run(persistence_config: Any) -> None:
    CAPTURE["runs"] = CAPTURE.get("runs", 0) + 1
    if persistence_config is not None:
        CAPTURE["persistence_config"] = persistence_config
