"""GraphRunner — lowers the declarative parse graph to engine operators.

Re-design of ``python/pathway/internals/graph_runner/`` (GraphRunner
``__init__.py:36``, storage_graph, expression_evaluator — ~30 evaluators).
Here every Table kind lowers to a small engine-operator subgraph; columnar
layouts are simply the tables' column dicts (the reference's tuple-layout
planner ``path_evaluator.py`` is unnecessary with struct-of-arrays batches).
Tree-shaking (reference ``__init__.py:93,101``) falls out of memoized
recursion from the requested outputs.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..engine import keys as K
from ..engine import operators as ops
from ..engine.executor import Executor, Node
from ..engine.reducers import make_reducer
from . import dtype as dt
from .expression import ColumnExpression, ColumnReference, HiddenRef, IdReference
from .expression_compiler import ColumnEnv, compile_expr
from .parse_graph import G
from .table import Table
from .thisclass import ThisPlaceholder


class GraphRunner:
    def __init__(self) -> None:
        self._cache: dict[int, Node] = {}
        self._nodes: list[Node] = []
        self.executor: Executor | None = None
        self.persistence: Any = None  # PersistenceManager when pw.run has one
        self.monitoring_level: int = 0
        self.with_http_server: bool = False
        #: request_stop() may fire while the graph is still building (before
        #: the executor exists); the flag is handed to the executor on
        #: creation so early stops aren't lost
        self.stop_requested: bool = False

    # ------------------------------------------------------------------

    def _want_http_server(self) -> bool:
        if self.with_http_server:
            return True
        try:
            from .config import get_pathway_config

            return get_pathway_config().monitoring_http_server
        except RuntimeError:
            return False

    def _start_observability(self, workers, comm=None):
        """Hub + HTTP endpoints + periodic telemetry flusher for this
        process's workers. Returns (http_server, flusher, hub); each may
        be None. ``workers`` is [(worker_id, EngineStats), ...]."""
        from ..observability import ObservabilityHub
        from ..observability.exporter import start_periodic_flusher
        from .config import get_pathway_config

        http_server = None
        hub = None
        if self._want_http_server():
            from ..engine.http_server import start_http_server

            try:
                hub = ObservabilityHub.from_config(get_pathway_config())
            except RuntimeError:
                hub = ObservabilityHub()
            for w, stats in workers:
                hub.register_worker(w, stats)
                # /metrics serves per-operator latency histograms, which
                # need per-node timing on (the dashboard's ALL level)
                stats.detailed = True
            if comm is not None:
                hub.register_comm(comm)
            # signals plane: windowed time-series sampling of every
            # registered worker + comm backend, SLO rule evaluation, and
            # the /query‖/attribution‖/alerts surface (observability/
            # timeseries.py, slo.py) — lives and dies with the hub
            hub.start_signals()
            try:
                http_server, _ = start_http_server(hub)
            except OSError as e:
                # telemetry must not fail the run it observes: a taken
                # port (another pipeline on this host) degrades to
                # metrics-off, it does not abort the dataflow
                import warnings

                warnings.warn(
                    f"monitoring HTTP server failed to start: {e}; "
                    "continuing without /metrics",
                    RuntimeWarning,
                )
                http_server = None
        #: bound server exposed for tests/tools needing the ephemeral port
        self._http_server_for_tests = http_server
        flusher = start_periodic_flusher(hub)
        return http_server, flusher, hub

    @staticmethod
    def _stop_observability(http_server, flusher, hub=None) -> None:
        if flusher is not None:
            flusher.stop()
        if hub is not None:
            hub.close()  # signals sampler thread
        if http_server is not None:
            http_server.shutdown()
            http_server.server_close()

    def _execute(self) -> None:
        self.executor = Executor(self._nodes, persistence=self.persistence)
        if self.stop_requested:
            self.executor.request_stop()
        stop_dashboard = None
        http_server, flusher, _hub = self._start_observability(
            [(0, self.executor.stats)]
        )
        if self.monitoring_level:
            from .monitoring import start_dashboard

            stop_dashboard = start_dashboard(
                self.executor.stats, self.monitoring_level
            )
        try:
            self.executor.run()
        finally:
            if stop_dashboard is not None:
                stop_dashboard()
            self._stop_observability(http_server, flusher, _hub)
            from .telemetry import export_from_env
            from .tracing import get_tracer

            export_from_env(get_tracer())

    def run_tables(self, *tables: Table, include_sinks: bool = False):
        """Build + execute; return one Capture per requested table."""
        captures = [self.capture(t) for t in tables]
        if include_sinks:
            for sink in G.sinks:
                self.lower_sink(sink)
        self._execute()
        return captures

    def run(self) -> None:
        from .config import get_pathway_config
        from .tracing import get_tracer, span

        cfg = get_pathway_config()
        if cfg.total_workers > 1:
            self._run_sharded(cfg)
            return
        try:
            with span("graph.build", n_sinks=len(G.sinks)):
                for sink in G.sinks:
                    self.lower_sink(sink)
            self._execute()
        finally:
            # a failed lowering still deserves its partial trace (executor
            # flushes are no-ops when nothing new happened since)
            tracer = get_tracer()
            if tracer is not None:
                tracer.flush()
                from .telemetry import export_from_env

                export_from_env(tracer)  # lowering-failure partial spans

    def _run_sharded(self, cfg) -> None:
        """Multi-worker execution (reference: timely workers over thread /
        cluster allocators). Every worker builds the same dataflow from the
        parse graph, owns the ``shard_of(key)`` slice of all stateful
        operator state, and exchanges records at stateful boundaries
        (engine/executor.shard_graph). Threads within this process; with
        PATHWAY_PROCESSES > 1, a TCP full mesh links the processes."""
        import threading

        from ..engine.executor import Executor
        from ..parallel.comm import LocalComm, WorkerContext

        n_workers = cfg.total_workers
        if cfg.processes > 1:
            from ..parallel.cluster import ClusterComm

            comm = ClusterComm(
                process_id=cfg.process_id,
                n_processes=cfg.processes,
                threads_per_process=cfg.threads,
                first_port=cfg.first_port,
                addresses=cfg.addresses,
            )
            local_worker_ids = [
                cfg.process_id * cfg.threads + i for i in range(cfg.threads)
            ]
        else:
            comm = LocalComm(n_workers)
            local_worker_ids = list(range(n_workers))
        if cfg.mesh_exchange:
            if cfg.processes > 1:
                # cross-host: bootstrap jax.distributed so the device mesh
                # spans every process (ICI within a pod, DCN across);
                # record exchange then rides MultiHostMeshComm
                from ..parallel import distributed
                from ..parallel.meshcomm import MultiHostMeshComm

                distributed.init_from_env()
                comm = MultiHostMeshComm(
                    comm,
                    process_id=cfg.process_id,
                    n_processes=cfg.processes,
                    threads=cfg.threads,
                )
            else:
                from ..parallel.meshcomm import MeshComm

                comm = MeshComm(comm)

        pcfg = getattr(self, "persistence_config", None)
        managers: list[Any] = []
        executors: list[Executor] = []
        from .tracing import span as _span

        errors: list[BaseException] = []

        def work(ex: Executor) -> None:
            try:
                ex.run()
            except BaseException as e:  # propagate cross-worker (panic model)
                errors.append(e)
                comm.abort()

        # comm exists from here on: a failed build must still close it (and
        # any managers), and still flush the partial trace
        try:
            with _span(
                "graph.build", n_sinks=len(G.sinks), n_workers=n_workers
            ):
                for w in local_worker_ids:
                    worker_runner = GraphRunner()
                    if pcfg is not None:
                        from ..persistence import (
                            PersistenceManager,
                            apply_replay_env,
                        )

                        manager = PersistenceManager(
                            pcfg, worker_id=w, n_workers=n_workers
                        )
                        apply_replay_env(manager, cfg)
                        worker_runner.persistence = manager
                        managers.append(manager)
                    for sink in G.sinks:
                        worker_runner.lower_sink(sink)
                    executors.append(
                        Executor(
                            worker_runner._nodes,
                            ctx=WorkerContext(w, n_workers, comm),
                            persistence=worker_runner.persistence,
                        )
                    )
            self.executor = executors[0]
            self._peer_executors = executors
            if self.stop_requested:
                for ex in executors:
                    ex.request_stop()

            # cluster observability: this process serves its workers'
            # stats on base_port + process_id; process 0's /metrics is
            # the merged per-worker view (it scrapes peer /snapshot)
            http_server, flusher, _hub = self._start_observability(
                list(zip(local_worker_ids, (ex.stats for ex in executors))),
                comm=comm,
            )
            try:
                if len(executors) == 1:
                    work(executors[0])
                else:
                    threads = [
                        threading.Thread(target=work, args=(ex,), daemon=True)
                        for ex in executors
                    ]
                    for t in threads:
                        t.start()
                    for t in threads:
                        t.join()
            finally:
                self._stop_observability(http_server, flusher, _hub)
        finally:
            comm.close()
            for manager in managers:
                manager.close()
            from .tracing import get_tracer
            from .telemetry import export_from_env

            tracer = get_tracer()
            if tracer is not None:
                tracer.flush()
                export_from_env(tracer)
        if errors:
            primary = [
                e for e in errors
                if "peer worker failed" not in str(e)
            ]
            raise (primary or errors)[0]

    def capture(self, table: Table) -> ops.Capture:
        node = self.lower(table)
        cap = ops.Capture(node)
        self._nodes.append(cap)
        return cap

    def _build_delivery_sink(self, spec: dict) -> Any:
        """Instantiate one delivery-managed sink (io/delivery.py) for this
        worker's runner. The DeliveryManager attaches to the persistence
        manager on EVERY worker (so all workers agree on the finish-path
        commit ordering), but only worker 0's sinks are transactional —
        sink callbacks gather there, and a peer's idle cursor must never
        drag the cluster's recovery floor down."""
        from ..io import delivery as _dlv

        mgr = getattr(self, "_delivery_mgr", None)
        worker_id = (
            self.persistence.worker_id if self.persistence is not None else 0
        )
        if mgr is None:
            mgr = self._delivery_mgr = _dlv.DeliveryManager(worker_id)
            if self.persistence is not None:
                self.persistence.delivery = mgr
        active = worker_id == 0
        transactional = self.persistence is not None and active
        dsink = _dlv.DeliverySink(
            spec["adapter_factory"](),
            spec["name"],
            policy=spec.get("retry_policy"),
            worker_id=worker_id,
            backend=self.persistence.backend if transactional else None,
            transactional=transactional,
            dlq=mgr.dlq,
        )
        mgr.add(dsink)
        return dsink

    def lower_sink(self, sink: Any) -> None:
        kind = sink["kind"]
        if kind == "subscribe":
            node = self.lower(sink["table"])
            dspec = sink.get("delivery")
            if dspec is not None:
                # delivery-managed sink: retries/acks/DLQ live in the
                # delivery layer; recovery dedup is the durable ack
                # cursor, NOT skip_until — replayed output above the
                # restore point must REACH the sink for re-delivery
                dsink = self._build_delivery_sink(dspec)
                self._nodes.append(ops.Subscribe(
                    node,
                    on_batch=dsink.on_batch,
                    on_end=dsink.on_end,
                    skip_until=-1,
                ))
                return
            skip_until = -1
            if (
                self.persistence is not None
                and sink.get("skip_persisted_batch", True)
                # CLI replay re-emits the recorded history — that is the
                # point; skip-persisted is a RECOVERY dedup mechanism
                and getattr(self.persistence, "replay_mode", None) is None
            ):
                skip_until = self.persistence.last_time
            sub = ops.Subscribe(
                node,
                on_change=sink.get("on_change"),
                on_time_end=sink.get("on_time_end"),
                on_end=sink.get("on_end"),
                on_batch=sink.get("on_batch"),
                skip_until=skip_until,
            )
            self._nodes.append(sub)
        elif kind == "callable":
            sink["build"](self)
        else:
            raise NotImplementedError(f"sink kind {kind}")

    # ------------------------------------------------------------------

    def _add(self, node: Node) -> Node:
        self._nodes.append(node)
        return node

    def lower(self, table: Table) -> Node:
        key = id(table)
        if key in self._cache:
            return self._cache[key]
        node = self._lower(table)
        scope = getattr(table, "_error_scope", None)
        if scope is not None and getattr(node, "error_scope", None) is None:
            # pw.local_error_log() attribution: errors raised while this
            # node processes carry the scope its table was built under
            node.error_scope = scope
        pw_name = getattr(table, "_pw_name", None)
        if pw_name is not None and node.pw_name is None:
            # Table.named() pins a stable identity for upgrade matching.
            # The pin names the STATE behind this table: tables like
            # `.reduce(...)` lower to a stateless column projection over
            # the stateful operator, so walk up through single-input
            # stateless wrappers and land the name on the operator whose
            # snapshot actually migrates.
            node.pw_name = pw_name
            cur = node
            while not cur.has_state() and len(cur.inputs) == 1:
                cur = cur.inputs[0]
                if cur.pw_name is None:
                    cur.pw_name = pw_name
        self._cache[key] = node
        return node

    def _lower(self, table: Table) -> Node:
        kind = table._kind
        p = table._params
        if kind == "static":
            return self._add(ops.StaticSource(p["keys"], p["data"]))
        if kind == "scheduled":
            from ..engine.delta import Delta

            batches = [
                (t, Delta(keys=k, data=data, diffs=diffs))
                for (t, k, data, diffs) in p["batches"]
            ]
            return self._add(ops.ScheduledSource(p["columns"], batches))
        if kind == "source":
            return self._add(p["build"]())
        if kind == "rowwise":
            return self._lower_rowwise(table)
        if kind == "filter":
            return self._lower_filter(table)
        if kind == "remove_errors":
            return self._add(ops.RemoveErrors(self.lower(table._inputs[0])))
        if kind == "reindex":
            return self._lower_reindex(table)
        if kind == "groupby_reduce":
            return self._lower_groupby(table)
        if kind == "join_select":
            return self._lower_join(table)
        if kind == "concat":
            inputs = [self.lower(t) for t in table._inputs]
            aligned = [
                self._project(n, t, table.column_names())
                for n, t in zip(inputs, table._inputs)
            ]
            # structurally proven disjointness (difference/intersection
            # shapes) needs no runtime liveness state; promised-only
            # disjointness is verified by the engine
            proven = G.solver.query_are_disjoint(
                *[t._universe for t in table._inputs], structural_only=True
            )
            return self._add(ops.Concat(aligned, verify=not proven))
        if kind == "concat_reindex":
            parts = []
            for i, t in enumerate(table._inputs):
                n = self.lower(t)
                salt = 0xC0 + i
                rw = self._add(ops.Rowwise(n, {
                    **{c: _colref(c) for c in t.column_names()},
                    "__newkey__": (lambda cols, keys, s=salt: K.derive(keys, s)),
                }))
                parts.append(self._add(ops.Reindex(rw, "__newkey__",
                                                   keep=table.column_names())))
            return self._add(ops.Concat(parts))
        if kind == "update_rows":
            l = self._project(self.lower(table._inputs[0]), table._inputs[0], table.column_names())
            r = self._project(self.lower(table._inputs[1]), table._inputs[1], table.column_names())
            return self._add(ops.UpdateRows(l, r))
        if kind == "update_cells":
            l = self.lower(table._inputs[0])
            r = self.lower(table._inputs[1])
            return self._add(ops.UpdateCells(l, r, p["override"]))
        if kind in ("restrict", "intersect", "with_universe_of"):
            if kind == "with_universe_of":
                return self.lower(table._inputs[0])
            self_node = self.lower(table._inputs[0])
            other_node = self.lower(table._inputs[1])
            cols = table.column_names()
            return self._add(ops.Join(
                self_node, other_node, None, None,
                left_cols=cols, right_cols=[], out_names=cols,
                mode="inner", key_mode="left",
            ))
        if kind == "difference":
            self_node = self.lower(table._inputs[0])
            other_node = self.lower(table._inputs[1])
            cols = table.column_names()
            return self._add(ops.Join(
                self_node, other_node, None, None,
                left_cols=cols, right_cols=[], out_names=cols,
                mode="left", key_mode="left", emit_matched=False,
            ))
        if kind == "having":
            # result = rows of the indexer's table whose pointer is a key
            # of base, keyed by the indexer table's ids and carrying base's
            # columns (reference HavingContext: universe ⊆ indexer's)
            base_t, other_t = table._inputs
            other_node, env = self._zip_env(other_t, {"__k": p["key_expr"]})
            kc = compile_expr(p["key_expr"], env)
            rw = self._add(ops.Rowwise(other_node, {"__ptr__": kc.fn}))
            base_node = self.lower(base_t)
            cols = table.column_names()
            return self._add(ops.Join(
                rw, base_node, "__ptr__", None,
                left_cols=[], right_cols=cols, out_names=cols,
                mode="inner", key_mode="left",
            ))
        if kind == "ix":
            return self._lower_ix(table)
        if kind == "flatten":
            inp = self.lower(table._inputs[0])
            node = ops.Flatten(inp, p["column"])
            if "origin_id" in p:
                src = self._add(ops.Rowwise(inp, {
                    **{c: _colref(c) for c in table._inputs[0].column_names()},
                    p["origin_id"]: (lambda cols, keys: keys),
                }))
                node = ops.Flatten(src, p["column"])
            return self._add(node)
        if kind == "deduplicate":
            base_t = table._inputs[0]
            exprs: dict[str, ColumnExpression] = {"__val__": p["value"]}
            if p["instance"] is not None:
                exprs["__inst__"] = p["instance"]
            node, env = self._zip_env(base_t, exprs)
            rw_cols = {c: _colref(c) for c in base_t.column_names()}
            rw_cols["__val__"] = compile_expr(p["value"], env).fn
            if p["instance"] is not None:
                rw_cols["__inst__"] = compile_expr(p["instance"], env).fn
            rw = self._add(ops.Rowwise(node, rw_cols))
            dd = self._add(ops.Deduplicate(
                rw, "__val__",
                "__inst__" if p["instance"] is not None else None,
                p["acceptor"],
            ))
            return self._add(ops.Rowwise(dd, {
                c: _colref(c) for c in table.column_names()
            }))
        if kind == "gradual_broadcast":
            main_t, thr_t = table._inputs
            main = self.lower(main_t)
            lower_e, value_e, upper_e = p["cols"]
            thr_node, env = self._zip_env(thr_t, {
                "__l": lower_e, "__v": value_e, "__u": upper_e,
            })
            thr_rw = self._add(ops.Rowwise(thr_node, {
                "__l": compile_expr(lower_e, env).fn,
                "__v": compile_expr(value_e, env).fn,
                "__u": compile_expr(upper_e, env).fn,
            }))
            return self._add(ops.GradualBroadcast(
                main, thr_rw, ("__l", "__v", "__u")
            ))
        if kind == "custom":
            # stdlib escape hatch: the table carries its own lowering function
            return p["lower"](self, table)
        if kind == "iter_pin":
            raise RuntimeError(
                "pw.iterate placeholder table used outside its iterate body "
                f"(input {p.get('name')!r}) — tables created inside the "
                "iterated function must not escape it"
            )
        raise NotImplementedError(f"lowering for kind {kind!r}")

    # ------------------------------------------------------------------

    def _project(self, node: Node, t: Table, names: list[str]) -> Node:
        if node.column_names == names:
            return node
        return self._add(ops.Rowwise(node, {c: _colref(c) for c in names}))

    def _zip_env(
        self, primary: Table, exprs: dict[str, ColumnExpression]
    ) -> tuple[Node, ColumnEnv]:
        """Engine node + env for expressions over `primary` that may also
        reference other (same/super-universe) tables — foreign columns are
        zipped in by key (engine Join on row keys, key_mode='left')."""
        foreign: list[Table] = []
        need_foreign_id: set[int] = set()
        seen = {id(primary)}

        def walk(e: ColumnExpression) -> None:
            if isinstance(e, ColumnReference) and not isinstance(e.table, ThisPlaceholder):
                t = e.table
                if isinstance(t, Table) and id(t) not in seen:
                    seen.add(id(t))
                    foreign.append(t)
                if isinstance(e, IdReference) and t is not primary and isinstance(t, Table):
                    need_foreign_id.add(id(t))
            for d in getattr(e, "_deps", ()):
                walk(d)

        for e in exprs.values():
            walk(e)

        env = ColumnEnv()
        env.add_table(primary)
        node = self.lower(primary)
        cur_cols = list(node.column_names)
        for i, ft in enumerate(foreign):
            # foreign table must cover every primary row: primary ⊆ foreign
            if not (
                primary._universe.is_subset_of(ft._universe)
                or primary._universe.is_equal(ft._universe)
            ):
                raise ValueError(
                    f"column of table {ft!r} used in a context with a different "
                    "universe; consider promise_universes_are_equal"
                )
            fnode = self.lower(ft)
            prefix = f"__f{i}."
            fexprs = {prefix + c: _colref(c) for c in ft.column_names()}
            fexprs[prefix + "id"] = lambda cols, keys: keys
            frw = self._add(ops.Rowwise(fnode, fexprs))
            out_names = cur_cols + list(fexprs.keys())
            node = self._add(ops.Join(
                node, frw, None, None,
                left_cols=cur_cols, right_cols=list(fexprs.keys()),
                out_names=out_names, mode="inner", key_mode="left",
            ))
            cur_cols = out_names
            for c, cs in ft.schema.columns().items():
                env.add(ft, c, prefix + c, cs.dtype)
            env.add(ft, "id", prefix + "id", dt.POINTER)
        return node, env

    def _lower_rowwise(self, table: Table) -> Node:
        primary = table._inputs[0]
        node, env = self._zip_env(primary, table._params["exprs"])
        compiled = {
            name: compile_expr(e, env).fn
            for name, e in table._params["exprs"].items()
        }
        return self._add(ops.Rowwise(node, compiled))

    def _lower_filter(self, table: Table) -> Node:
        primary = table._inputs[0]
        pred = table._params["predicate"]
        node, env = self._zip_env(primary, {"__pred__": pred})
        pc = compile_expr(pred, env)
        filtered = self._add(ops.Filter(node, pc.fn))
        return self._project(filtered, primary, table.column_names())

    def _lower_reindex(self, table: Table) -> Node:
        primary = table._inputs[0]
        key_expr = table._params["key_expr"]
        node, env = self._zip_env(primary, {"__k": key_expr})
        kc = compile_expr(key_expr, env)
        rw = self._add(ops.Rowwise(node, {
            **{c: _colref(c) for c in table.column_names()},
            "__newkey__": kc.fn,
        }))
        return self._add(ops.Reindex(rw, "__newkey__", keep=table.column_names()))

    def _lower_groupby(self, table: Table) -> Node:
        primary = table._inputs[0]
        p = table._params
        grouping: list[ColumnExpression] = p["grouping"]
        reducers = p["reducers"]
        all_exprs: dict[str, ColumnExpression] = {}
        for i, g in enumerate(grouping):
            all_exprs[f"gk{i}"] = g
        for out_name, rname, rargs, rkw in reducers:
            for j, a in enumerate(rargs):
                all_exprs[f"__a_{out_name}_{j}"] = a
        node, env = self._zip_env(primary, all_exprs)
        pre = {name: compile_expr(e, env).fn for name, e in all_exprs.items()}
        pre_node = self._add(ops.Rowwise(node, pre))

        engine_reducers = []
        for out_name, rname, rargs, rkw in reducers:
            if rname in ("sorted_tuple", "tuple", "ndarray"):
                impl = make_reducer(rname, skip_nones=rkw.get("skip_nones", False))
            elif rname == "stateful":
                from ..engine.reducers import StatefulReducer

                impl = StatefulReducer(rkw["combine_fn"])
            elif rname == "custom_accumulator":
                from ..engine.reducers import CustomAccumulatorReducer

                impl = CustomAccumulatorReducer(rkw["accumulator"])
            else:
                impl = make_reducer(rname)
            engine_reducers.append(
                (out_name, impl, [f"__a_{out_name}_{j}" for j in range(len(rargs))])
            )
        group_cols = [f"gk{i}" for i in range(len(grouping))]
        by_id = p["by_id"] and len(grouping) == 1
        gb = self._add(ops.GroupByReduce(
            pre_node, group_cols, engine_reducers,
            key_from_column="gk0" if by_id else None,
            skip_errors=p.get("skip_errors", True),
        ))
        # post projection: grouping refs -> gk{i}, hidden refs resolve directly
        post_env = ColumnEnv()
        for name, i in p["group_names"].items():
            g = grouping[i]
            src = g.table if isinstance(g, ColumnReference) and isinstance(g.table, Table) else primary
            cs = src.schema.columns().get(name) if hasattr(src, "schema") else None
            post_env.add(src, name, f"gk{i}", cs.dtype if cs is not None else dt.ANY)
            if src is not primary:
                post_env.add(primary, name, f"gk{i}", cs.dtype if cs is not None else dt.ANY)
        post = {}
        for name, e in p["outputs"].items():
            post[name] = compile_expr(e, post_env).fn
        return self._add(ops.Rowwise(gb, post))

    def _lower_join(self, table: Table) -> Node:
        lt, rt = table._inputs
        p = table._params
        lnode, lenv = self._zip_env(lt, {f"__c{i}": e for i, e in enumerate(p["left_on"])})
        rnode, renv = self._zip_env(rt, {f"__c{i}": e for i, e in enumerate(p["right_on"])})
        l_on = [compile_expr(e, lenv).fn for e in p["left_on"]]
        r_on = [compile_expr(e, renv).fn for e in p["right_on"]]

        def jk_fn(fns):
            def fn(cols, keys):
                n = len(keys)
                vals = [np.asarray(_mat(f(cols, keys), n)) for f in fns]
                jks = K.mix_columns(vals, n)
                from ..engine.error import Error as _Err, errors_seen

                if errors_seen():
                    # Error join keys hash by repr and would spuriously
                    # match each other — mark them with the reserved
                    # sentinel; the Join node drops sentinel rows + logs
                    for v in vals:
                        if v.dtype == object:
                            m = np.fromiter(
                                (type(x) is _Err for x in v), bool, n
                            )
                            if m.any():
                                jks[m] = K.ERROR_KEY
                return jks
            # static analysis (shard-skew pass): the per-key compiled
            # kernels carry _pw_dtype/_pw_expr breadcrumbs; expose them
            # through the mixing closure the Rowwise node actually holds
            fn._pw_key_fns = fns
            return fn

        lrw = self._add(ops.Rowwise(lnode, {
            **{f"l.{c}": _colref(c) for c in lt.column_names()},
            "l.__id__": lambda cols, keys: keys,
            "__jk__": jk_fn(l_on),
        }))
        rrw = self._add(ops.Rowwise(rnode, {
            **{f"r.{c}": _colref(c) for c in rt.column_names()},
            "r.__id__": lambda cols, keys: keys,
            "__jk__": jk_fn(r_on),
        }))
        lcols = [f"l.{c}" for c in lt.column_names()] + ["l.__id__"]
        rcols = [f"r.{c}" for c in rt.column_names()] + ["r.__id__"]
        key_mode = {"left": "left", "right": "right", None: "pair"}[p["id_side"]]
        join_node = self._add(ops.Join(
            lrw, rrw, "__jk__", "__jk__",
            left_cols=lcols, right_cols=rcols, out_names=lcols + rcols,
            mode=p["mode"], key_mode=key_mode,
            react_to_right=not p.get("asof_now", False),
        ))
        env = ColumnEnv()
        l_opt = p["mode"] in ("right", "outer")
        r_opt = p["mode"] in ("left", "outer")
        for c, cs in lt.schema.columns().items():
            env.add(lt, c, f"l.{c}", dt.Optional(cs.dtype) if l_opt else cs.dtype)
        env.add(lt, "id", "l.__id__", dt.Optional(dt.POINTER) if l_opt else dt.POINTER)
        for c, cs in rt.schema.columns().items():
            env.add(rt, c, f"r.{c}", dt.Optional(cs.dtype) if r_opt else cs.dtype)
        env.add(rt, "id", "r.__id__", dt.Optional(dt.POINTER) if r_opt else dt.POINTER)
        post = {name: compile_expr(e, env).fn for name, e in p["exprs"].items()}
        return self._add(ops.Rowwise(join_node, post))

    def _lower_ix(self, table: Table) -> Node:
        context_t, src_t = table._inputs
        p = table._params
        node, env = self._zip_env(context_t, {"__k": p["key_expr"]})
        kc = compile_expr(p["key_expr"], env)
        rw = self._add(ops.Rowwise(node, {"__ptr__": kc.fn}))
        src_node = self.lower(src_t)
        cols = table.column_names()
        src_proj = self._project(src_node, src_t, src_t.column_names())
        if p["optional"]:
            return self._add(ops.Join(
                rw, src_proj, "__ptr__", None,
                left_cols=[], right_cols=src_t.column_names(), out_names=cols,
                mode="left",
                key_mode="left",
            ))
        # strict ix: a PERMANENTLY missing key is a runtime KeyError
        # (reference test_common.py:2480 test_ix_missing_key). The check
        # fires at end-of-stream, not per tick — a probe may legitimately
        # arrive a commit before its indexed row does (incremental join
        # semantics); only a probe still unmatched when the frontier
        # closes is an error. Infinite streams never raise, they just
        # withhold the unmatched probe rows, exactly as the inner join.
        joined = self._add(ops.Join(
            rw, src_proj, "__ptr__", None,
            left_cols=[], right_cols=src_t.column_names(), out_names=cols,
            mode="inner",
            key_mode="left",
        ))
        self._add(ops.IxStrictCheck(rw, joined))
        return joined


def _colref(name: str):
    return lambda cols, keys, n=name: cols[n]


def _mat(v, n):
    from .expression_compiler import _materialize

    return _materialize(v, n)
