"""Widened UDF lifting: AST lifter + probe-row value tracer.

Two escalating ways to turn a per-row Python UDF into a columnar plan,
both emitting ordinary :class:`ColumnExpression` trees that
``expression_compiler`` compiles to whole-batch kernels (the reference
never executes per-row Python — ``src/engine/expression.rs``):

- :func:`ast_lift` — *static* lifting from the function's source AST.
  Handles what the bytecode-execution trace in ``expression_compiler``
  cannot: method-call chains (``s.lower() + "!"`` via the
  ``MethodCallExpression`` namespaces), dict/tuple-style access
  (``r["x"]``), value conditionals (ternaries, ``if``/``return``
  statements, ``and``/``or``/``not`` — all rewritten to ``if_else``,
  whose per-row truthiness selection matches Python's), ``is None``
  tests, f-strings, and a whitelisted builtin subset (``len``/``abs``/
  ``round``/``str``/``int``/``float``/``bool``/``min``/``max``). Runs
  NO user code — it is side-effect-free by construction. Refuses
  anything it cannot prove equivalent (closure/global reads stay
  late-binding, loops stay per-row).

- :class:`ValueTracer` — *runtime* probe tracing for callables whose
  source is unavailable (``eval``/REPL lambdas) or whose method usage
  only types at runtime. The UDF runs ONCE on a real probe row with
  each argument wrapped in a tracer that computes the genuine scalar
  result while recording the symbolic expression. Control flow on a
  traced value (``bool``/``len``/``iter``/``str``) raises
  :class:`TraceRefused` — a plan traced down one branch of a value
  branch would be wrong for other rows. :func:`traceable` is the
  widened bytecode gate deciding which callables may be probed at all
  (no stores, no imports, no closures, no iteration, globals limited
  to a safe builtin subset) so the single probe run cannot execute
  side effects the per-row path would have run per row.

Both paths share one method/attribute table so a form lifts
identically whichever path catches it.
"""

from __future__ import annotations

import ast
import builtins
import datetime
from typing import Any, Callable

from . import dtype as dt
from .expression import (
    CastExpression,
    ColumnBinaryOpExpression,
    ColumnConstExpression,
    ColumnExpression,
    ColumnUnaryOpExpression,
    GetExpression,
    IfElseExpression,
    IsNoneExpression,
    IsNotNoneExpression,
    MakeTupleExpression,
    MethodCallExpression,
    smart_coerce,
)

__all__ = [
    "LiftRefused",
    "TraceRefused",
    "ValueTracer",
    "ast_lift",
    "trace_probe",
    "traceable",
]


class LiftRefused(Exception):
    """A construct outside the provably-equivalent liftable subset."""


class TraceRefused(BaseException):
    """Raised by tracer dunders the probe run must not fold (bool/len/
    str/iter). BaseException on purpose: a UDF's own ``except
    Exception`` must not swallow it and corrupt the trace."""


# ---------------------------------------------------------------------------
# shared method / attribute tables
# ---------------------------------------------------------------------------

# Python method name -> expression builder. Every builder constructs a
# MethodCallExpression whose engine impl (expressions_namespaces._METHODS)
# is the EXACT Python method it replaces, so lifted and per-row semantics
# agree cell for cell. The two long-refused corners are now aligned
# instead of absent: the engine's ``str.split`` returns a plain Python
# list (it used to wrap in tuple), and ``timestamp`` maps to
# ``py.timestamp`` — the genuine ``datetime.timestamp()``, tz-aware
# exactly like Python (naive datetimes use the local timezone, unlike
# the namespace's epoch-anchored ``dt.timestamp(unit=...)``).
_METHOD_LIFTS: dict[str, Callable[..., ColumnExpression]] = {
    "lower": lambda r: MethodCallExpression("str.lower", [r]),
    "upper": lambda r: MethodCallExpression("str.upper", [r]),
    "strip": lambda r, c=None: MethodCallExpression("str.strip", [r, c]),
    "title": lambda r: MethodCallExpression("str.title", [r]),
    "swapcase": lambda r: MethodCallExpression("str.swapcase", [r]),
    "startswith": lambda r, p: MethodCallExpression("str.startswith", [r, p]),
    "endswith": lambda r, s: MethodCallExpression("str.endswith", [r, s]),
    "removeprefix": lambda r, p: MethodCallExpression(
        "str.removeprefix", [r, p]
    ),
    "removesuffix": lambda r, s: MethodCallExpression(
        "str.removesuffix", [r, s]
    ),
    "count": lambda r, s: MethodCallExpression("str.count", [r, s]),
    "find": lambda r, s: MethodCallExpression("str.find", [r, s]),
    "rfind": lambda r, s: MethodCallExpression("str.rfind", [r, s]),
    "replace": lambda r, o, n, c=-1: MethodCallExpression(
        "str.replace", [r, o, n, c]
    ),
    "split": lambda r, sep=None, m=-1: MethodCallExpression(
        "str.split", [r, sep, m]
    ),
    "strftime": lambda r, f: MethodCallExpression("dt.strftime", [r, f]),
    "weekday": lambda r: MethodCallExpression("dt.weekday", [r]),
    "timestamp": lambda r: MethodCallExpression("py.timestamp", [r]),
}

#: methods only the VALUE TRACER may lift: their compiled expression
#: assumes a receiver type the AST lifter cannot see. ``.get`` compiles
#: to a dict-typed GetExpression — on a non-dict receiver the per-row
#: path raises AttributeError while the kernel would silently index, so
#: lifting is sound only after the probe row proves the receiver IS a
#: dict (the tracer checks the real type before intercepting).
_TRACER_ONLY_LIFTS: dict[str, Callable[..., ColumnExpression]] = {
    "get": lambda r, k, d=None: GetExpression(
        r, k, default=d, check_if_exists=False
    ),
}

#: datetime attribute -> engine method whose impl is exactly that
#: attribute read. timedelta's ``.days``/``.seconds`` are deliberately
#: absent: Python floors them while the engine's ``dt.days`` truncates
#: toward zero — negative durations would diverge.
_ATTR_LIFTS: dict[str, str] = {
    "year": "dt.year",
    "month": "dt.month",
    "day": "dt.day",
    "hour": "dt.hour",
    "minute": "dt.minute",
    "second": "dt.second",
    "microsecond": "dt.microsecond",
}

#: constants a lifted tree may embed (late-binding / aliasing hazards
#: rule out everything mutable)
_CONST_TYPES = (
    type(None), bool, int, float, str, bytes,
    datetime.datetime, datetime.date, datetime.timedelta,
)


def _builtin_ok(fn: Callable, name: str) -> bool:
    """True when ``name`` resolves to the genuine builtin in ``fn``'s
    globals — a module-level shadow must keep its late-binding per-row
    semantics."""
    b = getattr(builtins, name, None)
    if b is None:
        return False
    g = getattr(fn, "__globals__", None)
    return g is None or g.get(name, b) is b


def _not_expr(x: ColumnExpression) -> ColumnExpression:
    # Python `not x` is truthiness-exact for ANY operand type via the
    # if_else kernel (bool(cell) per object cell) — `~x` would be int
    # complement on non-bools
    return IfElseExpression(x, False, True)


def _min_expr(a, b) -> ColumnExpression:
    # Python's exact rule: `b if b < a else a` — returns the FIRST
    # minimal argument on ties AND keeps Python's NaN behavior
    # (min(nan, x) is nan, min(x, nan) is x: NaN comparisons are False)
    return IfElseExpression(
        ColumnBinaryOpExpression(b, a, "<"), b, a
    )


def _max_expr(a, b) -> ColumnExpression:
    return IfElseExpression(
        ColumnBinaryOpExpression(b, a, ">"), b, a
    )


def _round_expr(x, nd=None) -> ColumnExpression:
    if nd is None:
        # 1-arg round returns int in Python; num.round keeps the dtype
        return CastExpression(dt.INT, MethodCallExpression("num.round", [x, 0]))
    return MethodCallExpression("num.round", [x, nd])


#: builtin name -> (expression builder, min positional args, max)
_BUILTIN_LIFTS: dict[str, tuple[Callable[..., Any], int, int]] = {
    "len": (lambda x: MethodCallExpression("str.len", [x]), 1, 1),
    "abs": (lambda x: ColumnUnaryOpExpression(x, "abs"), 1, 1),
    "round": (_round_expr, 1, 2),
    "str": (lambda x: MethodCallExpression("to_string", [x]), 1, 1),
    # per-element int(): the dense CastExpression astype would turn
    # NaN/inf into INT64_MIN silently instead of a per-row Error
    "int": (lambda x: MethodCallExpression("py.int", [x]), 1, 1),
    "float": (lambda x: CastExpression(dt.FLOAT, x), 1, 1),
    "bool": (lambda x: CastExpression(dt.BOOL, x), 1, 1),
    "min": (_min_expr, 2, 2),
    "max": (_max_expr, 2, 2),
}


# ---------------------------------------------------------------------------
# AST lifting
# ---------------------------------------------------------------------------

_BIN_OPS = {
    ast.Add: "+", ast.Sub: "-", ast.Mult: "*", ast.Div: "/",
    ast.FloorDiv: "//", ast.Mod: "%", ast.Pow: "**", ast.MatMult: "@",
    ast.LShift: "<<", ast.RShift: ">>", ast.BitAnd: "&",
    ast.BitOr: "|", ast.BitXor: "^",
}

_CMP_OPS = {
    ast.Eq: "==", ast.NotEq: "!=", ast.Lt: "<", ast.LtE: "<=",
    ast.Gt: ">", ast.GtE: ">=",
}

#: statement-lifting bound: sequential `if` statements duplicate their
#: tail into both branches, so cap the lifted-node budget rather than
#: risk exponential trees on pathological inputs
_NODE_BUDGET = 400


def ast_lift(
    fn: Callable,
    args: tuple,
    kwargs: dict[str, Any],
    reason_out: list | None = None,
) -> ColumnExpression | None:
    """Build the ColumnExpression equivalent of ``fn(*args, **kwargs)``
    from ``fn``'s source AST, or None when any construct falls outside
    the liftable subset (source unavailable, closures/globals, loops,
    unknown methods...). ``args``/``kwargs`` are the already-coerced
    argument ColumnExpressions of the apply node. ``reason_out``, when
    given, receives the refusing construct as a string — the static
    analyzer's dispatch-tax diagnostic reports it verbatim."""
    try:
        node = _fn_ast(fn)
        if node is None:
            if reason_out is not None:
                reason_out.append("source unavailable or ambiguous")
            return None
        scope = _bind_params(fn, node, args, kwargs)
        lifter = _AstLifter(fn)
        if isinstance(node, ast.Lambda):
            return lifter.lift(node.body, scope)
        return lifter.lift_body(list(node.body), scope)
    except RecursionError:
        if reason_out is not None:
            reason_out.append("recursion limit during lift")
        return None
    except LiftRefused as e:
        if reason_out is not None:
            reason_out.append(str(e) or "refused construct")
        return None


def _fn_ast(fn: Callable) -> ast.Lambda | ast.FunctionDef | None:
    import inspect
    import textwrap

    if getattr(fn, "__wrapped__", None) is not None:
        # functools.wraps-style decoration: getsource unwraps to the
        # ORIGINAL body while the callable runs the wrapper — compiling
        # the original would silently drop the wrapper's behavior
        return None
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return None
    try:
        tree = ast.parse(src)
    except SyntaxError:
        # a lambda extracted mid-expression (continuation lines, trailing
        # operators) may not parse standalone
        return None
    code = getattr(fn, "__code__", None)
    if code is None:
        return None
    # the matched node must be THIS callable's code, not a same-named
    # sibling: arg names come from fn.__code__, so a wrapper whose
    # signature differs from the wrapped def never matches it
    want = code.co_varnames[: code.co_argcount + code.co_kwonlyargcount]
    matches: list[ast.Lambda | ast.FunctionDef] = []
    for n in ast.walk(tree):
        if isinstance(n, ast.Lambda):
            names = tuple(a.arg for a in n.args.args + n.args.kwonlyargs)
            if names == want:
                matches.append(n)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names = tuple(
                a.arg for a in n.args.args + n.args.kwonlyargs
            ) + tuple(a.arg for a in n.args.posonlyargs)
            if n.name == getattr(fn, "__name__", None) and names == want:
                matches.append(n)
    if len(matches) != 1 or isinstance(matches[0], ast.AsyncFunctionDef):
        # zero: not found; several: ambiguous (two lambdas on one line)
        return None
    node = matches[0]
    a = node.args
    if a.vararg or a.kwarg or a.posonlyargs:
        return None
    # fn must take the same parameter shape the node declares (a *args
    # wrapper around a plain def has different code flags)
    if code.co_flags & (inspect.CO_VARARGS | inspect.CO_VARKEYWORDS):
        return None
    return node


def _bind_params(
    fn: Callable,
    node: ast.Lambda | ast.FunctionDef,
    args: tuple,
    kwargs: dict[str, Any],
) -> dict[str, ColumnExpression]:
    names = [a.arg for a in node.args.args]
    kw_names = [a.arg for a in node.args.kwonlyargs]
    scope: dict[str, ColumnExpression] = {}
    if len(args) > len(names):
        raise LiftRefused("too many positional args")
    for name, e in zip(names, args):
        scope[name] = e
    for k, e in kwargs.items():
        if k not in names + kw_names or k in scope:
            raise LiftRefused(f"bad kwarg {k}")
        scope[k] = e
    # defaults for unbound params — immutable constants only
    defaults = node.args.defaults
    for name, dnode in zip(names[len(names) - len(defaults):], defaults):
        if name not in scope:
            v = _const_of(dnode)
            scope[name] = smart_coerce(v)
    for a, dnode in zip(node.args.kwonlyargs, node.args.kw_defaults):
        if a.arg not in scope:
            if dnode is None:
                raise LiftRefused(f"missing kwonly {a.arg}")
            scope[a.arg] = smart_coerce(_const_of(dnode))
    missing = [n for n in names + kw_names if n not in scope]
    if missing:
        raise LiftRefused(f"unbound params {missing}")
    return scope


def _const_of(node: ast.AST) -> Any:
    if isinstance(node, ast.Constant) and isinstance(
        node.value, _CONST_TYPES
    ):
        return node.value
    if isinstance(node, ast.Tuple):
        return tuple(_const_of(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _const_of(node.operand)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return -v
    raise LiftRefused("non-constant default")


class _AstLifter:
    def __init__(self, fn: Callable):
        self.fn = fn
        self.budget = _NODE_BUDGET

    def _spend(self) -> None:
        self.budget -= 1
        if self.budget <= 0:
            raise LiftRefused("lift budget exhausted")

    # -- statements -----------------------------------------------------

    def lift_body(
        self, stmts: list[ast.stmt], scope: dict[str, ColumnExpression]
    ) -> ColumnExpression:
        """Lift a straight-line function body: docstring + simple
        assignments + ``if``/``return`` trees. An ``if`` duplicates the
        statement tail into both branches (each with its own scope copy),
        so assignments under a branch stay branch-local — exactly
        Python's dataflow for side-effect-free bodies."""
        for i, st in enumerate(stmts):
            self._spend()
            if isinstance(st, ast.Expr) and isinstance(st.value, ast.Constant):
                continue  # docstring / bare literal
            if isinstance(st, ast.Pass):
                continue
            if isinstance(st, ast.Assign):
                if len(st.targets) != 1 or not isinstance(
                    st.targets[0], ast.Name
                ):
                    raise LiftRefused("complex assignment")
                scope[st.targets[0].id] = self.lift(st.value, scope)
                continue
            if isinstance(st, ast.AnnAssign):
                if st.value is None or not isinstance(st.target, ast.Name):
                    raise LiftRefused("annotation-only assignment")
                scope[st.target.id] = self.lift(st.value, scope)
                continue
            if isinstance(st, ast.Return):
                if st.value is None:
                    raise LiftRefused("bare return")
                return self.lift(st.value, scope)
            if isinstance(st, ast.If):
                cond = self.lift(st.test, scope)
                tail = stmts[i + 1:]
                then_v = self.lift_body(list(st.body) + tail, dict(scope))
                else_v = self.lift_body(list(st.orelse) + tail, dict(scope))
                return IfElseExpression(cond, then_v, else_v)
            raise LiftRefused(f"statement {type(st).__name__}")
        raise LiftRefused("fell off the end (implicit return None)")

    # -- expressions ----------------------------------------------------

    def lift(
        self, node: ast.expr, scope: dict[str, ColumnExpression]
    ) -> ColumnExpression | Any:
        self._spend()
        if isinstance(node, ast.Constant):
            if not isinstance(node.value, _CONST_TYPES):
                raise LiftRefused(f"constant {type(node.value).__name__}")
            return smart_coerce(node.value)
        if isinstance(node, ast.Name):
            if node.id in scope:
                return scope[node.id]
            # bare builtin names (uncalled) and module globals keep their
            # late-binding per-row semantics
            raise LiftRefused(f"free name {node.id}")
        if isinstance(node, ast.BinOp):
            op = _BIN_OPS.get(type(node.op))
            if op is None:
                raise LiftRefused(f"binop {type(node.op).__name__}")
            return ColumnBinaryOpExpression(
                self.lift(node.left, scope), self.lift(node.right, scope), op
            )
        if isinstance(node, ast.UnaryOp):
            v = self.lift(node.operand, scope)
            if isinstance(node.op, ast.USub):
                return ColumnUnaryOpExpression(v, "-")
            if isinstance(node.op, ast.UAdd):
                return v
            if isinstance(node.op, ast.Invert):
                return ColumnUnaryOpExpression(v, "~")
            if isinstance(node.op, ast.Not):
                return _not_expr(v)
            raise LiftRefused("unary op")
        if isinstance(node, ast.Compare):
            return self._lift_compare(node, scope)
        if isinstance(node, ast.BoolOp):
            # `a and b` == b if truthy(a) else a; `a or b` == a if
            # truthy(a) else b — if_else selects per row by Python
            # truthiness, so this is exact for any operand types.
            # (Operands are evaluated eagerly; errors become per-row
            # Error values, which where-selection then discards for rows
            # whose branch was not taken.)
            vals = [self.lift(v, scope) for v in node.values]
            out = vals[0]
            for v in vals[1:]:
                if isinstance(node.op, ast.And):
                    out = IfElseExpression(out, v, out)
                else:
                    out = IfElseExpression(out, out, v)
            return out
        if isinstance(node, ast.IfExp):
            return IfElseExpression(
                self.lift(node.test, scope),
                self.lift(node.body, scope),
                self.lift(node.orelse, scope),
            )
        if isinstance(node, ast.Subscript):
            if isinstance(node.slice, ast.Slice):
                raise LiftRefused("slice subscript")
            return GetExpression(
                self.lift(node.value, scope),
                self.lift(node.slice, scope),
                check_if_exists=True,
            )
        if isinstance(node, ast.Attribute):
            engine = _ATTR_LIFTS.get(node.attr)
            if engine is None:
                raise LiftRefused(f"attribute {node.attr}")
            return MethodCallExpression(engine, [self.lift(node.value, scope)])
        if isinstance(node, ast.Call):
            return self._lift_call(node, scope)
        if isinstance(node, ast.Tuple):
            return MakeTupleExpression(
                *[self.lift(e, scope) for e in node.elts]
            )
        if isinstance(node, ast.JoinedStr):
            return self._lift_fstring(node, scope)
        raise LiftRefused(f"expression {type(node).__name__}")

    def _lift_compare(self, node: ast.Compare, scope) -> ColumnExpression:
        if any(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            # only the sole-comparator `x is [not] None` form lifts
            if len(node.ops) != 1 or not (
                isinstance(node.comparators[0], ast.Constant)
                and node.comparators[0].value is None
            ):
                raise LiftRefused("`is` outside `x is [not] None`")
            cls = (
                IsNoneExpression
                if isinstance(node.ops[0], ast.Is)
                else IsNotNoneExpression
            )
            return cls(self.lift(node.left, scope))
        parts: list[ColumnExpression] = []
        left = self.lift(node.left, scope)
        for op, comparator in zip(node.ops, node.comparators):
            sym = _CMP_OPS.get(type(op))
            if sym is None:
                raise LiftRefused(f"compare {type(op).__name__}")
            right = self.lift(comparator, scope)
            parts.append(ColumnBinaryOpExpression(left, right, sym))
            left = right
        out = parts[0]
        for p in parts[1:]:
            out = ColumnBinaryOpExpression(out, p, "&")
        return out

    def _lift_call(self, node: ast.Call, scope) -> ColumnExpression:
        if node.keywords:
            raise LiftRefused("call with keywords")
        args = [self.lift(a, scope) for a in node.args]
        f = node.func
        if isinstance(f, ast.Attribute):
            builder = _METHOD_LIFTS.get(f.attr)
            if builder is None:
                raise LiftRefused(f"method {f.attr}")
            recv = self.lift(f.value, scope)
            try:
                return builder(recv, *args)
            except TypeError:
                raise LiftRefused(f"arity of {f.attr}") from None
        if isinstance(f, ast.Name):
            entry = _BUILTIN_LIFTS.get(f.id)
            if entry is None or not _builtin_ok(self.fn, f.id):
                raise LiftRefused(f"call to {getattr(f, 'id', '?')}")
            builder, lo, hi = entry
            if not lo <= len(args) <= hi:
                raise LiftRefused(f"arity of {f.id}")
            return builder(*args)
        raise LiftRefused("computed call")

    def _lift_fstring(self, node: ast.JoinedStr, scope) -> ColumnExpression:
        out: ColumnExpression | None = None
        for part in node.values:
            if isinstance(part, ast.Constant):
                piece: Any = smart_coerce(part.value)
            elif isinstance(part, ast.FormattedValue):
                if part.format_spec is not None or part.conversion not in (
                    -1, 115,  # default / !s — both str()
                ):
                    raise LiftRefused("f-string format spec")
                piece = MethodCallExpression(
                    "to_string", [self.lift(part.value, scope)]
                )
            else:
                raise LiftRefused("f-string part")
            out = piece if out is None else ColumnBinaryOpExpression(
                out, piece, "+"
            )
        if out is None:
            return smart_coerce("")
        return out


# ---------------------------------------------------------------------------
# probe-row value tracing
# ---------------------------------------------------------------------------


def _unwrap_operand(x: Any) -> tuple[Any, Any]:
    """(real value, expression operand) of a tracer or plain constant."""
    if isinstance(x, ValueTracer):
        return x.v, x.e
    if isinstance(x, ColumnExpression):
        raise TraceRefused
    return x, x  # constant — smart_coerce'd by the expression ctor


def _trace_binop(sym: str):
    import operator as _op

    py = {
        "+": _op.add, "-": _op.sub, "*": _op.mul, "/": _op.truediv,
        "//": _op.floordiv, "%": _op.mod, "**": _op.pow,
        "==": _op.eq, "!=": _op.ne, "<": _op.lt, "<=": _op.le,
        ">": _op.gt, ">=": _op.ge, "&": _op.and_, "|": _op.or_,
        "^": _op.xor, "<<": _op.lshift, ">>": _op.rshift, "@": _op.matmul,
    }[sym]

    def fwd(self: "ValueTracer", other: Any) -> "ValueTracer":
        ov, oe = _unwrap_operand(other)
        return ValueTracer(
            py(self.v, ov), ColumnBinaryOpExpression(self.e, oe, sym)
        )

    def rev(self: "ValueTracer", other: Any) -> "ValueTracer":
        ov, oe = _unwrap_operand(other)
        return ValueTracer(
            py(ov, self.v), ColumnBinaryOpExpression(oe, self.e, sym)
        )

    return fwd, rev


class _TracedMethod:
    __slots__ = ("_recv", "_name")

    def __init__(self, recv: "ValueTracer", name: str):
        self._recv = recv
        self._name = name

    def __call__(self, *args: Any, **kwargs: Any) -> "ValueTracer":
        if kwargs:
            raise TraceRefused
        real_args, expr_args = [], []
        for a in args:
            rv, re_ = _unwrap_operand(a)
            real_args.append(rv)
            expr_args.append(re_)
        real = getattr(self._recv.v, self._name)(*real_args)
        builder = (
            _METHOD_LIFTS.get(self._name) or _TRACER_ONLY_LIFTS[self._name]
        )
        try:
            expr = builder(self._recv.e, *expr_args)
        except TypeError:
            raise TraceRefused from None
        return ValueTracer(real, expr)


class ValueTracer:
    """A probe-row scalar carrying (real value, symbolic expression).
    Every supported operation computes the genuine Python result AND
    records the columnar expression; anything that would fold a value
    into control flow or a foreign type raises :class:`TraceRefused`."""

    __slots__ = ("v", "e")

    def __init__(self, v: Any, e: Any):
        self.v = v
        self.e = smart_coerce(e) if not isinstance(e, ColumnExpression) else e

    # control flow / coercions a trace cannot represent
    def __bool__(self) -> bool:
        raise TraceRefused

    def __len__(self) -> int:
        raise TraceRefused

    def __iter__(self):
        raise TraceRefused

    def __contains__(self, item):
        raise TraceRefused

    def __int__(self):
        raise TraceRefused

    def __float__(self):
        raise TraceRefused

    def __index__(self):
        raise TraceRefused

    def __str__(self):
        raise TraceRefused

    def __format__(self, spec):
        raise TraceRefused

    def __hash__(self):
        return object.__hash__(self)

    # value access
    def __getitem__(self, k):
        kv, ke = _unwrap_operand(k)
        return ValueTracer(
            self.v[kv], GetExpression(self.e, ke, check_if_exists=True)
        )

    def __getattr__(self, name: str):
        engine = _ATTR_LIFTS.get(name)
        if engine is not None and isinstance(
            self.v, (datetime.date, datetime.datetime)
        ):
            return ValueTracer(
                getattr(self.v, name), MethodCallExpression(engine, [self.e])
            )
        if (name in _METHOD_LIFTS or name in _TRACER_ONLY_LIFTS) and callable(
            getattr(type(self.v), name, None)
        ):
            if name in _TRACER_ONLY_LIFTS and not isinstance(self.v, dict):
                raise TraceRefused  # .get's kernel is dict-typed
            return _TracedMethod(self, name)
        raise TraceRefused

    # unary
    def __neg__(self):
        return ValueTracer(-self.v, ColumnUnaryOpExpression(self.e, "-"))

    def __pos__(self):
        return ValueTracer(+self.v, self.e)

    def __invert__(self):
        return ValueTracer(~self.v, ColumnUnaryOpExpression(self.e, "~"))

    def __abs__(self):
        return ValueTracer(abs(self.v), ColumnUnaryOpExpression(self.e, "abs"))

    def __round__(self, nd=None):
        if nd is None:
            return ValueTracer(round(self.v), _round_expr(self.e))
        nv, ne = _unwrap_operand(nd)
        return ValueTracer(round(self.v, nv), _round_expr(self.e, ne))


for _sym in (
    "+", "-", "*", "/", "//", "%", "**", "&", "|", "^", "<<", ">>", "@",
):
    _f, _r = _trace_binop(_sym)
    _n = {
        "+": "add", "-": "sub", "*": "mul", "/": "truediv",
        "//": "floordiv", "%": "mod", "**": "pow", "&": "and",
        "|": "or", "^": "xor", "<<": "lshift", ">>": "rshift",
        "@": "matmul",
    }[_sym]
    setattr(ValueTracer, f"__{_n}__", _f)
    setattr(ValueTracer, f"__r{_n}__", _r)
for _sym, _n in (
    ("==", "eq"), ("!=", "ne"), ("<", "lt"),
    ("<=", "le"), (">", "gt"), (">=", "ge"),
):
    _f, _r = _trace_binop(_sym)
    setattr(ValueTracer, f"__{_n}__", _f)
del _sym, _n, _f, _r


def trace_probe(
    fn: Callable,
    probe_args: list,
    arg_exprs: list,
    probe_kwargs: dict[str, Any],
    kwarg_exprs: dict[str, Any],
) -> tuple[ColumnExpression, Any]:
    """Run ``fn`` once on the probe row with tracer-wrapped arguments.
    Returns (traced expression, the genuine scalar result for the probe
    row — the caller's consistency check). Raises TraceRefused (or any
    error the probe row itself would raise per-row) on failure."""
    tracers = [ValueTracer(v, e) for v, e in zip(probe_args, arg_exprs)]
    kts = {
        k: ValueTracer(probe_kwargs[k], kwarg_exprs[k]) for k in probe_kwargs
    }
    out = fn(*tracers, **kts)
    if isinstance(out, ValueTracer):
        return out.e, out.v
    if isinstance(out, _CONST_TYPES):
        # a constant-valued UDF still lifts (rare but valid)
        return smart_coerce(out), out
    raise TraceRefused


# ---------------------------------------------------------------------------
# widened bytecode gate for probe tracing
# ---------------------------------------------------------------------------

#: globals a traced callable may read — they resolve to tracer dunders
#: (``abs``/``round``) so the probe stays symbolic
_TRACE_GLOBAL_WHITELIST = frozenset({"abs", "round"})

_BLOCKED_TRACE_OPS = (
    # side effects / late binding
    "IMPORT", "MAKE_FUNCTION", "MAKE_CELL",
    "STORE_GLOBAL", "STORE_DEREF", "STORE_ATTR", "STORE_SUBSCR",
    "DELETE_GLOBAL", "DELETE_DEREF", "DELETE_ATTR", "DELETE_SUBSCR",
    "LOAD_DEREF", "LOAD_CLASSDEREF", "LOAD_NAME", "LOAD_BUILD_CLASS",
    # iteration / generators (tracer iteration would spin or fold)
    "GET_ITER", "FOR_ITER", "GET_AITER", "GET_ANEXT", "GET_AWAITABLE",
    "YIELD", "RETURN_GENERATOR", "UNPACK",
    # identity tests fold silently on a tracer (no dunder fires)
    "IS_OP", "POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE",
    "CONTAINS_OP",
    # exception machinery: a UDF-level `except` could mask TraceRefused
    # ordering subtleties; stay per-row
    "SETUP_FINALLY", "SETUP_WITH", "BEFORE_WITH", "RAISE_VARARGS",
    "RERAISE", "PUSH_EXC_INFO", "CHECK_EXC_MATCH", "JUMP_IF_NOT_EXC",
    "WITH_EXCEPT", "END_ASYNC",
)

#: verdicts per code object; capped with oldest-half eviction (the
#: verdict is a pure bytecode property, so the code object is the key)
_TRACEABLE_CACHE: dict[Any, bool] = {}
_TRACEABLE_CACHE_MAX = 1024


def evict_oldest_half(d: dict) -> None:
    """Drop the least-recently-inserted half of a dict-backed cache —
    the cliff-free replacement for wholesale ``clear()`` (a long-lived
    multi-pipeline process must not re-derive every cached verdict at
    once)."""
    import itertools

    for k in list(itertools.islice(iter(d), max(1, len(d) // 2))):
        del d[k]


def traceable(fn: Callable) -> bool:
    """May ``fn`` be probe-traced? A single probe run must be unable to
    execute side effects the per-row path would have run per row: no
    stores outside locals, no imports, no closure/global reads (beyond
    the safe builtin subset), no iteration, no exception handling.
    CALLs are allowed — with globals restricted, the only reachable
    callables are tracer methods (intercepted) and whitelisted
    builtins."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return False
    hit = _TRACEABLE_CACHE.get(code)
    if hit is not None:
        return hit
    import dis

    try:
        instructions = list(dis.get_instructions(fn))
    except TypeError:
        return False
    verdict = True
    for ins in instructions:
        name = ins.opname
        if name.startswith("LOAD_GLOBAL"):
            if (
                ins.argval not in _TRACE_GLOBAL_WHITELIST
                or not _builtin_ok(fn, ins.argval)
            ):
                verdict = False
                break
            continue
        if name.startswith(_BLOCKED_TRACE_OPS):
            verdict = False
            break
    if len(_TRACEABLE_CACHE) >= _TRACEABLE_CACHE_MAX:
        evict_oldest_half(_TRACEABLE_CACHE)
    _TRACEABLE_CACHE[code] = verdict
    return verdict
