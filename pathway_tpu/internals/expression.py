"""ColumnExpression AST for the declarative Table API.

Re-design of the reference's expression tree
(``python/pathway/internals/expression.py:88-1140``). Nodes are pure data;
typing and compilation to columnar kernels live in
``internals/expression_compiler.py`` (the analog of the reference's
``type_interpreter.py`` + the Rust typed interpreter ``src/engine/expression.rs``,
except expressions here compile to whole-batch numpy/JAX functions instead of
row-at-a-time evaluation).
"""

from __future__ import annotations

import operator
from typing import TYPE_CHECKING, Any, Callable, Iterable

from . import dtype as dt

if TYPE_CHECKING:
    from .table import Table


class ColumnExpression:
    _dtype: dt.DType | None = None

    # -- arithmetic --
    def __add__(self, other):
        return ColumnBinaryOpExpression(self, other, "+")

    def __radd__(self, other):
        return ColumnBinaryOpExpression(other, self, "+")

    def __sub__(self, other):
        return ColumnBinaryOpExpression(self, other, "-")

    def __rsub__(self, other):
        return ColumnBinaryOpExpression(other, self, "-")

    def __mul__(self, other):
        return ColumnBinaryOpExpression(self, other, "*")

    def __rmul__(self, other):
        return ColumnBinaryOpExpression(other, self, "*")

    def __truediv__(self, other):
        return ColumnBinaryOpExpression(self, other, "/")

    def __rtruediv__(self, other):
        return ColumnBinaryOpExpression(other, self, "/")

    def __floordiv__(self, other):
        return ColumnBinaryOpExpression(self, other, "//")

    def __rfloordiv__(self, other):
        return ColumnBinaryOpExpression(other, self, "//")

    def __mod__(self, other):
        return ColumnBinaryOpExpression(self, other, "%")

    def __rmod__(self, other):
        return ColumnBinaryOpExpression(other, self, "%")

    def __pow__(self, other):
        return ColumnBinaryOpExpression(self, other, "**")

    def __rpow__(self, other):
        return ColumnBinaryOpExpression(other, self, "**")

    def __matmul__(self, other):
        return ColumnBinaryOpExpression(self, other, "@")

    def __rmatmul__(self, other):
        return ColumnBinaryOpExpression(other, self, "@")

    def __lshift__(self, other):
        return ColumnBinaryOpExpression(self, other, "<<")

    def __rlshift__(self, other):
        return ColumnBinaryOpExpression(other, self, "<<")

    def __rshift__(self, other):
        return ColumnBinaryOpExpression(self, other, ">>")

    def __rrshift__(self, other):
        return ColumnBinaryOpExpression(other, self, ">>")

    def __neg__(self):
        return ColumnUnaryOpExpression(self, "-")

    # -- comparisons --
    def __eq__(self, other):  # type: ignore[override]
        return ColumnBinaryOpExpression(self, other, "==")

    def __ne__(self, other):  # type: ignore[override]
        return ColumnBinaryOpExpression(self, other, "!=")

    def __lt__(self, other):
        return ColumnBinaryOpExpression(self, other, "<")

    def __le__(self, other):
        return ColumnBinaryOpExpression(self, other, "<=")

    def __gt__(self, other):
        return ColumnBinaryOpExpression(self, other, ">")

    def __ge__(self, other):
        return ColumnBinaryOpExpression(self, other, ">=")

    # -- boolean / bitwise --
    def __and__(self, other):
        return ColumnBinaryOpExpression(self, other, "&")

    def __rand__(self, other):
        return ColumnBinaryOpExpression(other, self, "&")

    def __or__(self, other):
        return ColumnBinaryOpExpression(self, other, "|")

    def __ror__(self, other):
        return ColumnBinaryOpExpression(other, self, "|")

    def __xor__(self, other):
        return ColumnBinaryOpExpression(self, other, "^")

    def __rxor__(self, other):
        return ColumnBinaryOpExpression(other, self, "^")

    def __invert__(self):
        return ColumnUnaryOpExpression(self, "~")

    def __abs__(self):
        return ColumnUnaryOpExpression(self, "abs")

    def __hash__(self):
        return object.__hash__(self)

    def __bool__(self):
        raise TypeError(
            "ColumnExpression is not a boolean; use & | ~ instead of and/or/not, "
            "and == inside expressions builds an expression."
        )

    # -- item access --
    def __getitem__(self, index):
        return GetExpression(self, index, check_if_exists=True)

    def get(self, index, default=None):
        return GetExpression(self, index, default=default, check_if_exists=False)

    def is_none(self):
        return IsNoneExpression(self)

    def is_not_none(self):
        return IsNotNoneExpression(self)

    def to_string(self):
        return MethodCallExpression("to_string", [self])

    def as_int(self):
        return CastExpression(dt.Optional(dt.INT), self)

    def as_float(self):
        return CastExpression(dt.Optional(dt.FLOAT), self)

    def as_str(self):
        return CastExpression(dt.Optional(dt.STR), self)

    def as_bool(self):
        return CastExpression(dt.Optional(dt.BOOL), self)

    @property
    def dt(self):
        from .expressions_namespaces import DateTimeNamespace

        return DateTimeNamespace(self)

    @property
    def str(self):
        from .expressions_namespaces import StringNamespace

        return StringNamespace(self)

    @property
    def num(self):
        from .expressions_namespaces import NumericalNamespace

        return NumericalNamespace(self)

    @property
    def _deps(self) -> tuple["ColumnExpression", ...]:
        return ()

    def _collect_tables(self, order: list) -> None:
        if isinstance(self, ColumnReference):
            t = self._table
            if all(t is not o for o in order):
                order.append(t)
        for d in self._deps:
            if isinstance(d, ColumnExpression):
                d._collect_tables(order)

    def _fmt(self, tables: dict) -> str:
        return f"<{type(self).__name__}>"

    def __repr__(self) -> str:
        # reference ExpressionFormatter: tables number in first-appearance
        # order within ONE repr -> stable "<table1>.col" labels
        order: list = []
        self._collect_tables(order)
        tables = {id(t): i + 1 for i, t in enumerate(order)}
        return self._fmt(tables)


def smart_coerce(v: Any) -> ColumnExpression:
    if isinstance(v, ColumnExpression):
        return v
    return ColumnConstExpression(v)


class ColumnConstExpression(ColumnExpression):
    def __init__(self, value: Any):
        self._value = value

    def _fmt(self, tables: dict) -> str:
        return f"{self._value!r}"



class ColumnReference(ColumnExpression):
    """Reference to a column of a concrete table (``t.colname``)."""

    def __init__(self, table: "Table", name: str):
        self._table = table
        self._name = name

    @property
    def table(self) -> "Table":
        return self._table

    @property
    def name(self) -> str:
        return self._name

    def _fmt(self, tables: dict) -> str:
        n = tables.get(id(self._table))
        label = f"<table{n}>" if n is not None else f"<table {id(self._table):#x}>"
        return f"{label}.{self._name}"


class IdReference(ColumnReference):
    """``t.id`` — the pointer (row key) pseudo-column."""

    def __init__(self, table: "Table"):
        super().__init__(table, "id")


class SelfKeysExpression(ColumnExpression):
    """Compiles to the current batch's row keys (join-output ``pw.this.id``)."""

    @property
    def _deps(self):
        return ()


class HiddenRef(ColumnExpression):
    """Reference to a hidden engine column (reducer results etc.)."""

    def __init__(self, engine_name: str, dtype=None):
        self._engine_name = engine_name
        self._dtype = dtype

    @property
    def _deps(self):
        return ()

    def _fmt(self, tables: dict) -> str:
        return f"<hidden {self._engine_name}>"


class ColumnBinaryOpExpression(ColumnExpression):
    def __init__(self, left: Any, right: Any, op: str):
        self._left = smart_coerce(left)
        self._right = smart_coerce(right)
        self._op = op

    @property
    def _deps(self):
        return (self._left, self._right)

    def _fmt(self, tables: dict) -> str:
        return (
            f"({self._left._fmt(tables)} {self._op} "
            f"{self._right._fmt(tables)})"
        )


class ColumnUnaryOpExpression(ColumnExpression):
    def __init__(self, expr: Any, op: str):
        self._expr = smart_coerce(expr)
        self._op = op

    @property
    def _deps(self):
        return (self._expr,)


class ReducerExpression(ColumnExpression):
    def __init__(self, name: str, args: tuple, **kwargs: Any):
        self._reducer = name
        self._args = tuple(smart_coerce(a) for a in args)
        self._kwargs = kwargs

    @property
    def _deps(self):
        return self._args

    def _fmt(self, tables: dict) -> str:
        inner = ", ".join(a._fmt(tables) for a in self._args)
        return f"pathway.reducers.{self._reducer}({inner})"


class ApplyExpression(ColumnExpression):
    def __init__(
        self,
        fn: Callable,
        return_type: Any,
        args: tuple,
        kwargs: dict[str, Any],
        *,
        propagate_none: bool = False,
        deterministic: bool = True,
    ):
        self._fn = fn
        self._return_type = dt.wrap(return_type)
        self._args = tuple(smart_coerce(a) for a in args)
        self._kwargs = {k: smart_coerce(v) for k, v in kwargs.items()}
        self._propagate_none = propagate_none
        self._deterministic = deterministic

    @property
    def _deps(self):
        return self._args + tuple(self._kwargs.values())


class AsyncApplyExpression(ApplyExpression):
    pass


class CastExpression(ColumnExpression):
    def __init__(self, return_type: Any, expr: Any):
        self._return_type = dt.wrap(return_type)
        self._expr = smart_coerce(expr)

    @property
    def _deps(self):
        return (self._expr,)


class ConvertExpression(ColumnExpression):
    """Json value conversion (``.as_int()`` etc on Json)."""

    def __init__(self, return_type: Any, expr: Any, default: Any = None, unwrap: bool = False):
        self._return_type = dt.wrap(return_type)
        self._expr = smart_coerce(expr)
        self._default = smart_coerce(default)
        self._unwrap = unwrap

    @property
    def _deps(self):
        return (self._expr, self._default)


class DeclareTypeExpression(ColumnExpression):
    def __init__(self, return_type: Any, expr: Any):
        self._return_type = dt.wrap(return_type)
        self._expr = smart_coerce(expr)

    @property
    def _deps(self):
        return (self._expr,)


class CoalesceExpression(ColumnExpression):
    def __init__(self, *args: Any):
        self._args = tuple(smart_coerce(a) for a in args)

    @property
    def _deps(self):
        return self._args


class RequireExpression(ColumnExpression):
    def __init__(self, expr: Any, *args: Any):
        self._expr = smart_coerce(expr)
        self._args = tuple(smart_coerce(a) for a in args)

    @property
    def _deps(self):
        return (self._expr,) + self._args


class IfElseExpression(ColumnExpression):
    def __init__(self, _if: Any, _then: Any, _else: Any):
        self._if = smart_coerce(_if)
        self._then = smart_coerce(_then)
        self._else = smart_coerce(_else)

    @property
    def _deps(self):
        return (self._if, self._then, self._else)


class IsNoneExpression(ColumnExpression):
    def __init__(self, expr: Any):
        self._expr = smart_coerce(expr)

    @property
    def _deps(self):
        return (self._expr,)


class IsNotNoneExpression(IsNoneExpression):
    pass


class PointerExpression(ColumnExpression):
    """``table.pointer_from(*args, instance=...)`` — derive a row pointer."""

    def __init__(self, table: "Table | None", *args: Any, instance: Any = None, optional: bool = False):
        self._table = table
        self._args = tuple(smart_coerce(a) for a in args)
        self._instance = smart_coerce(instance) if instance is not None else None
        self._optional = optional

    @property
    def _deps(self):
        extra = (self._instance,) if self._instance is not None else ()
        return self._args + extra


class MakeTupleExpression(ColumnExpression):
    def __init__(self, *args: Any):
        self._args = tuple(smart_coerce(a) for a in args)

    @property
    def _deps(self):
        return self._args


class GetExpression(ColumnExpression):
    def __init__(self, obj: Any, index: Any, default: Any = None, check_if_exists: bool = True):
        self._obj = smart_coerce(obj)
        self._index = smart_coerce(index)
        self._default = smart_coerce(default)
        self._check_if_exists = check_if_exists

    @property
    def _deps(self):
        return (self._obj, self._index, self._default)


class MethodCallExpression(ColumnExpression):
    """Namespace method call (``x.dt.round('1h')``, ``x.str.lower()``)."""

    def __init__(self, method: str, args: Iterable[Any], **kwargs: Any):
        self._method = method
        self._args = tuple(smart_coerce(a) for a in args)
        self._method_kwargs = kwargs

    @property
    def _deps(self):
        return self._args


class UnwrapExpression(ColumnExpression):
    def __init__(self, expr: Any):
        self._expr = smart_coerce(expr)

    @property
    def _deps(self):
        return (self._expr,)


class FillErrorExpression(ColumnExpression):
    def __init__(self, expr: Any, replacement: Any):
        self._expr = smart_coerce(expr)
        self._replacement = smart_coerce(replacement)

    @property
    def _deps(self):
        return (self._expr, self._replacement)


# ---------------------------------------------------------------------------
# free functions (exported at package level)
# ---------------------------------------------------------------------------


def cast(target_type: Any, expr: Any) -> CastExpression:
    return CastExpression(target_type, expr)


def declare_type(target_type: Any, expr: Any) -> DeclareTypeExpression:
    return DeclareTypeExpression(target_type, expr)


def coalesce(*args: Any) -> CoalesceExpression:
    return CoalesceExpression(*args)


def require(val: Any, *args: Any) -> RequireExpression:
    return RequireExpression(val, *args)


def if_else(if_clause: Any, then_clause: Any, else_clause: Any) -> IfElseExpression:
    return IfElseExpression(if_clause, then_clause, else_clause)


def make_tuple(*args: Any) -> MakeTupleExpression:
    return MakeTupleExpression(*args)


def unwrap(col: Any) -> UnwrapExpression:
    return UnwrapExpression(col)


def fill_error(col: Any, replacement: Any) -> FillErrorExpression:
    return FillErrorExpression(col, replacement)


def apply(fn: Callable, *args: Any, **kwargs: Any) -> ApplyExpression:
    import typing

    hints = typing.get_type_hints(fn) if callable(fn) else {}
    ret = hints.get("return", dt.ANY)
    return ApplyExpression(fn, ret, args, kwargs)


def apply_with_type(fn: Callable, ret_type: Any, *args: Any, **kwargs: Any) -> ApplyExpression:
    return ApplyExpression(fn, ret_type, args, kwargs)


def apply_async(fn: Callable, *args: Any, **kwargs: Any) -> AsyncApplyExpression:
    import typing

    hints = typing.get_type_hints(fn) if callable(fn) else {}
    ret = hints.get("return", dt.ANY)
    return AsyncApplyExpression(fn, ret, args, kwargs)
