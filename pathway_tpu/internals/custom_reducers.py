"""Custom reducer accumulators (reference ``internals/custom_reducers.py``)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any


class BaseCustomAccumulator(ABC):
    """Subclass with ``from_row``, ``update`` (mutating), ``compute_result``,
    and optionally ``retract`` to support deletions
    (reference custom_reducers.py:271)."""

    @classmethod
    @abstractmethod
    def from_row(cls, row: list) -> "BaseCustomAccumulator": ...

    @abstractmethod
    def update(self, other: "BaseCustomAccumulator") -> None: ...

    def retract(self, other: "BaseCustomAccumulator") -> None:
        raise NotImplementedError(
            f"{type(self).__name__} does not support retractions; "
            "override retract() to handle deletions"
        )

    @abstractmethod
    def compute_result(self) -> Any: ...


def stateful_single(combine_fn, *args):
    from .. import reducers

    return reducers.stateful_single(combine_fn, *args)


def stateful_many(combine_fn, *args):
    from .. import reducers

    return reducers.stateful_many(combine_fn, *args)


def udf_reducer(reducer_cls):
    from .. import reducers

    return reducers.udf_reducer(reducer_cls)
