"""ArgTuple — named/positional result wrapper (reference
``internals/arg_tuple.py``): functions returning a dict or iterable get
their result wrapped so callers can unpack positionally, index by name,
or use attribute access; single-element results collapse to the bare
value, scalars pass through."""

from __future__ import annotations

from typing import Any, Callable

__all__ = ["ArgTuple", "wrap_arg_tuple"]


class ArgTuple:
    def __init__(self, entries: dict[str, Any]):
        self._entries = dict(entries)

    def __iter__(self):
        return iter(self._entries.values())

    def __len__(self) -> int:
        return len(self._entries)

    def __getitem__(self, key: str) -> Any:
        return self._entries[str(key)]

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return self._entries[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def __eq__(self, other: object) -> bool:
        if isinstance(other, ArgTuple):
            return self._entries == other._entries
        return NotImplemented

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}={v!r}" for k, v in self._entries.items())
        return f"ArgTuple({inner})"

    def to_dict(self) -> dict[str, Any]:
        return dict(self._entries)


def as_arg_tuple(result: Any) -> Any:
    """Wrap a dict/iterable result as an ArgTuple; collapse one-element
    results to the bare value; scalars pass through unchanged."""
    if isinstance(result, ArgTuple):
        entries = result.to_dict()
    elif isinstance(result, dict):
        entries = dict(result)
    elif isinstance(result, (list, tuple)):
        entries = {str(i): v for i, v in enumerate(result)}
    else:
        return result
    if len(entries) == 1:
        (only,) = entries.values()
        if isinstance(only, (dict, list, tuple)):
            # keep structure when the single element is itself structured
            return ArgTuple(entries)
        # single-element collapse still supports name/index access
        wrapped = ArgTuple(entries)
        return _Scalarish(only, wrapped)
    return ArgTuple(entries)


class _Scalarish:
    """A single-element result: compares/acts like the bare value but
    keeps the name/index access of its ArgTuple."""

    __slots__ = ("_value", "_tuple")

    def __init__(self, value: Any, tup: ArgTuple):
        object.__setattr__(self, "_value", value)
        object.__setattr__(self, "_tuple", tup)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, _Scalarish):
            other = other._value
        return self._value == other

    def __getattr__(self, name: str) -> Any:
        try:
            return getattr(object.__getattribute__(self, "_tuple"), name)
        except AttributeError:
            return getattr(object.__getattribute__(self, "_value"), name)

    def __getitem__(self, key: Any) -> Any:
        try:
            return self._tuple[key]
        except (KeyError, TypeError):
            return self._value[key]

    def __repr__(self) -> str:
        return repr(self._value)

    def __hash__(self) -> int:
        return hash(self._value)

    def __iter__(self):
        return iter(self._tuple)


def wrap_arg_tuple(fn: Callable[..., Any]) -> Callable[..., Any]:
    """Decorator: the function's result goes through ``as_arg_tuple``."""

    def wrapped(*args: Any, **kwargs: Any) -> Any:
        return as_arg_tuple(fn(*args, **kwargs))

    return wrapped
