"""Interactive mode: live tables in a REPL/notebook.

Re-design of ``python/pathway/internals/interactive.py`` (LiveTable
``:130``, ``enable_interactive_mode`` ``:202``): ``t.live()`` runs the
table's upstream subgraph on a background engine thread and returns a
handle whose ``snapshot()``/``frontier()``/``failed()`` observe the
continuously-updated state; printing a live table (or any snapshot)
renders the current rows. ``enable_interactive_mode()`` installs a
displayhook so a bare ``t.live()`` at the REPL prints itself, like the
reference's ``InteractiveModeController``.

Where the reference exports through the engine's ExportedTable handoff
(``src/engine/dataflow/export.rs``), here the background runner feeds a
plain key→row dict through a Subscribe sink — the total-order tick sweep
makes every observed snapshot a consistent prefix of the stream.
"""

from __future__ import annotations

import sys
import threading
from typing import Any, Callable

__all__ = [
    "LiveTable",
    "LiveTableSnapshot",
    "enable_interactive_mode",
    "is_interactive_mode_enabled",
]


class DisplayAsStr:
    """Rendered via str() by the interactive displayhook."""


class LiveTableSnapshot(DisplayAsStr):
    """A consistent view of a live table as of one frontier time."""

    def __init__(self, frontier: int, names: list[str], rows: dict[int, tuple]):
        self.frontier = frontier
        self.column_names = names
        self.rows = rows

    def __len__(self) -> int:
        return len(self.rows)

    def __str__(self) -> str:
        from ..debug import _format_snapshot

        return _format_snapshot(self.column_names, self.rows, self.frontier)


class LiveTable(DisplayAsStr):
    """A table running live on a background engine thread.

    Reference ``interactive.py:130`` — snapshot/frontier/failed have the
    same meaning; ``subscribe`` works here (the reference left it TODO).
    """

    def __init__(self, origin: Any):
        from ..engine import operators as ops
        from .graph_runner import GraphRunner

        self._names = list(origin.column_names())
        self._lock = threading.Lock()
        self._rows: dict[int, tuple] = {}
        self._frontier = 0
        self._error: BaseException | None = None
        self._callbacks: list[Callable[..., None]] = []
        self._stopped = threading.Event()

        runner = GraphRunner()
        node = runner.lower(origin)
        sub = ops.Subscribe(
            node,
            # one call per consolidated tick delta: the whole tick applies
            # under a single lock acquisition, so a concurrent snapshot()
            # never observes a half-applied tick
            on_batch=self._on_tick_delta,
            on_time_end=self._on_time_end,
        )
        runner._nodes.append(sub)
        self._runner = runner

        def work() -> None:
            try:
                runner._execute()
            except BaseException as e:  # noqa: BLE001 — surfaced via failed()
                self._error = e
            finally:
                self._stopped.set()

        self._thread = threading.Thread(
            target=work, name=f"live table {origin!r}", daemon=True
        )
        self._thread.start()

    # -- state ingestion (engine thread) -------------------------------

    def _on_tick_delta(self, time, delta) -> None:
        from ..engine.delta import rows_equal

        entries = list(delta.iter_rows())  # (key, row_tuple, diff)
        with self._lock:
            for key, values, diff in entries:
                if diff > 0:
                    self._rows[key] = values
                elif rows_equal(self._rows.get(key), values):
                    # value-aware (array-safe: tuple == on ndarray cells
                    # raises): within a tick the retract of the OLD row may
                    # come after the insert of the new one for the same
                    # key — only remove what is actually stored
                    self._rows.pop(key, None)
            # snapshot under the lock: subscribe() appends concurrently.
            # Callbacks run on the engine thread, after the tick's rows are
            # applied but before the next tick can mutate them (the engine
            # sweep is single-threaded per worker).
            cbs = list(self._callbacks)
        for cb in cbs:
            for key, values, diff in entries:
                cb(
                    key=key,
                    row=dict(zip(self._names, values)),
                    time=time,
                    is_addition=diff > 0,
                )

    def _on_time_end(self, time: int) -> None:
        with self._lock:
            self._frontier = max(self._frontier, time)

    # -- observers (any thread) -----------------------------------------

    def live(self) -> "LiveTable":
        return self

    def failed(self) -> bool:
        return self._error is not None

    def frontier(self) -> int:
        with self._lock:
            return self._frontier

    def snapshot(self) -> LiveTableSnapshot:
        with self._lock:
            return LiveTableSnapshot(
                self._frontier, self._names, dict(self._rows)
            )

    def subscribe(self, callback: Callable[..., None]) -> None:
        """Register an on_change-style callback (key=, row=, time=,
        is_addition=) fired for every future update."""
        self._callbacks.append(callback)

    def stop(self) -> None:
        """Wind the background engine down (joins the thread)."""
        # the flag covers the window before the executor exists
        # (graph_runner honors stop_requested at executor creation)
        self._runner.stop_requested = True
        if self._runner.executor is not None:
            self._runner.executor.request_stop()
        self._stopped.wait(timeout=30)

    def __str__(self) -> str:
        if self._error is not None:
            return f"LiveTable FAILED: {self._error!r}"
        return str(self.snapshot())


class InteractiveModeController:
    def __init__(self) -> None:
        self._orig_displayhook = sys.displayhook
        sys.displayhook = self._displayhook

    def _displayhook(self, value: object) -> None:
        if isinstance(value, DisplayAsStr):
            import builtins

            builtins._ = value
            print(str(value))
        else:
            self._orig_displayhook(value)

    def disable(self) -> None:
        global _controller
        sys.displayhook = self._orig_displayhook
        if _controller is self:
            _controller = None  # a later enable() reinstalls the hook


_controller: InteractiveModeController | None = None


def is_interactive_mode_enabled() -> bool:
    return _controller is not None


def enable_interactive_mode() -> InteractiveModeController:
    import warnings

    global _controller
    if _controller is None:
        warnings.warn("interactive mode is experimental", stacklevel=2)
        _controller = InteractiveModeController()
    return _controller
