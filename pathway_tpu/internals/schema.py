"""``pw.Schema`` — typed table schemas.

Re-design of ``python/pathway/internals/schema.py`` (947 LoC in the
reference): a Schema subclass's annotations define column names and dtypes;
``column_definition`` adds per-column options (primary keys, defaults).
"""

from __future__ import annotations

import typing
from dataclasses import dataclass, field
from typing import Any

from . import dtype as dt

__all__ = [
    "Schema",
    "SchemaProperties",
    "ColumnDefinition",
    "column_definition",
    "schema_from_types",
    "schema_from_dict",
    "schema_builder",
    "assert_table_has_schema",
]


@dataclass(frozen=True)
class ColumnSchema:
    name: str
    dtype: dt.DType
    primary_key: bool = False
    default_value: Any = None
    has_default: bool = False
    #: reference column property: the column never retracts (connector
    #: hint + optimization flag; carried as metadata here)
    append_only: bool = False


@dataclass(frozen=True)
class SchemaProperties:
    """Schema-wide properties (reference internals/schema.py
    SchemaProperties): ``append_only`` marks every column append-only."""

    append_only: bool = False


@dataclass
class ColumnDefinition:
    primary_key: bool = False
    default_value: Any = None
    dtype: Any = None
    name: str | None = None
    _has_default: bool = False
    append_only: bool | None = None


_NO_DEFAULT = object()


def column_definition(
    *,
    primary_key: bool = False,
    default_value: Any = _NO_DEFAULT,
    dtype: Any = None,
    name: str | None = None,
    append_only: bool | None = None,
) -> Any:
    return ColumnDefinition(
        primary_key=primary_key,
        default_value=None if default_value is _NO_DEFAULT else default_value,
        dtype=dtype,
        name=name,
        _has_default=default_value is not _NO_DEFAULT,
        append_only=append_only,
    )


class SchemaMetaclass(type):
    __columns__: dict[str, ColumnSchema]

    def __new__(mcls, name, bases, namespace, **kwargs):
        # class keywords (append_only=...) are schema properties, not
        # __init_subclass__ arguments
        return super().__new__(mcls, name, bases, namespace)

    def __init__(cls, name, bases, namespace, **kwargs):
        super().__init__(name, bases, namespace)
        # ``class S(pw.Schema, append_only=True)`` (reference schema
        # class-keyword properties)
        schema_ao = bool(kwargs.get("append_only", False)) or any(
            getattr(base, "__append_only__", False) for base in bases
        )
        cls.__append_only__ = schema_ao
        columns: dict[str, ColumnSchema] = {}
        for base in reversed(bases):
            columns.update(getattr(base, "__columns__", {}))
        try:
            hints = typing.get_type_hints(cls)
        except Exception:
            hints = dict(namespace.get("__annotations__", {}))
        for col_name, annotation in namespace.get("__annotations__", {}).items():
            if col_name.startswith("__"):
                continue
            resolved = hints.get(col_name, annotation)
            definition = namespace.get(col_name)
            if isinstance(definition, ColumnDefinition):
                out_name = definition.name or col_name
                columns[out_name] = ColumnSchema(
                    name=out_name,
                    dtype=dt.wrap(definition.dtype) if definition.dtype is not None else dt.wrap(resolved),
                    primary_key=definition.primary_key,
                    default_value=definition.default_value,
                    has_default=definition._has_default,
                    append_only=(
                        schema_ao
                        if definition.append_only is None
                        else definition.append_only
                    ),
                )
            else:
                columns[col_name] = ColumnSchema(
                    name=col_name, dtype=dt.wrap(resolved),
                    append_only=schema_ao,
                )
        cls.__columns__ = columns

    def column_names(cls) -> list[str]:
        return list(cls.__columns__.keys())

    def columns(cls) -> dict[str, ColumnSchema]:
        return dict(cls.__columns__)

    def properties(cls) -> "SchemaProperties":
        return SchemaProperties(
            append_only=bool(getattr(cls, "__append_only__", False))
            or (
                bool(cls.__columns__)
                and all(c.append_only for c in cls.__columns__.values())
            )
        )

    def primary_key_columns(cls) -> list[str] | None:
        pks = [c.name for c in cls.__columns__.values() if c.primary_key]
        return pks or None

    def typehints(cls) -> dict[str, Any]:
        return {n: c.dtype.typehint() for n, c in cls.__columns__.items()}

    def dtypes(cls) -> dict[str, dt.DType]:
        return {n: c.dtype for n, c in cls.__columns__.items()}

    def __or__(cls, other: "SchemaMetaclass") -> "SchemaMetaclass":  # type: ignore[override]
        merged = dict(cls.__columns__)
        merged.update(other.__columns__)
        return schema_from_columns(merged, name=f"{cls.__name__}|{other.__name__}")

    def update_types(cls, **kwargs: Any) -> "SchemaMetaclass":
        cols = dict(cls.__columns__)
        for name, t in kwargs.items():
            if name not in cols:
                raise ValueError(f"Schema has no column {name!r}")
            old = cols[name]
            cols[name] = ColumnSchema(
                name=name, dtype=dt.wrap(t), primary_key=old.primary_key,
                default_value=old.default_value, has_default=old.has_default,
            )
        return schema_from_columns(cols, name=cls.__name__)

    def without(cls, *names: str) -> "SchemaMetaclass":
        cols = {n: c for n, c in cls.__columns__.items() if n not in names}
        return schema_from_columns(cols, name=cls.__name__)

    def __repr__(cls) -> str:
        inner = ", ".join(f"{n}: {c.dtype!r}" for n, c in cls.__columns__.items())
        return f"<pw.Schema {cls.__name__}({inner})>"


class Schema(metaclass=SchemaMetaclass):
    pass


def schema_from_columns(
    columns: dict[str, ColumnSchema], name: str = "Schema"
) -> SchemaMetaclass:
    cls = SchemaMetaclass(name, (Schema,), {})
    cls.__columns__ = dict(columns)
    return cls


def schema_from_types(_name: str = "Schema", **kwargs: Any) -> SchemaMetaclass:
    return schema_from_columns(
        {n: ColumnSchema(name=n, dtype=dt.wrap(t)) for n, t in kwargs.items()},
        name=_name,
    )


def schema_from_dict(
    types: dict[str, Any], name: str = "Schema"
) -> SchemaMetaclass:
    cols: dict[str, ColumnSchema] = {}
    for col, spec in types.items():
        if isinstance(spec, dict):
            cols[col] = ColumnSchema(
                name=col,
                dtype=dt.wrap(spec.get("dtype", dt.ANY)),
                primary_key=spec.get("primary_key", False),
                default_value=spec.get("default_value"),
                has_default="default_value" in spec,
            )
        else:
            cols[col] = ColumnSchema(name=col, dtype=dt.wrap(spec))
    return schema_from_columns(cols, name=name)


def schema_builder(
    columns: dict[str, Any], *, name: str = "Schema", properties: Any = None
) -> SchemaMetaclass:
    schema_ao = bool(getattr(properties, "append_only", False))
    cols: dict[str, ColumnSchema] = {}
    for col, definition in columns.items():
        if isinstance(definition, ColumnDefinition):
            # column_definition(name=...) renames the column (reference
            # schema_builder/class parity)
            cols[definition.name or col] = ColumnSchema(
                name=definition.name or col,
                dtype=dt.wrap(definition.dtype) if definition.dtype is not None else dt.ANY,
                primary_key=definition.primary_key,
                default_value=definition.default_value,
                has_default=definition._has_default,
                append_only=(
                    schema_ao
                    if definition.append_only is None
                    else definition.append_only
                ),
            )
        else:
            cols[col] = ColumnSchema(
                name=col, dtype=dt.wrap(definition), append_only=schema_ao
            )
    out = schema_from_columns(cols, name=name)
    out.__append_only__ = schema_ao
    return out


def assert_table_has_schema(
    table: Any,
    schema: SchemaMetaclass,
    *,
    allow_superset: bool = True,
    ignore_primary_keys: bool = True,
) -> None:
    actual = table.schema.dtypes()
    for name, expected in schema.dtypes().items():
        if name not in actual:
            raise AssertionError(f"table is missing column {name!r}")
        if expected != dt.ANY and actual[name] != expected:
            raise AssertionError(
                f"column {name!r} has dtype {actual[name]!r}, expected {expected!r}"
            )
    if not allow_superset:
        extra = set(actual) - set(schema.dtypes())
        if extra:
            raise AssertionError(f"table has extra columns: {sorted(extra)}")
