"""DType lattice for the declarative layer and the engine.

TPU-native re-design of the reference's type system (reference:
``python/pathway/internals/dtype.py`` and ``src/engine/value.rs:507-524``).
Unlike the reference, a single module serves both the Python API layer and the
engine: columns are numpy/JAX arrays, so each DType also knows its storage
representation (``numpy_dtype``; ``object`` for irregular data).
"""

from __future__ import annotations

import datetime
import typing
from abc import ABC
from typing import Any

import numpy as np

from .json import Json

__all__ = [
    "DType",
    "ANY",
    "NONE",
    "INT",
    "FLOAT",
    "BOOL",
    "STR",
    "BYTES",
    "POINTER",
    "DATE_TIME_NAIVE",
    "DATE_TIME_UTC",
    "DURATION",
    "JSON",
    "Optional",
    "Tuple",
    "List",
    "Array",
    "Callable",
    "PyObjectWrapper",
    "wrap",
    "unoptionalize",
    "types_lca",
    "dtype_issubclass",
]


class DType(ABC):
    """Base of the dtype lattice."""

    _name: str = "DType"

    @property
    def numpy_dtype(self) -> np.dtype:
        return np.dtype(object)

    @property
    def is_optional(self) -> bool:
        return False

    def to_python_type(self) -> Any:
        return object

    def __repr__(self) -> str:
        return self._name

    def typehint(self) -> Any:
        return self.to_python_type()


class _SimpleDType(DType):
    def __init__(self, name: str, np_dtype: Any, py_type: Any):
        self._name = name
        self._np_dtype = np.dtype(np_dtype)
        self._py_type = py_type

    @property
    def numpy_dtype(self) -> np.dtype:
        return self._np_dtype

    def to_python_type(self) -> Any:
        return self._py_type

    def __eq__(self, other: object) -> bool:
        return isinstance(other, _SimpleDType) and other._name == self._name

    def __hash__(self) -> int:
        return hash(("dtype", self._name))


class _AnyDType(_SimpleDType):
    pass


ANY = _AnyDType("ANY", object, object)
NONE = _SimpleDType("NONE", object, type(None))
INT = _SimpleDType("INT", np.int64, int)
FLOAT = _SimpleDType("FLOAT", np.float64, float)
BOOL = _SimpleDType("BOOL", np.bool_, bool)
STR = _SimpleDType("STR", object, str)
BYTES = _SimpleDType("BYTES", object, bytes)
# Pointers (row keys) are engine 64-bit hashes; see engine/keys.py.
POINTER = _SimpleDType("POINTER", np.uint64, int)


class Pointer(int):
    """Typehint for pointer (row-key) columns — ``pw.Pointer[Any]`` in
    schemas (reference ``internals/api.py`` Pointer)."""

    def __class_getitem__(cls, item: Any) -> type:
        return cls
# datetimes/durations stored as int64 nanoseconds (epoch / delta).
DATE_TIME_NAIVE = _SimpleDType("DATE_TIME_NAIVE", np.int64, datetime.datetime)
DATE_TIME_UTC = _SimpleDType("DATE_TIME_UTC", np.int64, datetime.datetime)
DURATION = _SimpleDType("DURATION", np.int64, datetime.timedelta)
JSON = _SimpleDType("JSON", object, object)


class Optional(DType):
    def __init__(self, wrapped: DType):
        # collapse Optional(Optional(x)) and Optional(ANY/NONE)
        while isinstance(wrapped, Optional):
            wrapped = wrapped.wrapped
        self.wrapped = wrapped
        self._name = f"Optional({wrapped!r})"

    def __new__(cls, wrapped: DType):
        if isinstance(wrapped, Optional):
            return wrapped
        if wrapped is ANY or wrapped is NONE:
            return wrapped  # type: ignore[return-value]
        return super().__new__(cls)

    @property
    def is_optional(self) -> bool:
        return True

    @property
    def numpy_dtype(self) -> np.dtype:
        return np.dtype(object)

    def to_python_type(self) -> Any:
        return typing.Optional[self.wrapped.to_python_type()]

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Optional) and other.wrapped == self.wrapped

    def __hash__(self) -> int:
        return hash(("Optional", self.wrapped))


class Tuple(DType):
    def __new__(cls, *args: Any):
        # Tuple(T, ...) IS List(T) (reference dtype identity,
        # test_dtypes.py: dt.Tuple(dt.INT, ...) is dt.List(dt.INT))
        if len(args) == 2 and args[1] is Ellipsis:
            return List(args[0])  # type: ignore[return-value]
        return super().__new__(cls)

    def __init__(self, *args: DType):
        if len(args) == 2 and args[1] is Ellipsis:
            return  # __new__ returned a List; skip Tuple init
        self.args = tuple(args)
        self._name = f"Tuple({', '.join(map(repr, args))})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Tuple) and other.args == self.args

    def __hash__(self) -> int:
        return hash(("Tuple", self.args))

    def to_python_type(self) -> Any:
        return tuple


class List(DType):
    def __init__(self, wrapped: DType):
        self.wrapped = wrapped
        self._name = f"List({wrapped!r})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, List) and other.wrapped == self.wrapped

    def __hash__(self) -> int:
        return hash(("List", self.wrapped))

    def to_python_type(self) -> Any:
        return list


class Array(DType):
    """ndarray column type (reference value.rs:507-524 `Type::Array`)."""

    def __init__(self, n_dim: int | None = None, wrapped: DType = FLOAT):
        self.n_dim = n_dim
        self.wrapped = wrapped
        self._name = f"Array({n_dim}, {wrapped!r})"

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Array)
            and other.n_dim == self.n_dim
            and other.wrapped == self.wrapped
        )

    def __hash__(self) -> int:
        return hash(("Array", self.n_dim, self.wrapped))

    def to_python_type(self) -> Any:
        return np.ndarray


class Callable(DType):
    _name = "Callable"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Callable)

    def __hash__(self) -> int:
        return hash("Callable")


class PyObjectWrapper(DType):
    """Opaque wrapped-python-object dtype; optionally parameterized with
    the wrapped class (``pw.PyObjectWrapper[MyClass]`` annotations)."""

    _name = "PyObjectWrapper"

    def __init__(self, wrapped: Any = None):
        self.wrapped = wrapped

    def __repr__(self) -> str:
        if self.wrapped is None:
            return self._name
        return f"PyObjectWrapper[{getattr(self.wrapped, '__name__', self.wrapped)!s}]"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, PyObjectWrapper)

    def __hash__(self) -> int:
        return hash("PyObjectWrapper")

    def is_value_compatible(self, value: Any) -> bool:
        from .py_object_wrapper import PyObjectWrapper as _Wrapper

        if not isinstance(value, _Wrapper):
            return False
        if self.wrapped is None:
            return True
        return type(value.value) is self.wrapped or isinstance(
            value.value, self.wrapped
        )


_FROM_PY: dict[Any, DType] = {
    int: INT,
    float: FLOAT,
    bool: BOOL,
    str: STR,
    bytes: BYTES,
    type(None): NONE,
    datetime.datetime: DATE_TIME_NAIVE,
    datetime.timedelta: DURATION,
    np.ndarray: Array(),
    Any: ANY,
    object: ANY,
    dict: JSON,
    Json: JSON,
    list: List(ANY),
    tuple: Tuple(),
}


def wrap(t: Any) -> DType:
    """Convert a python type / typing annotation / DType into a DType.
    String type names ("int", "str", "float", ...) are accepted too, for
    schemas loaded from JSON/YAML (reference schema.py:783: "both int and
    'int' are accepted"); unrecognized strings degrade to ANY like any
    other unresolvable annotation (e.g. an unevaluated forward ref)."""
    if isinstance(t, DType):
        return t
    if t is None:
        return NONE
    if isinstance(t, str):
        return _FROM_NAME.get(t.strip().lower(), ANY)
    origin = typing.get_origin(t)
    if origin is typing.Union:
        args = typing.get_args(t)
        non_none = [a for a in args if a is not type(None)]
        inner = types_lca_many([wrap(a) for a in non_none]) if non_none else NONE
        if type(None) in args:
            return Optional(inner)
        return inner
    if origin in (tuple,):
        args = typing.get_args(t)
        if len(args) == 2 and args[1] is Ellipsis:
            return List(wrap(args[0]))
        return Tuple(*[wrap(a) for a in args])
    if origin in (list,):
        args = typing.get_args(t)
        return List(wrap(args[0]) if args else ANY)
    if isinstance(t, type) and issubclass(t, Pointer):
        return POINTER
    from .py_object_wrapper import PyObjectWrapper as _PyObjWrapper

    if t is _PyObjWrapper:
        return PyObjectWrapper()
    if origin is _PyObjWrapper:  # PyObjectWrapper[MyClass]
        args = typing.get_args(t)
        return PyObjectWrapper(args[0] if args else None)
    if t in _FROM_PY:
        return _FROM_PY[t]
    if isinstance(t, type) and issubclass(t, np.integer):
        return INT
    if isinstance(t, type) and issubclass(t, np.floating):
        return FLOAT
    return ANY


#: string type names for JSON/YAML-loaded schemas (wrap() docstring)
_FROM_NAME = {
    "int": INT, "float": FLOAT, "str": STR, "string": STR,
    "bool": BOOL, "bytes": BYTES, "any": ANY, "json": JSON,
    "pointer": POINTER, "datetime": DATE_TIME_NAIVE,
    "datetimenaive": DATE_TIME_NAIVE, "datetimeutc": DATE_TIME_UTC,
    "duration": DURATION,
}


def unoptionalize(t: DType) -> DType:
    return t.wrapped if isinstance(t, Optional) else t


def dtype_of_value(v: Any) -> DType:
    if v is None:
        return NONE
    if isinstance(v, bool) or isinstance(v, np.bool_):
        return BOOL
    if isinstance(v, (int, np.integer)):
        return INT
    if isinstance(v, (float, np.floating)):
        return FLOAT
    if isinstance(v, str):
        return STR
    if isinstance(v, bytes):
        return BYTES
    if isinstance(v, datetime.timedelta):
        return DURATION
    if isinstance(v, datetime.datetime):
        return DATE_TIME_UTC if v.tzinfo is not None else DATE_TIME_NAIVE
    if isinstance(v, np.ndarray):
        return Array(v.ndim, wrap(type(v.reshape(-1)[0].item())) if v.size else FLOAT)
    if isinstance(v, tuple):
        return Tuple(*[dtype_of_value(x) for x in v])
    if isinstance(v, (dict, Json)):
        return JSON
    from .py_object_wrapper import PyObjectWrapper as _PyObjWrapper

    if isinstance(v, _PyObjWrapper):
        return PyObjectWrapper(type(v.value))
    return ANY


def dtype_issubclass(sub: DType, sup: DType) -> bool:
    if sup == ANY or sub == sup:
        return True
    if sub == NONE:
        return isinstance(sup, Optional) or sup == NONE
    if isinstance(sup, Optional):
        return dtype_issubclass(sub, sup.wrapped) or sub == NONE
    if isinstance(sub, Optional):
        return False
    if sub == INT and sup == FLOAT:
        return True
    if sub == BOOL and sup == INT:
        return True
    if isinstance(sub, Tuple) and isinstance(sup, Tuple):
        return len(sub.args) == len(sup.args) and all(
            dtype_issubclass(a, b) for a, b in zip(sub.args, sup.args)
        )
    if isinstance(sub, Array) and isinstance(sup, Array):
        return (sup.n_dim is None or sub.n_dim == sup.n_dim) and dtype_issubclass(
            sub.wrapped, sup.wrapped
        )
    return False


def types_lca(a: DType, b: DType) -> DType:
    """Least common ancestor in the lattice."""
    if a == b:
        return a
    if dtype_issubclass(a, b):
        return b
    if dtype_issubclass(b, a):
        return a
    if a == NONE:
        return Optional(b)
    if b == NONE:
        return Optional(a)
    ua, ub = unoptionalize(a), unoptionalize(b)
    opt = isinstance(a, Optional) or isinstance(b, Optional)
    if ua != a or ub != b:
        inner = types_lca(ua, ub)
        return Optional(inner) if opt else inner
    if {ua, ub} == {INT, FLOAT}:
        return FLOAT
    if {ua, ub} == {BOOL, INT}:
        return INT
    return ANY


def types_lca_many(ts: list[DType]) -> DType:
    out = ts[0]
    for t in ts[1:]:
        out = types_lca(out, t)
    return out


def numpy_storage_dtype(t: DType) -> np.dtype:
    return t.numpy_dtype
