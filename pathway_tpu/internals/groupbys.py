"""GroupedTable — groupby().reduce() plumbing.

Re-design of ``python/pathway/internals/groupbys.py``. The reduce() call
rewrites its output expressions: reducer sub-expressions become hidden
reduced columns, grouping-column references become group-key columns; the
actual incremental reduction happens in the engine's GroupByReduce operator
(reference: ``Graph::group_by_table`` graph.rs:885 + reduce.rs).
"""

from __future__ import annotations

import copy
from typing import Any

from . import dtype as dt
from .expression import (
    ColumnExpression,
    ColumnReference,
    HiddenRef,
    IdReference,
    ReducerExpression,
    smart_coerce,
)
from .parse_graph import Universe
from .schema import ColumnSchema, schema_from_columns
from .thisclass import substitute, this


class GroupedTable:
    def __init__(
        self,
        table,
        grouping: list[ColumnExpression],
        instance: ColumnExpression | None = None,
        by_id: bool = False,
        skip_errors: bool = True,
    ):
        self._table = table
        self._grouping = grouping
        self._instance = instance
        self._by_id = by_id
        #: reference groupby(_skip_errors=True) default: value reducers
        #: ignore Error cells; False = the aggregate reads Error until
        #: the error row retracts (reduce.rs error_count)
        self._skip_errors = skip_errors
        # map grouping expr by (reference identity) so reduce() args can refer to them
        self._group_names: dict[str, int] = {}
        for i, g in enumerate(grouping):
            if isinstance(g, ColumnReference):
                self._group_names[g.name] = i

    def reduce(self, *args: Any, **kwargs: Any):
        from .table import Table

        table = self._table
        outputs: dict[str, ColumnExpression] = {}
        for arg in args:
            if isinstance(arg, str):
                raise ValueError(
                    f"Expected a ColumnReference, found a string. Did you "
                    f"mean this.{arg} instead of {arg!r}?"
                )
            arg = substitute(smart_coerce(arg), {this: table})
            if not isinstance(arg, ColumnReference):
                raise ValueError(
                    "In reduce() all positional arguments have to be a "
                    "ColumnReference."
                )
            outputs[arg.name] = arg
        for name, e in kwargs.items():
            outputs[name] = substitute(smart_coerce(e), {this: table})

        # collect reducers from output expressions; replace with hidden refs
        reducers: list[tuple[str, str, list[ColumnExpression], dict]] = []
        hidden_refs: list[HiddenRef] = []

        def extract(expr: ColumnExpression) -> ColumnExpression:
            if isinstance(expr, ReducerExpression):
                name = expr._reducer
                if name == "avg":
                    s = extract(ReducerExpression("sum", expr._args))
                    c = extract(ReducerExpression("count", ()))
                    return s / c
                idx = len(reducers)
                out_name = f"__r{idx}"
                args_exprs = [substitute(a, {this: self._table}) for a in expr._args]
                if name in ("min", "max", "sum", "unique", "any", "sorted_tuple", "tuple", "ndarray", "argmin", "argmax", "earliest", "latest") and not args_exprs:
                    raise ValueError(f"reducer {name} needs an argument")
                reducers.append((out_name, name, args_exprs, dict(expr._kwargs)))
                ref = HiddenRef(out_name)
                hidden_refs.append(ref)
                return ref
            if not getattr(expr, "_deps", ()):
                return expr
            clone = copy.copy(expr)
            for attr, value in list(vars(clone).items()):
                if isinstance(value, ColumnExpression):
                    setattr(clone, attr, extract(value))
                elif isinstance(value, tuple) and any(isinstance(v, ColumnExpression) for v in value):
                    setattr(clone, attr, tuple(
                        extract(v) if isinstance(v, ColumnExpression) else v for v in value
                    ))
            return clone

        rewritten = {name: extract(e) for name, e in outputs.items()}

        grouping = list(self._grouping)
        if self._instance is not None:
            grouping = grouping + [self._instance]

        return Table(
            "groupby_reduce",
            [self._table],
            {
                "grouping": grouping,
                "by_id": self._by_id,
                "reducers": reducers,
                "outputs": rewritten,
                "group_names": dict(self._group_names),
                "skip_errors": self._skip_errors,
            },
            _infer_reduce_schema(self._table, grouping, self._group_names, reducers, rewritten),
            Universe(),
        )


def _infer_reduce_schema(table, grouping, group_names, reducers, outputs):
    from .expression_compiler import ColumnEnv, infer_dtype
    from .table import _add_reachable_tables

    env = ColumnEnv()
    reach: dict[str, Any] = {f"g{i}": g for i, g in enumerate(grouping)}
    for out_name, _rname, rargs, _rkwargs in reducers:
        for j, a in enumerate(rargs):
            reach[f"{out_name}.{j}"] = a
    _add_reachable_tables(env, reach, table)

    reducer_dts: dict[str, dt.DType] = {}
    for out_name, rname, rargs, rkwargs in reducers:
        arg_ts = [infer_dtype(a, env) for a in rargs]
        reducer_dts[out_name] = _reducer_out_dtype(rname, arg_ts)

    def fill_hidden(e):
        if isinstance(e, HiddenRef):
            e._dtype = reducer_dts[e._engine_name]
        for d in getattr(e, "_deps", ()):
            fill_hidden(d)

    cols = {}
    for name, e in outputs.items():
        fill_hidden(e)
        try:
            d = infer_dtype(e, env)
        except Exception:
            d = dt.ANY
        cols[name] = ColumnSchema(name=name, dtype=d)
    return schema_from_columns(cols, name="Reduced")


def _reducer_out_dtype(name: str, arg_ts: list[dt.DType]) -> dt.DType:
    if name == "count":
        return dt.INT
    if name in ("sum", "min", "max", "unique", "any", "earliest", "latest"):
        return arg_ts[0] if arg_ts else dt.ANY
    if name in ("argmin", "argmax"):
        return dt.POINTER
    if name in ("sorted_tuple", "tuple"):
        return dt.List(arg_ts[0] if arg_ts else dt.ANY)
    if name == "tuple_by":
        return dt.List(arg_ts[1] if len(arg_ts) > 1 else dt.ANY)
    if name == "ndarray":
        return dt.Array(1, arg_ts[0] if arg_ts else dt.FLOAT)
    if name == "stateful":
        return dt.ANY
    return dt.ANY


