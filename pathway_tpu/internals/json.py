"""``pw.Json`` — JSON value wrapper (reference ``internals/json.py``)."""

from __future__ import annotations

import json as _json
from typing import Any


class Json:
    """Immutable wrapper for a JSON value held in a column."""

    NULL: "Json"

    def __init__(self, value: Any = None):
        if isinstance(value, Json):
            value = value.value
        self._value = value

    @property
    def value(self) -> Any:
        return self._value

    @staticmethod
    def parse(s: str | bytes) -> "Json":
        return Json(_json.loads(s))

    @staticmethod
    def dumps(obj: Any) -> str:
        if isinstance(obj, Json):
            obj = obj.value
        return _json.dumps(obj)

    def __getitem__(self, key: Any) -> "Json":
        return Json(self._value[key])

    def get(self, key: Any, default: Any = None) -> Any:
        if isinstance(self._value, dict):
            v = self._value.get(key, default)
            return Json(v) if not isinstance(v, Json) else v
        return default

    def as_int(self) -> int:
        return int(self._value)

    def as_float(self) -> float:
        return float(self._value)

    def as_str(self) -> str:
        return str(self._value)

    def as_bool(self) -> bool:
        if not isinstance(self._value, bool):
            raise ValueError(f"not a bool: {self._value!r}")
        return self._value

    def as_list(self) -> list:
        return list(self._value)

    def as_dict(self) -> dict:
        return dict(self._value)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Json):
            return self._value == other._value
        return self._value == other

    def __hash__(self) -> int:
        return hash(_json.dumps(self._value, sort_keys=True, default=str))

    def __repr__(self) -> str:
        return f"pw.Json({self._value!r})"

    def __str__(self) -> str:
        return _json.dumps(self._value)

    def __len__(self) -> int:
        return len(self._value)

    def __iter__(self):
        return iter(self._value)


Json.NULL = Json(None)
