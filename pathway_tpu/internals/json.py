"""``pw.Json`` — JSON value wrapper (reference ``internals/json.py``)."""

from __future__ import annotations

import json as _json
from typing import Any

import numpy as np


class Json:
    """Immutable wrapper for a JSON value held in a column."""

    NULL: "Json"

    def __init__(self, value: Any = None):
        if isinstance(value, Json):
            value = value.value
        self._value = value

    @property
    def value(self) -> Any:
        return self._value

    @staticmethod
    def parse(s: str | bytes) -> "Json":
        return Json(_json.loads(s))

    @staticmethod
    def dumps(obj: Any) -> str:
        if isinstance(obj, Json):
            obj = obj.value
        return _json.dumps(obj)

    def __getitem__(self, key: Any) -> "Json":
        """Null-safe traversal (reference json access semantics,
        test_json.py:185-230): a missing key, an out-of-bounds / negative
        array index, or indexing a non-container all yield ``Json(None)``
        instead of raising — chained paths degrade to null."""
        v = self._value
        if isinstance(v, dict):
            return Json(v.get(key)) if isinstance(key, str) else Json(None)
        if isinstance(v, list):
            if isinstance(key, (int, np.integer)) and not isinstance(
                key, bool
            ) and 0 <= key < len(v):
                return Json(v[int(key)])
            return Json(None)
        return Json(None)

    def get(self, key: Any, default: Any = None) -> Any:
        if isinstance(self._value, dict) and isinstance(key, str):
            v = self._value.get(key, default)
        elif (
            isinstance(self._value, list)
            and isinstance(key, (int, np.integer))
            and not isinstance(key, bool)
            and 0 <= key < len(self._value)
        ):
            v = self._value[int(key)]
        else:
            v = default
        return Json(v) if not isinstance(v, Json) else v

    def as_int(self) -> int:
        if isinstance(self._value, bool) or not isinstance(
            self._value, (int, float)
        ) or (isinstance(self._value, float) and not self._value.is_integer()):
            raise ValueError(f"not an int: {self._value!r}")
        return int(self._value)

    def as_float(self) -> float:
        if isinstance(self._value, bool) or not isinstance(
            self._value, (int, float)
        ):
            raise ValueError(f"not a float: {self._value!r}")
        return float(self._value)

    def as_str(self) -> str:
        if not isinstance(self._value, str):
            raise ValueError(f"not a str: {self._value!r}")
        return self._value

    def as_bool(self) -> bool:
        if not isinstance(self._value, bool):
            raise ValueError(f"not a bool: {self._value!r}")
        return self._value

    def as_list(self) -> list:
        return list(self._value)

    def as_dict(self) -> dict:
        return dict(self._value)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Json):
            return self._value == other._value
        return self._value == other

    def __hash__(self) -> int:
        return hash(_json.dumps(self._value, sort_keys=True, default=str))

    def __repr__(self) -> str:
        return f"pw.Json({self._value!r})"

    def __str__(self) -> str:
        return _json.dumps(self._value)

    def __len__(self) -> int:
        return len(self._value)

    def __iter__(self):
        return iter(self._value)


Json.NULL = Json(None)
