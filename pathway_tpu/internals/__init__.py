"""Internals: declarative layer (reference python/pathway/internals)."""
