"""``pw.run`` — execute all registered outputs (reference internals/run.py:42)."""

from __future__ import annotations

from typing import Any

from .graph_runner import GraphRunner


class MonitoringLevel:
    NONE = 0
    IN_OUT = 1
    ALL = 2
    AUTO = 3
    AUTO_ALL = 4


def run(
    *,
    debug: bool = False,
    monitoring_level: int = MonitoringLevel.NONE,
    with_http_server: bool = False,
    default_logging: bool = True,
    persistence_config: Any = None,
    runtime_typechecking: bool | None = None,
    **kwargs: Any,
) -> None:
    """Build and run the whole dataflow (all sinks registered so far)."""
    GraphRunner().run()


def run_all(**kwargs: Any) -> None:
    run(**kwargs)
