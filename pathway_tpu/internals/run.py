"""``pw.run`` — execute all registered outputs (reference internals/run.py:42)."""

from __future__ import annotations

import threading
from typing import Any

from .graph_runner import GraphRunner
from .monitoring import MonitoringLevel

_current: dict[str, GraphRunner | None] = {"runner": None}
_lock = threading.Lock()


def run(
    *,
    debug: bool = False,
    monitoring_level: int = MonitoringLevel.NONE,
    with_http_server: bool = False,
    default_logging: bool = True,
    persistence_config: Any = None,
    runtime_typechecking: bool | None = None,
    **kwargs: Any,
) -> None:
    """Build and run the whole dataflow (all sinks registered so far).
    Blocks until all sources finish (streaming sources may run forever —
    stop from another thread with ``request_stop()``)."""
    from . import lintmode

    if lintmode.ACTIVE:
        # `pathway-tpu lint` / pw.analyze() drive the script only to BUILD
        # its graph; execution (and every side effect behind it) is skipped
        # and the analyzer reads the parse graph + this captured config
        lintmode.note_run(persistence_config)
        return
    from .tracing import init_from_env

    init_from_env()  # each pw.run re-reads PATHWAY_TRACE_FILE
    runner = GraphRunner()
    runner.monitoring_level = monitoring_level
    runner.with_http_server = with_http_server
    with _lock:
        _current["runner"] = runner
    restore_sigterm = _install_supervised_sigterm()
    try:
        if persistence_config is None:
            # the CLI's record/replay env (pathway-tpu spawn --record /
            # replay --mode ...) must work WITHOUT program changes
            # (reference run.py reads the replay config from env)
            from .config import get_pathway_config

            cfg = get_pathway_config()
            if cfg.replay_storage and cfg.snapshot_access in (
                "record", "replay"
            ):
                from ..persistence import Backend, Config

                persistence_config = Config.simple_config(
                    Backend.filesystem(cfg.replay_storage)
                )
        if persistence_config is not None:
            from ..persistence import run_with_persistence

            run_with_persistence(runner, persistence_config)
        else:
            runner.run()
    finally:
        restore_sigterm()
        with _lock:
            _current["runner"] = None


def _install_supervised_sigterm():
    """Under ``spawn --supervise`` (PATHWAY_SUPERVISED=1) a SIGTERM is the
    supervisor's cooperative teardown request: translate it into
    ``request_stop()`` so the streaming loop winds down and the persistence
    manager's ``close()`` flushes the recorded input tail before exit.
    Returns a restore callback; a no-op off the main thread or when not
    supervised."""
    import os

    if not os.environ.get("PATHWAY_SUPERVISED"):
        return lambda: None
    import signal

    try:
        prev = signal.signal(
            signal.SIGTERM, lambda signum, frame: request_stop()
        )
    except ValueError:  # not the main thread — supervisor falls back to kill
        return lambda: None
    return lambda: signal.signal(signal.SIGTERM, prev)


def request_stop() -> None:
    """Ask the currently running streaming engine loop to wind down after
    the in-flight tick (callable from any thread)."""
    with _lock:
        runner = _current["runner"]
    if runner is not None:
        runner.stop_requested = True
        for ex in getattr(runner, "_peer_executors", None) or (
            [runner.executor] if runner.executor is not None else []
        ):
            ex.request_stop()


def run_all(**kwargs: Any) -> None:
    run(**kwargs)
