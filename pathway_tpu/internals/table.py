"""``pw.Table`` — the declarative, incrementally-maintained table.

Re-design of ``python/pathway/internals/table.py`` (2,675 LoC; method parity
cites below). Every method appends a node to the parse graph; nothing
executes until ``pw.run``/debug computes outputs. Each node kind maps to one
engine operator family (see ``internals/graph_runner.py``).
"""

from __future__ import annotations

import itertools
from typing import Any, Iterable

from . import dtype as dt
from .expression import (
    ColumnExpression,
    ColumnReference,
    IdReference,
    PointerExpression,
    ReducerExpression,
    smart_coerce,
)
from .parse_graph import G, Universe
from .schema import Schema, SchemaMetaclass, schema_from_columns, schema_from_types
from .schema import ColumnSchema
from .thisclass import ThisPlaceholder, substitute, this


class TableLike:
    _universe: Universe


class Table(TableLike):
    _kind: str
    _inputs: list["Table"]
    _params: dict[str, Any]
    _schema: SchemaMetaclass

    _id_seq = itertools.count(1)

    def __init__(self, kind: str, inputs: list["Table"], params: dict[str, Any],
                 schema: SchemaMetaclass, universe: Universe):
        self._kind = kind
        self._inputs = inputs
        self._params = params
        self._schema = schema
        self._universe = universe
        self._table_seq = next(Table._id_seq)
        from . import lintmode

        if lintmode.ACTIVE:
            # static analysis: remember which script line built this table
            # so diagnostics (and `# pathway: ignore[...]` suppressions)
            # can anchor to source
            lintmode.note_table(self._table_seq)
        from .error_log_table import current_build_scope

        #: pw.local_error_log() scope active when this table was built —
        #: its nodes' runtime row errors carry the scope
        self._error_scope = current_build_scope()
        #: user-pinned stable operator name (``named``); None = unnamed
        self._pw_name: str | None = None

    def named(self, name: str) -> "Table":
        """Pin a stable, user-visible operator identity onto this table's
        node. ``pathway-tpu upgrade`` matches operators across code
        versions by structural fingerprint first and pinned name second —
        naming a stateful table lets its snapshots survive structural
        edits (the *remapped* plan verb) instead of being dropped."""
        if not name or not isinstance(name, str):
            raise ValueError("named() needs a non-empty string")
        self._pw_name = name
        return self

    # -- schema surface -----------------------------------------------------

    @property
    def schema(self) -> SchemaMetaclass:
        return self._schema

    def column_names(self) -> list[str]:
        return self._schema.column_names()

    def typehints(self) -> dict[str, Any]:
        return self._schema.typehints()

    @property
    def id(self) -> IdReference:
        return IdReference(self)

    def keys(self):
        return self._schema.columns()

    def __getattr__(self, name: str) -> ColumnReference:
        if name.startswith("__"):
            raise AttributeError(name)
        if name in self._schema.__columns__:
            return ColumnReference(self, name)
        raise AttributeError(
            f"Table has no column {name!r}; columns: {self.column_names()}"
        )

    def _column_ref(self, name: str) -> ColumnReference:
        """Schema-direct column reference — bypasses attribute lookup so
        columns named like Table methods/properties (select, C, ...) still
        resolve (the ``.C`` namespace and ``t["name"]`` route here)."""
        if name == "id":
            return IdReference(self)
        if name in self._schema.__columns__:
            return ColumnReference(self, name)
        raise AttributeError(
            f"Table has no column {name!r}; columns: {self.column_names()}"
        )

    def __getitem__(self, arg):
        if isinstance(arg, str):
            return self._column_ref(arg)
        if isinstance(arg, ColumnReference):
            return self._column_ref(arg.name)
        if isinstance(arg, (list, tuple)):
            return self.select(*[self[a] for a in arg])
        raise TypeError(f"cannot index Table with {arg!r}")

    def __iter__(self):
        raise TypeError("Table is not iterable; use pw.debug.compute_and_print")

    def __repr__(self) -> str:
        inner = ", ".join(f"{n}: {c.dtype!r}" for n, c in self._schema.columns().items())
        return f"<pw.Table ({inner})>"

    @property
    def C(self):
        """``.C`` column accessor (reference table.C.colname): columns
        whose names collide with Table method names."""
        from .thisclass import _ColNamespace

        return _ColNamespace(self)

    # -- live visualization (reference table.py:96 binds stdlib.viz) --------

    def plot(self, plotting_function, sorting_col: str | None = None):
        """Live-updating Bokeh plot of this table (reference viz.plot):
        ``plotting_function(source) -> figure`` gets a ColumnDataSource
        that streams append-only ticks incrementally after ``pw.run()``.
        Without bokeh/panel, returns the LiveTableSource mirror."""
        from ..stdlib.viz import plot as _plot

        return _plot(self, plotting_function, sorting_col)

    def show(self, sorting_col: str | None = None, **kwargs):
        """Live table widget (reference viz.table_viz/show)."""
        from ..stdlib.viz import table_viz as _table_viz

        return _table_viz(self, sorting_col, **kwargs)

    def _has_realtime_inputs(self) -> bool:
        seen: set[int] = set()
        stack: list[Table] = [self]
        while stack:
            t = stack.pop()
            if id(t) in seen:
                continue
            seen.add(id(t))
            if t._kind == "source":
                return True
            stack.extend(t._inputs)
        return False

    def _repr_html_(self) -> str:
        """Notebook display: a static snapshot when the table has no
        streaming inputs; otherwise the reference's run-first hint
        (plotting.py:81) — computing a live table here could block on an
        unbounded source."""
        if self._has_realtime_inputs():
            return (
                f"<em>{self!r} — depends on streaming inputs; run "
                "pw.run() with t.plot(...)/t.show() for live output</em>"
            )
        try:
            from ..debug import table_to_pandas

            df = table_to_pandas(self, include_id=False)
            return df.to_html()
        except Exception:
            return f"<em>{self!r}</em>"

    # -- desugaring helpers -------------------------------------------------

    def _sub(self, expr: Any) -> ColumnExpression:
        return substitute(smart_coerce(expr), {this: self})

    def _named_exprs(self, args: tuple, kwargs: dict[str, Any]) -> dict[str, ColumnExpression]:
        out: dict[str, ColumnExpression] = {}
        from .table_slice import TableSlice

        from .thisclass import ThisWithout

        flat: list[Any] = []
        for arg in args:
            if isinstance(arg, ThisWithout):
                # pw.this / pw.this.without(...): all of this table's
                # columns minus the excluded names
                flat.extend(
                    ColumnReference(self, n)
                    for n in self.column_names()
                    if n not in arg.excluded
                )
            elif isinstance(arg, TableSlice):
                # a TableSlice unpacks into its (possibly renamed) references
                flat.extend(arg)
            else:
                flat.append(arg)
        for arg in flat:
            if isinstance(arg, str):
                # reference error_messages: a bare string is the most
                # common slip — point at the fix
                raise ValueError(
                    f"Expected a ColumnReference, found a string. Did you "
                    f"mean this.{arg} instead of {arg!r}?"
                )
            arg = self._sub(arg)
            if not isinstance(arg, ColumnReference):
                raise ValueError(
                    "positional select arguments must be column references; "
                    "use keyword arguments for expressions"
                )
            # RenamedReference (from slice.rename): output name differs from
            # the referenced column
            out[arg.name] = getattr(arg, "_source", arg)
        for name, e in kwargs.items():
            out[name] = self._sub(e)
        return out

    def pointer_from(self, *args: Any, instance: Any = None, optional: bool = False) -> PointerExpression:
        # args stay UNBOUND: ``this`` in them refers to the table the
        # expression is used in (reference semantics — e.g. an expected
        # table built with ``.with_columns(k=t.pointer_from(this.k))``
        # reads ITS OWN k column and keys into t's universe)
        return PointerExpression(
            self, *[smart_coerce(a) for a in args],
            instance=instance, optional=optional,
        )

    # -- rowwise ops (table.py:382 select, :490 filter, :1613 with_columns) --

    def select(self, *args: Any, **kwargs: Any) -> "Table":
        exprs = self._named_exprs(args, kwargs)
        return self._rowwise(exprs)

    def _rowwise(self, exprs: dict[str, ColumnExpression], universe: Universe | None = None) -> "Table":
        from .expression_compiler import ColumnEnv

        schema = _infer_schema(exprs, self)
        return Table(
            "rowwise",
            [self],
            {"exprs": exprs},
            schema,
            universe if universe is not None else self._universe,
        )

    @classmethod
    def empty(cls, **kwargs: Any) -> "Table":
        """An empty table with the given column types (reference
        ``pw.Table.empty(cnt=int)``)."""
        from .schema import schema_from_types
        from .table_io import rows_to_table

        return rows_to_table(
            list(kwargs), [], schema=schema_from_types(**kwargs)
        )

    def remove_errors(self) -> "Table":
        """Drop rows in which any column holds an Error value (reference
        ``Table.remove_errors``, test_errors.py:620 — the engine's
        filter_out_results_of_failed_computations)."""
        return Table(
            "remove_errors",
            [self],
            {},
            self._schema,
            Universe(parent=self._universe),
        )

    def filter(self, filter_expression: Any) -> "Table":
        expr = self._sub(filter_expression)
        return Table(
            "filter",
            [self],
            {"predicate": expr},
            self._schema,
            Universe(parent=self._universe),
        )

    def with_columns(self, *args: Any, **kwargs: Any) -> "Table":
        new = self._named_exprs(args, kwargs)
        exprs: dict[str, ColumnExpression] = {
            name: ColumnReference(self, name) for name in self.column_names()
        }
        exprs.update(new)
        return self._rowwise(exprs)

    def without(self, *columns: Any) -> "Table":
        names = {c.name if isinstance(c, ColumnReference) else c for c in columns}
        exprs = {
            n: ColumnReference(self, n) for n in self.column_names() if n not in names
        }
        return self._rowwise(exprs)

    def rename_columns(self, **kwargs: Any) -> "Table":
        mapping = {}
        for new_name, old in kwargs.items():
            mapping[old.name if isinstance(old, ColumnReference) else old] = new_name
        return self._rename(mapping)

    def rename_by_dict(self, names_mapping: dict) -> "Table":
        mapping = {
            (old.name if isinstance(old, ColumnReference) else old): new
            for old, new in names_mapping.items()
        }
        return self._rename(mapping)

    def rename(self, names_mapping: dict | None = None, **kwargs: Any) -> "Table":
        if names_mapping is not None:
            return self.rename_by_dict(names_mapping)
        return self.rename_columns(**kwargs)

    def _rename(self, mapping: dict[str, str]) -> "Table":
        unknown = set(mapping) - set(self.column_names())
        if unknown:
            raise KeyError(
                f"rename: unknown column(s) {sorted(unknown)}; columns: "
                f"{self.column_names()}"
            )
        exprs = {
            mapping.get(n, n): ColumnReference(self, n) for n in self.column_names()
        }
        return self._rowwise(exprs)

    def copy(self) -> "Table":
        return self._rowwise(
            {n: ColumnReference(self, n) for n in self.column_names()}
        )

    def cast_to_types(self, **kwargs: Any) -> "Table":
        from .expression import CastExpression

        exprs: dict[str, ColumnExpression] = {}
        for n in self.column_names():
            if n in kwargs:
                exprs[n] = CastExpression(kwargs[n], ColumnReference(self, n))
            else:
                exprs[n] = ColumnReference(self, n)
        return self._rowwise(exprs)

    def update_types(self, **kwargs: Any) -> "Table":
        """Override DECLARED column dtypes without touching runtime values
        (reference ``Table.update_types`` — a type annotation, not a cast;
        use ``cast_to_types`` to convert values)."""
        cols = dict(self._schema.columns())
        unknown = set(kwargs) - set(cols)
        if unknown:
            raise KeyError(f"update_types: unknown column(s) {sorted(unknown)}")
        for n, t in kwargs.items():
            cols[n] = ColumnSchema(name=n, dtype=dt.wrap(t))
        schema = schema_from_columns(cols, name="Retyped")
        # "with_universe_of" lowers to a pure pass-through of input 0
        return Table("with_universe_of", [self], {}, schema, self._universe)

    # -- groupby / reduce (table.py:942, :1025) -----------------------------

    def groupby(self, *args: Any, id: Any = None, instance: Any = None,
                _skip_errors: bool = True, **kwargs: Any):
        from .groupbys import GroupedTable

        grouping = [self._sub(a) for a in args]
        by_id = False
        if id is not None:
            grouping = [self._sub(id)]
            by_id = True
        return GroupedTable(
            self,
            grouping,
            instance=self._sub(instance) if instance is not None else None,
            by_id=by_id,
            skip_errors=_skip_errors,
        )

    def reduce(self, *args: Any, **kwargs: Any) -> "Table":
        return self.groupby().reduce(*args, **kwargs)

    def deduplicate(
        self,
        value: Any = None,
        instance: Any = None,
        acceptor: Any = None,
        persistent_id: str | None = None,
    ) -> "Table":
        value = self._sub(value) if value is not None else IdReference(self)
        instance = self._sub(instance) if instance is not None else None
        return Table(
            "deduplicate",
            [self],
            {"value": value, "instance": instance, "acceptor": acceptor},
            self._schema,
            Universe(),
        )

    # -- joins (table.py / joins.py) ----------------------------------------

    @staticmethod
    def _with_instance_cond(on: tuple, kwargs: dict) -> tuple:
        """``left_instance=``/``right_instance=`` desugar to an extra
        equality condition (reference join instance kwargs)."""
        li = kwargs.pop("left_instance", None)
        ri = kwargs.pop("right_instance", None)
        if (li is None) != (ri is None):
            raise ValueError(
                "left_instance and right_instance must be given together"
            )
        if li is not None:
            on = (*on, li == ri)
        return on

    def join(self, other: "Table", *on: Any, id: Any = None, how: Any = None, **kwargs):
        from .joins import JoinMode, JoinResult

        on = self._with_instance_cond(on, kwargs)
        mode = how if how is not None else JoinMode.INNER
        return JoinResult(self, other, on, mode=mode, id=id)

    def join_inner(self, other: "Table", *on: Any, id: Any = None, **kwargs):
        from .joins import JoinMode, JoinResult

        on = self._with_instance_cond(on, kwargs)
        return JoinResult(self, other, on, mode=JoinMode.INNER, id=id)

    def join_left(self, other: "Table", *on: Any, id: Any = None, **kwargs):
        from .joins import JoinMode, JoinResult

        on = self._with_instance_cond(on, kwargs)
        return JoinResult(self, other, on, mode=JoinMode.LEFT, id=id)

    def join_right(self, other: "Table", *on: Any, id: Any = None, **kwargs):
        from .joins import JoinMode, JoinResult

        on = self._with_instance_cond(on, kwargs)
        return JoinResult(self, other, on, mode=JoinMode.RIGHT, id=id)

    def join_outer(self, other: "Table", *on: Any, id: Any = None, **kwargs):
        from .joins import JoinMode, JoinResult

        on = self._with_instance_cond(on, kwargs)
        return JoinResult(self, other, on, mode=JoinMode.OUTER, id=id)

    # -- set ops ------------------------------------------------------------

    def concat(self, *others: "Table") -> "Table":
        tables = [self, *others]
        schema = _common_schema(tables)
        universes = [t._universe for t in tables]
        if not G.solver.query_are_disjoint(*universes):
            # reference table.py:1334 `_concat`: concat keeps original row
            # ids, so colliding key sets are refused at build time unless
            # disjointness is provable or promised
            raise ValueError(
                "Table.concat: universes of the concatenated tables might "
                "collide; use pw.universes.promise_are_pairwise_disjoint "
                "(or concat_reindex, which reindexes)"
            )
        result = Universe()
        G.solver.register_as_union(result, *universes)
        return Table("concat", tables, {}, schema, result)

    def concat_reindex(self, *others: "Table") -> "Table":
        tables = [self, *others]
        schema = _common_schema(tables)
        return Table("concat_reindex", tables, {}, schema, Universe())

    def update_rows(self, other: "Table") -> "Table":
        schema = _common_schema([self, other])
        return Table("update_rows", [self, other], {}, schema, Universe())

    def update_cells(self, other: "Table") -> "Table":
        if not other._universe.is_subset_of(self._universe):
            raise ValueError(
                "update_cells requires other's universe to be a subset of self's; "
                "use promise_universe_is_subset_of if you know it holds"
            )
        extra = set(other.column_names()) - set(self.column_names())
        if extra:
            raise ValueError(f"update_cells: unknown columns {sorted(extra)}")
        return Table(
            "update_cells",
            [self, other],
            {"override": other.column_names()},
            self._schema,
            self._universe,
        )

    def __lshift__(self, other: "Table") -> "Table":
        """``self << other`` = ``update_cells`` (reference table.py
        ``__lshift__`` alias)."""
        return self.update_cells(other)

    @staticmethod
    def from_columns(*args: Any, **kwargs: Any) -> "Table":
        """Build a table from columns of (universe-compatible) tables
        (reference table.py ``Table.from_columns``)."""
        refs = list(args) + list(kwargs.values())
        if not refs:
            raise ValueError("from_columns needs at least one column")
        base = refs[0].table
        return base.select(*args, **kwargs)

    def __add__(self, other: "Table") -> "Table":
        """Column-wise sum of two same-universe tables (zip columns)."""
        if not isinstance(other, Table):
            return NotImplemented
        exprs: dict[str, ColumnExpression] = {
            n: ColumnReference(self, n) for n in self.column_names()
        }
        for n in other.column_names():
            if n in exprs:
                raise ValueError(f"duplicate column {n!r} in Table + Table")
            exprs[n] = ColumnReference(other, n)
        return self._rowwise(exprs)

    def restrict(self, other: TableLike) -> "Table":
        if not other._universe.is_subset_of(self._universe):
            raise ValueError(
                "restrict requires other's universe to be a provable subset "
                "of self's; use promise_universe_is_subset_of if you know "
                "it holds (reference table.py:1334)"
            )
        return Table(
            "restrict",
            [self, other],  # type: ignore[list-item]
            {},
            self._schema,
            other._universe,
        )

    def intersect(self, *tables: "Table") -> "Table":
        out = self
        for t in tables:
            u = Universe()
            G.solver.register_as_intersection(
                u, out._universe, t._universe
            )
            out = Table("intersect", [out, t], {}, self._schema, u)
        return out

    def difference(self, other: "Table") -> "Table":
        u = Universe()
        G.solver.register_as_difference(
            u, self._universe, other._universe
        )
        return Table("difference", [self, other], {}, self._schema, u)

    def having(self, *indexers: Any) -> "Table":
        """Rows of each indexer's table whose pointer value is a key of
        ``self``, carrying ``self``'s columns — the result universe is a
        provable subset of the indexer table's (reference ``_having``,
        table.py:2027 / ``HavingContext`` column.py:794: universe =
        ``key_column.universe.subset()``)."""
        out = self
        for ix in indexers:
            if not isinstance(ix, ColumnReference) or not isinstance(
                getattr(ix, "table", None), Table
            ):
                # pw.this.x is a ColumnReference too, but its "table" is
                # the ThisPlaceholder — there is no concrete universe to
                # subset, so refuse it here with a clear error
                raise TypeError(
                    "having takes pointer-valued column references on a "
                    "concrete table (e.g. q.select(p=t.pointer_from(q.k)).p)"
                )
            out = Table(
                "having",
                [out, ix.table],
                {"key_expr": ix},
                out._schema,
                Universe(parent=ix.table._universe),
            )
        return out

    # -- reindexing (table.py:1690 with_id_from) ----------------------------

    def with_id_from(self, *args: Any, instance: Any = None) -> "Table":
        key_expr = PointerExpression(
            self, *[self._sub(a) for a in args],
            instance=self._sub(instance) if instance is not None else None,
        )
        return self.with_id(key_expr)

    def with_id(self, new_id: Any) -> "Table":
        return Table(
            "reindex",
            [self],
            {"key_expr": self._sub(new_id)},
            self._schema,
            Universe(),
        )

    # -- pointer indexing (table.py:1164 ix) --------------------------------

    def ix(self, expression: Any, *, optional: bool = False, context: Any = None) -> "Table":
        if context is None:
            context = _expression_table(expression)
        if context is None:
            raise ValueError("cannot infer context table for ix; pass context=")
        key_expr = substitute(smart_coerce(expression), {this: context})
        schema = self._schema
        if optional:
            schema = schema_from_columns({
                n: ColumnSchema(name=n, dtype=dt.Optional(c.dtype))
                for n, c in schema.columns().items()
            }, name="Ixed")
        return Table(
            "ix",
            [context, self],
            {"key_expr": key_expr, "optional": optional},
            schema,
            context._universe,
        )

    def ix_ref(self, *args: Any, optional: bool = False, context: Any = None, instance: Any = None) -> "Table":
        if context is None:
            for a in args:
                context = _expression_table(smart_coerce(a))
                if context is not None:
                    break
        if context is None:
            # no args (singleton broadcast) or only pw.this args: the
            # context table is the enclosing select's — defer until its
            # desugaring binds pw.this (reference desugaring ix support)
            from .thisclass import DeferredIxTable

            return DeferredIxTable(self, args, optional, instance)  # type: ignore[return-value]
        return self.ix(
            PointerExpression(self, *args, instance=instance),
            optional=optional,
            context=context,
        )

    # -- flatten (table.py:2089) --------------------------------------------

    def flatten(self, to_flatten: Any, origin_id: str | None = None) -> "Table":
        ref = self._sub(to_flatten)
        if not isinstance(ref, ColumnReference):
            raise ValueError("flatten takes a column reference")
        cols = dict(self._schema.columns())
        inner = cols[ref.name].dtype
        iu = dt.unoptionalize(inner)
        if isinstance(iu, dt.List):
            new_dt: dt.DType = iu.wrapped
        elif isinstance(iu, dt.Tuple) and iu.args:
            new_dt = dt.types_lca_many(list(iu.args))
        elif iu == dt.STR:
            new_dt = dt.STR
        elif iu in (dt.INT, dt.FLOAT, dt.BOOL, dt.POINTER, dt.DURATION,
                    dt.DATE_TIME_NAIVE, dt.DATE_TIME_UTC, dt.BYTES):
            # statically non-iterable — a build-time error, as in the
            # reference (test_common.py:1095 test_flatten_incorrect_type);
            # dynamically wrong values in ANY/JSON columns are skipped at
            # run time with an error-log entry instead
            raise TypeError(
                f"flatten: column {ref.name!r} of type {iu} is not iterable"
            )
        else:
            new_dt = dt.ANY
        cols[ref.name] = ColumnSchema(name=ref.name, dtype=new_dt)
        params: dict[str, Any] = {"column": ref.name}
        schema = schema_from_columns(cols, name="Flattened")
        if origin_id is not None:
            schema = schema_from_columns(
                {**cols, origin_id: ColumnSchema(name=origin_id, dtype=dt.POINTER)},
                name="Flattened",
            )
            params["origin_id"] = origin_id
        return Table("flatten", [self], params, schema, Universe())

    def _gradual_broadcast(
        self, threshold_table: "Table", lower_column: Any, value_column: Any,
        upper_column: Any,
    ) -> "Table":
        """Append an ``apx_value`` column split by a moving threshold
        (reference table.py:631 over ``gradual_broadcast.rs``): each key
        deterministically lands on ``lower`` or ``upper`` such that about
        (value-lower)/(upper-lower) of keys read ``upper``; a threshold
        move re-emits only the keys whose side flips."""
        apx = Table(
            "gradual_broadcast",
            [self, threshold_table],
            {
                "cols": (
                    self._sub(lower_column), self._sub(value_column),
                    self._sub(upper_column),
                )
            },
            schema_from_columns({
                "apx_value": ColumnSchema(name="apx_value", dtype=dt.FLOAT)
            }),
            self._universe,
        )
        return self + apx

    # -- universe promises --------------------------------------------------

    def promise_universes_are_equal(self, other: "Table") -> "Table":
        G.promise_equal(self._universe, other._universe)
        return self

    def promise_universe_is_equal_to(self, other: "Table") -> "Table":
        G.promise_equal(self._universe, other._universe)
        return self

    def promise_universe_is_subset_of(self, other: "Table") -> "Table":
        G.promise_subset(self._universe, other._universe)
        return self

    def promise_universes_are_disjoint(self, other: "Table") -> "Table":
        G.promise_disjoint(self._universe, other._universe)
        return self

    # -- deprecated pre-1.0 aliases (reference test_backward_compatibility) --

    @staticmethod
    def _deprecated(old: str, new: str) -> None:
        import warnings

        warnings.warn(
            f"{old} is deprecated; use {new} instead",
            DeprecationWarning, stacklevel=3,
        )

    def unsafe_promise_same_universe_as(self, other: "Table") -> "Table":
        self._deprecated(
            "unsafe_promise_same_universe_as", "with_universe_of"
        )
        return self.promise_universes_are_equal(other).with_universe_of(other)

    def unsafe_promise_universe_is_subset_of(self, other: "Table") -> "Table":
        self._deprecated(
            "unsafe_promise_universe_is_subset_of",
            "promise_universe_is_subset_of",
        )
        return self.promise_universe_is_subset_of(other)

    def unsafe_promise_universes_are_pairwise_disjoint(
        self, *others: "Table"
    ) -> "Table":
        self._deprecated(
            "unsafe_promise_universes_are_pairwise_disjoint",
            "promise_universes_are_disjoint",
        )
        out = self
        for other in others:
            out = out.promise_universes_are_disjoint(other)
        return out

    def left_join(self, other: "Table", *on: Any, **kwargs: Any):
        self._deprecated("left_join", "join_left")
        return self.join_left(other, *on, **kwargs)

    def right_join(self, other: "Table", *on: Any, **kwargs: Any):
        self._deprecated("right_join", "join_right")
        return self.join_right(other, *on, **kwargs)

    def outer_join(self, other: "Table", *on: Any, **kwargs: Any):
        self._deprecated("outer_join", "join_outer")
        return self.join_outer(other, *on, **kwargs)

    def with_universe_of(self, other: TableLike) -> "Table":
        if not self._universe.is_equal(other._universe):
            raise ValueError(
                "with_universe_of requires provably equal universes; use "
                "promise_universes_are_equal if you know they match "
                "(reference table.py:1613)"
            )
        return Table(
            "with_universe_of",
            [self, other],  # type: ignore[list-item]
            {},
            self._schema,
            other._universe,
        )

    # -- misc ---------------------------------------------------------------

    @property
    def slice(self) -> "TableSlice":
        """A manipulable collection of references to this table's columns
        (reference table.py:468 / table_slice.py)."""
        from .table_slice import TableSlice

        return TableSlice(
            {name: ColumnReference(self, name) for name in self.column_names()},
            self,
        )

    def sort(self, key: Any = None, instance: Any = None) -> "Table":
        """``prev``/``next`` pointer columns ordering this table by ``key``
        (reference table.py:2157, backed by prev_next.rs:770)."""
        from ..stdlib.indexing.sorting import sort_from_index

        return sort_from_index(self, key, instance)

    def windowby(self, time_expr: Any, *, window: Any, instance: Any = None, behavior: Any = None, **kwargs):
        from ..stdlib.temporal import windowby as _windowby

        return _windowby(self, time_expr, window=window, instance=instance, behavior=behavior)

    def interval_join(self, other: "Table", self_time: Any, other_time: Any, interval: Any, *on: Any, **kwargs):
        from ..stdlib.temporal import interval_join as _ij

        return _ij(self, other, self_time, other_time, interval, *on, **kwargs)

    def interval_join_inner(self, other, self_time, other_time, interval, *on, **kw):
        from ..stdlib.temporal import interval_join_inner as _f

        return _f(self, other, self_time, other_time, interval, *on, **kw)

    def interval_join_left(self, other, self_time, other_time, interval, *on, **kw):
        from ..stdlib.temporal import interval_join_left as _f

        return _f(self, other, self_time, other_time, interval, *on, **kw)

    def interval_join_right(self, other, self_time, other_time, interval, *on, **kw):
        from ..stdlib.temporal import interval_join_right as _f

        return _f(self, other, self_time, other_time, interval, *on, **kw)

    def interval_join_outer(self, other, self_time, other_time, interval, *on, **kw):
        from ..stdlib.temporal import interval_join_outer as _f

        return _f(self, other, self_time, other_time, interval, *on, **kw)

    def window_join(self, other, self_time, other_time, window, *on, **kw):
        from ..stdlib.temporal import window_join as _f

        return _f(self, other, self_time, other_time, window, *on, **kw)

    def window_join_inner(self, other, self_time, other_time, window, *on):
        from ..stdlib.temporal import window_join_inner as _f

        return _f(self, other, self_time, other_time, window, *on)

    def window_join_left(self, other, self_time, other_time, window, *on):
        from ..stdlib.temporal import window_join_left as _f

        return _f(self, other, self_time, other_time, window, *on)

    def window_join_right(self, other, self_time, other_time, window, *on):
        from ..stdlib.temporal import window_join_right as _f

        return _f(self, other, self_time, other_time, window, *on)

    def window_join_outer(self, other, self_time, other_time, window, *on):
        from ..stdlib.temporal import window_join_outer as _f

        return _f(self, other, self_time, other_time, window, *on)

    def asof_join(self, other, self_time, other_time, *on, **kw):
        from ..stdlib.temporal import asof_join as _f

        return _f(self, other, self_time, other_time, *on, **kw)

    def asof_join_left(self, other, self_time, other_time, *on, **kw):
        from ..stdlib.temporal import asof_join_left as _f

        return _f(self, other, self_time, other_time, *on, **kw)

    def asof_now_join(self, other, *on, **kw):
        from ..stdlib.temporal import asof_now_join as _f

        return _f(self, other, *on, **kw)

    def sort(self, key: Any, instance: Any = None) -> "Table":
        """Sort rows by `key` (within `instance`); returns a same-universe
        table with ``prev``/``next`` pointer columns (reference table.py:2157,
        backed by prev_next.rs in the reference engine)."""
        from ..stdlib._sorted import sorted_group_transform

        key_e = self._sub(key)
        inst_e = self._sub(instance) if instance is not None else None

        def fn(entries):
            out = []
            for i, (rk, order, _payload) in enumerate(entries):
                prev_k = entries[i - 1][0] if i > 0 else None
                next_k = entries[i + 1][0] if i + 1 < len(entries) else None
                out.append((rk, (
                    None if prev_k is None else __import__("numpy").uint64(prev_k),
                    None if next_k is None else __import__("numpy").uint64(next_k),
                )))
            return out

        return sorted_group_transform(
            self, key_e, [], inst_e,
            {"prev": dt.Optional(dt.POINTER), "next": dt.Optional(dt.POINTER)},
            fn,
        )

    def diff(self, timestamp: Any, *values: Any, instance: Any = None) -> "Table":
        from ..stdlib.ordered import diff as _diff

        return _diff(self, timestamp, *values, instance=instance)

    def interpolate(self, timestamp: Any, *values: Any, **kwargs: Any) -> "Table":
        # reference attaches the stdlib fn as a Table method (table.py:75)
        from ..stdlib.statistical import interpolate as _interp

        return _interp(self, timestamp, *values, **kwargs)

    def live(self):
        """Run this table's subgraph on a background engine thread and
        return a continuously-updated handle (reference interactive.py:130
        ``LiveTable``; observe with snapshot()/frontier()/subscribe())."""
        from .interactive import LiveTable

        return LiveTable(self)


def _expression_table(expr: Any):
    """The unique concrete table an expression refers to (for ix context)."""
    tables = []

    def walk(e):
        # PointerExpression._table is the *indexed* table, not the context —
        # only column references inside the expression locate the context.
        if isinstance(e, ColumnReference) and not isinstance(e.table, ThisPlaceholder):
            tables.append(e.table)
        for d in getattr(e, "_deps", ()):
            walk(d)

    if isinstance(expr, ColumnExpression):
        walk(expr)
    uniq = {id(t): t for t in tables}
    if len(uniq) == 1:
        return next(iter(uniq.values()))
    return None


def _infer_schema(exprs: dict[str, ColumnExpression], table: "Table") -> SchemaMetaclass:
    """Static type propagation (the analog of type_interpreter.py)."""
    from .expression_compiler import ColumnEnv, infer_dtype

    env = ColumnEnv()
    _add_reachable_tables(env, exprs, table)
    cols = {}
    for name, e in exprs.items():
        cols[name] = ColumnSchema(name=name, dtype=infer_dtype(e, env))
    return schema_from_columns(cols, name="Selected")


def _add_reachable_tables(env, exprs, primary: "Table") -> None:
    env.add_table(primary)
    seen = {id(primary)}

    def walk(e):
        if isinstance(e, ColumnReference) and not isinstance(e.table, ThisPlaceholder):
            t = e.table
            if id(t) not in seen and isinstance(t, Table):
                seen.add(id(t))
                env.add_table(t)
        for d in getattr(e, "_deps", ()):
            walk(d)

    for e in exprs.values():
        walk(e)


def _common_schema(tables: list["Table"]) -> SchemaMetaclass:
    names = tables[0].column_names()
    for t in tables[1:]:
        if set(t.column_names()) != set(names):
            raise ValueError(
                f"tables have different columns: {names} vs {t.column_names()}"
            )
    cols = {}
    for n in names:
        dts = [t._schema.columns()[n].dtype for t in tables]
        cols[n] = ColumnSchema(name=n, dtype=dt.types_lca_many(dts))
    return schema_from_columns(cols, name="Concat")


# free functions mirroring the reference's module-level API


def groupby(table: Table, *args, **kwargs):
    return table.groupby(*args, **kwargs)


def join(left: Table, right: Table, *on, **kwargs):
    return left.join(right, *on, **kwargs)


def join_inner(left: Table, right: Table, *on, **kwargs):
    return left.join_inner(right, *on, **kwargs)


def join_left(left: Table, right: Table, *on, **kwargs):
    return left.join_left(right, *on, **kwargs)


def join_right(left: Table, right: Table, *on, **kwargs):
    return left.join_right(right, *on, **kwargs)


def join_outer(left: Table, right: Table, *on, **kwargs):
    return left.join_outer(right, *on, **kwargs)
