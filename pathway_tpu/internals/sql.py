"""``pw.sql(query, **tables)`` — SQL compiled to Table operations.

Re-design of ``python/pathway/internals/sql.py`` (726 LoC). The reference
parses with sqlglot and walks its AST into Table ops; sqlglot is not in
this environment, so this module carries its own tokenizer + recursive-
descent parser for the supported subset, then compiles to the same Table
operations (select/filter/join/groupby-reduce/union):

    SELECT [DISTINCT] expr [AS name], ...
    FROM t [AS a] [ [INNER|LEFT|RIGHT|OUTER] JOIN t2 ON cond ]*
    [WHERE cond] [GROUP BY e, ... [HAVING cond]]
    [UNION [ALL] <select>]

Expressions: literals, [table.]column, + - * / % arithmetic, comparisons,
AND/OR/NOT, IS [NOT] NULL, IN (v, ...), BETWEEN, CASE WHEN, COALESCE,
and the aggregates COUNT(*)/COUNT/SUM/AVG/MIN/MAX.
"""

from __future__ import annotations

import re
from typing import Any

from . import dtype as dt
from .expression import ColumnExpression, apply_with_type, if_else
from .table import Table

__all__ = ["sql"]

# ---------------------------------------------------------------------------
# tokenizer


_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<num>\d+\.\d+|\d+)
      | (?P<str>'(?:[^']|'')*')
      | (?P<op><>|!=|<=|>=|=|<|>|\+|-|\*|/|%|\(|\)|,|\.)
      | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
    )""",
    re.VERBOSE,
)

_KEYWORDS = {
    "select", "distinct", "from", "where", "group", "by", "having", "as",
    "join", "inner", "left", "right", "outer", "full", "on", "and", "or",
    "not", "is", "null", "in", "between", "like", "union", "all", "case",
    "when", "then", "else", "end", "true", "false", "with",
}


class SqlSyntaxError(ValueError):
    pass


def _tokenize(src: str) -> list[tuple[str, Any]]:
    out: list[tuple[str, Any]] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            rest = src[pos:].strip()
            if not rest:
                break
            raise SqlSyntaxError(f"cannot tokenize SQL at: {rest[:30]!r}")
        pos = m.end()
        if m.group("num") is not None:
            text = m.group("num")
            out.append(("num", float(text) if "." in text else int(text)))
        elif m.group("str") is not None:
            out.append(("str", m.group("str")[1:-1].replace("''", "'")))
        elif m.group("op") is not None:
            out.append(("op", m.group("op")))
        else:
            name = m.group("name")
            if name.lower() in _KEYWORDS:
                out.append(("kw", name.lower()))
            else:
                out.append(("name", name))
    return out


# ---------------------------------------------------------------------------
# AST


class _Node(dict):
    def __init__(self, kind: str, **kw: Any):
        super().__init__(kind=kind, **kw)

    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError as e:
            raise AttributeError(k) from e


class _Parser:
    def __init__(self, tokens: list[tuple[str, Any]]):
        self._anon = 0
        self.toks = tokens
        self.i = 0

    # -- token helpers --

    def peek(self, ahead: int = 0) -> tuple[str, Any]:
        j = self.i + ahead
        return self.toks[j] if j < len(self.toks) else ("eof", None)

    def next(self) -> tuple[str, Any]:
        t = self.peek()
        self.i += 1
        return t

    def accept(self, kind: str, value: Any = None) -> bool:
        k, v = self.peek()
        if k == kind and (value is None or v == value):
            self.i += 1
            return True
        return False

    def expect(self, kind: str, value: Any = None) -> Any:
        k, v = self.peek()
        if k != kind or (value is not None and v != value):
            raise SqlSyntaxError(
                f"expected {value or kind}, got {v!r} (token {self.i})"
            )
        self.i += 1
        return v

    # -- grammar --

    def parse(self) -> _Node:
        node = self.query()
        if self.peek()[0] != "eof":
            raise SqlSyntaxError(f"trailing tokens: {self.toks[self.i:]}")
        return node

    def query(self) -> _Node:
        """[WITH ctes] SELECT with optional UNION [ALL] chain (also the
        body of a parenthesized derived table)."""
        ctes: list[tuple[str, _Node]] = []
        if self.accept("kw", "with"):
            while True:
                name = self.expect("name")
                self.expect("kw", "as")
                self.expect("op", "(")
                body = self.query()
                self.expect("op", ")")
                ctes.append((name, body))
                if not self.accept("op", ","):
                    break
        node = self.select()
        while self.accept("kw", "union"):
            all_ = self.accept("kw", "all")
            rhs = self.select()
            node = _Node("union", left=node, right=rhs, all=all_)
        if ctes:
            node = _Node("with", ctes=ctes, body=node)
        return node

    def select(self) -> _Node:
        self.expect("kw", "select")
        distinct = self.accept("kw", "distinct")
        items = [self.select_item()]
        while self.accept("op", ","):
            items.append(self.select_item())
        self.expect("kw", "from")
        table = self.table_ref()
        joins = []
        while True:
            mode = None
            if self.accept("kw", "join") or (
                self.accept("kw", "inner") and self.expect("kw", "join")
            ):
                mode = "inner"
            elif self.accept("kw", "left"):
                self.accept("kw", "outer")
                self.expect("kw", "join")
                mode = "left"
            elif self.accept("kw", "right"):
                self.accept("kw", "outer")
                self.expect("kw", "join")
                mode = "right"
            elif self.accept("kw", "full") or self.accept("kw", "outer"):
                self.accept("kw", "outer")
                self.expect("kw", "join")
                mode = "outer"
            else:
                break
            jt = self.table_ref()
            self.expect("kw", "on")
            cond = self.expr()
            joins.append(_Node("join", table=jt, on=cond, mode=mode))
        where = self.expr() if self.accept("kw", "where") else None
        group = None
        having = None
        # the reference (via sqlglot) tolerates HAVING before GROUP BY —
        # accept the clauses in either order, each at most once
        while True:
            if self.accept("kw", "group"):
                if group is not None:
                    raise SqlSyntaxError("duplicate GROUP BY clause")
                self.expect("kw", "by")
                group = [self.expr()]
                while self.accept("op", ","):
                    group.append(self.expr())
            elif self.accept("kw", "having"):
                if having is not None:
                    raise SqlSyntaxError("duplicate HAVING clause")
                having = self.expr()
            else:
                break
        return _Node(
            "select", items=items, table=table, joins=joins,
            where=where, group=group, having=having, distinct=distinct,
        )

    def table_ref(self) -> _Node:
        if self.accept("op", "("):
            # derived table: FROM (SELECT ... [UNION ...]) [AS] alias
            inner = self.query()
            self.expect("op", ")")
            alias = None
            if self.accept("kw", "as"):
                alias = self.expect("name")
            elif self.peek()[0] == "name":
                alias = self.next()[1]
            if alias is None:
                # distinct fallback aliases: two anonymous derived tables
                # in one query must not evict each other from the env
                self._anon += 1
                alias = f"_subquery_{self._anon}"
            return _Node("subquery", select=inner, alias=alias)
        name = self.expect("name")
        alias = None
        if self.accept("kw", "as"):
            alias = self.expect("name")
        elif self.peek()[0] == "name":
            alias = self.next()[1]
        return _Node("table", name=name, alias=alias or name)

    def select_item(self) -> _Node:
        if self.accept("op", "*"):
            return _Node("star", table=None)
        if (
            self.peek()[0] == "name"
            and self.peek(1) == ("op", ".")
            and self.peek(2) == ("op", "*")
        ):
            tname = self.next()[1]
            self.next()
            self.next()
            return _Node("star", table=tname)
        e = self.expr()
        alias = None
        if self.accept("kw", "as"):
            alias = self.expect("name")
        elif self.peek()[0] == "name":
            alias = self.next()[1]
        return _Node("item", expr=e, alias=alias)

    # precedence: or < and < not < comparison < additive < multiplicative

    def expr(self) -> _Node:
        node = self.and_expr()
        while self.accept("kw", "or"):
            node = _Node("or", left=node, right=self.and_expr())
        return node

    def and_expr(self) -> _Node:
        node = self.not_expr()
        while self.accept("kw", "and"):
            node = _Node("and", left=node, right=self.not_expr())
        return node

    def not_expr(self) -> _Node:
        if self.accept("kw", "not"):
            return _Node("not", arg=self.not_expr())
        return self.comparison()

    def comparison(self) -> _Node:
        node = self.additive()
        k, v = self.peek()
        if k == "op" and v in ("=", "<>", "!=", "<", "<=", ">", ">="):
            self.next()
            return _Node("cmp", op=v, left=node, right=self.additive())
        if k == "kw" and v == "is":
            self.next()
            negate = self.accept("kw", "not")
            self.expect("kw", "null")
            return _Node("isnull", arg=node, negate=negate)
        if k == "kw" and v == "in":
            self.next()
            self.expect("op", "(")
            vals = [self.additive()]
            while self.accept("op", ","):
                vals.append(self.additive())
            self.expect("op", ")")
            return _Node("in", arg=node, values=vals)
        if k == "kw" and v == "between":
            self.next()
            lo = self.additive()
            self.expect("kw", "and")
            hi = self.additive()
            return _Node("between", arg=node, lo=lo, hi=hi)
        if k == "kw" and v == "like":
            self.next()
            pat = self.additive()
            return _Node("like", arg=node, pattern=pat)
        return node

    def additive(self) -> _Node:
        node = self.multiplicative()
        while True:
            if self.accept("op", "+"):
                node = _Node("bin", op="+", left=node, right=self.multiplicative())
            elif self.accept("op", "-"):
                node = _Node("bin", op="-", left=node, right=self.multiplicative())
            else:
                return node

    def multiplicative(self) -> _Node:
        node = self.unary()
        while True:
            if self.accept("op", "*"):
                node = _Node("bin", op="*", left=node, right=self.unary())
            elif self.accept("op", "/"):
                node = _Node("bin", op="/", left=node, right=self.unary())
            elif self.accept("op", "%"):
                node = _Node("bin", op="%", left=node, right=self.unary())
            else:
                return node

    def unary(self) -> _Node:
        if self.accept("op", "-"):
            return _Node("neg", arg=self.unary())
        return self.primary()

    def primary(self) -> _Node:
        k, v = self.peek()
        if k == "num" or k == "str":
            self.next()
            return _Node("lit", value=v)
        if k == "kw" and v in ("true", "false"):
            self.next()
            return _Node("lit", value=(v == "true"))
        if k == "kw" and v == "null":
            self.next()
            return _Node("lit", value=None)
        if k == "kw" and v == "case":
            return self.case_expr()
        if self.accept("op", "("):
            node = self.expr()
            self.expect("op", ")")
            return node
        if k == "name":
            self.next()
            # function call?
            if self.accept("op", "("):
                fname = v.lower()
                if fname == "count" and self.accept("op", "*"):
                    self.expect("op", ")")
                    return _Node("func", name="count", args=[])
                args = []
                if not self.accept("op", ")"):
                    args.append(self.expr())
                    while self.accept("op", ","):
                        args.append(self.expr())
                    self.expect("op", ")")
                return _Node("func", name=fname, args=args)
            # qualified column?
            if self.accept("op", "."):
                col = self.expect("name")
                return _Node("col", table=v, name=col)
            return _Node("col", table=None, name=v)
        raise SqlSyntaxError(f"unexpected token {v!r}")

    def case_expr(self) -> _Node:
        self.expect("kw", "case")
        whens = []
        while self.accept("kw", "when"):
            cond = self.expr()
            self.expect("kw", "then")
            whens.append((cond, self.expr()))
        default = self.expr() if self.accept("kw", "else") else _Node("lit", value=None)
        self.expect("kw", "end")
        return _Node("case", whens=whens, default=default)


# ---------------------------------------------------------------------------
# compiler

_AGGREGATES = {"count", "sum", "avg", "min", "max"}


def _walk(node: Any):
    if isinstance(node, dict):
        yield node
        for v in node.values():
            yield from _walk(v)
    elif isinstance(node, (list, tuple)):
        for x in node:
            yield from _walk(x)


def _has_aggregate(node: _Node) -> bool:
    return any(
        n.get("kind") == "func" and n.get("name") in _AGGREGATES
        for n in _walk(node)
    )


class _Compiler:
    def __init__(self, tables: dict[str, Table]):
        self.tables = tables

    def compile(self, node: _Node) -> Table:
        if node["kind"] == "with":
            # each CTE materializes into the table env for the WITH body
            # only — restore afterwards so a CTE inside a subquery cannot
            # shadow outer tables
            saved = self.tables
            try:
                for name, body in node.ctes:
                    self.tables = {**self.tables, name: self.compile(body)}
                return self.compile(node.body)
            finally:
                self.tables = saved
        if node["kind"] == "union":
            left = self.compile(node.left)
            right = self.compile(node.right)
            out = left.concat_reindex(right)
            if not node.all:
                out = _distinct(out)
            return out
        return self.compile_select(node)

    # -- FROM/JOIN resolution --

    def _resolve_source(self, sel: _Node) -> tuple[Table, dict[str, Table]]:
        """The working table + alias env. Joins compile to pw joins keeping
        both sides' columns (qualified names disambiguated). Also records
        ``self._alias_cols``: alias -> the names its columns carry in the
        working table (for qualified ``alias.*`` expansion)."""
        def lookup(tref: _Node) -> Table:
            if tref["kind"] == "subquery":
                return self.compile(tref["select"])  # handles UNION bodies
            name = tref["name"]
            if name not in self.tables:
                raise KeyError(f"unknown table {name!r} in SQL (pass it as kwarg)")
            return self.tables[name]

        base = lookup(sel.table)
        env: dict[str, Table] = {sel.table["alias"]: base}
        # built locally: lookup() of a derived table (subquery in JOIN
        # position) recursively compiles and would clobber self._alias_cols
        # mid-loop (ADVICE r4) — publish only once all joins resolve
        alias_cols = {sel.table["alias"]: list(base.column_names())}
        current = base
        for join in sel.joins:
            right = lookup(join.table)
            alias = join.table["alias"]
            env[alias] = right
            cond = join.on
            # only equi-joins compile to keyed joins
            if cond["kind"] != "cmp" or cond["op"] != "=":
                raise SqlSyntaxError("JOIN ON requires an equality condition")
            lexpr = self._expr(cond["left"], env)
            rexpr = self._expr(cond["right"], env)
            from .joins import JoinMode

            mode = join["mode"]
            joined = current.join(
                right, lexpr == rexpr,
                how={"inner": JoinMode.INNER, "left": JoinMode.LEFT,
                     "right": JoinMode.RIGHT, "outer": JoinMode.OUTER}[mode],
            )
            # materialize both sides' columns under unqualified names where
            # unambiguous; qualified refs re-resolve via env
            out_cols: dict[str, Any] = {}
            from .thisclass import left as l_, right as r_

            for c in current.column_names():
                out_cols[c] = getattr(l_, c)
            right_names = []
            for c in right.column_names():
                if c in out_cols:
                    out_cols[f"{alias}.{c}"] = getattr(r_, c)
                    right_names.append(f"{alias}.{c}")
                else:
                    out_cols[c] = getattr(r_, c)
                    right_names.append(c)
            alias_cols[alias] = right_names
            current = joined.select(**out_cols)
            env = {a: current for a in env}  # all aliases now view the join
        self._alias_cols = alias_cols
        return current, env

    # -- expressions --

    def _expr(self, node: _Node, env: dict[str, Table]) -> Any:
        kind = node["kind"]
        if kind == "lit":
            return node["value"]
        if kind == "col":
            tname, cname = node["table"], node["name"]
            if tname is not None:
                t = env.get(tname)
                if t is None:
                    raise KeyError(f"unknown table alias {tname!r}")
                qual = f"{tname}.{cname}"
                if qual in t.column_names():
                    return t[qual]
                return t[cname]
            for t in dict.fromkeys(env.values()):
                if cname in t.column_names():
                    return t[cname]
            raise KeyError(f"unknown column {cname!r}")
        if kind == "bin":
            lhs, rhs = self._expr(node["left"], env), self._expr(node["right"], env)
            op = node["op"]
            if op == "+":
                return lhs + rhs
            if op == "-":
                return lhs - rhs
            if op == "*":
                return lhs * rhs
            if op == "/":
                return lhs / rhs
            return lhs % rhs
        if kind == "neg":
            return -self._expr(node["arg"], env)
        if kind == "cmp":
            lhs, rhs = self._expr(node["left"], env), self._expr(node["right"], env)
            op = node["op"]
            if op == "=":
                return lhs == rhs
            if op in ("<>", "!="):
                return lhs != rhs
            if op == "<":
                return lhs < rhs
            if op == "<=":
                return lhs <= rhs
            if op == ">":
                return lhs > rhs
            return lhs >= rhs
        if kind == "and":
            return self._expr(node["left"], env) & self._expr(node["right"], env)
        if kind == "or":
            return self._expr(node["left"], env) | self._expr(node["right"], env)
        if kind == "not":
            return ~self._expr(node["arg"], env)
        if kind == "isnull":
            arg = self._expr(node["arg"], env)
            isnull = apply_with_type(lambda v: v is None, dt.BOOL, arg)
            return ~isnull if node["negate"] else isnull
        if kind == "in":
            arg = self._expr(node["arg"], env)
            vals = [self._expr(v, env) for v in node["values"]]
            if any(isinstance(v, ColumnExpression) for v in vals):
                raise SqlSyntaxError("IN list must be literal values")
            vs = tuple(vals)
            return apply_with_type(lambda x, vs=vs: x in vs, dt.BOOL, arg)
        if kind == "between":
            arg = self._expr(node["arg"], env)
            lo = self._expr(node["lo"], env)
            hi = self._expr(node["hi"], env)
            return (arg >= lo) & (arg <= hi)
        if kind == "like":
            arg = self._expr(node["arg"], env)
            pat = self._expr(node["pattern"], env)
            if isinstance(pat, ColumnExpression):
                raise SqlSyntaxError("LIKE pattern must be a literal")
            rx = re.compile(
                "^"
                + "".join(
                    ".*" if ch == "%" else "." if ch == "_" else re.escape(ch)
                    for ch in str(pat)
                )
                + "$"
            )
            return apply_with_type(
                lambda s, rx=rx: s is not None and rx.match(str(s)) is not None,
                dt.BOOL, arg,
            )
        if kind == "case":
            result: Any = self._expr(node["default"], env)
            for cond, then in reversed(node["whens"]):
                result = if_else(
                    self._expr(cond, env), self._expr(then, env), result
                )
            return result
        if kind == "func":
            return self._func(node, env)
        raise SqlSyntaxError(f"unsupported expression kind {kind!r}")

    def _func(self, node: _Node, env: dict[str, Table]) -> Any:
        name = node["name"]
        if name in _AGGREGATES:
            raise SqlSyntaxError(
                f"aggregate {name}() outside SELECT/HAVING of a GROUP BY"
            )
        args = [self._expr(a, env) for a in node["args"]]
        return self._scalar_func(name, args)

    def _scalar_func(self, name: str, args: list[Any]) -> Any:
        if name == "coalesce":
            from .expression import coalesce

            return coalesce(*args)
        if name == "abs":
            return apply_with_type(
                lambda v: None if v is None else abs(v), dt.ANY, args[0]
            )
        if name in ("upper", "lower"):
            fn = str.upper if name == "upper" else str.lower
            return apply_with_type(
                lambda v, fn=fn: None if v is None else fn(str(v)), dt.STR, args[0]
            )
        if name == "length":
            return apply_with_type(
                lambda v: None if v is None else len(v), dt.INT, args[0]
            )
        if name == "round":
            return apply_with_type(
                lambda v, *nd: None if v is None else round(v, *(int(n) for n in nd)),
                dt.ANY, *args,
            )
        raise SqlSyntaxError(f"unsupported SQL function {name!r}")

    def _aggregate(self, node: _Node, env: dict[str, Table]):
        """Aggregate call -> pw.reducers expression."""
        from .. import reducers

        name = node["name"]
        if name == "count":
            if not node["args"]:
                return reducers.count()
            # COUNT(expr) counts non-NULL values only (SQL semantics)
            (arg,) = [self._expr(a, env) for a in node["args"]]
            return reducers.sum(
                apply_with_type(lambda v: 0 if v is None else 1, dt.INT, arg)
            )
        (arg,) = [self._expr(a, env) for a in node["args"]]
        return {
            "sum": reducers.sum,
            "avg": reducers.avg,
            "min": reducers.min,
            "max": reducers.max,
        }[name](arg)

    def _agg_expr(self, node: _Node, env: dict[str, Table]) -> Any:
        """Expression that may contain aggregates (SELECT item / HAVING of a
        grouped query): aggregates lower to reducer expressions inline."""
        if node["kind"] == "func" and node["name"] in _AGGREGATES:
            return self._aggregate(node, env)
        if node["kind"] in ("bin", "cmp", "and", "or"):
            left = self._agg_expr(node["left"], env)
            right = self._agg_expr(node["right"], env)
            op = node.get("op")
            if node["kind"] == "bin":
                return {"+": lambda a, b: a + b, "-": lambda a, b: a - b,
                        "*": lambda a, b: a * b, "/": lambda a, b: a / b,
                        "%": lambda a, b: a % b}[op](left, right)
            if node["kind"] == "cmp":
                return {"=": lambda a, b: a == b, "<>": lambda a, b: a != b,
                        "!=": lambda a, b: a != b, "<": lambda a, b: a < b,
                        "<=": lambda a, b: a <= b, ">": lambda a, b: a > b,
                        ">=": lambda a, b: a >= b}[op](left, right)
            if node["kind"] == "and":
                return left & right
            return left | right
        if node["kind"] == "neg":
            return -self._agg_expr(node["arg"], env)
        if node["kind"] == "not":
            return ~self._agg_expr(node["arg"], env)
        if node["kind"] == "case":
            result: Any = self._agg_expr(node["default"], env)
            for cond, then in reversed(node["whens"]):
                result = if_else(
                    self._agg_expr(cond, env), self._agg_expr(then, env), result
                )
            return result
        if node["kind"] == "func" and node["name"] not in _AGGREGATES:
            return self._scalar_func(
                node["name"], [self._agg_expr(a, env) for a in node["args"]]
            )
        return self._expr(node, env)

    # -- SELECT --

    def compile_select(self, sel: _Node) -> Table:
        current, env = self._resolve_source(sel)

        if sel.where is not None:
            current = current.filter(self._expr(sel.where, env))
            env = {a: current for a in env}

        grouped = sel.group is not None or any(
            n["kind"] == "item" and _has_aggregate(n["expr"]) for n in sel["items"]
        )
        if sel.having is not None and not grouped:
            raise SqlSyntaxError(
                "HAVING requires GROUP BY or aggregate select items"
            )

        if not grouped:
            out_cols: dict[str, Any] = {}
            for i, item in enumerate(sel["items"]):
                if item["kind"] == "star":
                    # `tab.*` expands only the named alias's columns (a
                    # typo'd alias raises, like qualified column refs);
                    # bare `*` expands the whole working table
                    if item["table"] is not None:
                        if item["table"] not in self._alias_cols:
                            raise KeyError(
                                f"unknown table alias {item['table']!r}"
                            )
                        for c in self._alias_cols[item["table"]]:
                            out = (
                                c.split(".", 1)[1]
                                if c.startswith(item["table"] + ".")
                                else c
                            )
                            out_cols[out] = current[c]
                    else:
                        for c in current.column_names():
                            out_cols[c] = current[c]
                    continue
                name = item["alias"] or _default_name(item["expr"], i)
                out_cols[name] = self._expr(item["expr"], env)
            result = current.select(**out_cols)
            if sel.distinct:
                result = _distinct(result)
            return result

        # grouped query
        group_exprs = [self._expr(g, env) for g in (sel.group or [])]
        gb = current.groupby(*group_exprs)
        out_cols = {}
        for i, item in enumerate(sel["items"]):
            if item["kind"] == "star":
                raise SqlSyntaxError("SELECT * not allowed with GROUP BY")
            name = item["alias"] or _default_name(item["expr"], i)
            out_cols[name] = self._agg_expr(item["expr"], env)
        if sel.having is not None:
            out_cols["__having__"] = self._agg_expr(sel.having, env)
        result = gb.reduce(**out_cols)
        if sel.having is not None:
            from .thisclass import this

            result = result.filter(this["__having__"]).select(
                **{c: this[c] for c in out_cols if c != "__having__"}
            )
        if sel.distinct:
            result = _distinct(result)
        return result


def _default_name(node: _Node, i: int) -> str:
    if node["kind"] == "col":
        return node["name"]
    if node["kind"] == "func":
        return node["name"]
    return f"_col_{i}"


def _distinct(table: Table) -> Table:
    from .. import reducers
    from .thisclass import this

    cols = table.column_names()
    gb = table.groupby(*[table[c] for c in cols])
    return gb.reduce(**{c: this[c] for c in cols})


def sql(query: str, **tables: Table) -> Table:
    """Execute a SQL query against the given tables
    (reference internals/sql.py:10 ``pw.sql``)."""
    ast = _Parser(_tokenize(query)).parse()
    return _Compiler(tables).compile(ast)
