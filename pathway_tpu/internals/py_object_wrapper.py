"""``pw.PyObjectWrapper`` — carry an arbitrary python object as a column
value (reference ``internals/api`` PyObjectWrapper + ``value.rs``
Value::PyObjectWrapper): the engine treats it as an opaque value that
survives serialization (pickle), groups by content, and round-trips
through UDFs via ``.value``. Type annotations may parameterize it
(``pw.PyObjectWrapper[MyClass]``) — the schema layer checks the wrapped
object's class."""

from __future__ import annotations

from typing import Generic, TypeVar

T = TypeVar("T")

__all__ = ["PyObjectWrapper"]


class PyObjectWrapper(Generic[T]):
    __slots__ = ("value",)

    def __init__(self, value: T):
        self.value = value

    def __repr__(self) -> str:
        # content-based repr: the engine's object hash falls back to repr,
        # so equal-valued wrappers key identically (groupby by wrapper)
        return f"PyObjectWrapper({self.value!r})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, PyObjectWrapper) and self.value == other.value

    def __ne__(self, other: object) -> bool:
        return not self.__eq__(other)

    def __hash__(self) -> int:
        try:
            return hash(("PyObjectWrapper", self.value))
        except TypeError:
            return hash(("PyObjectWrapper", repr(self.value)))

    # pickle via __slots__
    def __getstate__(self):
        return self.value

    def __setstate__(self, state):
        self.value = state

    def __copy__(self):
        return PyObjectWrapper(self.value)

    def __deepcopy__(self, memo):
        import copy

        return PyObjectWrapper(copy.deepcopy(self.value, memo))
