"""Global parse graph: every Table operation appends a node.

Re-design of ``python/pathway/internals/parse_graph.py:104-247`` +
``operator.py:84-444``. Here the graph is held directly by ``Table`` objects
(kind + inputs + params); the global ``G`` tracks sinks, static-table
content cache (shared universes for identical definitions — what makes
id-sensitive table equality asserts work, cf. reference
``debug/__init__.py:396-403``) and universe equivalences.
"""

from __future__ import annotations

from typing import Any

__all__ = ["G", "ParseGraph", "Universe"]


class Universe:
    """A key-set identity (reference ``internals/universe.py``). Subset links
    + promised equivalences form the solver (a light union-find version of
    the reference's SAT-based ``universe_solver.py``)."""

    _ids = 0

    def __init__(self, parent: "Universe | None" = None):
        Universe._ids += 1
        self.uid = Universe._ids
        self.parent = parent  # self ⊆ parent

    def find(self) -> "Universe":
        root = G.equiv.get(self, self)
        if root is self:
            return self
        top = root.find()
        G.equiv[self] = top
        return top

    def is_equal(self, other: "Universe") -> bool:
        return self.find() is other.find()

    def is_subset_of(self, other: "Universe") -> bool:
        seen = set()
        u: Universe | None = self
        while u is not None and u not in seen:
            seen.add(u)
            if u.is_equal(other):
                return True
            nxt = u.find()
            if nxt is not u and nxt not in seen:
                u = nxt
                continue
            u = u.parent
        # subset promises
        for sub, sup in G.subset_promises:
            if self.is_equal(sub) and sup.is_equal(other):
                return True
        return False


class ParseGraph:
    def __init__(self) -> None:
        self.sinks: list[Any] = []  # sink Tables / subscribe nodes
        self.static_tables_cache: dict[Any, Any] = {}
        self.equiv: dict[Universe, Universe] = {}
        self.subset_promises: list[tuple[Universe, Universe]] = []
        self.error_log: list[Any] = []

    def clear(self) -> None:
        self.__init__()

    def promise_equal(self, a: Universe, b: Universe) -> None:
        ra, rb = a.find(), b.find()
        if ra is not rb:
            self.equiv[ra] = rb

    def promise_subset(self, sub: Universe, sup: Universe) -> None:
        self.subset_promises.append((sub, sup))

    def add_sink(self, sink: Any) -> None:
        self.sinks.append(sink)


G = ParseGraph()
