"""Global parse graph: every Table operation appends a node.

Re-design of ``python/pathway/internals/parse_graph.py:104-247`` +
``operator.py:84-444``. Here the graph is held directly by ``Table`` objects
(kind + inputs + params); the global ``G`` tracks sinks, static-table
content cache (shared universes for identical definitions — what makes
id-sensitive table equality asserts work, cf. reference
``debug/__init__.py:396-403``) and universe equivalences.
"""

from __future__ import annotations

from typing import Any

__all__ = ["G", "ParseGraph", "Universe"]


class Universe:
    """A key-set identity (reference ``internals/universe.py``). Relations
    (parent subset links, promises, intersection/difference registrations)
    feed the propositional universe solver
    (``internals/universe_solver.py``, mirroring the reference's SAT-based
    ``universe_solver.py``); queries delegate to it."""

    _ids = 0

    def __init__(self, parent: "Universe | None" = None):
        Universe._ids += 1
        self.uid = Universe._ids
        self.parent = parent  # self ⊆ parent
        if parent is not None:
            G.solver.register_as_subset(self, parent)

    def is_equal(self, other: "Universe") -> bool:
        return self is other or G.solver.query_are_equal(self, other)

    def is_subset_of(self, other: "Universe") -> bool:
        return self is other or G.solver.query_is_subset(self, other)

    def is_disjoint_from(self, other: "Universe") -> bool:
        return self is not other and G.solver.query_are_disjoint(self, other)


class ParseGraph:
    def __init__(self) -> None:
        from .universe_solver import UniverseSolver

        self.sinks: list[Any] = []  # sink Tables / subscribe nodes
        self.static_tables_cache: dict[Any, Any] = {}
        self.solver = UniverseSolver()
        self.error_log: list[Any] = []

    def clear(self) -> None:
        self.__init__()

    def promise_equal(self, a: Universe, b: Universe) -> None:
        self.solver.register_as_equal(a, b, promised=True)

    def promise_subset(self, sub: Universe, sup: Universe) -> None:
        self.solver.register_as_subset(sub, sup, promised=True)

    def promise_disjoint(self, *universes: Universe) -> None:
        self.solver.register_as_disjoint(*universes, promised=True)

    def add_sink(self, sink: Any) -> None:
        from . import lintmode

        if lintmode.ACTIVE and isinstance(sink, dict):
            # static analysis: anchor sink diagnostics to the script line
            # that registered the output connector
            loc = lintmode.script_location()
            if loc is not None:
                target = sink.get("delivery")
                (target if isinstance(target, dict) else sink)[
                    "_lint_loc"
                ] = loc
        self.sinks.append(sink)


G = ParseGraph()
