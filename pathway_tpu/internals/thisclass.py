"""``pw.this`` / ``pw.left`` / ``pw.right`` placeholders.

Re-design of the reference's desugaring machinery
(``python/pathway/internals/thisclass.py`` + ``desugaring.py``): a
placeholder is a fake table; expressions built on it are rewritten against
concrete tables at the call site (select/filter/join/reduce) by
``substitute``.
"""

from __future__ import annotations

from typing import Any

from .expression import (
    ApplyExpression,
    ColumnExpression,
    ColumnReference,
    IdReference,
    PointerExpression,
)


class _ColNamespace:
    """``.C`` column accessor (reference ``table.C.colname``): reaches
    columns whose names collide with Table/this METHOD names — ``.C`` has
    no methods of its own, so every attribute is a column reference."""

    __slots__ = ("_owner",)

    def __init__(self, owner: Any):
        self._owner = owner

    def __getattr__(self, name: str):
        if name.startswith("__"):
            raise AttributeError(name)
        return self._owner[name]

    def __getitem__(self, name: str):
        return self._owner[name]


class ThisPlaceholder:
    def __init__(self, label: str):
        self._label = label

    @property
    def C(self) -> _ColNamespace:
        return _ColNamespace(self)

    def __getattr__(self, name: str) -> ColumnReference:
        if name.startswith("__") or name in ("_label", "_ipython_canary_method_should_not_exist_"):
            raise AttributeError(name)
        if name == "id":
            return IdReference(self)  # type: ignore[arg-type]
        return ColumnReference(self, name)  # type: ignore[arg-type]

    def __getitem__(self, name: str) -> ColumnReference:
        if name == "id":
            return IdReference(self)  # type: ignore[arg-type]
        return ColumnReference(self, name)  # type: ignore[arg-type]

    def pointer_from(self, *args: Any, instance: Any = None, optional: bool = False):
        return PointerExpression(self, *args, instance=instance, optional=optional)  # type: ignore[arg-type]

    def without(self, *columns: Any) -> "ThisWithout":
        """Wildcard minus named columns (reference ``pw.this.without``):
        ``t.select(*pw.this.without(pw.this.c))`` selects every column of
        the binding table except ``c``."""
        return ThisWithout(columns, self)

    def __iter__(self):
        # ``t.select(*pw.this)`` — all columns of the binding table
        return iter((ThisWithout((), self),))

    def __repr__(self) -> str:
        return f"<pw.{self._label}>"


class ThisWithout:
    """Deferred 'all columns except…' marker, expanded by select. Carries
    its source placeholder so join selects expand the correct side
    (``pw.left.without(...)`` vs ``pw.right.without(...)``)."""

    def __init__(self, excluded: tuple, placeholder: "ThisPlaceholder"):
        self.placeholder = placeholder
        self.excluded = tuple(
            c.name if isinstance(c, ColumnReference) else str(c)
            for c in excluded
        )

    def __iter__(self):
        return iter((self,))


this = ThisPlaceholder("this")
left = ThisPlaceholder("left")
right = ThisPlaceholder("right")


class DeferredIxTable:
    """``table.ix_ref(...)`` whose context table cannot be inferred from the
    arguments (no args, or only ``pw.this`` args) — the reference resolves
    these during select desugaring (``desugaring.py`` ix machinery); here a
    column read off this proxy becomes a :class:`DeferredIxColumn` that
    ``substitute`` binds once the enclosing select knows its table."""

    def __init__(self, table: Any, args: tuple, optional: bool, instance: Any):
        self._dtable = table
        self._dargs = args
        self._doptional = optional
        self._dinstance = instance

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeferredIxColumn(self, name)

    def __getitem__(self, name: str):
        return DeferredIxColumn(self, name)


class DeferredIxColumn(ColumnExpression):
    def __init__(self, deferred: DeferredIxTable, name: str):
        self._dix = deferred
        self._name = name

    @property
    def _deps(self):
        return tuple(
            a for a in self._dix._dargs if isinstance(a, ColumnExpression)
        )

    def __repr__(self) -> str:
        return f"<deferred {self._dix._dtable!r}.ix_ref(...).{self._name}>"


def substitute(expr: ColumnExpression, mapping: dict[Any, Any]) -> ColumnExpression:
    """Rewrite placeholder column references to concrete tables.

    mapping: placeholder-or-table -> concrete table. References to tables not
    in the mapping pass through unchanged.
    """
    import copy

    if isinstance(expr, DeferredIxColumn):
        ctx = mapping.get(this)
        if ctx is None:
            for ph in (left, right):
                if ph in mapping:
                    ctx = mapping[ph]
                    break
        if ctx is None:
            raise ValueError(
                "ix_ref context could not be inferred; pass context="
            )
        d = expr._dix
        args = tuple(
            substitute(a, mapping) if isinstance(a, ColumnExpression) else a
            for a in d._dargs
        )
        ixed = d._dtable.ix_ref(
            *args, optional=d._doptional, instance=d._dinstance, context=ctx
        )
        return ColumnReference(ixed, expr._name)
    if isinstance(expr, IdReference):
        if expr.table in mapping:
            return IdReference(mapping[expr.table])
        return expr
    if isinstance(expr, ColumnReference):
        if expr.table in mapping:
            target = mapping[expr.table]
            schema = getattr(target, "schema", None)
            if schema is not None and expr.name not in schema.__columns__:
                raise AttributeError(
                    f"Table has no column {expr.name!r}; columns: "
                    f"{schema.column_names()}"
                )
            return ColumnReference(target, expr.name)
        return expr
    if not expr._deps:
        return expr
    clone = copy.copy(expr)
    _substitute_in_place(clone, mapping)
    return clone


def _substitute_in_place(expr: ColumnExpression, mapping: dict[Any, Any]) -> None:
    for attr, value in list(vars(expr).items()):
        if isinstance(value, ColumnExpression):
            setattr(expr, attr, substitute(value, mapping))
        elif isinstance(value, tuple) and any(isinstance(v, ColumnExpression) for v in value):
            setattr(expr, attr, tuple(
                substitute(v, mapping) if isinstance(v, ColumnExpression) else v
                for v in value
            ))
        elif isinstance(value, dict) and any(
            isinstance(v, ColumnExpression) for v in value.values()
        ):
            setattr(expr, attr, {
                k: substitute(v, mapping) if isinstance(v, ColumnExpression) else v
                for k, v in value.items()
            })
