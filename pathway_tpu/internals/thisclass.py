"""``pw.this`` / ``pw.left`` / ``pw.right`` placeholders.

Re-design of the reference's desugaring machinery
(``python/pathway/internals/thisclass.py`` + ``desugaring.py``): a
placeholder is a fake table; expressions built on it are rewritten against
concrete tables at the call site (select/filter/join/reduce) by
``substitute``.
"""

from __future__ import annotations

from typing import Any

from .expression import (
    ApplyExpression,
    ColumnExpression,
    ColumnReference,
    IdReference,
    PointerExpression,
)


class ThisPlaceholder:
    def __init__(self, label: str):
        self._label = label

    def __getattr__(self, name: str) -> ColumnReference:
        if name.startswith("__") or name in ("_label", "_ipython_canary_method_should_not_exist_"):
            raise AttributeError(name)
        if name == "id":
            return IdReference(self)  # type: ignore[arg-type]
        return ColumnReference(self, name)  # type: ignore[arg-type]

    def __getitem__(self, name: str) -> ColumnReference:
        if name == "id":
            return IdReference(self)  # type: ignore[arg-type]
        return ColumnReference(self, name)  # type: ignore[arg-type]

    def pointer_from(self, *args: Any, instance: Any = None, optional: bool = False):
        return PointerExpression(self, *args, instance=instance, optional=optional)  # type: ignore[arg-type]

    def __repr__(self) -> str:
        return f"<pw.{self._label}>"


this = ThisPlaceholder("this")
left = ThisPlaceholder("left")
right = ThisPlaceholder("right")


def substitute(expr: ColumnExpression, mapping: dict[Any, Any]) -> ColumnExpression:
    """Rewrite placeholder column references to concrete tables.

    mapping: placeholder-or-table -> concrete table. References to tables not
    in the mapping pass through unchanged.
    """
    import copy

    if isinstance(expr, IdReference):
        if expr.table in mapping:
            return IdReference(mapping[expr.table])
        return expr
    if isinstance(expr, ColumnReference):
        if expr.table in mapping:
            target = mapping[expr.table]
            schema = getattr(target, "schema", None)
            if schema is not None and expr.name not in schema.__columns__:
                raise AttributeError(
                    f"Table has no column {expr.name!r}; columns: "
                    f"{schema.column_names()}"
                )
            return ColumnReference(target, expr.name)
        return expr
    if not expr._deps:
        return expr
    clone = copy.copy(expr)
    _substitute_in_place(clone, mapping)
    return clone


def _substitute_in_place(expr: ColumnExpression, mapping: dict[Any, Any]) -> None:
    for attr, value in list(vars(expr).items()):
        if isinstance(value, ColumnExpression):
            setattr(expr, attr, substitute(value, mapping))
        elif isinstance(value, tuple) and any(isinstance(v, ColumnExpression) for v in value):
            setattr(expr, attr, tuple(
                substitute(v, mapping) if isinstance(v, ColumnExpression) else v
                for v in value
            ))
        elif isinstance(value, dict) and any(
            isinstance(v, ColumnExpression) for v in value.values()
        ):
            setattr(expr, attr, {
                k: substitute(v, mapping) if isinstance(v, ColumnExpression) else v
                for k, v in value.items()
            })
