"""``pw.iterate`` — declarative fixpoint iteration.

Re-design of the reference's ``pw.iterate`` (``internals/operator.py:316``
IterateOperator; engine side ``dataflow.rs:3737-4222`` — nested differential
scope with ``Product<Timestamp, u32>`` timestamps and a feedback Variable).

The user passes a graph-building function and the tables it iterates over;
the function is traced **once** at parse time against placeholder tables to
capture the inner subgraph. Execution is a host-driven loop (engine
``Iterate`` node): each round lowers the captured subgraph with the current
iterated state as static sources, runs it (all rowwise/group compute jitted
through XLA), and feeds outputs whose names match inputs back in, until
nothing changes or ``iteration_limit`` rounds have run.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from ..engine import operators as ops
from ..engine.delta import rows_to_columns
from ..engine.iterate import Iterate, IterateOutput, states_equal
from .parse_graph import Universe
from .table import Table

__all__ = ["iterate", "iterate_universe"]


def iterate_universe(table: "Table") -> "Table":
    """Marker for iterated tables whose key set changes between rounds
    (reference ``pw.iterate_universe``). The TPU engine rebuilds iterated
    state from snapshots every round, so changing universes need no special
    handling — this is an identity passthrough kept for API parity."""
    return table


class _IterateDescriptor:
    def __init__(
        self,
        inputs: dict[str, Table],
        placeholders: dict[str, Table],
        outputs: dict[str, Table],
        feedback: list[str],
        iteration_limit: int | None,
    ):
        self.inputs = inputs
        self.placeholders = placeholders
        self.outputs = outputs
        self.feedback = feedback
        self.limit = iteration_limit
        # column permutation for feeding an output back into its input slot
        self._fb_perm: dict[str, list[int]] = {}
        for name in feedback:
            in_cols = inputs[name].column_names()
            out_cols = outputs[name].column_names()
            if set(in_cols) != set(out_cols):
                raise ValueError(
                    f"pw.iterate: output {name!r} columns {out_cols} do not "
                    f"match the iterated input's columns {in_cols}"
                )
            self._fb_perm[name] = [out_cols.index(c) for c in in_cols]

    # -- execution-time driver --------------------------------------------

    def driver(
        self, snapshots: dict[str, dict[int, tuple]]
    ) -> dict[str, dict[int, tuple]]:
        cur = {name: snapshots[name] for name in self.inputs}
        rounds = 0
        while True:
            rounds += 1
            out_states = self._run_once(cur)
            changed = False
            for name in self.feedback:
                perm = self._fb_perm[name]
                fb = {
                    k: tuple(row[j] for j in perm)
                    for k, row in out_states[name].items()
                }
                if not states_equal(fb, cur[name]):
                    cur[name] = fb
                    changed = True
            if not changed:
                break
            if self.limit is not None and rounds >= self.limit:
                break
        return out_states

    def _run_once(
        self, cur: dict[str, dict[int, tuple]]
    ) -> dict[str, dict[int, tuple]]:
        from .graph_runner import GraphRunner

        runner = GraphRunner()
        for name, ph in self.placeholders.items():
            state = cur[name]
            keys = np.fromiter(state.keys(), dtype=np.uint64, count=len(state))
            data = rows_to_columns(
                list(state.values()), self.inputs[name].column_names()
            )
            runner._cache[id(ph)] = runner._add(ops.StaticSource(keys, data))
        caps = runner.run_tables(*self.outputs.values())
        return {
            name: dict(cap.state._rows)
            for name, cap in zip(self.outputs, caps)
        }

    # -- lowering ----------------------------------------------------------

    def lower_output(self, runner: Any, name: str):
        registry = getattr(runner, "_iterate_nodes", None)
        if registry is None:
            registry = {}
            runner._iterate_nodes = registry
        node = registry.get(id(self))
        if node is None:
            in_nodes = [
                runner._project(runner.lower(t), t, t.column_names())
                for t in self.inputs.values()
            ]
            node = runner._add(
                Iterate(
                    in_nodes,
                    list(self.inputs),
                    self.driver,
                    {n: t.column_names() for n, t in self.outputs.items()},
                )
            )
            registry[id(self)] = node
        return runner._add(IterateOutput(node, name))


def iterate(
    func: Callable[..., Any],
    iteration_limit: int | None = None,
    **kwargs: Any,
):
    """Iterate ``func`` to fixpoint over the given tables.

    ``func`` is called once with placeholder tables to build the inner
    subgraph; outputs whose names match input keyword names are fed back each
    round. Returns table(s) of the same shape as ``func``'s return value
    (single Table, dict, or namedtuple of tables).
    """
    if iteration_limit is not None and iteration_limit < 1:
        raise ValueError("wrong value of iteration_limit")
    table_inputs = {
        name: v for name, v in kwargs.items() if isinstance(v, Table)
    }
    if not table_inputs:
        raise ValueError("pw.iterate needs at least one Table argument")
    placeholders = {
        name: Table("iter_pin", [], {"name": name}, t.schema, Universe())
        for name, t in table_inputs.items()
    }
    call_kwargs = dict(kwargs)
    call_kwargs.update(placeholders)
    result = func(**call_kwargs)

    single = isinstance(result, Table)
    if single:
        # a lone returned table iterates with the first table argument
        out_map = {next(iter(table_inputs)): result}
    elif isinstance(result, dict):
        out_map = dict(result)
    elif hasattr(result, "_asdict"):
        out_map = dict(result._asdict())
    else:
        raise TypeError(
            "pw.iterate function must return a Table, a dict of tables, or a "
            f"namedtuple of tables; got {type(result)!r}"
        )
    for name, t in out_map.items():
        if not isinstance(t, Table):
            raise TypeError(f"pw.iterate output {name!r} is not a Table")

    feedback = [n for n in out_map if n in table_inputs]
    desc = _IterateDescriptor(
        table_inputs, placeholders, out_map, feedback, iteration_limit
    )

    def make_output(name: str, t: Table) -> Table:
        return Table(
            "custom",
            list(table_inputs.values()),
            {"lower": (lambda runner, _table, n=name: desc.lower_output(runner, n))},
            t.schema,
            Universe(),
        )

    outer = {name: make_output(name, t) for name, t in out_map.items()}
    if single:
        return next(iter(outer.values()))
    if isinstance(result, dict):
        return outer
    return type(result)(**outer)
