"""Compile ColumnExpression trees to whole-batch columnar kernels.

This replaces two reference components at once:
- the static type interpreter (``python/pathway/internals/type_interpreter.py``)
- the row-at-a-time typed Rust interpreter (``src/engine/expression.rs:325``)

An expression DAG compiles to ONE function over column arrays. Pure-numeric
trees additionally compile to a fused ``jax.jit`` kernel that is used for
large batches, so on TPU the whole expression lands on the VPU/MXU as a
single XLA computation (cf. SURVEY §7: "jit whole expression DAGs into one
XLA kernel per operator per batch").
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable

import numpy as np

from . import dtype as dt
from . import expression as expr_mod
from ..engine import keys as K
from ..engine.error import Error as EngineError
from .json import Json
from .expression import (
    ApplyExpression,
    AsyncApplyExpression,
    CastExpression,
    CoalesceExpression,
    ColumnBinaryOpExpression,
    ColumnConstExpression,
    ColumnExpression,
    ColumnReference,
    ColumnUnaryOpExpression,
    ConvertExpression,
    DeclareTypeExpression,
    FillErrorExpression,
    GetExpression,
    IdReference,
    IfElseExpression,
    IsNoneExpression,
    IsNotNoneExpression,
    MakeTupleExpression,
    MethodCallExpression,
    PointerExpression,
    ReducerExpression,
    RequireExpression,
    UnwrapExpression,
)

JIT_THRESHOLD = int(os.environ.get("PATHWAY_TPU_JIT_THRESHOLD", "4096"))
JIT_WARMUP_BATCHES = int(os.environ.get("PATHWAY_TPU_JIT_WARMUP_BATCHES", "16"))

_NUMERIC = {dt.INT, dt.FLOAT, dt.BOOL}


class ColumnEnv:
    """Resolution of column references to engine column names + dtypes."""

    def __init__(self) -> None:
        self._map: dict[tuple[int, str], tuple[str | None, dt.DType]] = {}

    def add(self, table: Any, name: str, engine_col: str | None, dtype: dt.DType) -> None:
        self._map[(id(table), name)] = (engine_col, dtype)

    def add_table(self, table: Any, prefix: str = "") -> None:
        for name, dtype in table.schema.dtypes().items():
            self.add(table, name, prefix + name, dtype)
        self.add(table, "id", None if not prefix else prefix + "id", dt.POINTER)

    def resolve(self, ref: ColumnReference) -> tuple[str | None, dt.DType]:
        key = (id(ref.table), ref.name)
        if key not in self._map:
            raise KeyError(
                f"column {ref.name!r} is not available in this context "
                f"(table {ref.table!r})"
            )
        return self._map[key]

    def signature(self) -> frozenset:
        """Identity of the binding environment — compile results are valid
        for any env with the same bindings (used to reuse jitted kernels
        across pw.iterate rounds instead of re-tracing every round)."""
        return frozenset(
            (k, v[0], str(v[1])) for k, v in self._map.items()
        )


@dataclass
class Compiled:
    fn: Callable[[dict[str, np.ndarray], np.ndarray], np.ndarray]
    dtype: dt.DType
    #: the whole tree is jax-compilable (dense numeric, total ops) —
    #: the chain-fusion pass (engine/fusion.py) uses this both as the
    #: whole-chain XLA gate and as the mask-deferral proof (a total
    #: kernel evaluated on masked-out rows cannot raise, build Error
    #: carriers, or touch the error log)
    jax_ok: bool = False


def infer_dtype(expr: ColumnExpression, env: ColumnEnv) -> dt.DType:
    """Static dtype of an expression (reference: type_interpreter.py)."""
    if isinstance(expr, ReducerExpression):
        return _reducer_dtype(expr, env)
    _, dtype, _, _ = _build(expr, env)
    return dtype


def _reducer_dtype(expr: ReducerExpression, env: ColumnEnv) -> dt.DType:
    name = expr._reducer
    arg_ts = [infer_dtype(a, env) for a in expr._args]
    if name == "count":
        return dt.INT
    if name in ("sum", "min", "max", "unique", "any", "earliest", "latest"):
        return arg_ts[0] if arg_ts else dt.ANY
    if name in ("argmin", "argmax"):
        return dt.POINTER
    if name == "avg":
        return dt.FLOAT
    if name == "sorted_tuple" or name == "tuple":
        return dt.List(arg_ts[0] if arg_ts else dt.ANY)
    if name == "ndarray":
        return dt.Array(1, arg_ts[0] if arg_ts else dt.FLOAT)
    return dt.ANY


def compile_expr(expr: ColumnExpression, env: ColumnEnv) -> Compiled:
    # memoize per (expression, bindings): pw.iterate re-lowers the same
    # captured subgraph every fixpoint round — without this each round
    # would rebuild closures and re-trace XLA kernels from scratch
    cache: dict | None = getattr(expr, "_compiled_cache", None)
    if cache is None:
        cache = {}
        try:
            expr._compiled_cache = cache  # type: ignore[attr-defined]
        except Exception:
            cache = None
    sig = env.signature() if cache is not None else None
    if cache is not None and sig in cache:
        return cache[sig]
    result = _compile_expr_uncached(expr, env)
    try:
        # static-analysis breadcrumbs (pathway_tpu/analysis): the lowered
        # engine nodes hold only compiled kernels — tagging each kernel
        # with its source expression tree + static dtype lets the analyzer
        # walk the compiled graph without re-deriving the compile
        result.fn._pw_expr = expr
        result.fn._pw_dtype = result.dtype
        # chain-fusion breadcrumbs (engine/fusion.py): the fused-chain
        # compiler rebuilds member kernels with jax.numpy inside ONE
        # traced function, which needs the binding environment back
        result.fn._pw_env = env
        result.fn._pw_jax_ok = result.jax_ok
        if isinstance(expr, ColumnReference) and not isinstance(
            expr, IdReference
        ):
            # plain column pass-through: the groupby/join content-key
            # reuse fast path matches these against the source delta's
            # key-derivation columns (operators.py)
            try:
                engine_col, _cdt = env.resolve(expr)
                if engine_col is not None:
                    result.fn._pw_colref = engine_col
            except KeyError:
                pass
    except (AttributeError, TypeError):
        pass
    if cache is not None:
        cache[sig] = result
    return result


def _compile_expr_uncached(expr: ColumnExpression, env: ColumnEnv) -> Compiled:
    np_fn, dtype, jax_ok, refs = _build(expr, env)
    if jax_ok and _jax_available():
        jitted_box: list = []
        ref_cols = [c for c in refs if c is not None]

        hot = [0]  # large batches seen; compile only once it pays off
        jax_broken = [False]  # this fn's own short-circuit: a failed import
        # must not be retried per batch (each retry re-runs the whole
        # multi-second failing import inside the hot loop)

        def fn(cols: dict[str, np.ndarray], keys: np.ndarray) -> np.ndarray:
            n = len(keys)
            if (
                not jax_broken[0]
                and n >= JIT_THRESHOLD
                and all(cols[c].dtype != object for c in ref_cols)
            ):
                # warm-up gate: XLA compilation (~100ms) only pays for
                # expressions that keep seeing large batches (long-running
                # streams); short batch jobs stay on the numpy kernels.
                # jax itself imports only past the gate: without bytecode
                # caches (PYTHONDONTWRITEBYTECODE) the import costs ~2.5s
                # per process, which must not land on spawned host workers
                # that never reach the jit path.
                hot[0] += 1
                if hot[0] <= JIT_WARMUP_BATCHES:
                    return np_fn(cols, keys)
                try:
                    import jax

                    from ..utils import jaxcfg  # noqa: F401  (configures x64)
                except Exception:
                    # present-but-broken jax (e.g. jaxlib mismatch): degrade
                    # to the numpy kernels forever, as the old import-time
                    # probe did — never crash a running stream
                    _jax_checked[:] = [False]
                    jax_broken[0] = True
                    return np_fn(cols, keys)

                # x64 gate: without it the traced kernel silently truncates
                # INT/FLOAT columns to 32 bits — wrong values, and 32-bit
                # outputs knock every downstream key hash off the fast path.
                if not jax.config.jax_enable_x64:
                    return np_fn(cols, keys)
                if not jitted_box:
                    jitted_box.append(_jitted_kernel(expr, env))
                jitted = jitted_box[0]
                # pin to the host CPU backend: streaming tick batches are
                # latency-bound host work; shipping them to an accelerator
                # (worse, a tunneled one) per tick costs more than the fused
                # kernel saves. The TPU is for the dense kernels (knn,
                # embedder, window aggregation) that amortize the transfer.
                # Override with PATHWAY_TPU_EXPR_BACKEND=tpu.
                dev = _engine_device()
                if dev is not None:
                    with jax.default_device(dev):
                        return np.asarray(jitted(cols, keys))
                return np.asarray(jitted(cols, keys))
            return np_fn(cols, keys)

        return Compiled(fn, dtype, jax_ok=True)
    return Compiled(np_fn, dtype, jax_ok=jax_ok)


_engine_dev_cache: list = []


def _engine_device():
    if not _engine_dev_cache:
        import jax

        backend = os.environ.get("PATHWAY_TPU_EXPR_BACKEND", "cpu")
        try:
            _engine_dev_cache.append(jax.local_devices(backend=backend)[0])
        except Exception:
            _engine_dev_cache.append(None)
    return _engine_dev_cache[0]


_jax_checked: list[bool] = []


def _jax_available() -> bool:
    # spec lookup only — importing jax (via utils.jaxcfg) here would charge
    # every worker process ~2.5s at expression-compile time even when the
    # jit path is never taken
    if not _jax_checked:
        import importlib.util

        try:
            _jax_checked.append(importlib.util.find_spec("jax") is not None)
        except Exception:
            _jax_checked.append(False)
    return _jax_checked[0]


def _make_jitted(expr: ColumnExpression, env: ColumnEnv):
    import jax

    def traced(cols, keys):
        import jax.numpy as jnp

        fn, _, _, _ = _build(expr, env, xp_name="jax")
        return fn(cols, keys)

    return jax.jit(traced)


#: process-wide jitted-kernel memo: structural signature -> jit wrapper.
#: A pipeline REBUILT over fresh table objects (every bench run, every
#: pw.iterate round, a redeployed streaming service) used to re-trace and
#: re-compile every XLA kernel from scratch — ~100 ms per expression,
#: paid inside the tick loop right when the warmup gate opens. Two
#: expressions with equal structural signatures (same tree shape, ops,
#: scalar constants, and identically-resolved engine columns + dtypes)
#: compile to interchangeable kernels, and jax.jit re-traces per
#: input shape/dtype anyway — so sharing the wrapper is sound.
#: Tradeoff: each cached wrapper closes over its first (expr, env), so a
#: retired pipeline's expression tree + table objects stay pinned while
#: the entry lives — bounded by the cache cap (oldest half evicted at
#: the cap), and the pin IS the value: the next structurally-equal pipeline
#: reuses the compiled kernel instead of re-tracing XLA mid-stream.
_JIT_KERNEL_CACHE: dict = {}
_JIT_KERNEL_CACHE_MAX = 256


def _structural_sig(expr: ColumnExpression, env: ColumnEnv) -> tuple | None:
    """Identity-free signature of a jax-compilable expression tree, or
    None when the tree holds anything we cannot sign exactly (non-scalar
    constants, apply lambdas, method calls...) — those keep a private
    per-instance jit wrapper instead of risking a wrong cache hit."""
    t = type(expr)
    if isinstance(expr, expr_mod.SelfKeysExpression):
        return ("keys",)
    if isinstance(expr, expr_mod.HiddenRef):
        return ("href", expr._engine_name, str(expr._dtype))
    if isinstance(expr, (IdReference, ColumnReference)):
        try:
            engine_col, dtype = env.resolve(expr)
        except KeyError:
            return None
        return ("ref", t.__name__, engine_col, str(dtype))
    if t is ColumnConstExpression:
        v = expr._value
        if v is None or type(v) in (bool, int, float, str):
            return ("const", type(v).__name__, v)
        return None
    if t is ColumnBinaryOpExpression:
        l = _structural_sig(expr._left, env)
        r = _structural_sig(expr._right, env)
        return None if l is None or r is None else ("bin", expr._op, l, r)
    if t is ColumnUnaryOpExpression:
        s = _structural_sig(expr._expr, env)
        return None if s is None else ("un", expr._op, s)
    if t is IfElseExpression:
        parts = [
            _structural_sig(e, env)
            for e in (expr._if, expr._then, expr._else)
        ]
        return None if any(p is None for p in parts) else ("if", *parts)
    if t in (CastExpression, DeclareTypeExpression):
        s = _structural_sig(expr._expr, env)
        if s is None:
            return None
        return ("cast", t.__name__, str(expr._return_type), s)
    if t is CoalesceExpression:
        parts = [_structural_sig(e, env) for e in expr._args]
        return None if any(p is None for p in parts) else ("coal", *parts)
    if t in (UnwrapExpression,):
        s = _structural_sig(expr._expr, env)
        return None if s is None else ("unwrap", s)
    if t is FillErrorExpression:
        s = _structural_sig(expr._expr, env)
        r = _structural_sig(expr._replacement, env)
        return None if s is None or r is None else ("fillerr", s, r)
    return None


#: fused-chain cache entries ("chain", ...) -> frozenset of the member
#: expression signatures they were compiled from. A fused kernel is only
#: as alive as its members: the eviction sweep drops any chain entry
#: whose member signature it just evicted, so a rebuilt pipeline can
#: never pair a fresh member kernel with a stale fused composite.
_JIT_CHAIN_DEPS: dict = {}


def _evict_jit_cache() -> None:
    """Oldest-half eviction of the jit kernel cache, with fused-chain
    entries evicting as a unit with their member-node signatures."""
    from .udf_lift import evict_oldest_half

    before = set(_JIT_KERNEL_CACHE)
    evict_oldest_half(_JIT_KERNEL_CACHE)
    evicted = before - set(_JIT_KERNEL_CACHE)
    if evicted:
        for sig in [
            s
            for s in _JIT_KERNEL_CACHE
            if isinstance(s, tuple) and s and s[0] == "chain"
        ]:
            if _JIT_CHAIN_DEPS.get(sig, frozenset()) & evicted:
                del _JIT_KERNEL_CACHE[sig]
    for sig in list(_JIT_CHAIN_DEPS):
        if sig not in _JIT_KERNEL_CACHE:
            del _JIT_CHAIN_DEPS[sig]


def _jitted_kernel(expr: ColumnExpression, env: ColumnEnv):
    sig = _structural_sig(expr, env)
    if sig is None:
        return _make_jitted(expr, env)
    hit = _JIT_KERNEL_CACHE.get(sig)
    if hit is None:
        hit = _make_jitted(expr, env)
        if len(_JIT_KERNEL_CACHE) >= _JIT_KERNEL_CACHE_MAX:
            # oldest-half eviction, not clear(): a wholesale clear makes
            # every live pipeline re-trace its XLA kernels at once
            _evict_jit_cache()
        _JIT_KERNEL_CACHE[sig] = hit
    return hit


def fused_chain_kernel(chain_sig: tuple, member_sigs: list, build: Callable):
    """Whole-chain jit wrapper for engine/fusion.py: one ``jax.jit``
    callable per structurally-distinct chain, shared process-wide on the
    same cache the per-expression kernels ride (rebuilt pipelines reuse
    compiled chains instead of re-tracing XLA mid-stream). ``build()``
    returns the traceable composed function."""
    hit = _JIT_KERNEL_CACHE.get(chain_sig)
    if hit is None:
        import jax

        hit = jax.jit(build())
        if len(_JIT_KERNEL_CACHE) >= _JIT_KERNEL_CACHE_MAX:
            _evict_jit_cache()
        _JIT_KERNEL_CACHE[chain_sig] = hit
        _JIT_CHAIN_DEPS[chain_sig] = frozenset(member_sigs)
    return hit


# ---------------------------------------------------------------------------
# dtype rules
# ---------------------------------------------------------------------------

_CMP_OPS = {"==", "!=", "<", "<=", ">", ">="}
_ARITH_OPS = {"+", "-", "*", "/", "//", "%", "**", "@"}
_BITS_OPS = {"&", "|", "^", "<<", ">>"}


def binop_dtype(op: str, l: dt.DType, r: dt.DType) -> dt.DType:
    lu, ru = dt.unoptionalize(l), dt.unoptionalize(r)
    opt = l.is_optional or r.is_optional

    def w(t: dt.DType) -> dt.DType:
        return dt.Optional(t) if opt else t

    if op in _CMP_OPS:
        return w(dt.BOOL)
    if op in ("<<", ">>"):
        # shifts are integer arithmetic even on bools (True << True == 2);
        # the &/|/^ bool-closure rule must not apply
        if lu in (dt.INT, dt.BOOL) and ru in (dt.INT, dt.BOOL):
            return w(dt.INT)
        return w(dt.ANY)
    if op in _BITS_OPS:
        if lu == dt.BOOL and ru == dt.BOOL:
            return w(dt.BOOL)
        if lu == dt.INT and ru == dt.INT:
            return w(dt.INT)
        return w(dt.ANY)
    if op in _ARITH_OPS:
        # datetime algebra
        if lu in (dt.DATE_TIME_NAIVE, dt.DATE_TIME_UTC):
            if op == "-" and ru == lu:
                return w(dt.DURATION)
            if op in ("+", "-") and ru == dt.DURATION:
                return w(lu)
        if lu == dt.DURATION:
            if op == "+" and ru in (dt.DATE_TIME_NAIVE, dt.DATE_TIME_UTC):
                return w(ru)
            if op in ("+", "-") and ru == dt.DURATION:
                return w(dt.DURATION)
            if op in ("*",) and ru == dt.INT:
                return w(dt.DURATION)
            if op == "/" and ru == dt.DURATION:
                return w(dt.FLOAT)
            if op == "//" and ru == dt.DURATION:
                return w(dt.INT)
            if op in ("/", "//") and ru == dt.INT:
                return w(dt.DURATION)
        if lu == dt.STR and ru == dt.STR and op == "+":
            return w(dt.STR)
        if (lu == dt.STR and ru == dt.INT or lu == dt.INT and ru == dt.STR) and op == "*":
            return w(dt.STR)
        if isinstance(lu, dt.Array) or isinstance(ru, dt.Array):
            return w(lu if isinstance(lu, dt.Array) else ru)
        if op == "/":
            if lu in (dt.INT, dt.FLOAT, dt.BOOL) and ru in (dt.INT, dt.FLOAT, dt.BOOL):
                return w(dt.FLOAT)
        if lu == dt.FLOAT or ru == dt.FLOAT:
            if lu in _NUMERIC and ru in _NUMERIC:
                return w(dt.FLOAT)
        if lu in (dt.INT, dt.BOOL) and ru in (dt.INT, dt.BOOL):
            return w(dt.INT)
        if lu == dt.ANY or ru == dt.ANY:
            return w(dt.ANY)
    return w(dt.ANY)


# ---------------------------------------------------------------------------
# build: returns (fn, dtype, jax_ok, referenced engine cols)
# ---------------------------------------------------------------------------


def _build(
    expr: ColumnExpression, env: ColumnEnv, xp_name: str = "numpy"
) -> tuple[Callable, dt.DType, bool, set]:
    if xp_name == "jax":
        import jax.numpy as xp
    else:
        xp = np

    if isinstance(expr, expr_mod.SelfKeysExpression):
        return (lambda cols, keys: keys), dt.POINTER, True, set()

    if isinstance(expr, expr_mod.HiddenRef):
        name = expr._engine_name
        dtype = expr._dtype if expr._dtype is not None else dt.ANY
        numericable = dt.unoptionalize(dtype) in _NUMERIC
        return (lambda cols, keys: cols[name]), dtype, numericable, {name}

    if isinstance(expr, IdReference):
        engine_col, dtype = env.resolve(expr)
        if engine_col is None:
            return (lambda cols, keys: keys), dt.POINTER, True, {None}
        return (lambda cols, keys: cols[engine_col]), dtype, True, {engine_col}

    if isinstance(expr, ColumnReference):
        engine_col, dtype = env.resolve(expr)
        if engine_col is None:
            return (lambda cols, keys: keys), dt.POINTER, True, {None}
        numericable = dt.unoptionalize(dtype) in _NUMERIC or dtype == dt.POINTER
        return (
            (lambda cols, keys: cols[engine_col]),
            dtype,
            numericable,
            {engine_col},
        )

    if isinstance(expr, ColumnConstExpression):
        v = expr._value
        dtype = dt.dtype_of_value(v)
        numericable = dtype in _NUMERIC
        return (lambda cols, keys: v), dtype, numericable, set()

    if isinstance(expr, ColumnBinaryOpExpression):
        lf, ldt, lok, lrefs = _build(expr._left, env, xp_name)
        rf, rdt, rok, rrefs = _build(expr._right, env, xp_name)
        op = expr._op
        out_dt = binop_dtype(op, ldt, rdt)
        fn = _binop_fn(op, lf, rf, ldt, rdt, xp)
        jax_ok = (
            lok
            and rok
            and dt.unoptionalize(out_dt) in _NUMERIC
            and not ldt.is_optional
            and not rdt.is_optional
            and dt.unoptionalize(ldt) in _NUMERIC
            and dt.unoptionalize(rdt) in _NUMERIC
            # divisions stay on the numpy path: zero denominators must
            # become per-row Error values, which a jitted kernel can't hold
            and op not in ("/", "//", "%")
        )
        return fn, out_dt, jax_ok, lrefs | rrefs

    if isinstance(expr, ColumnUnaryOpExpression):
        f, d, ok, refs = _build(expr._expr, env, xp_name)
        op = expr._op
        if op == "-":
            return (lambda cols, keys: -f(cols, keys)), d, ok, refs
        if op == "~":
            out_dt = d
            def notfn(cols, keys, f=f):
                v = f(cols, keys)
                if isinstance(v, np.ndarray) and v.dtype == object:
                    return np.array([None if x is None else not x for x in v], dtype=object)
                return xp.logical_not(v) if dt.unoptionalize(d) == dt.BOOL else ~v
            return notfn, out_dt, ok and dt.unoptionalize(d) in _NUMERIC, refs
        if op == "abs":
            return (lambda cols, keys: xp.abs(f(cols, keys))), d, ok, refs
        raise NotImplementedError(f"unary op {op}")

    if isinstance(expr, IsNoneExpression):
        f, d, ok, refs = _build(expr._expr, env, xp_name)
        negate = isinstance(expr, IsNotNoneExpression)

        def fn(cols, keys, f=f, negate=negate):
            v = f(cols, keys)
            if isinstance(v, np.ndarray) and v.dtype == object:
                out = np.fromiter((x is None for x in v), dtype=bool, count=len(v))
            elif isinstance(v, np.ndarray):
                out = np.zeros(len(v), dtype=bool)
            else:
                out = np.zeros(len(keys), dtype=bool) if v is not None else np.ones(len(keys), dtype=bool)
            return ~out if negate else out

        return fn, dt.BOOL, False, refs

    if isinstance(expr, IfElseExpression):
        cf, cd, cok, crefs = _build(expr._if, env, xp_name)
        tf, td, tok, trefs = _build(expr._then, env, xp_name)
        ef, ed, eok, erefs = _build(expr._else, env, xp_name)
        out_dt = dt.types_lca(td, ed)

        def fn(cols, keys):
            cond = cf(cols, keys)
            tv, ev = tf(cols, keys), ef(cols, keys)
            if isinstance(cond, np.ndarray) and cond.dtype == object:
                cond = np.array([bool(x) for x in cond], dtype=bool)
            out = xp.where(cond, tv, ev)
            return out

        jax_ok = cok and tok and eok and dt.unoptionalize(out_dt) in _NUMERIC
        return fn, out_dt, jax_ok, crefs | trefs | erefs

    if isinstance(expr, CoalesceExpression):
        parts = [_build(a, env, xp_name) for a in expr._args]
        out_dt = dt.types_lca_many([p[1] for p in parts])
        non_none = [p[1] for p in parts if p[1] != dt.NONE]
        if non_none and any(not p[1].is_optional and p[1] != dt.NONE for p in parts):
            out_dt = dt.unoptionalize(out_dt)

        def fn(cols, keys):
            n = len(keys)
            result = _materialize(parts[0][0](cols, keys), n)
            for f, _, _, _ in parts[1:]:
                mask = np.fromiter((x is None for x in result), dtype=bool, count=n)
                if not mask.any():
                    break
                nxt = _materialize(f(cols, keys), n)
                result = np.where(mask, nxt, result)
            return _densify(result, out_dt)

        refs = set().union(*[p[3] for p in parts])
        return fn, out_dt, False, refs

    if isinstance(expr, RequireExpression):
        f, d, ok, refs = _build(expr._expr, env, xp_name)
        conds = [_build(a, env, xp_name) for a in expr._args]

        def fn(cols, keys):
            n = len(keys)
            result = _materialize(f(cols, keys), n)
            mask = np.zeros(n, dtype=bool)
            for cfn, _, _, _ in conds:
                v = _materialize(cfn(cols, keys), n)
                mask |= np.fromiter((x is None for x in v), dtype=bool, count=n)
            if mask.any():
                result = result.astype(object)
                result[mask] = None
            return result

        all_refs = refs.union(*[c[3] for c in conds]) if conds else refs
        return fn, dt.Optional(d), False, all_refs

    if isinstance(expr, UnwrapExpression):
        f, d, ok, refs = _build(expr._expr, env, xp_name)

        def fn(cols, keys):
            v = _materialize(f(cols, keys), len(keys))
            if v.dtype == object:
                for x in v:
                    if x is None:
                        raise ValueError("cannot unwrap, None found in column")
                    if isinstance(x, EngineError):
                        raise ValueError(
                            f"cannot unwrap, Error found in column: {x.message}"
                        )
                return _densify(v, dt.unoptionalize(d))
            return v

        return fn, dt.unoptionalize(d), False, refs

    if isinstance(expr, FillErrorExpression):
        f, d, ok, refs = _build(expr._expr, env, xp_name)
        rf, rd, rok, rrefs = _build(expr._replacement, env, xp_name)

        def fn(cols, keys):
            n = len(keys)
            try:
                v = _materialize(f(cols, keys), n)
            except Exception:
                # a vectorized kernel raises batch-wide; retry row by row so
                # only the genuinely failing rows receive the replacement —
                # the reference's per-row Value::Error replacement semantics
                repl = _materialize(rf(cols, keys), n)
                v = np.empty(n, dtype=object)
                for i in range(n):
                    row_cols = {c: a[i : i + 1] for c, a in cols.items()}
                    try:
                        out_i = _materialize(f(row_cols, keys[i : i + 1]), 1)[0]
                    except Exception:
                        out_i = repl[i]
                    # errors can also flow through as values (not raises)
                    v[i] = repl[i] if isinstance(out_i, EngineError) else out_i
                return _densify(v, dt.types_lca(d, rd))
            if v.dtype == object:
                err_mask = np.array(
                    [isinstance(x, EngineError) for x in v], dtype=bool
                )
                if err_mask.any():
                    repl = _materialize(rf(cols, keys), n)
                    v = v.copy()
                    v[err_mask] = repl[err_mask]
                # all errors gone — restore the dense (vectorizable) dtype
                return _densify(v, dt.types_lca(d, rd))
            return v

        return fn, dt.types_lca(d, rd), False, refs | rrefs

    if isinstance(expr, (CastExpression, ConvertExpression)):
        f, d, ok, refs = _build(expr._expr, env, xp_name)
        target = expr._return_type
        tu = dt.unoptionalize(target)
        fn = _cast_fn(f, d, target, xp)
        jax_ok = (
            ok
            and dt.unoptionalize(d) in _NUMERIC
            and tu in _NUMERIC
            and not d.is_optional
        )
        return fn, target, jax_ok, refs

    if isinstance(expr, DeclareTypeExpression):
        f, d, ok, refs = _build(expr._expr, env, xp_name)
        target = expr._return_type
        return f, target, ok and dt.unoptionalize(target) in _NUMERIC, refs

    if isinstance(expr, PointerExpression):
        parts = [_build(a, env, xp_name) for a in expr._args]
        if expr._instance is not None:
            parts.append(_build(expr._instance, env, xp_name))
        optional = getattr(expr, "_optional", False)

        def fn(cols, keys):
            n = len(keys)
            arrs = [_materialize(p[0](cols, keys), n) for p in parts]
            ptrs = K.mix_columns(arrs, n)
            if optional:
                # pointer_from(..., optional=True): any None argument
                # makes the pointer None (reference prev/next tables)
                null = np.zeros(n, dtype=bool)
                for a in arrs:
                    aa = np.asarray(a)
                    if aa.dtype == object:
                        null |= np.fromiter(
                            (v is None for v in aa), bool, n
                        )
                if null.any():
                    out = np.empty(n, dtype=object)
                    for i in range(n):
                        out[i] = None if null[i] else ptrs[i]
                    return out
            return ptrs

        refs = set().union(*[p[3] for p in parts]) if parts else set()
        out_dt = dt.Optional(dt.POINTER) if optional else dt.POINTER
        return fn, out_dt, False, refs

    if isinstance(expr, MakeTupleExpression):
        parts = [_build(a, env, xp_name) for a in expr._args]

        def fn(cols, keys):
            n = len(keys)
            arrs = [_materialize(p[0](cols, keys), n) for p in parts]
            out = np.empty(n, dtype=object)
            for i in range(n):
                out[i] = tuple(_unnp(a[i]) for a in arrs)
            return out

        out_dt = dt.Tuple(*[p[1] for p in parts])
        refs = set().union(*[p[3] for p in parts]) if parts else set()
        return fn, out_dt, False, refs

    if isinstance(expr, GetExpression):
        of, odt, ook, orefs = _build(expr._obj, env, xp_name)
        ixf, _, _, ixrefs = _build(expr._index, env, xp_name)
        df, ddt, _, drefs = _build(expr._default, env, xp_name)
        check = expr._check_if_exists

        def fn(cols, keys):
            n = len(keys)
            objs = _materialize(of(cols, keys), n)
            idxs = _materialize(ixf(cols, keys), n)
            dfts = _materialize(df(cols, keys), n)
            out = np.empty(n, dtype=object)
            for i in range(n):
                try:
                    v = objs[i]
                    if isinstance(v, dict):
                        out[i] = v[idxs[i]] if check else v.get(idxs[i], dfts[i])
                    else:
                        out[i] = v[idxs[i]]
                except (KeyError, IndexError, TypeError):
                    if check:
                        raise
                    out[i] = dfts[i]
            return out

        out_dt = dt.ANY
        if isinstance(dt.unoptionalize(odt), dt.List):
            out_dt = dt.unoptionalize(odt).wrapped
        elif isinstance(dt.unoptionalize(odt), dt.Tuple):
            args = dt.unoptionalize(odt).args
            if args:
                out_dt = dt.types_lca_many(list(args))
        elif dt.unoptionalize(odt) == dt.JSON:
            out_dt = dt.JSON
        if not check:
            out_dt = dt.types_lca(out_dt, ddt)
        return fn, out_dt, False, orefs | ixrefs | drefs

    if isinstance(expr, (AsyncApplyExpression, ApplyExpression)):
        return _build_apply(expr, env, xp_name)

    if isinstance(expr, MethodCallExpression):
        from .expressions_namespaces import compile_method

        return compile_method(expr, env, _build, xp_name)

    if isinstance(expr, ReducerExpression):
        raise TypeError(
            f"reducer {expr._reducer!r} used outside of a reduce() context"
        )

    raise NotImplementedError(f"cannot compile {type(expr).__name__}")


#: process-wide UDF path counters (satellite of the rowwise-fast-path
#: work): which execution path applies landed on — lifted (static
#: exec/AST lift at compile time), traced (probe-row plan built at
#: runtime, one per dtype signature), or per-row Python (counted in
#: rows, the number that actually hurts). Snapshotted onto /metrics as
#: pathway_udf_* and into the signals plane (observability.hub).
UDF_STATS: dict[str, int] = {
    "lifted_total": 0,
    "traced_total": 0,
    "perrow_rows_total": 0,
}


def udf_stats_snapshot() -> dict[str, float]:
    return {k: float(v) for k, v in UDF_STATS.items()}


def _pylist(a: np.ndarray) -> list:
    """Column array -> plain Python list, numpy scalars unwrapped in ONE
    pass (``tolist`` for dense dtypes) instead of a per-row ``_unnp``
    dispatch inside the UDF loop."""
    out = a.tolist()
    if a.dtype != object:
        return out
    return [x.item() if isinstance(x, np.generic) else x for x in out]


def _dispatch_perrow(fn_user, lists, klists, n, prop_none, return_type):
    """Vectorized residual dispatcher: the per-row path as ONE resolved
    loop — fn looked up once, argument columns pre-converted to Python
    lists, no per-row ``_unnp``/list-comprehension machinery. Per-row
    failures still become per-row Error values (reference Value::Error,
    value.rs:226)."""
    out = np.empty(n, dtype=object)
    name = getattr(fn_user, "__name__", "apply")
    if not klists and not prop_none:
        if len(lists) == 1:
            i = 0
            for a in lists[0]:
                try:
                    out[i] = fn_user(a)
                except Exception as e:
                    out[i] = EngineError(f"{type(e).__name__}: {e}", name)
                i += 1
        else:
            i = 0
            for args_i in zip(*lists):
                try:
                    out[i] = fn_user(*args_i)
                except Exception as e:
                    out[i] = EngineError(f"{type(e).__name__}: {e}", name)
                i += 1
    else:
        knames = list(klists)
        kcols = [klists[k] for k in knames]
        rows = zip(*lists) if lists else iter([()] * n)
        i = 0
        for args_i in rows:
            if prop_none and any(a is None for a in args_i):
                out[i] = None
                i += 1
                continue
            kw = {k: c[i] for k, c in zip(knames, kcols)}
            try:
                out[i] = fn_user(*args_i, **kw)
            except Exception as e:
                out[i] = EngineError(f"{type(e).__name__}: {e}", name)
            i += 1
    return _densify(out, return_type)


def _dtype_sig(arrs: list, karrs: dict) -> tuple | None:
    """Runtime dtype signature of one batch's argument columns — the
    guard that keeps a traced plan from serving rows it was not traced
    for. Dense arrays are uniform by construction (dtype char); object
    arrays are scanned (one C-speed type pass). None = this batch is
    not plan-servable (mixed types, None rows, Error carriers) and must
    run per-row."""
    sig: list = []
    for a in list(arrs) + [karrs[k] for k in sorted(karrs)]:
        if a.dtype != object:
            sig.append(a.dtype.char)
            continue
        kinds = set(map(type, a.tolist()))
        if len(kinds) != 1:
            return None
        t = next(iter(kinds))
        if t is type(None) or t is EngineError:
            return None
        sig.append(t)
    return tuple(sig)


def _build_apply(
    expr: "ApplyExpression", env: ColumnEnv, xp_name: str
) -> tuple[Callable, dt.DType, bool, set]:
    """Compile an apply node through the fast-path ladder:

    1. static lift (bytecode-execution trace, then AST lift) — the UDF
       becomes a columnar kernel at compile time;
    2. probe-row tracing at runtime, guarded by the batch's dtype
       signature (re-traced per signature on mixed-dtype streams);
    3. the vectorized per-row dispatcher — genuinely impure/unliftable
       callables, counted on /metrics.

    Lifted and traced kernels carry a per-row fallback: any batch-wide
    raise re-runs that batch through the exact per-row path (safe — the
    lift gates admit only side-effect-free callables), so row-error
    semantics are identical on every path.
    """
    import asyncio
    import inspect

    fn_user = expr._fn
    prop_none = expr._propagate_none
    is_coro = inspect.iscoroutinefunction(fn_user)
    deterministic = getattr(expr, "_deterministic", True)
    lift_eligible = (
        deterministic
        and not is_coro
        and not prop_none
        and os.environ.get("PATHWAY_UDF_LIFT", "auto") != "off"
    )
    trace_eligible = (
        deterministic
        and not is_coro
        and not prop_none
        and os.environ.get("PATHWAY_UDF_TRACE", "auto") != "off"
    )

    # arg kernels are built once and shared by every path (the refusal
    # memo and the Optional-dtype lift gate are keyed by arg dtypes)
    parts: list | None = None
    kparts: dict | None = None

    def _arg_parts() -> tuple[list, dict]:
        nonlocal parts, kparts
        if parts is None:
            parts = [_build(a, env, xp_name) for a in expr._args]
            kparts = {
                k: _build(v, env, xp_name) for k, v in expr._kwargs.items()
            }
        return parts, kparts

    def _lift_key() -> tuple:
        p, kp = _arg_parts()
        return (
            fn_user.__code__,
            tuple(str(x[1]) for x in p),
            tuple(sorted((k, str(x[1])) for k, x in kp.items())),
        )

    def _perrow(cols, keys):
        """The exact per-row path — also the fallback a lifted/traced
        kernel retries a raising batch through."""
        n = len(keys)
        p, kp = _arg_parts()
        lists = [_pylist(_materialize(x[0](cols, keys), n)) for x in p]
        klists = {
            k: _pylist(_materialize(x[0](cols, keys), n))
            for k, x in kp.items()
        }
        UDF_STATS["perrow_rows_total"] += n
        return _dispatch_perrow(
            fn_user, lists, klists, n, prop_none, expr._return_type
        )

    def _guard(vec: Callable) -> Callable:
        # numpy kernels only: under a fused-jax rebuild the tracer flows
        # through the try body and the fallback must not trace
        if xp_name != "numpy":
            return vec

        def fn(cols, keys):
            try:
                return vec(cols, keys)
            except Exception:
                return _perrow(cols, keys)

        return fn

    def _args_optional() -> bool:
        """Optional args stay off the static lift: a lifted kernel
        propagates None through _objsafe while the per-row path raises
        into a per-row Error — the runtime trace handles optional
        streams instead (its signature guard routes None-carrying
        batches per-row). Plain column refs resolve without building
        their kernels, preserving the lift fast path's lazy arg builds;
        only computed argument trees force a real build."""
        computed = False
        for a in list(expr._args) + list(expr._kwargs.values()):
            if isinstance(a, ColumnConstExpression):
                continue
            if isinstance(a, ColumnReference):  # incl. IdReference
                try:
                    _, d = env.resolve(a)
                except KeyError:
                    return True  # unresolvable here: stay off the lift
                if d.is_optional:
                    return True
                continue
            computed = True
        if computed:
            p, kp = _arg_parts()
            return any(x[1].is_optional for x in p + list(kp.values()))
        return False

    def _note_outcome(status: str, refusal: str | None = None) -> None:
        # static-analysis breadcrumb (analysis/passes.py dispatch-tax
        # pass): which ladder rung this apply landed on, and — when it
        # fell off the static lift — exactly why
        try:
            expr._pw_lift_outcome = {
                "status": status,
                "refusal": refusal,
                "traceable": None,  # filled on the dynamic path
            }
        except (AttributeError, TypeError):
            pass

    #: why the static lift was not even attempted (analysis surfaces it)
    refusal_reason: str | None = None
    if not lift_eligible:
        if not deterministic:
            refusal_reason = "declared non-deterministic"
        elif is_coro:
            refusal_reason = "async UDF"
        elif prop_none:
            refusal_reason = "propagate_none=True"
        else:
            refusal_reason = "PATHWAY_UDF_LIFT=off"

    # ---- 1. static lift (exec trace, then AST) -----------------------
    if lift_eligible and getattr(fn_user, "__code__", None) is not None:
        if (
            fn_user.__code__ in _LIFT_REFUSED_CODES
            and _lift_key() in _LIFT_REFUSED
        ):
            # memoized refusal: skip the re-trace, keep the recorded why
            refusal_reason = _LIFT_REFUSED[_lift_key()]
        elif _args_optional():
            refusal_reason = (
                "Optional-dtype arguments (runtime probe-trace handles "
                "None-carrying batches instead)"
            )
        else:
            traced = None
            gate_reason = _liftable_reason(fn_user)
            if gate_reason is None:
                # execution trace (reference expression.rs:325 — no
                # Python in the hot loop): call the lambda on the
                # ARGUMENT EXPRESSIONS; a pure-operator lambda returns a
                # ColumnExpression tree
                try:
                    traced = fn_user(*expr._args, **expr._kwargs)
                except Exception:
                    traced = None
                if not isinstance(traced, ColumnExpression) or isinstance(
                    traced, (ApplyExpression, AsyncApplyExpression)
                ):
                    traced = None
            if traced is None:
                # widened AST lift: method chains, dict access,
                # conditionals, builtin subset — no user code runs
                from .udf_lift import ast_lift

                ast_why: list = []
                traced = ast_lift(
                    fn_user, expr._args, expr._kwargs, reason_out=ast_why
                )
                if traced is None:
                    refusal_reason = gate_reason or (
                        f"AST lift: {ast_why[0]}" if ast_why
                        else "AST lift refused"
                    )
            lifted = None
            if traced is not None:
                try:
                    lifted, _odt, agg, refs = _build(traced, env, xp_name)
                except Exception as e:
                    # the traced tree may hit operator/dtype combinations
                    # the columnar compiler refuses (e.g. str * int);
                    # per-row Python still handles those
                    lifted = None
                    refusal_reason = (
                        f"columnar compile refused the lifted tree: {e}"
                    )
            if lifted is not None:
                UDF_STATS["lifted_total"] += 1
                _note_outcome("lifted")
                return (
                    _align_dtype(_guard(lifted), expr._return_type),
                    expr._return_type, agg, refs,
                )
            from .udf_lift import evict_oldest_half

            if len(_LIFT_REFUSED) >= 4096:
                evict_oldest_half(_LIFT_REFUSED)
                _LIFT_REFUSED_CODES.clear()
                _LIFT_REFUSED_CODES.update(k[0] for k in _LIFT_REFUSED)
            _LIFT_REFUSED[_lift_key()] = refusal_reason
            _LIFT_REFUSED_CODES.add(fn_user.__code__)

    parts, kparts = _arg_parts()
    refs = (
        set().union(*[p[3] for p in parts], *[p[3] for p in kparts.values()])
        if (parts or kparts)
        else set()
    )

    if is_coro:
        def fn_async(cols, keys):
            n = len(keys)
            arrs = [_materialize(p[0](cols, keys), n) for p in parts]
            karrs = {
                k: _materialize(p[0](cols, keys), n)
                for k, p in kparts.items()
            }

            async def gather():
                return await asyncio.gather(*[
                    fn_user(
                        *[_unnp(a[i]) for a in arrs],
                        **{k: _unnp(v[i]) for k, v in karrs.items()},
                    )
                    for i in range(n)
                ], return_exceptions=True)

            results = _run_async(gather())
            out = np.empty(n, dtype=object)
            for i, r in enumerate(results):
                if isinstance(r, BaseException):
                    if not isinstance(r, Exception):
                        raise r  # CancelledError etc. must not become data
                    out[i] = EngineError(
                        f"{type(r).__name__}: {r}",
                        getattr(fn_user, "__name__", "async apply"),
                    )
                else:
                    out[i] = r
            return _densify(out, expr._return_type)

        _note_outcome("async", refusal_reason)
        return fn_async, expr._return_type, False, refs

    # ---- 2./3. runtime: probe-row trace, else vectorized per-row -----
    trace_ok = False
    if trace_eligible and xp_name == "numpy":
        from .udf_lift import traceable

        trace_ok = traceable(fn_user)
    _note_outcome("dynamic", refusal_reason)
    try:
        expr._pw_lift_outcome["traceable"] = trace_ok
    except (AttributeError, TypeError):
        pass
    plans: dict[tuple, Callable] = {}
    refused_sigs: set = set()

    def _try_trace(sig, arrs, karrs, cols, keys):
        from .udf_lift import TraceRefused, trace_probe

        try:
            probe = [_unnp(a[0]) for a in arrs]
            kprobe = {k: _unnp(v[0]) for k, v in karrs.items()}
            texpr, probe_val = trace_probe(
                fn_user, probe, list(expr._args), kprobe, dict(expr._kwargs)
            )
            kernel, _odt, _agg, _refs = _build(texpr, env, "numpy")
            kernel = _align_dtype(kernel, expr._return_type)
            # consistency check: the compiled plan must reproduce the
            # probe row's genuine result before it serves the stream
            row0 = {c: a[:1] for c, a in cols.items()}
            got = _unnp(_materialize(kernel(row0, keys[:1]), 1)[0])
            same = got == probe_val or (
                isinstance(got, float)
                and isinstance(probe_val, float)
                and np.isnan(got)
                and np.isnan(probe_val)
            )
            if not bool(same):
                raise TraceRefused
        except (TraceRefused, Exception):
            refused_sigs.add(sig)
            return None
        plans[sig] = kernel
        UDF_STATS["traced_total"] += 1
        return kernel

    def fn(cols, keys):
        n = len(keys)
        arrs = [_materialize(p[0](cols, keys), n) for p in parts]
        karrs = {
            k: _materialize(p[0](cols, keys), n) for k, p in kparts.items()
        }
        if trace_ok and n:
            sig = _dtype_sig(arrs, karrs)
            if sig is not None:
                plan = plans.get(sig)
                if plan is None and sig not in refused_sigs:
                    plan = _try_trace(sig, arrs, karrs, cols, keys)
                if plan is not None:
                    try:
                        return plan(cols, keys)
                    except Exception:
                        pass  # batch-wide raise: exact per-row semantics
        lists = [_pylist(a) for a in arrs]
        klists = {k: _pylist(v) for k, v in karrs.items()}
        UDF_STATS["perrow_rows_total"] += n
        return _dispatch_perrow(
            fn_user, lists, klists, n, prop_none, expr._return_type
        )

    return fn, expr._return_type, False, refs


#: (fn code, arg dtypes) -> refusal reason (str | None) of apply lambdas
#: whose lift attempt failed — rebuilds skip the re-trace and land on the
#: per-row kernel directly, carrying the recorded reason into the
#: dispatch-tax lint diagnostic.
#: Insertion-ordered dict so hitting the cap evicts the OLDEST half
#: instead of clearing wholesale (a long-lived multi-pipeline process
#: must not re-trace every lambda at once); _LIFT_REFUSED_CODES is
#: rebuilt from the surviving keys on every eviction.
#: Two-level: the dtype-qualified key is only computed (it forces the
#: arg builds) for code objects that have SOME refusal on record —
#: never-refused lambdas pay nothing on the lift fast path
_LIFT_REFUSED: dict = {}
_LIFT_REFUSED_CODES: set = set()
#: liftability verdict per code object (bytecode-only property, so the
#: code object is the exact cache key); skips the dis scan on rebuilds.
#: Value is None (liftable) or the first blocking construct as a string
#: (surfaced verbatim by the per-row dispatch-tax lint diagnostic)
_LIFTABLE_CACHE: dict[Any, str | None] = {}


def _liftable(fn: Callable) -> bool:
    return _liftable_reason(fn) is None


def _liftable_reason(fn: Callable) -> str | None:
    """Safe to trace symbolically: a plain function whose bytecode contains
    no calls, no global/closure reads and no imports — so executing it once
    on expression placeholders cannot run user side effects per trace that
    the per-row path would have run per row, and captures no late-binding
    state. Operator expressions (``lambda x: x * 2 + 1``) pass; anything
    calling functions, reading globals/closures, or branching on values
    (guarded separately by ColumnExpression.__bool__ raising) falls back.
    Returns None when liftable, else the first blocking construct (the
    dispatch-tax diagnostic surfaces it verbatim). Memoized per code
    object — the verdict is a pure bytecode property."""
    code = getattr(fn, "__code__", None)
    if code is not None and code in _LIFTABLE_CACHE:
        return _LIFTABLE_CACHE[code]
    import dis

    try:
        instructions = list(dis.get_instructions(fn))
    except TypeError:
        return "not introspectable bytecode"
    blocked = (
        "CALL", "LOAD_GLOBAL", "LOAD_DEREF", "IMPORT", "MAKE_FUNCTION",
        # writes are side effects too: lifting would elide the per-row
        # store and leave the target bound to an expression placeholder
        "STORE_GLOBAL", "STORE_DEREF", "STORE_ATTR", "STORE_SUBSCR",
        # iteration over a ColumnExpression placeholder never terminates
        # (__getitem__ exists, __iter__ does not → legacy protocol spins)
        "GET_ITER", "FOR_ITER", "GET_AITER",
        # generator/comprehension machinery implies iteration as well
        "YIELD", "RETURN_GENERATOR",
        # identity tests fold silently at trace time: `a is None` on the
        # placeholder is plain False with NO __bool__ call, so a
        # None-handling branch would vanish from the traced tree
        "IS_OP", "POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE",
    )
    verdict: str | None = None
    for ins in instructions:
        if ins.opname.startswith(blocked):
            what = f" ({ins.argval})" if isinstance(ins.argval, str) else ""
            verdict = f"bytecode gate: {ins.opname}{what}"
            break
    if code is not None:
        if len(_LIFTABLE_CACHE) >= 1024:
            from .udf_lift import evict_oldest_half

            evict_oldest_half(_LIFTABLE_CACHE)
        _LIFTABLE_CACHE[code] = verdict
    return verdict


def _align_dtype(fn: Callable, want: dt.DType) -> Callable:
    """Cast a lifted-apply column to the dtype the ``apply`` declared, so
    downstream consumers see the same runtime dtype the per-row path's
    ``_densify`` would have produced (e.g. int arithmetic lifted under a
    declared float return)."""
    target = {
        dt.INT: np.int64, dt.FLOAT: np.float64, dt.BOOL: np.bool_
    }.get(want)
    if target is None:
        return fn

    def cast(cols, keys):
        out = fn(cols, keys)
        # trace-safe: never np.asarray here — under the fused-DAG jit
        # (``_make_jitted``) ``out`` is a jax tracer. astype exists on both
        # numpy arrays and tracers; anything without a dtype passes through.
        dtype = getattr(out, "dtype", None)
        if (
            dtype is not None
            and getattr(dtype, "kind", None) in "ifb"
            and getattr(out, "ndim", None) == 1
            and np.dtype(dtype) != target
        ):
            return out.astype(target)
        return out

    return cast


def _run_async(coro):
    import asyncio

    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        return asyncio.run(coro)
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(1) as pool:
        return pool.submit(asyncio.run, coro).result()


def _materialize(v: Any, n: int) -> np.ndarray:
    if isinstance(v, np.ndarray) and v.ndim == 1 and len(v) == n:
        return v
    out = np.empty(n, dtype=object)
    if isinstance(v, np.ndarray):
        out[:] = list(v)
    else:
        # fill() assigns the object per cell — slice-assigning tuple/list
        # values would make numpy broadcast them as nested arrays
        out.fill(v)
    return out


def _unnp(v: Any) -> Any:
    if isinstance(v, np.generic):
        return v.item()
    return v


def _densify(arr: np.ndarray, dtype: dt.DType) -> np.ndarray:
    """Try to store an object array densely according to its declared dtype."""
    if arr.dtype != object:
        return arr
    target = dtype.numpy_dtype
    if target == np.dtype(object) or dtype.is_optional:
        return arr
    try:
        return arr.astype(target)
    except (ValueError, TypeError):
        return arr


def _binop_fn(op, lf, rf, ldt, rdt, xp):
    lu, ru = dt.unoptionalize(ldt), dt.unoptionalize(rdt)

    if op in ("/", "//", "%") and (
        op != "/" or (lu in _NUMERIC and ru in _NUMERIC)
    ):
        base = {
            "/": xp.true_divide, "//": xp.floor_divide, "%": xp.mod
        }[op]

        def vec(lv, rv, keys):
            if xp is not np:  # inside a fused jax kernel: no Error carriers
                return base(lv, rv)
            ra = np.asarray(rv)
            if ra.dtype.kind in "iuf":
                zeros = ra == 0
                if zeros.any():
                    # reference DivisionByZero (expression.rs:846,935):
                    # zero denominators yield per-row Error values, not
                    # numpy's silent 0/inf
                    n = len(keys)
                    with np.errstate(divide="ignore", invalid="ignore"):
                        res = base(lv, rv)
                    out = _materialize(res, n).astype(object)
                    for i in np.flatnonzero(np.broadcast_to(zeros, (n,))):
                        out[i] = EngineError("division by zero", op)
                    return out
            return base(lv, rv)

        return _objsafe(vec, op, lf, rf)
    if op == "&" and lu == dt.BOOL and ru == dt.BOOL:
        return _objsafe(
            lambda lv, rv, keys: xp.logical_and(lv, rv), op, lf, rf
        )
    if op == "|" and lu == dt.BOOL and ru == dt.BOOL:
        return _objsafe(
            lambda lv, rv, keys: xp.logical_or(lv, rv), op, lf, rf
        )

    import operator as _op

    py_ops = {
        "+": _op.add, "-": _op.sub, "*": _op.mul, "/": _op.truediv,
        "**": _op.pow, "==": _op.eq, "!=": _op.ne, "<": _op.lt,
        "<=": _op.le, ">": _op.gt, ">=": _op.ge, "&": _op.and_,
        "|": _op.or_, "^": _op.xor, "@": _op.matmul,
        "<<": _op.lshift, ">>": _op.rshift,
    }
    f = py_ops[op]

    if op in _CMP_OPS and (lu == dt.POINTER or ru == dt.POINTER):
        def fn(cols, keys):
            return f(np.asarray(lf(cols, keys), dtype=np.uint64), np.asarray(rf(cols, keys), dtype=np.uint64))
        return fn

    if op == "@":
        def fn_mm(cols, keys):
            l, r = lf(cols, keys), rf(cols, keys)
            n = len(keys)
            la, ra = _materialize(l, n), _materialize(r, n)
            out = np.empty(n, dtype=object)
            for i in range(n):
                out[i] = la[i] @ ra[i]
            return out
        return fn_mm
    if op in ("+", "-", "*", "/", "**", "==", "!=", "<", "<=", ">", ">=",
              "&", "|", "^", "<<", ">>"):
        # object columns may carry None/Error rows — handle per element.
        # Applied even for statically dense dtypes: upstream zero-division
        # injects Error rows into columns typed non-optional, and _objsafe
        # only pays one dtype check when the operands stay dense
        return _objsafe(lambda lv, rv, keys: f(lv, rv), op, lf, rf)
    raise AssertionError(f"unhandled binop {op!r}")  # every py_ops key is covered above


def _objsafe(vec_fn, op, lf, rf):
    """Wrap a value-level vectorized op: operands are evaluated ONCE, then
    either handed to ``vec_fn`` (dense fast path) or walked per-row with
    None/Error semantics. ``vec_fn(lv, rv, keys)`` must not re-invoke the
    operand closures — that re-evaluation compounds 2**depth over nested
    expressions (review finding r3)."""
    import operator as _op

    py_ops = {
        "+": _op.add, "-": _op.sub, "*": _op.mul, "/": _op.truediv,
        "//": _op.floordiv, "%": _op.mod, "**": _op.pow,
        "==": _op.eq, "!=": _op.ne, "<": _op.lt, "<=": _op.le,
        ">": _op.gt, ">=": _op.ge,
        "&": lambda a, b: (a and b) if isinstance(a, (bool, np.bool_)) else a & b,
        "|": lambda a, b: (a or b) if isinstance(a, (bool, np.bool_)) else a | b,
        "^": _op.xor, "<<": _op.lshift, ">>": _op.rshift,
    }
    f = py_ops[op]

    def fn(cols, keys):
        l, r = lf(cols, keys), rf(cols, keys)
        lo = isinstance(l, np.ndarray) and l.dtype == object
        ro = isinstance(r, np.ndarray) and r.dtype == object
        if not lo and not ro:
            return vec_fn(l, r, keys)
        n = len(keys)
        la, ra = _materialize(l, n), _materialize(r, n)
        out = np.empty(n, dtype=object)
        for i in range(n):
            a, b = _unnp(la[i]), _unnp(ra[i])
            if isinstance(a, EngineError):
                out[i] = a  # errors flow through expressions (value.rs:226)
            elif isinstance(b, EngineError):
                out[i] = b
            elif a is None or b is None:
                out[i] = None
            else:
                try:
                    out[i] = f(a, b)
                except Exception as e:  # noqa: BLE001 — row error, not batch
                    # reference: any DataError becomes a per-row Value::Error
                    out[i] = EngineError(f"{type(e).__name__}: {e}", op)
        return out

    return fn


def _cast_fn(f, src: dt.DType, target: dt.DType, xp):
    tu = dt.unoptionalize(target)
    su = dt.unoptionalize(src)

    def convert_scalar(v):
        if v is None or isinstance(v, EngineError):
            return v
        if isinstance(v, Json):
            # .as_int()/.as_str()/… are STRICT typed accessors over the
            # json VALUE (reference expression.py as_* over Value::Json):
            # a type mismatch yields None per the Optional return type —
            # and str(Json) would re-serialize ('"x"', not 'x')
            v = v.value
            if tu == dt.INT:
                return v if type(v) is int else None
            if tu == dt.FLOAT:
                return float(v) if type(v) in (int, float) else None
            if tu == dt.BOOL:
                return v if type(v) is bool else None
            if tu == dt.STR:
                return v if type(v) is str else None
            return v
        if tu == dt.INT:
            return int(v)
        if tu == dt.FLOAT:
            return float(v)
        if tu == dt.BOOL:
            return bool(v)
        if tu == dt.STR:
            return str(v)
        return v

    def fn(cols, keys):
        v = f(cols, keys)
        n = len(keys)
        arr = _materialize(v, n) if not isinstance(v, np.ndarray) else v
        if arr.dtype == object:
            out = np.empty(n, dtype=object)
            for i in range(n):
                out[i] = convert_scalar(arr[i])
            return _densify(out, target)
        if tu == dt.INT:
            return xp.asarray(arr).astype(xp.int64 if xp is np else "int64")
        if tu == dt.FLOAT:
            return xp.asarray(arr).astype(xp.float64 if xp is np else "float64")
        if tu == dt.BOOL:
            return xp.asarray(arr).astype(bool)
        if tu == dt.STR:
            out = np.empty(n, dtype=object)
            av = np.asarray(arr)
            for i in range(n):
                out[i] = str(_unnp(av[i]))
            return out
        return arr

    return fn
