"""Live monitoring dashboard (reference
``python/pathway/internals/monitoring.py:56-190`` — a rich TUI table of
connector/operator progress fed by engine ProberStats). Renders from
``EngineStats`` on a background thread; falls back to plain-text lines
when rich is unavailable or stdout is not a TTY.
"""

from __future__ import annotations

import sys
import threading
from typing import Any

__all__ = ["MonitoringLevel", "start_dashboard"]


class MonitoringLevel:
    NONE = 0
    IN_OUT = 1
    ALL = 2
    AUTO = 3
    AUTO_ALL = 4


def _rows(stats: Any, level: int) -> list[tuple[str, str]]:
    out = [
        ("ticks (commits)", str(stats.ticks)),
        ("rows ingested", str(stats.input_rows)),
        ("rows emitted", str(stats.output_rows)),
        (
            "output latency",
            f"{stats.latency_ms:.0f} ms" if stats.latency_ms is not None else "-",
        ),
    ]
    tick_hist = getattr(stats, "tick_duration", None)
    if tick_hist is not None and len(tick_hist):
        from ..observability.histogram import quantile_from_snapshot

        snap = tick_hist.snapshot()
        p50 = quantile_from_snapshot(snap, 0.5) / 1e6
        p95 = quantile_from_snapshot(snap, 0.95) / 1e6
        out.append(("tick p50/p95", f"{p50:.1f}/{p95:.1f} ms"))
    if level >= MonitoringLevel.ALL:
        # snapshot: the executor thread inserts node keys concurrently.
        # per-operator row counts + cumulative processing time (the
        # reference's connector/operator latency table, monitoring.py:56-190)
        times = dict(stats.time_by_node)
        for label, count in sorted(list(stats.rows_by_node.items())):
            ms = times.get(label, 0) / 1e6
            out.append((
                f"  {label}",
                f"{count} rows / {ms:.1f} ms" if ms else f"{count} rows",
            ))
    return out


def start_dashboard(stats: Any, level: int, refresh_s: float = 1.0):
    """Returns a stop() callable."""
    if level == MonitoringLevel.NONE:
        # a NONE caller must get a no-op — without this early return a
        # refresh thread would still spawn and spam stderr
        return lambda: None
    if level in (MonitoringLevel.AUTO, MonitoringLevel.AUTO_ALL):
        if not sys.stderr.isatty():
            # AUTO means "dashboard only when interactive" (reference
            # resolves AUTO to NONE off-tty) — don't spam piped logs
            return lambda: None
        level = (
            MonitoringLevel.ALL
            if level == MonitoringLevel.AUTO_ALL
            else MonitoringLevel.IN_OUT
        )
    if level >= MonitoringLevel.ALL:
        stats.detailed = True  # turn on per-node timing in the executor
    stop_event = threading.Event()

    def plain_loop() -> None:
        while not stop_event.wait(refresh_s):
            parts = ", ".join(f"{k}={v}" for k, v in _rows(stats, level))
            print(f"[pathway monitoring] {parts}", file=sys.stderr)

    def rich_loop() -> None:
        from rich.console import Console
        from rich.live import Live
        from rich.table import Table as RichTable

        def render():
            table = RichTable(title="pathway_tpu engine")
            table.add_column("metric")
            table.add_column("value", justify="right")
            for k, v in _rows(stats, level):
                table.add_row(k, v)
            return table

        # dashboard goes to stderr (the tty we gated on) so redirected
        # stdout program output stays clean
        console = Console(file=sys.stderr)
        with Live(render(), refresh_per_second=4, transient=True,
                  console=console) as live:
            while not stop_event.wait(refresh_s):
                live.update(render())

    use_rich = sys.stderr.isatty()
    if use_rich:
        try:
            import rich  # noqa: F401
        except ImportError:
            use_rich = False
    thread = threading.Thread(
        target=rich_loop if use_rich else plain_loop, daemon=True
    )
    thread.start()

    def stop() -> None:
        stop_event.set()
        thread.join(timeout=2)

    return stop
