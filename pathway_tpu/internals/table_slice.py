"""TableSlice — a manipulable collection of column references.

Re-design of ``python/pathway/internals/table_slice.py``: created by the
``Table.slice`` property; supports ``without``/``rename``/``with_prefix``/
``with_suffix``/``ix``/``ix_ref`` and unpacks into ``select`` (each yielded
reference remembers the slice's name for it).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterator

from .expression import ColumnReference
from .thisclass import ThisPlaceholder

if TYPE_CHECKING:
    from .table import Table

__all__ = ["TableSlice"]


class RenamedReference(ColumnReference):
    """A column reference carrying a different output name — produced by
    renamed slices so ``select(*slice)`` lands on the slice's names."""

    def __init__(self, source: ColumnReference, name: str):
        super().__init__(source.table, name)
        self._source = source


class TableSlice:
    def __init__(self, mapping: dict[str, ColumnReference], table: "Table"):
        self._mapping = mapping
        self._table = table

    def __iter__(self) -> Iterator[ColumnReference]:
        for name, ref in self._mapping.items():
            yield ref if ref.name == name else RenamedReference(ref, name)

    def __repr__(self) -> str:
        return f"TableSlice({self._mapping})"

    def keys(self):
        return self._mapping.keys()

    def __getitem__(self, arg):
        if isinstance(arg, (ColumnReference, str)):
            return self._mapping[self._normalize(arg)]
        return TableSlice(
            {self._normalize(k): self[k] for k in arg}, self._table
        )

    def __getattr__(self, name: str):
        from .table import Table

        if name.startswith("_"):
            raise AttributeError(name)
        if hasattr(Table, name) and name != "id":
            raise ValueError(
                f"{name!r} is a method name. It is discouraged to use it as "
                f"a column name. If you really want to use it, use [{name!r}]."
            )
        if name not in self._mapping:
            raise AttributeError(f"Column name {name!r} not found in {self!r}.")
        return self._mapping[name]

    def without(self, *cols) -> "TableSlice":
        mapping = dict(self._mapping)
        for col in cols:
            colname = self._normalize(col)
            if colname not in mapping:
                raise KeyError(f"Column name {colname!r} not found in a {self}.")
            mapping.pop(colname)
        return TableSlice(mapping, self._table)

    def rename(self, rename_dict: dict) -> "TableSlice":
        normalized = {
            self._normalize(old): self._normalize(new)
            for old, new in rename_dict.items()
        }
        mapping = dict(self._mapping)
        for old in normalized:
            if old not in mapping:
                raise KeyError(f"Column name {old!r} not found in a {self}.")
            mapping.pop(old)
        for old, new in normalized.items():
            mapping[new] = self._mapping[old]
        return TableSlice(mapping, self._table)

    def with_prefix(self, prefix: str) -> "TableSlice":
        return self.rename({name: prefix + name for name in self.keys()})

    def with_suffix(self, suffix: str) -> "TableSlice":
        return self.rename({name: name + suffix for name in self.keys()})

    def ix(self, expression, *, optional: bool = False, context=None) -> "TableSlice":
        new_table = self._table.ix(expression, optional=optional, context=context)
        return TableSlice(
            {
                name: ColumnReference(new_table, ref.name)
                for name, ref in self._mapping.items()
            },
            new_table,
        )

    def ix_ref(self, *args, optional: bool = False, context=None) -> "TableSlice":
        new_table = self._table.ix_ref(*args, optional=optional, context=context)
        return TableSlice(
            {
                name: ColumnReference(new_table, ref.name)
                for name, ref in self._mapping.items()
            },
            new_table,
        )

    @property
    def slice(self) -> "TableSlice":
        return self

    def _normalize(self, arg) -> str:
        if isinstance(arg, ColumnReference):
            if isinstance(arg.table, ThisPlaceholder):
                return arg.name
            if arg.table is not self._table:
                raise ValueError(
                    "TableSlice method arguments should refer to table of "
                    "which the slice was created."
                )
            return arg.name
        return arg
